"""ShardedTrainer: a Symbol fused into one pjit train step.

This is the TPU-native performant path.  The reference runs forward,
backward, and optimizer as separate engine pushes with kvstore reduce in
between (SURVEY §3.1); here the whole training step — forward, vjp,
gradient collectives, optimizer update, aux-state update — is ONE
jit-compiled XLA program over a device mesh:

* batch sharded over the ``data`` axis → XLA inserts the gradient psum over
  ICI (the role of kvstore 'device', `src/kvstore/comm.h:220-385`);
* nominated weights sharded over the ``model`` axis → GSPMD tensor
  parallelism (absent in the reference, SURVEY §2.4);
* parameters are donated, so updates are in-place in HBM.

Mixed precision follows TPU practice rather than the reference's fp16
path: master weights live permanently in float32, activations/grads run
in ``dtype`` (bfloat16 on the MXU), and the optimizer updates the f32
masters.  ``layout="NHWC"`` feeds channel-minor activations end-to-end —
the layout XLA:TPU wants for convs — while weights keep the reference
OIHW layout (see ops/nn.py `image_layout`).

(Design note: a flat-packed fused optimizer — all masters concatenated
into one vector per hyperparameter group — was tried and measured SLOWER
on ResNet-50/v5e than per-parameter updates: the gradient concat and
unpack relayouts cost more than the small-op overhead they remove.  XLA
already fuses per-parameter updates adequately.)

The optimizer is pluggable: any name registered in
``mxnet_tpu.optimizer`` whose update rule has a fused formulation below
(sgd/nag/ccsgd/adam/adagrad/rmsprop/adadelta), with the reference's
lr_mult/wd_mult semantics (`python/mxnet/optimizer.py` _get_lr/_get_wd;
wd_mult defaults to 0 for params not ending in _weight/_gamma).

Module/Executor remain the API-parity path; bench.py and the pod-scale
training scripts use this.
"""
from __future__ import annotations

import os
import struct as _struct

import numpy as np

from ..base import MXNetError
from ..symbol import eval_graph, _classify_vars
from ..initializer import Xavier, InitDesc
from ..ops.nn import image_layout
from .. import optimizer as _opt_mod

__all__ = ["ShardedTrainer"]


def _make_update_rule(opt):
    """(n_state_slots, rule) for a fused, functional optimizer update.

    ``rule(w, g, slots, lr, wd, t) -> (new_w, new_slots)`` over f32 master
    weights; mirrors the semantics of the corresponding
    ``mxnet_tpu.optimizer`` classes (themselves mirroring the reference's
    fused update kernels, src/operator/optimizer_op.cc:18-161).
    ``t`` is the 1-based update count (traced scalar, adam bias correction).
    """
    import jax.numpy as jnp

    clip = opt.clip_gradient

    def prep(g, w, wd):
        if clip is not None and clip > 0:
            g = jnp.clip(g, -clip, clip)
        return g + wd * w

    name = type(opt).__name__.lower()

    if name in ("sgd", "ccsgd"):
        momentum = opt.momentum
        if momentum == 0.0:
            return 0, lambda w, g, s, lr, wd, t: (w - lr * prep(g, w, wd), s)

        def sgd_rule(w, g, s, lr, wd, t):
            m = momentum * s[0] - lr * prep(g, w, wd)
            return w + m, [m]
        return 1, sgd_rule

    if name == "nag":
        momentum = opt.momentum

        def nag_rule(w, g, s, lr, wd, t):
            g = prep(g, w, wd)
            m = momentum * s[0] + g
            return w - lr * (g + momentum * m), [m]
        return 1, nag_rule

    if name == "adam":
        b1, b2, eps = opt.beta1, opt.beta2, opt.epsilon

        def adam_rule(w, g, s, lr, wd, t):
            g = prep(g, w, wd)
            m = b1 * s[0] + (1 - b1) * g
            v = b2 * s[1] + (1 - b2) * jnp.square(g)
            lr_t = lr * jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)
            return w - lr_t * m / (jnp.sqrt(v) + eps), [m, v]
        return 2, adam_rule

    if name == "adagrad":
        eps = opt.float_stable_eps

        def adagrad_rule(w, g, s, lr, wd, t):
            if clip is not None and clip > 0:
                g = jnp.clip(g, -clip, clip)
            h = s[0] + jnp.square(g)
            return w - lr * (g / jnp.sqrt(h + eps) + wd * w), [h]
        return 1, adagrad_rule

    if name == "rmsprop" and not getattr(opt, "centered", False):
        g1, eps = opt.gamma1, opt.epsilon

        def rmsprop_rule(w, g, s, lr, wd, t):
            g = prep(g, w, wd)
            n = (1 - g1) * jnp.square(g) + g1 * s[0]
            return w - lr * g / jnp.sqrt(n + eps), [n]
        return 1, rmsprop_rule

    if name == "adadelta":
        rho, eps = opt.rho, opt.epsilon

        def adadelta_rule(w, g, s, lr, wd, t):
            if clip is not None and clip > 0:
                g = jnp.clip(g, -clip, clip)
            acc_g = rho * s[0] + (1 - rho) * jnp.square(g)
            delta = jnp.sqrt(s[1] + eps) / jnp.sqrt(acc_g + eps) * g
            acc_d = rho * s[1] + (1 - rho) * jnp.square(delta)
            return w - delta - wd * w, [acc_g, acc_d]
        return 2, adadelta_rule

    raise MXNetError(
        "optimizer %r has no fused ShardedTrainer formulation; supported: "
        "sgd, ccsgd, nag, adam, adagrad, rmsprop (non-centered), adadelta"
        % name)


class ShardedTrainer:
    def __init__(self, symbol, mesh, data_shapes, label_shapes=(),
                 optimizer="sgd", optimizer_params=None, learning_rate=0.05,
                 momentum=0.9, weight_decay=0.0, initializer=None,
                 dtype="float32", tp_rules=None, seed=0, layout=None,
                 auto_layouts=False, fuse_conv_bn=None, fuse_blocks=None,
                 stem_space_to_depth=None, elide_input_bn_grad=True,
                 strided_bwd_phase=None, pipeline_stages=1,
                 pipeline_microbatches=None, sequence_parallel=False,
                 input_mean=None, input_std=None, conv1x1_as_dot=None,
                 native_weight_layout=None, strict=None):
        """
        symbol: loss-headed Symbol (e.g. SoftmaxOutput net).
        mesh: jax.sharding.Mesh with ('data', 'model') axes.
        data_shapes/label_shapes: dict name -> GLOBAL shape (batch dim 0),
            in the reference NCHW convention regardless of ``layout``.
        optimizer: registry name (or an Optimizer instance) — see
            `_make_update_rule` for the fused set.  ``learning_rate`` /
            ``momentum`` / ``weight_decay`` are convenience defaults merged
            into ``optimizer_params``.
        dtype: compute dtype for activations/grads (master weights stay f32).
        tp_rules: {param_name: axis_index} — weight dims to shard over the
            'model' axis.  Default: classifier-style FullyConnected weights
            whose output dim divides the tp size.
        layout: None (reference NCHW) or "NHWC" (TPU-preferred channel-minor
            activations; host batches are transposed on ingest).  Weights
            keep reference layouts, so NHWC parameters are interchangeable
            with NCHW checkpoints whenever Flatten only ever sees 1x1
            spatial maps (global-pool-then-FC nets like ResNet/Inception);
            an MLP-style Flatten of a WxH map permutes the FC input order.
        strict: run the distributed-correctness pass
            (``analysis.spmd``, MXG011-016) over this (graph, mesh,
            parallel config) triple before any compile and raise a
            descriptive MXNetError on findings.  None -> the
            ``MXNET_TPU_STRICT_BIND`` env default.
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from . import multihost

        self.symbol = symbol
        self.mesh = mesh
        self.dtype = dtype
        self._stage_fns = {}      # lazy per-input device staging programs
        # process-spanning mesh (launch.py multi-host job): the SAME
        # jitted step runs on every process; host<->device staging goes
        # through parallel/multihost.py instead of device_put
        self._multiproc = multihost.spans_processes(mesh)
        if self._multiproc and auto_layouts:
            import logging
            # AOT AUTO-layout lowering is a per-process choice; keep the
            # multi-controller program deterministic across ranks
            logging.warning(
                "auto_layouts disabled on a process-spanning mesh: "
                "XLA-chosen AOT layouts are a per-process decision and "
                "could diverge across ranks of the multi-controller "
                "program")
            auto_layouts = False
        # input_mean/input_std: per-channel (or scalar) normalization
        # applied ON DEVICE to uint8 data inputs staged via put_batch —
        # the raw_uint8 ingest path (native reader ships bytes, the chip
        # does (x - mean)/std; the reference normalizes on the host,
        # src/io/iter_normalize.h)
        self._input_mean = input_mean
        self._input_std = input_std
        # auto_layouts: let XLA choose persistent param/state layouts
        # (Layout.AUTO) instead of jit's default-pinned I/O layouts —
        # kills the per-step relayout copies (docs/perf.md)
        self._auto_layouts = bool(auto_layouts)
        if layout not in (None, "NCHW", "NHWC"):
            raise MXNetError("unsupported layout %r" % (layout,))
        self._layout = layout or "NCHW"
        # fuse_conv_bn: conv1x1+BN GEMM-with-stats-epilogue fusion
        # (ops/fused.py); None -> MXNET_FUSE_CONV_BN env default
        if fuse_conv_bn is None:
            from ..ops import fused as _fused_mod
            fuse_conv_bn = _fused_mod.fusion_enabled()
        self._fuse_conv_bn = bool(fuse_conv_bn) and self._layout == "NHWC"
        # fuse_blocks: block-granularity fusion pass (analysis.fusion) —
        # conv+BN+ReLU / FC+activation chains emitted as single
        # custom-vjp regions with a pinned layout per boundary, on both
        # the train step's forward AND its backward.  Works in either
        # layout; None -> the MXNET_FUSE_BLOCKS env default.
        if fuse_blocks is None:
            from ..ops import fused as _fused_mod
            fuse_blocks = _fused_mod.block_fusion_enabled()
        self._fuse_blocks = bool(fuse_blocks)
        # stem_space_to_depth: equivalent 4x4/s1 rewrite of the 7x7/s2
        # C=3 stem conv (ops/fused.py stem_s2d_conv)
        if stem_space_to_depth is None:
            from ..ops import fused as _fused_mod
            stem_space_to_depth = _fused_mod.stem_s2d_enabled()
        self._stem_s2d = bool(stem_space_to_depth) and \
            self._layout == "NHWC"
        # elide_input_bn_grad: skip backward-data of convs that only feed
        # an input-BN beta grad (ops/fused.py).  Always sound here: the
        # trainer's vjp differentiates params only, never batch inputs.
        self._elide_input_grads = bool(elide_input_bn_grad)
        # strided_bwd_phase: phase-decomposed backward-data for stride-2
        # convs (ops/fused.py) — exact, NHWC only.  None -> the
        # MXNET_PHASE_BWD env default (off: measured 6% SLOWER end-to-end
        # on ResNet-50/v5e — XLA:TPU's dilated backward already skips the
        # inserted zeros; the 4 small sub-convs + interleave cost more
        # than they save, docs/perf.md)
        if strided_bwd_phase is None:
            from ..ops import fused as _fused_mod
            strided_bwd_phase = _fused_mod.phase_bwd_enabled()
        self._phase_bwd = bool(strided_bwd_phase) and \
            self._layout == "NHWC"
        # conv1x1_as_dot: lower pointwise convs as fusible dots
        # (ops/fused.py); None -> MXNET_CONV1X1_DOT env default
        if conv1x1_as_dot is None:
            from ..ops import fused as _fused_mod
            conv1x1_as_dot = _fused_mod.conv1x1_dot_enabled()
        self._conv1x1_dot = bool(conv1x1_as_dot) and \
            self._layout == "NHWC"
        # native_weight_layout: store conv-weight MASTERS physically as
        # HWIO (f32) so the default/canonical layout IS the layout the
        # TPU conv wants.  jit's Layout.AUTO cannot reach lax.scan loop
        # carries (run_steps), so OIHW masters pay per-step relayout
        # copies (the xprof "copies" bucket, docs/perf.md); a physical
        # shape change removes them everywhere.  Checkpoints and the
        # graph itself still see reference OIHW (converted at the
        # boundaries), so saved params stay interoperable.
        if native_weight_layout is None:
            native_weight_layout = \
                os.environ.get("MXNET_NATIVE_WEIGHT_LAYOUT", "0") == "1"
        self._native_weight_layout = bool(native_weight_layout) and \
            self._layout == "NHWC"
        # pipeline_stages > 1: GPipe over the mesh's 'pipe' axis — the
        # graph is cut into stages at single-live-tensor positions and
        # the step streams microbatches stage-to-stage over ICI
        # (parallel/pipeline.py heterogeneous schedule)
        self._pp = int(pipeline_stages)
        if self._pp > 1:
            if mesh.shape.get("pipe", 1) != self._pp:
                raise MXNetError(
                    "pipeline_stages=%d needs a mesh with a 'pipe' axis "
                    "of that size (build_mesh(pp=%d)); mesh has %r"
                    % (self._pp, self._pp, dict(mesh.shape)))
            if mesh.shape.get("model", 1) != 1:
                raise MXNetError("pipeline_stages cannot combine with "
                                 "tensor parallelism (packed stage "
                                 "params cannot also be tensor-sharded)")
        self._pp_microbatches = int(pipeline_microbatches or
                                    (2 * self._pp if self._pp > 1 else 1))
        if self._pp > 1:
            # the pipelined step manages its own sharding; AUTO-layout
            # AOT compilation is not composed with it
            self._auto_layouts = False
        # sequence_parallel: shard data inputs' dim 1 (the sequence) over
        # the 'model' axis and activate the ring-attention context, so
        # _contrib_RingAttention nodes run the ICI ring schedule
        # (parallel/sequence.py).  Weights stay replicated over 'model'
        # (tp_rules default {}): the axis carries sequence shards.
        self._seq_parallel = bool(sequence_parallel)
        if self._seq_parallel:
            sp_size = mesh.shape.get("model", 1)
            if sp_size <= 1:
                raise MXNetError(
                    "sequence_parallel=True needs a mesh 'model' axis of "
                    "size > 1 to shard the sequence over (build_mesh(tp="
                    "n) — the axis carries sequence shards here)")
            if self._pp > 1:
                raise MXNetError("sequence_parallel does not compose "
                                 "with pipeline_stages yet")
            for n, s in data_shapes.items():
                if len(s) >= 2 and s[1] % sp_size:
                    raise MXNetError(
                        "sequence_parallel: input %r sequence dim %d is "
                        "not divisible by the %d sequence shards"
                        % (n, s[1], sp_size))

        self._topo = symbol._topo()
        if self._layout == "NHWC":
            self._check_nhwc_safe()
        # plan-search decisions (analysis.plansearch): an ambient
        # plan_decisions context wins; otherwise consult the committed
        # graph_plan tuning-cache entry ONCE at construction — keyed by
        # the graph's structural digest + trace layout + THIS mesh's
        # axis sizes + backend — and activate it around every step
        # trace, so a tuned plan is dispatched with zero search cost
        # (greedy on miss, like kernel configs).  Pipeline stages never
        # fuse (seeded partial topos), so the lookup is skipped there.
        from ..analysis import fusion as _fusion_mod
        self._plan_decisions = _fusion_mod.active_decisions()
        if self._plan_decisions is None and self._fuse_blocks \
                and self._pp <= 1:
            from ..analysis import plansearch as _plansearch
            self._plan_decisions = _plansearch.committed_decisions(
                self._topo, symbol._entries, self._layout,
                mesh=self._mesh_axis_sizes())
        arg_nodes, aux_nodes = _classify_vars(self._topo)
        self._arg_nodes, self._aux_nodes = arg_nodes, aux_nodes
        arg_names = [n.name for n in arg_nodes]
        self._input_names = list(data_shapes) + list(label_shapes or ())
        self._data_names = list(data_shapes)
        self._label_shapes = dict(label_shapes or {})
        self._param_names = [n for n in arg_names
                             if n not in self._input_names]
        self._aux_names = [n.name for n in aux_nodes]

        # data inputs consumed as integer indices (Embedding/take/...):
        # these must NOT be cast to a narrow compute dtype — bf16 rounds
        # ids above 256, silently corrupting lookups (ADVICE r3).
        # Carrier tracking walks pass-through (shape-only) ops, so
        # Embedding(Reshape(data)) still registers the data input.
        _index_arg_of = {"Embedding": 0, "one_hot": 0, "take": 1,
                         "gather_nd": 1, "batch_take": 1}
        _pass_through = frozenset({
            "Reshape", "Flatten", "expand_dims", "transpose", "BlockGrad",
            "slice_axis", "slice", "identity", "stop_gradient",
            "SwapAxis", "squeeze"})
        carriers = {id(n): n.name for n in self._arg_nodes
                    if n.name in self._data_names}
        self._int_inputs = set()
        self._int_input_bounds = {}   # name -> max Embedding input_dim
        unbounded = set()             # consumed by a boundless index op
        for node in self._topo:
            if node.op is None:
                continue
            opname = node.op.name
            if opname in _pass_through and node.inputs:
                src = node.inputs[0][0]
                if id(src) in carriers:
                    carriers[id(node)] = carriers[id(src)]
            idx = _index_arg_of.get(opname)
            if idx is None or idx >= len(node.inputs):
                continue
            nm = carriers.get(id(node.inputs[idx][0]))
            if nm is None:
                continue
            self._int_inputs.add(nm)
            if opname == "Embedding" and nm not in unbounded:
                self._int_input_bounds[nm] = max(
                    self._int_input_bounds.get(nm, 0),
                    int(node.attrs.get("input_dim", 0)))
            elif opname != "Embedding":
                # take/one_hot/gather tables carry no declared id range
                unbounded.add(nm)
                self._int_input_bounds.pop(nm, None)

        # inputs whose activations move to channel-minor under NHWC
        self._nhwc_inputs = set()
        if self._layout == "NHWC":
            self._nhwc_inputs = {n for n, s in data_shapes.items()
                                 if len(s) == 4}

        def to_layout(name, shape):
            if name in self._nhwc_inputs:
                n, c, h, w = shape
                return (n, h, w, c)
            return tuple(shape)

        shapes = {n: to_layout(n, s) for n, s in data_shapes.items()}
        for n, s in (label_shapes or {}).items():
            shapes[n] = tuple(s)
        self._input_shapes = shapes
        # raw host-convention (NCHW) global shapes, for staging
        # per-process shards of untransposed host batches (multi-host)
        self._host_input_shapes = {n: tuple(s)
                                   for n, s in data_shapes.items()}
        for n, s in (label_shapes or {}).items():
            self._host_input_shapes[n] = tuple(s)
        with image_layout(self._layout):
            arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
        self._arg_shapes = dict(zip(arg_names, arg_shapes))
        self._aux_shapes = dict(zip(self._aux_names, aux_shapes))
        global_batch = next(iter(data_shapes.values()))[0]

        # ---- optimizer: registry-created, reference mult semantics
        if isinstance(optimizer, str):
            kw = dict(optimizer_params or {})
            kw.setdefault("learning_rate", learning_rate)
            kw.setdefault("wd", weight_decay)
            if optimizer.lower() in ("sgd", "ccsgd", "nag", "dcasgd"):
                kw.setdefault("momentum", momentum)
            kw.setdefault("rescale_grad", 1.0 / global_batch)
            kw.setdefault("param_idx2name",
                          {n: n for n in self._param_names})
            optimizer = _opt_mod.create(optimizer, **kw)
        else:
            # instance path: mirror Module.init_optimizer (reference
            # module.py:461-463) — default rescale to gradient averaging
            # and give the wd_mult/lr_mult machinery the param names
            if optimizer.rescale_grad == 1.0:
                optimizer.rescale_grad = 1.0 / global_batch
            if not optimizer.idx2name:
                optimizer.idx2name = {n: n for n in self._param_names}
                optimizer.set_lr_mult({})
                optimizer.set_wd_mult({})
        self.optimizer = optimizer
        self._rescale = optimizer.rescale_grad
        self._n_slots, self._update_rule = _make_update_rule(optimizer)

        # ---- native-layout weight set: conv masters stored HWIO
        self._native_w = frozenset()
        if self._native_weight_layout and self._pp == 1:
            self._native_w = self._derive_native_weights()
        self._store_shapes = dict(self._arg_shapes)
        for n in self._native_w:
            o, i, h, w = self._arg_shapes[n]
            self._store_shapes[n] = (h, w, i, o)

        # ---- init params on host (f32 masters), device_put with shardings.
        # Initializer errors propagate: a wrong-shape bug must not silently
        # become a different init.
        init = initializer or Xavier(rnd_type="gaussian", factor_type="in",
                                     magnitude=2)
        host_params = {}
        for name in self._param_names:
            arr = _HostArray(np.zeros(self._arg_shapes[name], np.float32))
            init(InitDesc(name), arr)
            host_params[name] = arr.data
        for name in self._native_w:   # initializers see reference OIHW
            host_params[name] = np.ascontiguousarray(
                host_params[name].transpose(2, 3, 1, 0))
        host_aux = {}
        for name in self._aux_names:
            v = np.zeros(self._aux_shapes[name], np.float32)
            if name.endswith("moving_var"):
                v[...] = 1.0
            host_aux[name] = v

        tp_size = mesh.shape.get("model", 1)
        if tp_rules is None:
            if self._seq_parallel:
                # the model axis carries sequence shards; weights replicate
                tp_rules = {}
            else:
                # graph-derived Megatron-style defaults: column/row-
                # parallel FC pairing (QKV/out-proj, ff1/ff2) + conv
                # output-channel sharding (parallel/tp_rules.py)
                from .tp_rules import derive_tp_rules
                tp_rules = derive_tp_rules(self._topo, self._arg_shapes,
                                           tp_size)
                if tp_size > 1 and tp_rules:
                    # surface the derived layout once: which weights got
                    # model-axis sharded (and on which dim) decides the
                    # communication pattern and per-chip memory
                    import logging
                    logging.info(
                        "ShardedTrainer derived tp_rules (Megatron "
                        "pairing, tp=%d): %s", tp_size,
                        {k: tp_rules[k] for k in sorted(tp_rules)})
        # reshard rule table (MXNET_TPU_RESHARD_RULES, parallel/reshard
        # grammar): regex rules overriding the derived tp_rules per
        # param — the operator's hand-written partition layout for the
        # CURRENT mesh, the match_partition_rules pattern.  Entries may
        # only name the 'model' axis (weights never shard over 'data');
        # an all-replicated spec ("name=") un-shards a derived rule.
        from . import reshard as _reshard
        rrules = _reshard.env_rules()
        if rrules:
            tp_rules = dict(tp_rules)
            for name in self._param_names:
                spec = _reshard.first_match(rrules, name)
                if spec is None:
                    continue
                dims = [d for d, ax in enumerate(spec) if ax is not None]
                for d in dims:
                    if str(spec[d]) != "model":
                        raise MXNetError(
                            "reshard rule for param %r names axis %r; "
                            "trainer params shard only over 'model' "
                            "(the 'data' axis carries batches)"
                            % (name, spec[d]))
                if len(dims) > 1:
                    raise MXNetError(
                        "reshard rule for param %r shards %d dims; the "
                        "trainer supports one sharded dim per weight"
                        % (name, len(dims)))
                if not dims or tp_size <= 1:
                    if dims:
                        # a model-sharding rule on a mesh with no
                        # model axis degenerates to replicated — loud
                        # enough to notice, soft enough that one fleet
                        # -wide rule file survives an elastic shrink
                        # to a single device
                        import logging
                        logging.warning(
                            "reshard rule for param %r requests "
                            "'model' sharding but the mesh has no "
                            "model axis (tp=1); the param stays "
                            "replicated", name)
                    tp_rules.pop(name, None)
                    continue
                d = dims[0]
                shp = self._arg_shapes[name]
                if d >= len(shp) or shp[d] % tp_size:
                    raise MXNetError(
                        "reshard rule for param %r cannot shard dim %d "
                        "of shape %s over the %d-way 'model' axis"
                        % (name, d, tuple(shp), tp_size))
                tp_rules[name] = d
        self.tp_rules = tp_rules

        # distributed-correctness pass (analysis.spmd, MXG011-016): the
        # composed (graph, mesh, parallel config) triple is verified
        # BEFORE any compile — mismatched collectives, infeasible
        # stage/axis partitions and conflicting sharding specs raise a
        # node-level diagnostic here instead of hanging a fleet
        if strict is None:
            from .. import config as _config
            strict = _config.get_bool("MXNET_TPU_STRICT_BIND")
        if strict:
            from ..analysis import spmd as _spmd
            _spmd.verify_trainer_config(
                symbol, mesh,
                data_shapes=dict(data_shapes),
                label_shapes=dict(label_shapes or {}),
                pipeline_stages=self._pp,
                pipeline_microbatches=self._pp_microbatches,
                sequence_parallel=self._seq_parallel,
                tp_rules=tp_rules, dtype=self.dtype,
                arg_shapes=self._arg_shapes,
            ).raise_if_errors("ShardedTrainer strict bind")
            # static memory-liveness pass (analysis.memlive): predict
            # the step's peak HBM from liveness intervals — sharding-
            # and donation-aware (the step jits donate params/opt/aux)
            # — and record it so budget checks and OOM reports compare
            # the static peak against the XLA plan (MXG018 drift
            # gauge).  With a budget armed, an over-budget step is
            # rejected HERE (MXG017), before any compile.
            from ..analysis import memlive as _memlive
            from ..analysis.verifier import Report as _Report
            try:
                axes = {str(k): int(v)
                        for k, v in dict(mesh.shape).items()}
            except Exception:  # mxlint: allow-broad-except(mesh.shape drifted across jax versions; an unknown mesh just disables sharding-aware byte division)
                axes = {}
            mem_report = _Report()
            _memlive.check_memory(
                symbol,
                shapes={**dict(data_shapes), **dict(label_shapes or {})},
                report=mem_report, is_train=True, mesh=axes,
                tp_rules=dict(tp_rules), n_slots=self._n_slots,
                donate=True, advice=False, record=True,
                program="trainer.step")
            mem_report.raise_if_errors(
                "ShardedTrainer strict bind (memory)")

        def param_spec(name):
            shp = self._store_shapes.get(name, self._aux_shapes.get(name))
            spec = [None] * len(shp)
            if name in tp_rules:
                d = tp_rules[name]
                if name in self._native_w:
                    # OIHW dim index -> its position in HWIO storage
                    d = (3, 2, 0, 1)[d]
                spec[d] = "model"
            return P(*spec)

        self._param_sharding = {
            n: NamedSharding(mesh, param_spec(n)) for n in self._param_names}
        self._aux_sharding = {
            n: NamedSharding(mesh, P(*([None] * len(self._aux_shapes[n]))))
            for n in self._aux_names}
        def batch_spec(n):
            dims = ["data"] + [None] * (len(shapes[n]) - 1)
            if self._seq_parallel and n in self._data_names \
                    and len(dims) >= 2:
                dims[1] = "model"       # the sequence dim
            return P(*dims)

        self._batch_sharding = {
            n: NamedSharding(mesh, batch_spec(n))
            for n in self._input_names}

        # NB multi-host: every process runs this constructor with the
        # same seeds, so host_params are identical full values on every
        # rank; _put_state slices out each process's addressable shards
        with mesh:
            self.params = {n: self._put_state(host_params[n],
                                              self._param_sharding[n])
                           for n in self._param_names}
            self.aux = {n: self._put_state(host_aux[n],
                                           self._aux_sharding[n])
                        for n in self._aux_names}
            self.opt_state = self._device_zero_slots()

        self._step_fn = self._build_step()
        if strict:
            # MXG012 over the REAL step program: trace the un-jitted
            # step (no XLA compile) and scan its jaxpr for collectives
            # under axis_index-conditioned control flow.  Strict-only —
            # costs one extra trace of the step
            self._verify_step_rank_divergence()
        # the numerics variant (telemetry.numerics): the same step with
        # an in-graph stat tree as a fifth output, compiled lazily on
        # the first SAMPLED step (MXNET_TPU_NUMERICS_EVERY) so runs with
        # numerics off never pay the extra compile
        self._stats_step_fn = None
        self._scan_fns = {}
        # AOT executables dispatched in place of the jit wrappers, keyed
        # (program, id(fn)): the memory plan comes from the SAME compile
        # that runs the step (jax shares no cache between lower().
        # compile() and jit calls, so a separate analysis compile would
        # double every compile)
        self._aot_exes = {}
        # costdb dispatch scope: process-unique and rotated on rebuild,
        # so a rebuilt fn reusing a collected fn's id cannot alias its
        # dispatch counters (a compile dispatch mistaken for post-warm
        # would get its compile timed as dispatch wall)
        from ..telemetry import costdb as _costdb
        self._costdb_scope = _costdb.next_scope()
        self._fwd_fn = None
        self._step_count = 0
        # current step's straggler-attribution accumulator (reset by
        # step()/run_steps(); see telemetry.distview)
        self._seg = {"input_s": 0.0, "collective_s": 0.0, "skew": None}
        # epoch this trainer resumed from (load_checkpoint sets it):
        # _step_count restarts at 0 after a resume, so anything deriving
        # a global step/epoch must add this offset
        self._resume_epoch = 0
        self._key = jax.random.PRNGKey(seed)
        self._hyper_snapshot = self._hyper_state()

    def _verify_step_rank_divergence(self):
        """MXG012 over the step this trainer will actually dispatch:
        trace the un-jitted step function with this trainer's own
        state/batch avals (``jax.make_jaxpr`` — no compile) and scan
        the jaxpr for collectives under rank-conditioned control flow
        (``analysis.spmd.verify_step_fn``).  Raises on findings."""
        import jax
        import jax.numpy as jnp
        from ..analysis import spmd as _spmd
        py_step = getattr(self, "_py_step", None)
        if py_step is None:
            return
        batch = {n: jax.ShapeDtypeStruct(
                     tuple(self._input_shapes[n]), jnp.float32)
                 for n in self._input_names}
        args = (self.params, self.opt_state, self.aux, batch,
                jax.ShapeDtypeStruct((2,), jnp.uint32),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.float32))
        _spmd.verify_step_fn(py_step, args).raise_if_errors(
            "ShardedTrainer strict bind")

    def _device_zero_slots(self):
        """Fresh optimizer slots created ON DEVICE by one jitted program
        (host-side np.zeros + device_put would ship the whole optimizer
        state — e.g. 1.5 GB for adam on a 190M-param model — over the
        host link just to write zeros)."""
        import jax
        import jax.numpy as jnp

        if self._n_slots == 0:
            return {n: [] for n in self._param_names}

        def make():
            return {n: [jnp.zeros(self._store_shapes[n], jnp.float32)
                        for _ in range(self._n_slots)]
                    for n in self._param_names}

        shardings = {n: [self._param_sharding[n]] * self._n_slots
                     for n in self._param_names}
        return jax.jit(make, out_shardings=shardings)()

    def _derive_native_weights(self):
        """Param names eligible for physical HWIO master storage: 4-d
        weights whose EVERY graph use is the ``weight`` input of a 2-d
        Convolution (shared/tied weights with any other consumer keep
        reference layout)."""
        uses = {}
        for node in self._topo:
            if node.is_variable or node.op is None:
                continue
            for pos, (src, _i) in enumerate(node.inputs):
                if src.is_variable:
                    uses.setdefault(src.name, []).append((node, pos))
        out = set()
        for name in self._param_names:
            shp = self._arg_shapes.get(name)
            if shp is None or len(shp) != 4:
                continue
            us = uses.get(name, ())
            if us and all(n.op.name == "Convolution" and pos == 1
                          for n, pos in us):
                out.add(name)
        return frozenset(out)

    def _compute_view(self, params, compute_dtype):
        """Compute-precision copies of the f32 masters, native-layout
        weights rotated back to the reference OIHW view the graph
        expects (the op-level OIHW->HWIO transpose then cancels, so the
        conv consumes the HWIO master directly)."""
        import jax.numpy as jnp
        native = self._native_w
        p = {}
        for k, v in params.items():
            v = v.astype(compute_dtype)
            if k in native:
                v = jnp.transpose(v, (3, 2, 0, 1))  # HWIO -> OIHW view
            p[k] = v
        return p

    def _put_state(self, value, target):
        """Stage a full host value (identical on every process) as a
        device array.  ``target`` is a NamedSharding, or under
        auto_layouts (single-process only) an XLA-chosen Format."""
        import jax
        if self._multiproc:
            from . import multihost
            return multihost.stage_local(target, value)
        return jax.device_put(value, target)

    def _hyper_state(self):
        """Optimizer hyperparameters baked into the compiled step."""
        opt = self.optimizer
        rule_attrs = tuple(
            (a, getattr(opt, a)) for a in
            ("momentum", "beta1", "beta2", "epsilon", "gamma1", "gamma2",
             "rho", "float_stable_eps") if hasattr(opt, a))
        return (dict(opt.lr_mult), dict(opt.wd_mult), opt.wd,
                opt.rescale_grad, opt.clip_gradient, rule_attrs)

    # ------------------------------------------------------------ builders
    # ops adapted to NHWC activations (ops/nn.py) — their axis attrs are
    # remapped at trace time, so an explicit channel-ish axis is fine
    _NHWC_ADAPTED = frozenset({
        "Convolution", "Deconvolution", "Pooling", "BatchNorm", "Concat",
        "SliceChannel", "LRN", "InstanceNorm", "LeakyReLU", "UpSampling",
        "Crop", "Pad", "SoftmaxActivation", "Flatten", "FullyConnected",
        "Activation", "Dropout", "SoftmaxOutput",
    })

    def _check_nhwc_safe(self):
        """Refuse NHWC mode for graphs whose ops would silently index the
        wrong axis.  Two classes: known channel-axis ops with no NHWC
        adaptation, and generic tensor ops pinning an explicit axis that
        could be spatial/channel (axis semantics are written against the
        reference NCHW convention)."""
        from ..ops.nn import NHWC_UNAWARE_OPS
        bad = set()
        for node in self._topo:
            if node.op is None:
                continue
            name = node.op.name
            if name in NHWC_UNAWARE_OPS:
                bad.add(name)
                continue
            if name in self._NHWC_ADAPTED:
                continue
            if name == "transpose" and not node.attrs.get("axes"):
                bad.add("transpose()")  # default axes reverse all dims
                continue
            for key in ("axis", "dim", "axes", "begin", "end"):
                v = node.attrs.get(key)
                vals = v if isinstance(v, (tuple, list)) else (v,)
                if any(isinstance(x, int) and
                       (1 <= x <= 3 or -3 <= x <= -1) for x in vals):
                    bad.add("%s(%s=%r)" % (name, key, v))
                    break
        if bad:
            raise MXNetError(
                "layout='NHWC' is not supported for graphs containing "
                "%s — these index axes in the reference NCHW convention "
                "and have no NHWC adaptation; use the default NCHW "
                "layout" % ", ".join(sorted(bad)))

    def _node_value_map(self, params, batch, aux):
        vals = {}
        for node in self._arg_nodes:
            if node.name in params:
                vals[id(node)] = params[node.name]
            else:
                vals[id(node)] = batch[node.name]
        for node in self._aux_nodes:
            vals[id(node)] = aux[node.name]
        return vals

    def _per_param_hyper(self, name):
        """Static (lr_mult, effective_wd) for one param, ref semantics."""
        opt = self.optimizer
        lr_mult = opt.lr_mult.get(name, 1.0)
        wd_mult = opt.wd_mult.get(name, 1.0)
        return lr_mult, wd_mult * opt.wd

    def _abstract_node_shapes(self, micro_bsz):
        """{(id(node), out_idx): shape} for every op-node output, traced
        abstractly at microbatch size (no FLOPs; jax.eval_shape)."""
        import jax
        import jax.numpy as jnp
        from ..symbol import eval_graph

        shapes = {}
        name2ni = {}
        for node in self._topo:
            if node.is_variable or node.op is None:
                continue
            for i, on in enumerate(node.output_names()):
                name2ni[on] = (id(node), i)

        def mon(name, val):
            k = name2ni.get(name)
            if k is not None:
                shapes[k] = tuple(val.shape)

        gbatch = self._input_shapes[self._data_names[0]][0]

        def absfwd():
            vv = {}
            for node in self._arg_nodes:
                nm = node.name
                if nm in self._input_names:
                    # leading dims scale by micro_bsz/gbatch so per-token
                    # labels declared (batch*seq,) trace at (micro*seq,),
                    # mirroring the runtime side-array microbatch split
                    full = self._input_shapes[nm]
                    shp = (full[0] * micro_bsz // gbatch,) + tuple(full[1:])
                    dt = jnp.float32 if "label" in nm \
                        else jnp.dtype(self.dtype)
                else:
                    shp = self._arg_shapes[nm]
                    dt = jnp.dtype(self.dtype)
                vv[id(node)] = jnp.zeros(shp, dt)
            for node in self._aux_nodes:
                vv[id(node)] = jnp.zeros(self._aux_shapes[node.name],
                                         jnp.float32)
            with image_layout(self._layout):
                eval_graph(self._topo, self.symbol._entries, vv,
                           is_train=False, key=None, monitor=mon,
                           batch_size=micro_bsz)
            return 0

        jax.eval_shape(absfwd)
        return shapes

    def _build_pipeline_step(self, collect_stats=False):
        """GPipe step: the graph cut into ``pipeline_stages`` segments,
        each stage's packed params resident on its 'pipe'-axis device,
        microbatches streamed stage-to-stage over ICI (ppermute), all
        inside ONE jit.  See parallel/pipeline.py for the schedule and
        the packing encoding.  Composes with data parallelism over the
        mesh's 'data' axis (shard_map transposition inserts the grad
        psum).  Successor of the reference's per-device layer placement
        (example/model-parallel-lstm/lstm.py:142-205)."""
        import functools
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .mesh import shard_map_nocheck
        from ..symbol import eval_graph
        from .pipeline import plan_pipeline_stages, hetero_pipeline_loss

        n_pp, m_micro = self._pp, self._pp_microbatches
        mesh = self.mesh
        dp = mesh.shape.get("data", 1)
        topo, entries = self._topo, self.symbol._entries

        if len(entries) != 1 or entries[0][0].op is None \
                or entries[0][0].op.name != "SoftmaxOutput":
            raise MXNetError(
                "the pipeline-parallel trainer currently supports a "
                "single SoftmaxOutput loss head (its custom vjp is "
                "cotangent-independent, so pipelined gradients are "
                "reference-exact); got %r"
                % [e[0].op.name if e[0].op else "var" for e in entries])
        hattrs = entries[0][0].attrs
        if float(hattrs.get("grad_scale", 1.0)) != 1.0 or \
                hattrs.get("normalization", "null") != "null" or \
                hattrs.get("use_ignore") or hattrs.get("multi_output"):
            raise MXNetError("pipeline path supports SoftmaxOutput with "
                             "default grad_scale/normalization/"
                             "multi_output only")
        head_label_var = entries[0][0].inputs[1][0]
        if not head_label_var.is_variable:
            raise MXNetError("pipeline path needs the loss label to be "
                             "a batch variable (got a computed input)")
        label_name = head_label_var.name
        if len(self._data_names) != 1:
            raise MXNetError("pipeline path supports one data input")
        dname = self._data_names[0]
        compute_dtype = jnp.dtype(self.dtype)
        if compute_dtype.kind == "f" and dname in self._int_inputs:
            # the pipeline ring buffer carries stage inputs in the
            # compute dtype; token ids above the dtype's exact-integer
            # range would be rounded in transit
            exact = 1 << (jnp.finfo(compute_dtype).nmant + 1)
            bound = self._int_input_bounds.get(dname)
            # ids run 0..input_dim-1, and integers up to `exact` are
            # representable, so input_dim == exact+1 is still safe
            if bound is None or bound > exact + 1:
                # unknown bound (take/gather consumer) is treated as
                # over-range: silent id rounding is worse than refusing
                raise MXNetError(
                    "pipeline_stages with dtype=%s cannot carry %r as "
                    "integer ids through the compute-dtype ring buffer: "
                    "id range %s exceeds (or cannot be proven within) "
                    "the dtype's exact-integer range %d; use "
                    "dtype='float32' or a first-stage cut after the "
                    "lookup" % (self.dtype, dname,
                                bound if bound is not None else "unknown",
                                exact))
        gbatch = self._input_shapes[dname][0]
        if gbatch % (dp * m_micro):
            raise MXNetError(
                "global batch %d not divisible by data-parallel size %d "
                "x %d microbatches" % (gbatch, dp, m_micro))
        bu = gbatch // (dp * m_micro)

        shapes = self._abstract_node_shapes(bu)

        def nelem(shp):
            n = 1
            for d in shp:
                n *= int(d)
            return n

        def cost_of(node):
            c = float(nelem(shapes.get((id(node), 0), (1,))))
            for (src, _i) in node.inputs:
                if src.is_variable and src.name in self._arg_shapes \
                        and src.name not in self._input_names:
                    c += float(nelem(self._arg_shapes[src.name]))
            return c

        def legal_cut(bound):
            # the ring buffer is (microbatch_rows, W): a boundary whose
            # leading dim is not the microbatch row count (e.g. after a
            # batch-folding Reshape) cannot ride it
            shp = shapes.get((id(bound[0]), bound[1]))
            return shp is not None and len(shp) >= 1 and shp[0] == bu

        stages = plan_pipeline_stages(topo, entries,
                                      set(self._input_names), n_pp,
                                      cost_of=cost_of,
                                      legal_cut=legal_cut)

        # boundary widths -> the common ring buffer width W
        widths = [nelem(self._input_shapes[dname][1:])]
        for s in stages[1:]:
            bnode, bidx = s["boundary_in"]
            widths.append(nelem(shapes[(id(bnode), bidx)][1:]))
        buf_w = max(widths)

        # packed per-stage parameter layouts
        layouts, lens = [], []
        for s in stages:
            off, lay = 0, []
            for nm in s["param_names"]:
                shp = self._arg_shapes[nm]
                lay.append((nm, tuple(shp), off, nelem(shp)))
                off += nelem(shp)
            layouts.append(lay)
            lens.append(off)
        pack_l = max(lens + [1])

        side_names = []
        for si, s in enumerate(stages):
            for nm in s["batch_names"]:
                if si == 0 and nm == dname:
                    continue
                if nm not in side_names:
                    side_names.append(nm)

        compute_dtype = jnp.dtype(self.dtype)
        layout = self._layout
        name2arg = {n.name: n for n in self._arg_nodes}

        head_node = entries[0][0]

        def make_branch(si):
            meta = stages[si]
            lay = layouts[si]
            is_last = si == n_pp - 1
            if si == 0:
                in_feat = tuple(self._input_shapes[dname][1:])
            else:
                bnode, bidx = meta["boundary_in"]
                in_feat = tuple(shapes[(id(bnode), bidx)][1:])
            insize = nelem(in_feat)
            # Last stage stops BEFORE the SoftmaxOutput head and computes
            # softmax + summed CE manually: the gradient is identically
            # (p - onehot) (the head's reference convention at
            # grad_scale=1/normalization null), but it flows through
            # standard autodiff — the head's cotangent-IGNORING
            # custom_vjp would inject gradients from the schedule's
            # inactive fill/drain ticks that the active-mask cannot zero.
            seg_nodes = meta["nodes"] if not is_last else \
                [n for n in meta["nodes"] if n is not head_node]
            seg_entries = [head_node.inputs[0]] if is_last \
                else [stages[si + 1]["boundary_in"]]
            # eval_graph binds variables by iterating them in topo order
            seg_vars, seen = [], set()
            for n in seg_nodes:
                for (src, _i) in n.inputs:
                    if src.is_variable and id(src) not in seen:
                        seen.add(id(src))
                        seg_vars.append(src)
            seg_topo = seg_vars + seg_nodes

            def branch(row, x_flat, mb, side):
                p = {nm: row[off:off + sz].reshape(shp)
                     for (nm, shp, off, sz) in lay}
                nb = x_flat.shape[0]
                x = x_flat[:, :insize].reshape((nb,) + in_feat)
                var_values = {id(name2arg[nm]): v for nm, v in p.items()}
                seed = {}
                if si == 0:
                    var_values[id(name2arg[dname])] = x
                else:
                    bnode, bidx = meta["boundary_in"]
                    seed[id(bnode)] = tuple(
                        x if j == bidx else None
                        for j in range(bnode.num_outputs()))
                label = None
                for nm in meta["batch_names"]:
                    if si == 0 and nm == dname:
                        continue
                    sv = side[side_names.index(nm)]
                    v = lax.dynamic_index_in_dim(sv, mb, 0,
                                                 keepdims=False)
                    var_values[id(name2arg[nm])] = v
                    if nm == label_name:
                        label = v
                with image_layout(layout):
                    heads, _aux = eval_graph(
                        seg_topo, seg_entries, var_values,
                        is_train=True, key=None, batch_size=nb,
                        seed_vals=seed)
                # the per-branch loss is shape (1,), never rank 0: a
                # scalar on the differentiated path becomes a rank-0
                # shard_map residual, which jax 0.4.x's partial-eval
                # fails to promote on the remat/transpose path
                if is_last:
                    logits = heads[0].astype(jnp.float32)
                    logp = jax.nn.log_softmax(logits, axis=-1)
                    idx = label.astype(jnp.int32).reshape((-1, 1))
                    psel = jnp.take_along_axis(logp, idx, axis=1,
                                               mode="clip")[:, 0]
                    loss = -jnp.sum(psel).reshape((1,))
                    y_flat = jnp.zeros((nb, buf_w), compute_dtype)
                else:
                    y = heads[0]
                    y2 = y.reshape(nb, -1).astype(compute_dtype)
                    y_flat = jnp.pad(y2,
                                     ((0, 0), (0, buf_w - y2.shape[1])))
                    loss = jnp.zeros((1,), jnp.float32)
                return y_flat, loss

            return branch

        branches = [make_branch(si) for si in range(n_pp)]
        rescale = self._rescale
        rule = self._update_rule
        hyper = {k: self._per_param_hyper(k) for k in self._param_names}
        # metric divisor: the summed CE covers every head row (per-token
        # labels have gbatch*k rows); match the plain path's mean
        label_rows = self._input_shapes.get(label_name, (gbatch,))[0]

        x_side_specs = tuple(
            P(*([None, "data"] +
                [None] * (len(self._input_shapes[nm]) - 1)))
            for nm in side_names)

        def step(params, opt_state, aux, batch, key, lr, t):
            def loss_fn(p32):
                p = {k: v.astype(compute_dtype) for k, v in p32.items()}
                rows = []
                for si in range(n_pp):
                    parts = [p[nm].reshape(-1)
                             for (nm, _s, _o, _z) in layouts[si]]
                    row = jnp.concatenate(parts) if parts else \
                        jnp.zeros((0,), compute_dtype)
                    rows.append(jnp.pad(row, (0, pack_l - row.shape[0])))
                # the packed stage rows enter the shard_map REPLICATED
                # and each device selects its row by stage id inside the
                # body: resharding this in-jit concatenate onto the pipe
                # axis trips a GSPMD partitioner bug under dp x pp (the
                # partial-update all-reduce double-counts the data
                # replicas, scaling every packed param by dp)
                stacked = lax.with_sharding_constraint(
                    jnp.stack(rows), NamedSharding(mesh, P(None, None)))
                x = batch[dname].astype(compute_dtype)
                xs = x.reshape((m_micro, gbatch // m_micro, -1))
                xs = jnp.pad(xs, ((0, 0), (0, 0),
                                  (0, buf_w - xs.shape[2])))
                # side arrays microbatch on dim 0; a leading dim of
                # gbatch*k (e.g. per-token labels (batch*seq,)) splits
                # row-major into (M, local*k) consistently with the data
                side = tuple(
                    batch[nm].reshape((m_micro, -1)
                                      + tuple(batch[nm].shape[1:]))
                    for nm in side_names)

                def smbody(ps, xs_, sd):
                    br = [(lambda f: (lambda row, xx, mb:
                                      f(row, xx, mb, sd)))(f)
                          for f in branches]
                    # (1,)-shaped loss through the body (see
                    # hetero_pipeline_loss: jax 0.4.x mishandles
                    # rank-0 shard_map residuals under grad)
                    local = hetero_pipeline_loss(br, xs_, ps, m_micro)
                    return lax.psum(lax.psum(local, "pipe"), "data")

                return shard_map_nocheck(
                    smbody, mesh,
                    (P(None, None), P(None, "data", None),
                     x_side_specs), P(None))(stacked, xs, side)[0]

            loss_sum, grads = jax.value_and_grad(loss_fn)(params)
            new_params, new_state = {}, {}
            for k, w in params.items():
                lr_mult, wd_eff = hyper[k]
                g = grads[k].astype(jnp.float32) * rescale
                new_params[k], new_state[k] = rule(
                    w, g, opt_state[k], lr * lr_mult, wd_eff, t)
            new_aux = {n.name: aux[n.name] for n in self._aux_nodes}
            loss = loss_sum / label_rows
            if collect_stats:
                # param/grad numerics on the pipelined step (fused-block
                # stats don't apply: seeded partial graphs never fuse)
                from ..telemetry import numerics as _numerics
                stats = _numerics.step_stats(params, grads, loss=loss)
                return new_params, new_state, new_aux, loss, stats
            return new_params, new_state, new_aux, loss

        if collect_stats:
            self._py_step_stats = step
        else:
            self._py_step = step
        state_sharding = {n: [self._param_sharding[n]] * self._n_slots
                          for n in self._param_names}
        in_shardings = (self._param_sharding, state_sharding,
                        self._aux_sharding, self._batch_sharding,
                        None, None, None)
        out_shardings = (self._param_sharding, state_sharding,
                         self._aux_sharding, None)
        if collect_stats:
            out_shardings = out_shardings + (None,)
        return jax.jit(step, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=(0, 1, 2))

    def _build_step(self, collect_stats=False):
        """Build the jitted train step.  ``collect_stats=True`` builds
        the NUMERICS VARIANT (telemetry.numerics): the same step with a
        fifth output — the in-graph tensor-stat tree over params, grads,
        and (when block fusion is active) fused-block outputs.  It is a
        SEPARATE compile dispatched only on sampled steps, so unsampled
        steps run the unmodified program (the jaxpr equation count is
        bit-for-bit the no-numerics one)."""
        import jax
        import jax.numpy as jnp
        if self._pp > 1:
            return self._build_pipeline_step(collect_stats=collect_stats)

        topo, entries = self._topo, self.symbol._entries
        head_is_loss = [bool(n.op is not None and n.op.is_loss)
                        for (n, _i) in entries]
        rescale = self._rescale
        compute_dtype = jnp.dtype(self.dtype)
        layout, rule = self._layout, self._update_rule
        hyper = {k: self._per_param_hyper(k) for k in self._param_names}

        def step(params, opt_state, aux, batch, key, lr, t):
            from ..telemetry import numerics as _numerics
            bsz = next(iter(batch.values())).shape[0]

            def fwd(p32):
                # compute-precision copies of the f32 masters (the astype
                # vjp returns f32 grads automatically); native-layout
                # weights arrive HWIO and grads flow back HWIO
                from ..ops.fused import (conv_bn_fusion, stem_s2d,
                                         elide_input_grads, phase_bwd,
                                         conv1x1_dot, block_fusion)
                from ..analysis.fusion import plan_decisions
                from .sequence import sequence_parallel as seq_ctx
                p = self._compute_view(p32, compute_dtype)
                with image_layout(layout), \
                        conv_bn_fusion(self._fuse_conv_bn), \
                        block_fusion(self._fuse_blocks), \
                        plan_decisions(self._plan_decisions), \
                        stem_s2d(self._stem_s2d), \
                        phase_bwd(self._phase_bwd), \
                        conv1x1_dot(self._conv1x1_dot), \
                        seq_ctx(self.mesh if self._seq_parallel
                                else None), \
                        elide_input_grads(
                            self._input_names
                            if self._elide_input_grads else ()):
                    var_values = self._node_value_map(p, batch, aux)
                    # fused-block output stats ride the stats variant
                    # only: the collection window is open while
                    # analysis.fusion.apply_block evaluates each block,
                    # and the stat scalars leave the vjp trace as part
                    # of fwd's auxiliary output (capturing the raw
                    # block tracers in a side dict would leak them)
                    with _numerics.block_stats(collect_stats) as sink:
                        heads, aux_upd = eval_graph(
                            topo, entries, var_values, is_train=True,
                            key=key, batch_size=bsz)
                return heads, (aux_upd, dict(sink) if sink else {})

            from ..ops.nn import maybe_mirror
            heads, vjp, (aux_upd, blk_stats) = jax.vjp(
                maybe_mirror(fwd), params, has_aux=True)
            cot = [jnp.ones_like(h) if il else jnp.zeros_like(h)
                   for h, il in zip(heads, head_is_loss)]
            (grads,) = vjp(list(cot))

            new_params, new_state = {}, {}
            for k, w in params.items():
                lr_mult, wd_eff = hyper[k]
                g = grads[k].astype(jnp.float32) * rescale
                new_params[k], new_state[k] = rule(
                    w, g, opt_state[k], lr * lr_mult, wd_eff, t)

            new_aux = {}
            for n in self._aux_nodes:
                upd = aux_upd.get(id(n), aux[n.name])
                new_aux[n.name] = upd.astype(jnp.float32)

            # monitoring loss: mean -log p(label) from the softmax head
            loss = jnp.float32(0)
            label = None
            for nm in self._input_names:
                if "label" in nm:
                    label = batch[nm]
            if label is not None and head_is_loss[0]:
                probs = heads[0]
                if probs.ndim == 2 and label.ndim >= 2 and \
                        label.size == probs.shape[0]:
                    # per-token labels fed as (batch, seq): the head
                    # flattened rows row-major, labels follow
                    label = label.reshape((-1,))
                if probs.ndim == 2 and label.ndim == 1:
                    idx = label.astype(jnp.int32).reshape((-1, 1))
                    # mode="clip": jit's default fill mode turns an
                    # out-of-range label into NaN and poisons the metric
                    p = jnp.take_along_axis(
                        probs.astype(jnp.float32), idx, axis=1,
                        mode="clip")[:, 0]
                    loss = -jnp.mean(jnp.log(jnp.maximum(p, 1e-10)))
            if collect_stats:
                stats = _numerics.step_stats(params, grads,
                                             blocks=blk_stats,
                                             loss=loss)
                return new_params, new_state, new_aux, loss, stats
            return new_params, new_state, new_aux, loss

        if collect_stats:
            self._py_step_stats = step
        else:
            # the scan chain (_build_multi_step) composes the PLAIN step
            self._py_step = step
        state_sharding = {n: [self._param_sharding[n]] * self._n_slots
                          for n in self._param_names}
        if self._auto_layouts:
            return self._compile_auto_layout(step, state_sharding)
        in_shardings = (self._param_sharding, state_sharding,
                        self._aux_sharding, self._batch_sharding,
                        None, None, None)
        out_shardings = (self._param_sharding, state_sharding,
                         self._aux_sharding, None)
        if collect_stats:
            out_shardings = out_shardings + (None,)
        return jax.jit(step, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=(0, 1, 2))

    def _build_multi_step(self, k):
        """k steps chained inside ONE compiled program via lax.scan.

        Per-step dispatch over a remote backend (the axon tunnel) costs
        ~2-3 ms; chaining steps in-program removes it entirely and lets
        XLA keep params/state resident between iterations.  lr and t are
        (k,) arrays (the host-side lr_scheduler is evaluated per step up
        front), so schedules behave exactly as in :meth:`step`.
        """
        import jax
        from jax import lax

        step = self._py_step

        def multi(params, opt_state, aux, batch, key, lrs, ts):
            def body(carry, xs):
                p, s, a, ky = carry
                lr, t = xs
                ky, sub = jax.random.split(ky)
                p, s, a, loss = step(p, s, a, batch, sub, lr, t)
                return (p, s, a, ky), loss

            (params, opt_state, aux, _), losses = lax.scan(
                body, (params, opt_state, aux, key), (lrs, ts), length=k)
            return params, opt_state, aux, losses

        state_sharding = {n: [self._param_sharding[n]] * self._n_slots
                          for n in self._param_names}
        if self._auto_layouts:
            import jax.numpy as jnp
            return self._compile_auto_layout(
                multi, state_sharding,
                lr_example=jnp.zeros((k,), jnp.float32),
                t_example=jnp.ones((k,), jnp.float32),
                migrate=False)
        in_shardings = (self._param_sharding, state_sharding,
                        self._aux_sharding, self._batch_sharding,
                        None, None, None)
        out_shardings = (self._param_sharding, state_sharding,
                         self._aux_sharding, None)
        return jax.jit(multi, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=(0, 1, 2))

    def _compile_auto_layout(self, step, state_sharding, lr_example=None,
                             t_example=None, migrate=True):
        """Compile the step with XLA-chosen parameter/state layouts.

        jit pins donated I/O to default layouts, so every step pays
        per-weight relayout copies between the conv-preferred tilings
        and the I/O layout (docs/perf.md "copies" bucket).  With
        Layout.AUTO on the persistent state, XLA keeps params/opt/aux
        in its preferred tilings ACROSS steps (the state is donated, so
        the layout round-trips for free); the one-time device_put below
        migrates the live state into the chosen formats.

        Each AOT compile may choose different layouts, so the chosen
        formats are recorded on the compiled object (``_state_formats``)
        and callers re-migrate via :meth:`_ensure_state_formats` when
        switching between compiled entry points (step vs run_steps).
        """
        import jax
        import jax.numpy as jnp
        try:
            # jax >= 0.5 naming
            from jax.experimental.layout import Format, Layout
            _auto = Layout.AUTO
        except ImportError:
            # jax 0.4.x: Layout(device_local_layout, sharding) is the
            # format wrapper and DeviceLocalLayout carries AUTO
            from jax.experimental.layout import (DeviceLocalLayout,
                                                 Layout as Format)
            _auto = DeviceLocalLayout.AUTO

        def auto_of(sharding_tree):
            return jax.tree.map(lambda s: Format(_auto, s),
                                sharding_tree,
                                is_leaf=lambda x: hasattr(x, "spec"))

        in_shardings = (auto_of(self._param_sharding),
                        auto_of(state_sharding),
                        auto_of(self._aux_sharding),
                        self._batch_sharding, None, None, None)
        out_shardings = (auto_of(self._param_sharding),
                         auto_of(state_sharding),
                         auto_of(self._aux_sharding), None)
        jf = jax.jit(step, in_shardings=in_shardings,
                     out_shardings=out_shardings, donate_argnums=(0, 1, 2))
        # _input_shapes are already layout-converted; stage zeros directly
        # (put_batch would transpose a host NCHW batch a second time)
        zero_batch = {
            n: jax.device_put(
                jnp.zeros(s, jnp.float32
                          if ("label" in n or n in self._int_inputs)
                          else jnp.dtype(self.dtype)),
                self._batch_sharding[n])
            for n, s in self._input_shapes.items()}
        def as_spec(tree):
            # AUTO-layout args must be abstract at lower time
            return jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

        if lr_example is None:
            lr_example = jnp.float32(0.0)
        if t_example is None:
            t_example = jnp.float32(1.0)
        example = (as_spec(self.params), as_spec(self.opt_state),
                   as_spec(self.aux), zero_batch, jax.random.PRNGKey(0),
                   lr_example, t_example)
        compiled = jf.lower(*example).compile()
        fmts = (compiled.input_formats
                if hasattr(compiled, "input_formats")
                else compiled.input_layouts)[0]
        compiled._state_formats = (fmts[0], fmts[1], fmts[2])
        if migrate:
            # migrate live state into the chosen layouts (one-time copies)
            self._migrate_state(compiled._state_formats)
        return compiled

    def _migrate_state(self, fmts):
        import jax
        self.params = jax.device_put(self.params, fmts[0])
        self.opt_state = jax.device_put(self.opt_state, fmts[1])
        self.aux = jax.device_put(self.aux, fmts[2])
        self._live_formats = fmts

    def _ensure_state_formats(self, compiled):
        """Under auto_layouts, move live state into the layouts the given
        compiled entry point was lowered with (no-op when they match)."""
        fmts = getattr(compiled, "_state_formats", None)
        if fmts is not None and \
                getattr(self, "_live_formats", None) is not fmts:
            self._migrate_state(fmts)

    # ------------------------------------------------------------------ api
    def _maybe_rebuild(self):
        """Recompile when optimizer hyperparameters changed.

        The reference Optimizer reads lr_mult/wd_mult/rescale on every
        update; they are baked into the compiled step here, so post-build
        set_lr_mult()/set_wd_mult()/rescale changes are honored by
        recompiling (and reallocating slots if the rule changed)."""
        import jax
        opt = self.optimizer
        if self._hyper_state() == self._hyper_snapshot:
            return
        self._rescale = opt.rescale_grad
        old_slots = self._n_slots
        self._n_slots, self._update_rule = _make_update_rule(opt)
        if self._n_slots != old_slots:
            with self.mesh:
                self.opt_state = self._device_zero_slots()
        self._step_fn = self._build_step()
        self._stats_step_fn = None
        self._scan_fns = {}
        self._aot_exes = {}
        # retire the old costdb dispatch scope (see __init__): the new
        # fns must warm up as compiles, and the old counters are pruned
        from ..telemetry import costdb as _costdb
        _costdb.drop_scope(self._costdb_scope)
        self._costdb_scope = _costdb.next_scope()
        self._hyper_snapshot = self._hyper_state()

    def _cast_batch(self, batch):
        """Data inputs follow the compute dtype (bf16 training); labels
        keep their own dtype.  No layout work happens on the host — the
        NCHW->NHWC transpose runs on device in :meth:`put_batch` (a host
        transpose of a full image batch costs hundreds of ms on small
        hosts and doubles peak host memory)."""
        out = {}
        for k, v in batch.items():
            v = np.asarray(v)
            if "label" not in k and v.dtype.kind == "f" \
                    and k not in self._int_inputs:
                # integer-semantic inputs (token ids feeding Embedding/
                # take) stay float32: exact for ids < 2^24, while bf16
                # rounds ids above 256
                v = v.astype(self.dtype)
            out[k] = v
        return out

    def put_batch(self, batch):
        """Stage a host batch (reference NCHW convention) onto the mesh
        as sharded device arrays in the trainer's active layout.  Use
        with :meth:`step` to overlap host IO with compute, or to reuse a
        batch without re-transfer.  Under layout='NHWC' the image
        transpose happens ON DEVICE after the (layout-untouched) host
        bytes land — XLA transposes in microseconds what numpy pays
        hundreds of ms for.

        On a process-spanning mesh each process passes its OWN
        contiguous shard of the global batch (dim 0 split across the
        processes of the 'data' axis, reference num_parts/part_index
        slicing); the staged result is one global array."""
        import jax
        import numpy as _np
        out = {}
        normalize = (self._input_mean is not None
                     or self._input_std is not None)
        for k, v in self._cast_batch(batch).items():
            # batch dim may differ (partial tail batches): compare the
            # feature dims only to detect a host-NCHW image batch.  A
            # batch whose dims also match the NCHW reading (C==H==W) is
            # ambiguous and follows the documented host-NCHW convention
            feat = tuple(v.shape[1:])
            needs_transpose = (
                k in self._nhwc_inputs and v.ndim == 4
                and (feat != tuple(self._input_shapes[k][1:])
                     or feat == tuple(self._host_input_shapes[k][1:])))
            # uint8 inputs are normalized on device ONLY when the
            # trainer was configured for it; otherwise they reach the
            # graph unchanged (integer data, in-graph normalization)
            is_u8 = (v.dtype == _np.uint8 and k in self._data_names
                     and normalize)
            if needs_transpose or is_u8:
                fn, sharding = self._get_stage_fn(k, needs_transpose,
                                                  is_u8, v.ndim)
                out[k] = fn(self._stage_batch_value(v, sharding))
            else:
                out[k] = self._stage_batch_value(v,
                                                 self._batch_sharding[k])
        return out

    def _stage_batch_value(self, v, sharding):
        """One batch input onto the mesh: device_put single-process,
        per-process-shard assembly on a process-spanning mesh.  The
        global shape follows the LOCAL shard's dims (scaled by the
        process count along sharded axes), so partial tail batches work
        multi-host too — every process must pass the same-sized shard."""
        import jax
        if not self._multiproc:
            return jax.device_put(v, sharding)
        from . import multihost
        return multihost.stage_local(
            sharding, v, multihost.scale_local_shape(sharding, v.shape))

    def _get_stage_fn(self, name, needs_transpose, is_u8, ndim):
        """Jitted on-device staging program for one input: NCHW->NHWC
        transpose and/or uint8 -> (x - mean)/std -> compute dtype."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (name, needs_transpose, is_u8, ndim)
        hit = self._stage_fns.get(key)
        if hit is not None:
            return hit
        # the RAW host layout lands batch-sharded; the staged result
        # takes the input's full batch sharding (seq-parallel inputs
        # keep their dim-1 'model' shard)
        in_sharding = NamedSharding(self.mesh, P("data"))
        out_sharding = self._batch_sharding[name]
        compute_dtype = jnp.dtype(self.dtype)
        mean, std = self._input_mean, self._input_std
        ch_axis = -1 if (self._layout == "NHWC" or needs_transpose) else 1

        def reshape_stat(s, x_ndim):
            a = jnp.asarray(s, jnp.float32)
            if a.ndim == 0:
                return a
            shape = [1] * x_ndim
            shape[ch_axis] = a.shape[0]
            return a.reshape(shape)

        def stage(a):
            if needs_transpose:
                a = jnp.transpose(a, (0, 2, 3, 1))
            if is_u8:
                x = a.astype(jnp.float32)
                if mean is not None:
                    x = x - reshape_stat(mean, x.ndim)
                if std is not None:
                    x = x / reshape_stat(std, x.ndim)
                return x.astype(compute_dtype)
            return a

        fn = jax.jit(stage, out_shardings=out_sharding)
        self._stage_fns[key] = (fn, in_sharding)
        return fn, in_sharding

    def _stage_accounted(self, host_batch):
        """Stage one host batch, charging the wall to the ioview
        ``device_stage`` pipeline stage (the H2D half of the data
        plane).  Unlike :meth:`_stage_timed` this runs OUTSIDE a step,
        so nothing lands in the step's ``input_wait`` segment — that
        is the point of prefetched staging."""
        import time as _time
        from ..telemetry import ioview as _iov
        t0 = _time.perf_counter()
        dev = self.put_batch(host_batch)
        _iov.account("device_stage", _time.perf_counter() - t0,
                     items=1,
                     nbytes=sum(getattr(v, "nbytes", 0)
                                for v in host_batch.values()))
        return dev

    def staged_batches(self, batches):
        """Double-buffered host->device staging over an iterable of
        HOST batches: yields staged device batches (feedable straight
        to :meth:`step`), dispatching batch N+1's transfer right after
        the caller resumes from batch N — i.e. while batch N's step is
        still in flight on an async backend, so the H2D transfer
        overlaps the current step's compute instead of serializing
        into its ``input_wait`` segment.

        The thread-free sibling of :class:`~mxnet_tpu.io.
        DevicePrefetchIter` (which adds a worker thread and a depth-N
        queue on top of the same staging seam; the ioview
        ``device_stage`` metric times both).  Use when the host batches
        are already cheap to produce (synthetic/benchmark loops)::

            for dev_batch in trainer.staged_batches(host_batches):
                loss = trainer.step(dev_batch)
        """
        it = iter(batches)
        try:
            nxt = self._stage_accounted(next(it))
        except StopIteration:
            return
        for host in it:
            cur, nxt = nxt, None
            yield cur
            # the caller just dispatched its step on `cur`; this
            # transfer rides under that still-running step
            nxt = self._stage_accounted(host)
        yield nxt

    def step(self, batch):
        """One fused training step.  ``batch``: dict name -> host array
        with GLOBAL batch dim (or a dict from :meth:`put_batch`).
        Returns the (device) loss scalar.

        Telemetry: each call is a ``trainer.step`` span and one
        ``step_end`` record (step time is host-side dispatch+staging —
        on an async backend the device may still be computing).  The
        step is split into compute / input-wait / collective-wait
        segments (``mxtpu_step_segment_seconds``, telemetry.distview):
        input-wait is the host->device staging time, and on a
        process-spanning mesh a pre-collective timestamp barrier
        measures how long this rank waited for its slowest peer
        (``mxtpu_collective_wait_seconds`` / skew gauge) — the
        straggler-attribution signal tools/run_top.py aggregates.  The
        first call registers the compiled step's memory plan
        (``mxtpu_memory_plan_bytes{program="trainer.step"}``) and
        budget-checks it before dispatch; a backend RESOURCE_EXHAUSTED
        is re-raised with the plan + live-bytes forensics attached, and
        any MXNetError dumps the flight recorder's black box
        (MXNET_TPU_FLIGHT_DIR)."""
        import time as _time
        from .. import telemetry
        from ..telemetry import flight as _flight, memory as _tmem
        from ..telemetry import tracing as _tracing
        # one distributed trace per step: the existing distview
        # segments become its child spans, and flight events recorded
        # inside (step_begin, any error) carry the trace id
        tr = _tracing.start_trace("trainer.step",
                                  attrs={"step": self._step_count + 1})
        with tr:
            _flight.record("step_begin", program="trainer.step",
                           step=self._step_count + 1)
            self._seg = {"input_s": 0.0, "collective_s": 0.0,
                         "skew": None}
            t0 = _time.perf_counter()
            ts0 = _time.time()
            step_ctx = None
            with telemetry.span("trainer.step", category="trainer"), \
                    _flight.crash_guard("trainer.step"), \
                    _tmem.annotate_oom("trainer.step"):
                step_ctx = _tracing.current()
                loss = self._step_impl(batch)
            total = _time.perf_counter() - t0
            if step_ctx is not None:
                self._record_segment_spans(step_ctx, ts0, total)
        telemetry.step_end(samples=self._batch_samples(batch),
                           step_time=total,
                           extra=self._segments_extra(total))
        return loss

    def _record_segment_spans(self, ctx, ts0, total_s):
        """The step's segment split as trace spans under the
        ``trainer.step`` span (``ctx`` is that span's own context, so
        these land as its children): input_wait, compute (the
        remainder, distview's definition), collective_wait — laid out
        sequentially from ``ts0`` so the waterfall reads like the
        step."""
        from ..telemetry import tracing as _tracing
        seg = self._seg
        inp = max(0.0, float(seg["input_s"]))
        coll = max(0.0, float(seg["collective_s"]))
        comp = max(0.0, float(total_s) - inp - coll)
        _tracing.record_span(ctx, "step.input_wait", ts0, inp)
        _tracing.record_span(ctx, "step.compute", ts0 + inp, comp)
        attrs = None
        sk = seg.get("skew")
        if sk is not None:
            attrs = {"skew_s": round(sk["skew_s"], 6),
                     "slowest_rank": sk["slowest_rank"]}
        _tracing.record_span(ctx, "step.collective_wait",
                             ts0 + inp + comp, coll, attrs=attrs)

    def _segments_extra(self, total_s, count=1):
        """The straggler-attribution fields for this step's JSONL
        record: the segment split (recorded into
        ``mxtpu_step_segment_seconds`` as a side effect) plus the
        measured skew when the pre-collective barrier ran."""
        from ..telemetry import distview as _dv
        seg = self._seg
        extra = {"segments": _dv.record_step_segments(
            total_s, input_s=seg["input_s"],
            collective_s=seg["collective_s"], count=count)}
        sk = seg["skew"]
        if sk is not None:
            extra["skew_s"] = round(sk["skew_s"], 6)
            extra["slowest_rank"] = sk["slowest_rank"]
        num = seg.get("numerics")
        if num is not None:
            import math as _math
            from ..telemetry import numerics as _numerics
            # the compact numerics pair rides the step's JSONL record so
            # the run aggregator can surface cross-rank grad-norm skew
            # and digest drift next to the time skew (tools/run_top.py);
            # a non-finite grad norm stays out (the nonfinite rule
            # already carries it, and the step-log must stay strict JSON)
            gn = num.get("grad_norm")
            if isinstance(gn, float) and _math.isfinite(gn):
                extra["grad_norm"] = gn
            if num.get("digest") is not None:
                extra["digest"] = num["digest"]
            if _numerics.ledger_path() is None:
                # no dedicated ledger file: the step-log itself is the
                # ledger — the full record rides under "numerics", so
                # tools/numdiff.py accepts MXNET_TPU_TELEMETRY_JSONL
                # directly (read_ledger's inline carrier form)
                extra["numerics"] = _numerics.json_safe(
                    {k: v for k, v in num.items() if k != "anomalies"})
        return extra

    def _batch_samples(self, batch):
        try:
            first = next(iter(batch.values()))
            return int(first.shape[0])
        except (StopIteration, AttributeError, IndexError, TypeError):
            return 0

    def _dispatch_planned(self, program, fn, args, steps=1):
        """Dispatch through the AOT executable with the memory plan
        registered + budget-checked on first use
        (telemetry.memory.dispatch_planned).  Process-spanning meshes
        keep the plain jit dispatch (AOT example staging is a
        per-process choice) and skip the costdb sampling — a sampled
        ``block_until_ready`` on one rank would skew the fleet.

        Cost-database seam (telemetry.costdb): the fused blocks this
        program's compile traced bind to it, and sampled dispatches
        record synchronized wall time + flops/bytes + mesh shape as
        persistent MFU/roofline records (:meth:`cost_summary`).
        ``steps``: inner train steps one dispatch executes
        (``run_steps`` passes its chain length so the per-step wall
        meets the signatures' per-step flops)."""
        from ..telemetry import costdb as _costdb, memory as _tmem
        if self._multiproc:
            # bind-only: the compile's traced block signatures must not
            # dangle (they would attach to the next single-proc program
            # dispatched in this process); timing stays off — a sampled
            # block_until_ready on one rank would skew the fleet
            try:
                return fn(*args)
            finally:
                _costdb.bind_pending(
                    program, key=(self._costdb_scope, id(fn)))
        obs = _costdb.begin_dispatch(
            program, key=(self._costdb_scope, id(fn)))
        try:
            out = _tmem.dispatch_planned(self._aot_exes, program, fn,
                                         args)
        except BaseException:  # mxlint: allow-broad-except(re-raised unchanged — the handler only closes the costdb observation bind-only, so the compile's traced signatures cannot dangle and attach to the next program dispatched)
            _costdb.end_dispatch(obs, failed=True)
            raise
        _costdb.end_dispatch(obs, out=out, args=args,
                             mesh=self._mesh_axis_sizes(), steps=steps)
        return out

    def _mesh_axis_sizes(self):
        """{axis name: size} of the trainer's mesh — part of every
        costdb record key (the same block costs differently on a
        different mesh)."""
        try:
            return {str(k): int(v)
                    for k, v in dict(self.mesh.shape).items()}
        except (AttributeError, TypeError, ValueError):
            return None

    def _stage_timed(self, batch):
        """Stage a host batch, charging the wall time to the step's
        ``input_wait`` segment (already-staged device batches cost 0)
        and to the ioview ``device_stage`` pipeline stage (the H2D half
        of the data plane; a DevicePrefetchIter staging on its worker
        thread accounts there instead — the two paths are disjoint)."""
        import time as _time
        import jax
        from ..telemetry import ioview as _iov
        first = next(iter(batch.values()))
        if isinstance(first, jax.Array):
            return batch
        t0 = _time.perf_counter()
        dev_batch = self.put_batch(batch)
        dt = _time.perf_counter() - t0
        self._seg["input_s"] += dt
        _iov.account("device_stage", dt, items=1,
                     nbytes=sum(getattr(v, "nbytes", 0)
                                for v in batch.values()))
        return dev_batch

    def _measure_collective_entry(self, site):
        """On a process-spanning mesh, run the distview timestamp
        barrier just before dispatching the collective-bearing program:
        the measured wait/skew land in this step's segments."""
        if not self._multiproc:
            return
        from ..telemetry import distview as _dv
        info = _dv.pre_collective_barrier(site)
        if info is not None:
            self._seg["collective_s"] += info["wait_s"]
            self._seg["skew"] = info

    def _step_impl(self, batch):
        import jax
        import jax.numpy as jnp
        from .. import resilience
        from ..telemetry import numerics as _numerics
        resilience.fault_point("trainer.step")
        self._key, sub = jax.random.split(self._key)
        dev_batch = self._stage_timed(batch)
        opt = self.optimizer
        self._maybe_rebuild()
        self._step_count += 1
        # num_update honors begin_num_update so lr schedule AND adam bias
        # correction continue consistently across resume
        opt.num_update = max(opt.num_update, opt.begin_num_update
                             + self._step_count)
        lr = (opt.lr_scheduler(opt.num_update)
              if opt.lr_scheduler is not None else opt.lr)
        sampled = self._numerics_sampled()
        if sampled:
            # the numerics.nonfinite seam is evaluated ONLY on sampled
            # steps: an injected NaN must land where detection runs —
            # poisoning an unsampled (or auto_layouts-gated) step would
            # corrupt the run with zero anomaly signal
            dev_batch = self._maybe_poison_batch(dev_batch)
        fn = self._step_fn
        if sampled:
            if self._stats_step_fn is None:
                self._stats_step_fn = self._build_step(collect_stats=True)
            fn = self._stats_step_fn
        self._ensure_state_formats(fn)
        args = (self.params, self.opt_state, self.aux, dev_batch, sub,
                jnp.float32(lr), jnp.float32(opt.num_update))
        self._measure_collective_entry("trainer.step")
        if sampled:
            program = "trainer.step_stats"
            self.params, self.opt_state, self.aux, loss, stats = \
                self._dispatch_planned(program, fn, args)
            # the stats fetch is the ONLY host sync numerics adds, and
            # only on sampled steps; every rank samples the same step
            # numbers, so a multi-process fleet syncs symmetrically
            payload = _numerics.process_step(
                stats, step=self._resume_epoch + self._step_count,
                program="trainer.step",
                provenance_fn=lambda: self._numerics_provenance(
                    dev_batch, sub),
                # instance-unique EWMA scope (rotated on rebuild): two
                # trainers in one process must not share a grad_spike
                # baseline — model A's small norms would false-trip B
                scope=("trainer.step", self._costdb_scope))
            if payload is not None:
                self._seg["numerics"] = payload
        else:
            self.params, self.opt_state, self.aux, loss = \
                self._dispatch_planned("trainer.step", fn, args)
        return loss

    def _numerics_sampled(self):
        """Whether THIS step dispatches the numerics stats variant.
        The cadence is phased on the GLOBAL step (resume epoch + local
        count — the number the ledger records carry), so a resumed run
        samples the same step numbers as a from-scratch one and the
        pre- vs post-resume ledgers stay numdiff-comparable.
        auto_layouts is excluded: the stats variant would need its own
        AOT layout choice and a state migration per sampled step."""
        from ..telemetry import numerics as _numerics
        if not _numerics.sampled(self._resume_epoch + self._step_count):
            return False
        if self._auto_layouts:
            if not getattr(self, "_numerics_warned", False):
                self._numerics_warned = True
                import logging
                logging.warning(
                    "MXNET_TPU_NUMERICS_EVERY is set but auto_layouts "
                    "is active; numerics sampling is disabled for this "
                    "trainer (the stats variant would re-migrate the "
                    "state's XLA-chosen layouts on every sampled step)")
            return False
        return True

    def _maybe_poison_batch(self, dev_batch):
        """The ``numerics.nonfinite`` chaos seam: when armed
        (MXNET_TPU_FAULTS), the injected hazard is a NUMERIC one — the
        first float data input is poisoned with NaNs instead of raising,
        so the detection/provenance path is what gets exercised
        (tools/ci_check.py stage 11).  Called only on SAMPLED steps
        (see ``_step_impl``), so the injection is always detectable."""
        from .. import resilience
        try:
            resilience.fault_point("numerics.nonfinite")
            return dev_batch
        except resilience.FaultInjected:
            import jax.numpy as jnp
            import numpy as _np
            out = dict(dev_batch)
            for name in self._data_names:
                v = out[name]
                if _np.dtype(v.dtype).kind == "f" \
                        and name not in self._int_inputs:
                    out[name] = v * jnp.asarray(float("nan"), v.dtype)
                    return out
            # no float data input to poison: fall back to a param (the
            # provenance then names its first consumer)
            name = self._param_names[0]
            self.params = dict(self.params)
            self.params[name] = self.params[name] * jnp.float32(
                float("nan"))
            return dev_batch

    def _numerics_provenance(self, dev_batch, key):
        """NaN/Inf provenance: replay the step's forward EAGERLY (no
        jit) through ``eval_graph``'s per-node monitor hook — the
        executor's ``_forward_monitored`` path — and name the FIRST
        node producing a non-finite output.  Host-syncs per node, which
        is fine: it runs once, on a step already known to be anomalous.

        The replay binds the CURRENT (post-update) params — the step's
        input params were donated — so when the corruption entered
        through the update itself, the named node is the first to
        CONSUME a non-finite param rather than the backward op that
        produced it; either way it localizes the blast radius.  Batch-
        borne NaNs (the seeded-injection case) replay exactly."""
        import jax
        import jax.numpy as jnp

        found = {}
        order = [0]

        def mon(name, val):
            order[0] += 1
            if found:
                return
            try:
                bad = int(jax.device_get(jnp.sum(
                    ~jnp.isfinite(jnp.asarray(val).astype(jnp.float32)))))
            except (TypeError, ValueError):
                return
            if bad:
                found.update(node=str(name), nonfinite=bad,
                             position=order[0])

        compute_dtype = jnp.dtype(self.dtype)
        p = self._compute_view(self.params, compute_dtype)
        bsz = next(iter(dev_batch.values())).shape[0]
        with image_layout(self._layout):
            var_values = self._node_value_map(p, dev_batch, self.aux)
            eval_graph(self._topo, self.symbol._entries, var_values,
                       is_train=True, key=key, monitor=mon,
                       batch_size=bsz)
        return dict(found) if found else None

    def run_steps(self, batch, num_steps):
        """``num_steps`` fused training steps in ONE device program.

        The scan-chained equivalent of calling :meth:`step` in a loop on
        the same batch: per-step host dispatch (~2-3 ms over a remote
        tunnel) disappears and XLA keeps the donated state resident
        between iterations.  lr schedules advance per inner step exactly
        as in :meth:`step` (the scheduler is evaluated on host into a
        (num_steps,) lr array).  Returns the per-step loss array.

        Use for throughput-critical loops where the batch is staged once
        (benchmarks, synthetic-data soak runs); for distinct batches per
        step, stage the next batch with :meth:`put_batch` while the chip
        runs (double buffering) and call :meth:`step` per batch.
        """
        import time as _time
        from .. import telemetry
        from ..telemetry import flight as _flight, memory as _tmem
        _flight.record("step_begin", program="trainer.run_steps",
                       step=self._step_count + 1, count=num_steps)
        self._seg = {"input_s": 0.0, "collective_s": 0.0, "skew": None}
        t0 = _time.perf_counter()
        with telemetry.span("trainer.run_steps", category="trainer"), \
                _flight.crash_guard("trainer.run_steps"), \
                _tmem.annotate_oom("trainer.run_steps"):
            losses = self._run_steps_impl(batch, num_steps)
        # the scan chain IS num_steps full optimizer updates observed
        # once from the host: counters/percentiles advance per inner
        # step, but the JSONL gets ONE record (count=num_steps) — per-
        # record snapshots of an opaque chain would be byte-identical
        total = _time.perf_counter() - t0
        telemetry.step_end(
            samples=self._batch_samples(batch),
            step_time=total / max(1, num_steps),
            count=num_steps,
            extra=self._segments_extra(total, count=num_steps))
        return losses

    def health(self):
        """This rank's SLO health verdict (``mxtpu-health/1`` dict —
        see ``telemetry.slo``).  The training-run rules (step-time
        regression vs the rolling baseline, collective-wait share,
        starved-input share, the step heartbeat, numerics/io
        passthrough) are evaluated on the ``step_end`` cadence every
        :meth:`step`/:meth:`run_steps` already drives, so this is a
        read, not an evaluation."""
        from ..telemetry import slo
        return slo.health()

    def _run_steps_impl(self, batch, num_steps):
        import jax
        import jax.numpy as jnp
        import numpy as _np
        from ..telemetry import numerics as _numerics

        if _numerics.enabled() and \
                not getattr(self, "_numerics_scan_warned", False):
            # the scan chain is one opaque program; numerics samples the
            # step() path only — say so ONCE instead of silently leaving
            # the ledger empty while the knob claims every Nth step
            self._numerics_scan_warned = True
            import logging
            logging.warning(
                "MXNET_TPU_NUMERICS_EVERY is set but run_steps chains "
                "are not sampled (the lax.scan chain is one opaque "
                "program); use step() where numerics coverage matters")
        dev_batch = self._stage_timed(batch)
        self._maybe_rebuild()
        fn = self._scan_fns.get(num_steps)
        if fn is None:
            fn = self._build_multi_step(num_steps)
            self._scan_fns[num_steps] = fn
        opt = self.optimizer
        ts, lrs = [], []
        for _ in range(num_steps):
            self._step_count += 1
            opt.num_update = max(opt.num_update, opt.begin_num_update
                                 + self._step_count)
            ts.append(opt.num_update)
            lrs.append(opt.lr_scheduler(opt.num_update)
                       if opt.lr_scheduler is not None else opt.lr)
        self._key, sub = jax.random.split(self._key)
        self._ensure_state_formats(fn)
        args = (self.params, self.opt_state, self.aux, dev_batch, sub,
                jnp.asarray(_np.asarray(lrs, _np.float32)),
                jnp.asarray(_np.asarray(ts, _np.float32)))
        self._measure_collective_entry("trainer.run_steps")
        self.params, self.opt_state, self.aux, losses = \
            self._dispatch_planned("trainer.run_steps", fn, args,
                                   steps=num_steps)
        return losses

    def forward(self, batch, is_train=False):
        """Jitted inference forward returning head arrays."""
        import jax
        if self._fwd_fn is None:
            topo, entries = self._topo, self.symbol._entries
            layout = self._layout
            import jax.numpy as jnp
            compute_dtype = jnp.dtype(self.dtype)

            def fwd(params, aux, batch):
                from ..ops.fused import block_fusion
                from ..analysis.fusion import plan_decisions
                from .sequence import sequence_parallel as seq_ctx
                p = self._compute_view(params, compute_dtype)
                bsz = next(iter(batch.values())).shape[0]
                # loss heads still take label inputs at inference; their
                # forward ignores the values, so zeros stand in
                full = dict(batch)
                for n, s in self._label_shapes.items():
                    if n not in full:
                        full[n] = jnp.zeros((bsz,) + tuple(s[1:]),
                                            jnp.float32)
                # the fused blocks keep eval-mode BN semantics inside
                # the region, so inference lowers through the same plan
                with image_layout(layout), \
                        block_fusion(self._fuse_blocks), \
                        plan_decisions(self._plan_decisions), \
                        seq_ctx(self.mesh if self._seq_parallel
                                else None):
                    var_values = self._node_value_map(p, full, aux)
                    heads, _ = eval_graph(topo, entries, var_values,
                                          is_train=False, key=None,
                                          batch_size=bsz)
                return heads
            self._fwd_fn = jax.jit(fwd, in_shardings=(
                self._param_sharding, self._aux_sharding,
                {k: self._batch_sharding[k] for k in self._data_names}))
        first = next(iter(batch.values()))
        # inference takes data inputs only — drop labels if supplied
        batch = {k: v for k, v in batch.items() if k in self._data_names}
        if isinstance(first, jax.Array):
            dev_batch = batch  # already staged via put_batch
        else:
            dev_batch = self.put_batch(batch)
        return self._fwd_fn(self.params, self.aux, dev_batch)


    def fusion_summary(self):
        """Summary of the most recent block-fusion plan traced in this
        process (blocks fused by kind, relayouts eliminated, fallback
        reasons) — None before the first fused compile or when
        ``fuse_blocks`` is off.  See docs/api/fusion.md."""
        from ..analysis import fusion as _fusion
        return _fusion.last_plan_summary() if self._fuse_blocks else None

    def cost_summary(self, top=5):
        """Roll-up of the process cost database
        (:mod:`mxnet_tpu.telemetry.costdb`): record counts, measured
        per-program wall/MFU, and the ``top`` worst-MFU fused blocks
        with their roofline bound — the autotuner targeting signal.
        Sampled collection runs through this trainer's dispatches
        (``MXNET_TPU_COSTDB_SAMPLE``); ``MXNET_TPU_COSTDB`` persists
        the records across runs.  See docs/api/telemetry.md."""
        from ..telemetry import costdb as _costdb
        return _costdb.summary(top=top)

    # ------------------------------------------------------- checkpoints
    def mesh_descriptor(self):
        """JSON-able descriptor of this trainer's mesh + per-param
        partition layout (``parallel/reshard.py``): axis sizes, the
        saving world size, and each param's spec in the REFERENCE
        (OIHW) dim convention — native-layout HWIO storage is a device
        detail the descriptor never sees, exactly like the checkpoint
        files themselves.  Recorded in the checkpoint manifest's
        ``meta["mesh"]`` (schema v2) so a later load can detect a mesh
        reshape; see :meth:`load_checkpoint`."""
        from . import multihost, reshard as _reshard
        specs = _reshard.specs_from_tp_rules(
            self.tp_rules,
            {n: self._arg_shapes[n] for n in self._param_names})
        return _reshard.mesh_descriptor(self.mesh, specs=specs,
                                        world=multihost.world_size())

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Write reference-format checkpoint files from the sharded
        state: ``prefix-symbol.json`` + ``prefix-%04d.params`` (arg:/aux:
        name prefixes — Module/FeedForward can load these) and optionally
        ``prefix-%04d.states`` holding the fused optimizer slots + the
        update counter.  NOTE: the .states layout is the fused-path's own
        (name-keyed slot arrays); Module's .states files are pickled
        per-index Updater dicts and the two are NOT interchangeable —
        params/aux files are.

        Multi-host: call on EVERY process (process-sharded state is
        all-gathered collectively); rank 0 writes the files and a
        barrier orders the write before any rank's subsequent load —
        the reference's rank-0 checkpointing in dist training
        (example/image-classification/train_model.py saves on
        kv.rank==0 only).  ``prefix`` must live on storage every host
        can read (NFS/GCS): load_checkpoint has all ranks read the
        files rank 0 wrote.
        """
        import jax
        import numpy as _np
        from .. import ndarray as _nd
        from . import multihost

        def to_ref(k, a):
            # native-layout masters/slots live HWIO on device; files
            # keep the reference OIHW so checkpoints stay interoperable
            return a.transpose(3, 2, 0, 1) if k in self._native_w else a

        from .. import resilience
        # gather-on-save streams ONE array at a time off the mesh (the
        # host dict accumulates numpy copies; device memory never holds
        # a second full param).  The reshard.gather seam fires per
        # array — the chaos window of an elastic gather.
        host = {}
        for k, v in self.params.items():
            resilience.fault_point("reshard.gather")
            host["arg:%s" % k] = to_ref(k, multihost.gather_to_host(v))
        for k, v in self.aux.items():
            resilience.fault_point("reshard.gather")
            host["aux:%s" % k] = multihost.gather_to_host(v)
        st = None
        if save_optimizer_states:
            st = {"meta:num_update": _np.array(
                [self.optimizer.begin_num_update + self._step_count],
                _np.int64)}
            for k, slots in self.opt_state.items():
                for i, sl in enumerate(slots):
                    resilience.fault_point("reshard.gather")
                    st["slot%d:%s" % (i, k)] = to_ref(
                        k, multihost.gather_to_host(sl))
        if not self._multiproc or jax.process_index() == 0:
            resilience.atomic_write("%s-symbol.json" % prefix,
                                    self.symbol.save)
            param_name = "%s-%04d.params" % (prefix, epoch)
            resilience.atomic_write(
                param_name,
                lambda tmp: _nd.save(
                    tmp, {k: _nd.array(v) for k, v in host.items()}),
                fault_site="checkpoint.save")
            files = [param_name]
            arrays = dict(host)
            if st is not None:
                states_name = "%s-%04d.states" % (prefix, epoch)
                resilience.atomic_write(
                    states_name,
                    lambda tmp: _nd.save(
                        tmp, {k: _nd.array(v) for k, v in st.items()}))
                files.append(states_name)
                arrays.update(st)
            # the manifest commits the checkpoint: written LAST (itself
            # atomically), so a crash anywhere above leaves no epoch a
            # verified loader would pick up.  meta["mesh"] (schema v2)
            # records the saving mesh so a later load on a different
            # shape reshards instead of guessing (docs/api/reshard.md);
            # meta["data_position"] is the ADVISORY iterator position of
            # the run's tracked data iterator (telemetry.ioview) — the
            # recorded half of mid-epoch resume (restore comes later)
            meta = {"mesh": self.mesh_descriptor()}
            from ..telemetry import ioview as _iov
            from .. import io_resume as _ior
            pos = _iov.current_position()
            if pos is not None:
                meta["data_position"] = pos
            # data_state (mxnet_tpu.io_resume) is the RESTORED half:
            # the tracked iterator's durable state, consumed by
            # load_checkpoint -> restore_data_iter/fit.  Rank 0's
            # iterator describes the fleet under the lockstep SPMD
            # contract (ledger states remap per rank on load)
            entry = _ior.data_state_entry()
            if entry is not None:
                meta["data_state"] = entry
            resilience.write_manifest(prefix, epoch, files, arrays=arrays,
                                      meta=meta)
        if self._multiproc:
            multihost.process_barrier("sharded_trainer_ckpt_save")

    def _state_target(self, live, sharding):
        """device_put target preserving the live array's layout: under
        auto_layouts the AOT-compiled step was lowered with XLA-chosen
        formats, which a plain NamedSharding put would discard.
        (jax 0.4.x spells the array's format ``.layout``.)"""
        if not self._auto_layouts:
            return sharding
        return getattr(live, "format", None) or live.layout

    def load_checkpoint(self, prefix, epoch, load_optimizer_states=False):
        """Restore params/aux (and fused optimizer slots) saved by
        :meth:`save_checkpoint`.  Params/aux files are Module-format, so
        Module-trained checkpoints resume on the fused path; optimizer
        .states files are fused-path-specific (see save_checkpoint).
        Multi-host: every rank reads the files (``prefix`` must be on
        shared storage) and stages its own shards.
        Raises on any name mismatch — a silent partial load would look
        like a resume while actually restarting from random init.

        Elastic (docs/api/reshard.md): when the manifest's mesh
        descriptor (schema v2) names a different device grid than this
        trainer's mesh, the load RESHARDS instead of raising — every
        array is validated against the target layout up front
        (``reshard.plan_reshard``), then shard-on-load stages ONE array
        at a time onto the new mesh (the ``reshard.scatter`` seam fires
        per array) into a staged copy that only replaces the live state
        once every array landed, so a mid-reshard failure degrades to a
        descriptive MXNetError with the old-mesh state untouched.  A
        world-size change additionally fires the ``elastic.rejoin``
        seam and records ``rank_join``/``rank_leave`` events.  v1
        manifests (no descriptor) keep the legacy behavior."""
        import time as _time
        import jax
        import numpy as _np
        from .. import ndarray as _nd
        from .. import resilience
        from . import reshard as _reshard

        resilience.fault_point("checkpoint.load")
        param_name = "%s-%04d.params" % (prefix, epoch)
        # manifest CRC verification first: a truncated/corrupt file must
        # surface as a named MXNetError, not an unpickle traceback
        manifest = resilience.verify_manifest(prefix, epoch)
        try:
            loaded = _nd.load(param_name)
        except FileNotFoundError as e:
            raise MXNetError(
                "checkpoint params file %r is missing for epoch %d"
                % (param_name, epoch)) from e
        except (ValueError, EOFError, _struct.error) as e:
            raise MXNetError("checkpoint params file %r is corrupt: %s"
                             % (param_name, e)) from e
        file_args = {k.split(":", 1)[1]: v for k, v in loaded.items()
                     if k.startswith("arg:")}
        file_aux = {k.split(":", 1)[1]: v for k, v in loaded.items()
                    if k.startswith("aux:")}
        missing = (set(self.params) - set(file_args)) |             (set(self.aux) - set(file_aux))
        unexpected = (set(file_args) - set(self.params)) |             (set(file_aux) - set(self.aux))
        if missing or unexpected:
            raise MXNetError(
                "checkpoint/model mismatch: missing %s, unexpected %s"
                % (sorted(missing), sorted(unexpected)))
        def to_store(name, a):
            # files hold reference OIHW; native-layout state lives HWIO
            return a.transpose(2, 3, 1, 0) if name in self._native_w else a

        # ---- elastic detection: the manifest's mesh descriptor vs the
        # mesh this trainer was built on.  The plan validates EVERY
        # array against the target layout before any state moves.
        saved_desc = _reshard.manifest_mesh(manifest)
        cur_desc = self.mesh_descriptor()
        reshaping = saved_desc is not None and \
            not _reshard.same_mesh(saved_desc, cur_desc)
        plan = None
        if reshaping:
            shapes = {n: self._arg_shapes[n] for n in file_args}
            shapes.update({n: self._aux_shapes[n] for n in file_aux})
            plan = _reshard.plan_reshard(saved_desc, cur_desc, shapes)
        from . import multihost as _mh
        saved_world = (saved_desc or {}).get("world")
        world_changed = saved_world is not None and \
            int(saved_world) != _mh.world_size()
        if world_changed:
            # the rank join/leave seam fires BEFORE any state moves: an
            # injected rejoin fault leaves the old-mesh state intact
            resilience.fault_point("elastic.rejoin")

        t0 = _time.perf_counter()
        # reshard loads stage into a copy and commit only once every
        # array landed (transiently ~2x state, like any resume over
        # random init); same-mesh loads keep the in-place replacement
        target_params = {} if reshaping else self.params
        target_aux = {} if reshaping else self.aux
        target_slots = None
        new_num_update = None
        try:
            with self.mesh:
                for name, v in file_args.items():
                    if reshaping:
                        resilience.fault_point("reshard.scatter")
                    target_params[name] = self._put_state(
                        to_store(name,
                                 _np.asarray(v.asnumpy(), _np.float32)),
                        self._state_target(self.params[name],
                                           self._param_sharding[name]))
                for name, v in file_aux.items():
                    if reshaping:
                        resilience.fault_point("reshard.scatter")
                    target_aux[name] = self._put_state(
                        _np.asarray(v.asnumpy(), _np.float32),
                        self._state_target(self.aux[name],
                                           self._aux_sharding[name]))
                if load_optimizer_states:
                    states_name = "%s-%04d.states" % (prefix, epoch)
                    try:
                        st = _nd.load(states_name)
                    except FileNotFoundError as e:
                        raise MXNetError(
                            "checkpoint states file %r is missing for "
                            "epoch %d" % (states_name, epoch)) from e
                    except (ValueError, EOFError, _struct.error) as e:
                        raise MXNetError(
                            "checkpoint states file %r is corrupt: %s"
                            % (states_name, e)) from e
                    slots_in_file = {}
                    for k in st:
                        if k.startswith("slot"):
                            slot, name = k.split(":", 1)
                            i = int(slot[len("slot"):])
                            slots_in_file[name] = max(
                                slots_in_file.get(name, 0), i + 1)
                    for name, n in slots_in_file.items():
                        if name not in self.opt_state or                                 n != len(self.opt_state[name]):
                            raise MXNetError(
                                "optimizer state mismatch for %r: file "
                                "has %d slots, trainer (%s) expects %d "
                                "— resume with the optimizer the "
                                "checkpoint was saved with"
                                % (name, n,
                                   type(self.optimizer).__name__,
                                   self._n_slots))
                    target_slots = {n: list(s)
                                    for n, s in self.opt_state.items()} \
                        if reshaping else self.opt_state
                    for k, v in st.items():
                        if k == "meta:num_update":
                            new_num_update = int(
                                v.asnumpy().astype(_np.int64)[0])
                            continue
                        slot, name = k.split(":", 1)
                        i = int(slot[len("slot"):])
                        if reshaping:
                            resilience.fault_point("reshard.scatter")
                        target_slots[name][i] = self._put_state(
                            to_store(name,
                                     _np.asarray(v.asnumpy(),
                                                 _np.float32)),
                            self._state_target(
                                self.opt_state[name][i],
                                self._param_sharding[name]))
        except (MXNetError, ValueError, RuntimeError, TypeError) as e:
            if reshaping:
                # degrade to the old-mesh error path: the live state
                # was never touched (staged copies are dropped)
                raise MXNetError(
                    "resharding checkpoint %r epoch %d from mesh %s "
                    "onto mesh %s failed: %s — trainer state left "
                    "unchanged on the current mesh"
                    % (prefix, epoch, plan["src"], plan["dst"], e)) \
                    from e
            raise
        if reshaping:
            self.params = target_params
            self.aux = target_aux
            if target_slots is not None:
                self.opt_state = target_slots
            _reshard.note_reshape("load", plan,
                                  seconds=_time.perf_counter() - t0,
                                  epoch=epoch)
        if world_changed:
            _reshard.note_world_change(saved_world, _mh.world_size(),
                                       kind="load")
        if new_num_update is not None:
            self.optimizer.begin_num_update = new_num_update
        # the restored state IS the new baseline: steps counted before
        # this load no longer describe it (with optimizer states the
        # meta handling above also restored begin_num_update)
        self._resume_epoch = int(epoch)
        self._step_count = 0
        # stash the durable data-iterator state for restore_data_iter /
        # fit to consume (mxnet_tpu.io_resume): model state and data
        # cursor resume from the SAME checkpoint, so a SIGKILL mid-epoch
        # replays no sample and drops none — across a world-size change
        # the ledger state re-cuts per rank (io.remap)
        if manifest is not None:
            from .. import io_resume as _ior
            _ior.note_loaded_state(
                _reshard.manifest_data_state(manifest),
                source="%s epoch %d" % (prefix, epoch))

    def load_latest_checkpoint(self, prefix, load_optimizer_states=False):
        """Restore from the NEWEST complete checkpoint under ``prefix``,
        falling back past corrupt/incomplete epochs (a save interrupted
        between tmp-write and rename is invisible; a CRC-failing file is
        skipped with a warning).  Returns the restored epoch, or None
        when no checkpoint exists yet (caller starts fresh) — the
        preemption-restart resume path."""
        import logging
        from ..base import MXNetError as _Err
        from ..model import find_checkpoints

        for ep in reversed(find_checkpoints(
                prefix, require_states=load_optimizer_states)):
            try:
                self.load_checkpoint(
                    prefix, ep, load_optimizer_states=load_optimizer_states)
                return ep
            except _Err as e:
                logging.warning("falling back past checkpoint epoch %d "
                                "of %r: %s", ep, prefix, e)
        return None

    def restore_data_iter(self, it):
        """Restore ``it`` from the ``data_state`` entry the last
        :meth:`load_checkpoint` found (``mxnet_tpu.io_resume``), and
        register it as the run's tracked iterator so subsequent
        checkpoints carry ITS state.  Returns the consumed manifest
        entry, or None when the checkpoint carried no durable state.
        A restore fault (the ``io.resume`` seam) propagates with the
        entry still pending — retry with the same iterator after
        clearing the fault."""
        from .. import io_resume as _ior
        from ..telemetry import ioview as _iov
        _iov.track(it)
        return _ior.apply_pending(it)

    def install_preemption_handler(self, prefix, save_optimizer_states=True,
                                   signals=None, exit_process=True):
        """Checkpoint-and-exit cleanly on SIGTERM (host preemption).

        Cloud TPU hosts get a SIGTERM grace window before shutdown; the
        handler writes an atomic checkpoint at epoch = resumed epoch +
        completed step count and exits 0, so the supervisor (tools/launch.py watchdog
        or an external scheduler) can restart the job and
        :meth:`load_latest_checkpoint` resumes it.  Runs in the MAIN
        thread between Python bytecodes — an in-flight jitted step
        finishes first, so the saved state is step-consistent.

        Multi-host caveat: save_checkpoint is collective (the gather);
        the handler assumes every rank receives the signal (true for
        whole-slice preemption and for launch.py's group teardown).

        Returns the handler (its ``.triggered`` attribute flips to True
        after it fires — useful when ``exit_process=False`` and the
        training loop wants to drain and stop itself)."""
        import signal as _signal
        import sys as _sys
        import logging

        if signals is None:
            signals = (_signal.SIGTERM,)

        def handler(signum, frame):
            if handler._saving:         # repeated TERM during the save
                return
            handler._saving = True
            try:
                # _step_count restarts at 0 after a resume: offset by
                # the resumed epoch so a SECOND preemption never writes
                # a lower epoch than the first (load_latest would
                # resume the older checkpoint and re-train the same
                # window forever)
                epoch = self._resume_epoch + self._step_count
                logging.warning(
                    "preemption signal %d: checkpointing to %r epoch "
                    "%d and exiting", signum, prefix, epoch)
                # black box first: if the grace window expires mid-save
                # the flight dump still tells the postmortem what the
                # run was doing when the preemption landed
                from ..telemetry import flight as _flight
                _flight.record("preemption", signum=int(signum),
                               epoch=epoch)
                _flight.dump("sigterm")
                self.save_checkpoint(
                    prefix, epoch,
                    save_optimizer_states=save_optimizer_states)
                handler.triggered = True
                if exit_process:
                    _sys.exit(0)
            finally:
                # in drain mode (exit_process=False) a LATER preemption
                # must checkpoint again, not be swallowed by a latch
                handler._saving = False

        handler._saving = False
        handler.triggered = False
        for sig in signals:
            _signal.signal(sig, handler)
        return handler



class _HostArray:
    """Minimal NDArray-like shim so Initializers can write numpy in-place."""

    def __init__(self, data):
        self.data = data

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def __setitem__(self, key, value):
        self.data[key] = np.asarray(value)

    def __getitem__(self, key):
        return self.data[key]

