"""Checkpoint resharding + elastic mesh reshape.

The reference's ps-lite parameter server tolerated worker churn by
design (``src/kvstore/kvstore_dist.h:39-80`` heartbeats + restart-from-
checkpoint); the TPU-native fused path compiles ONE program against ONE
``jax.sharding.Mesh``, so a fleet that grows or shrinks must *reshard*:
the state saved under one mesh shape has to come back under another.
This module is the shared substrate (ROADMAP item 5):

* a ``match_partition_rules``-style **rule table** (regex rules →
  PartitionSpec-like tuples, the fmengine/fmtrainer exemplar in
  SNIPPETS.md): :func:`parse_rules` / :func:`match_partition_rules` /
  :func:`first_match`, armed process-wide via
  ``MXNET_TPU_RESHARD_RULES`` (:func:`env_rules`);

* **mesh descriptors** recorded in checkpoint-manifest ``meta`` (schema
  v2, :func:`mesh_descriptor` / :func:`manifest_mesh`): the axis sizes,
  per-param partition specs, and the saving world size.  v1 manifests
  (no descriptor) still load — the loader then has nothing to compare
  and keeps the legacy behavior;

* a **reshard planner** (:func:`plan_reshard`): validates that every
  target spec divides its param's dims on the target mesh and returns
  the per-param action list with byte accounting — infeasible targets
  raise a descriptive :class:`~mxnet_tpu.base.MXNetError` BEFORE any
  state is touched, so a failed reshape degrades to the old-mesh error
  path with the live state intact;

* **observability**: every reshape emits ``mxtpu_reshard_*`` metrics, a
  ``reshard`` flight event, a JSONL event record (aggregated into the
  ``mxtpu-run/1`` timeline), and — on a world-size change —
  ``rank_join``/``rank_leave`` events plus the
  ``mxtpu_elastic_resizes_total`` counter.  The fault seams
  ``reshard.gather`` / ``reshard.scatter`` / ``elastic.rejoin``
  (:data:`mxnet_tpu.resilience.KNOWN_SITES`) let ``tools/chaos_run.py``
  chaos-test the new paths.

Consumers: ``ShardedTrainer.save_checkpoint/load_checkpoint`` (reshard
on mesh mismatch), ``DistKVStore.save_state/load_state`` (kvstore
migration across world sizes), ``tools/reshard.py`` (offline converter)
and ``tools/launch.py --elastic`` (rank leave/join supervision).  See
``docs/api/reshard.md``.
"""
from __future__ import annotations

import json
import os
import re

from ..base import MXNetError

__all__ = [
    "parse_rules", "env_rules", "match_partition_rules", "first_match",
    "mesh_axes", "parse_axes", "normalized_axes", "mesh_descriptor",
    "manifest_mesh", "same_mesh", "spec_to_json", "specs_from_tp_rules",
    "plan_reshard", "note_reshape", "note_world_change",
]

#: manifest meta schema version written by descriptor-carrying savers
MESH_SCHEMA = 2


# ------------------------------------------------------------- rule tables

def parse_rules(spec):
    """Parse a reshard rule table.

    Two accepted forms:

    * inline grammar — ``;``-separated ``regex=axis,axis,...`` entries
      where each axis is a mesh axis name or ``None``/'' (replicated
      dim), e.g. ``".*fc1_weight=model,None;.*_bias=None"``.  An entry
      with no ``=`` (or an empty axis list) replicates every dim.
    * ``@/path/to/rules.json`` — a JSON list of ``[regex, [axes...]]``
      pairs (``null`` = replicated dim), the
      ``match_partition_rules``-style table from SNIPPETS.md.

    Returns a list of ``(compiled_regex, spec_tuple)``; first match
    wins.  A malformed table raises :class:`MXNetError` naming the
    offending entry — a typo'd rule must fail loudly, not silently
    replicate a weight."""
    if not spec:
        return []
    if spec.startswith("@"):
        path = spec[1:]
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            raise MXNetError("reshard rule file %r is unreadable or not "
                             "JSON: %s" % (path, e)) from e
        if not isinstance(doc, list):
            raise MXNetError("reshard rule file %r: expected a JSON "
                             "list of [regex, [axes...]] pairs" % path)
        out = []
        for i, entry in enumerate(doc):
            if (not isinstance(entry, (list, tuple)) or len(entry) != 2
                    or not isinstance(entry[0], str)
                    or not isinstance(entry[1], (list, tuple))):
                raise MXNetError(
                    "reshard rule file %r entry %d: expected "
                    "[regex, [axes...]], got %r" % (path, i, entry))
            out.append((_compile(entry[0]),
                        tuple(None if a in (None, "", "None") else str(a)
                              for a in entry[1])))
        return out
    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        pat, _, axes = part.partition("=")
        pat = pat.strip()
        if not pat:
            raise MXNetError("reshard rule %r: empty pattern "
                             "(grammar: regex=axis,axis,...;regex2=...)"
                             % part)
        dims = []
        for a in axes.split(","):
            a = a.strip()
            if a in ("", "None", "null"):
                dims.append(None)
            else:
                dims.append(a)
        while dims and dims[-1] is None:
            dims.pop()          # trailing replicated dims are implicit
        out.append((_compile(pat), tuple(dims)))
    return out


def _compile(pat):
    try:
        return re.compile(pat)
    except re.error as e:
        raise MXNetError("reshard rule pattern %r is not a valid "
                         "regex: %s" % (pat, e)) from e


def env_rules():
    """Rule table armed via ``MXNET_TPU_RESHARD_RULES`` (inline grammar
    or ``@file``); empty list when unset."""
    return parse_rules(os.environ.get("MXNET_TPU_RESHARD_RULES", ""))


def first_match(rules, name):
    """Spec tuple of the first rule matching ``name`` (re.search
    semantics, the SNIPPETS.md convention), or None when nothing
    matches."""
    for pat, spec in rules:
        if pat.search(name) is not None:
            return spec
    return None


def match_partition_rules(rules, shapes, default=MXNetError):
    """{name: spec tuple} for every entry of ``shapes`` ({name: shape}).

    Scalar/one-element leaves are never partitioned (they get ``()``,
    the SNIPPETS.md convention).  A name no rule matches raises
    :class:`MXNetError` naming it — pass ``default=`` a spec tuple
    (e.g. ``()``) to replicate unmatched params instead."""
    out = {}
    for name, shape in shapes.items():
        shape = tuple(shape)
        if len(shape) == 0 or _nelem(shape) == 1:
            out[name] = ()
            continue
        spec = first_match(rules, name)
        if spec is None:
            if default is MXNetError:
                raise MXNetError(
                    "no reshard rule matches param %r (%d rule(s) "
                    "tried); add a catch-all '.*=' entry to replicate "
                    "unmatched params" % (name, len(rules)))
            spec = tuple(default)
        if len(spec) > len(shape):
            raise MXNetError(
                "reshard rule spec %r for param %r names %d dims but "
                "the param has %d" % (spec, name, len(spec), len(shape)))
        out[name] = tuple(spec)
    return out


def _nelem(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


# -------------------------------------------------------- mesh descriptors

def mesh_axes(mesh):
    """{axis name: size} of a ``jax.sharding.Mesh``."""
    return {str(k): int(v) for k, v in dict(mesh.shape).items()}


def parse_axes(spec):
    """``"data=4,model=2"`` → ``{"data": 4, "model": 2}`` (the
    build_mesh_from_axes/mesh-descriptor axes form); ``""``/``"1"`` →
    ``{}`` (single device).  The ONE parser behind every ``--mesh``
    flag (tools/reshard.py, tools/plan_search.py, the analysis CLI) so
    the grammar cannot drift between tools.  Raises ValueError naming
    the offending entry."""
    axes = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or part == "1":
            continue
        name, _, size = part.partition("=")
        if not name or not size.strip().isdigit():
            raise ValueError(
                "bad mesh entry %r (expected axis=size[,axis=size])"
                % part)
        axes[name.strip()] = int(size)
    return axes


def normalized_axes(axes):
    """Axes dict with size-1 axes dropped: ``{data:4, model:1}`` and
    ``{data:4}`` describe the same device grid, and a single device is
    ``{}`` under any naming."""
    return {k: int(v) for k, v in (axes or {}).items() if int(v) > 1}


def spec_to_json(spec):
    """PartitionSpec (or tuple) → JSON-able list, trailing replicated
    dims trimmed.  Tuple-of-axes entries (multi-axis sharding) are kept
    as lists."""
    out = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, (list, tuple)):
            out.append([str(a) for a in entry])
        else:
            out.append(str(entry))
    while out and out[-1] is None:
        out.pop()
    return out


def specs_from_tp_rules(tp_rules, shapes):
    """{name: spec tuple} from a ShardedTrainer ``tp_rules`` table
    ({name: sharded dim index} over the 'model' axis)."""
    out = {}
    for name, shape in shapes.items():
        spec = [None] * len(shape)
        if name in tp_rules:
            spec[tp_rules[name]] = "model"
        out[name] = tuple(spec)
    return out


def mesh_descriptor(mesh, specs=None, world=None):
    """JSON-able descriptor of a mesh + the param partition layout on
    it, recorded in checkpoint-manifest ``meta["mesh"]`` (schema v2).

    ``specs``: {param name: PartitionSpec/tuple}; ``world``: saving
    process count (defaults to ``jax.process_count()`` best-effort)."""
    if world is None:
        try:
            import jax
            world = int(jax.process_count())
        except Exception:  # mxlint: allow-broad-except(descriptor stays writable before/without a jax runtime; world then defaults to 1)
            world = 1
    doc = {"format": MESH_SCHEMA, "axes": mesh_axes(mesh),
           "world": int(world)}
    if specs is not None:
        doc["specs"] = {n: spec_to_json(s) for n, s in specs.items()}
    return doc


def manifest_mesh(manifest):
    """The mesh descriptor a checkpoint manifest carries, or None for
    v1/legacy manifests (pre-elastic checkpoints load unchanged)."""
    if not isinstance(manifest, dict):
        return None
    mesh = (manifest.get("meta") or {}).get("mesh")
    return mesh if isinstance(mesh, dict) else None


def manifest_data_state(manifest):
    """The durable data-iterator state entry a checkpoint manifest
    carries (``meta.data_state``, written by ``io_resume``), or None for
    manifests saved without one — loading such checkpoints simply skips
    the mid-epoch data resume."""
    if not isinstance(manifest, dict):
        return None
    entry = (manifest.get("meta") or {}).get("data_state")
    return entry if isinstance(entry, dict) else None


def same_mesh(a, b):
    """True when two descriptors name the same device grid (size-1 axes
    ignored — ``{data:4, model:1}`` == ``{data:4}`` == 4 devices on one
    axis)."""
    return normalized_axes((a or {}).get("axes")) == \
        normalized_axes((b or {}).get("axes"))


def describe_axes(desc):
    """Human form of a descriptor's axes, e.g. ``{data:4, model:2}``
    (``{1}`` for a single device)."""
    axes = normalized_axes((desc or {}).get("axes"))
    if not axes:
        return "{1}"
    return "{%s}" % ", ".join("%s:%d" % (k, axes[k]) for k in sorted(axes))


# --------------------------------------------------------------- planning

def plan_reshard(src_desc, dst_desc, shapes, dtype_bytes=4):
    """Validate + account a mesh reshape for a set of named arrays.

    ``src_desc``/``dst_desc``: mesh descriptors (src may be None —
    legacy checkpoint, unknown source layout); ``shapes``: {name:
    shape} of the arrays to move.  Returns a plan dict::

        {"params": {name: {"src": [...], "dst": [...], "resharded":
         bool}}, "n_params": N, "n_resharded": K, "bytes": B,
         "src": "{data:4, model:2}", "dst": "{data:8}"}

    where ``resharded`` marks names whose partition spec changes.
    Every dst spec is validated against the dst axes: a spec naming a
    missing axis, or sharding a dim the axis sizes do not divide,
    raises :class:`MXNetError` listing every offender — the caller's
    state is untouched, so the load degrades to the old-mesh error
    path."""
    src_specs = (src_desc or {}).get("specs") or {}
    dst_axes = normalized_axes((dst_desc or {}).get("axes"))
    # every axis NAME the target mesh declares, size-1 included: a
    # size-1 axis legitimately shards nothing, but a spec naming an
    # axis the mesh does not have at all is a typo'd rule table and
    # must fail loudly (the parse_rules contract), not silently
    # replicate the weight
    known_axes = set((dst_desc or {}).get("axes") or {})
    dst_specs = (dst_desc or {}).get("specs") or {}
    problems = []
    params = {}
    total_bytes = 0
    n_resharded = 0
    for name in sorted(shapes):
        shape = tuple(int(d) for d in shapes[name])
        src = list(src_specs.get(name) or ())
        dst = list(dst_specs.get(name) or ())
        for d, entry in enumerate(dst):
            if entry is None:
                continue
            axes = entry if isinstance(entry, (list, tuple)) else [entry]
            factor = 1
            for ax in axes:
                if str(ax) not in known_axes:
                    problems.append(
                        "%s: spec %r names axis %r which the target "
                        "mesh does not have (axes: %s)"
                        % (name, dst, ax, sorted(known_axes) or "{1}"))
                    continue
                factor *= dst_axes.get(str(ax), 1)
            if d >= len(shape):
                problems.append(
                    "%s: spec %r names dim %d but the param is %d-d"
                    % (name, dst, d, len(shape)))
            elif factor > 1 and shape[d] % factor:
                problems.append(
                    "%s: dim %d of shape %s is not divisible by the %d "
                    "shards of axis %r on the target mesh"
                    % (name, d, shape, factor, entry))
        resharded = _norm_spec(src) != _norm_spec(dst)
        if resharded:
            n_resharded += 1
        total_bytes += _nelem(shape) * dtype_bytes
        params[name] = {"src": src, "dst": dst, "resharded": resharded}
    if problems:
        raise MXNetError(
            "cannot reshard %s -> %s: %s"
            % (describe_axes(src_desc), describe_axes(dst_desc),
               "; ".join(problems)))
    return {"params": params, "n_params": len(params),
            "n_resharded": n_resharded, "bytes": total_bytes,
            "src": describe_axes(src_desc),
            "dst": describe_axes(dst_desc)}


def _norm_spec(spec):
    out = [list(e) if isinstance(e, (list, tuple)) else e for e in spec]
    while out and out[-1] is None:
        out.pop()
    return out


# ----------------------------------------------------------- observability

def note_reshape(kind, plan, seconds=None, epoch=None):
    """Record one completed mesh reshape: ``mxtpu_reshard_*`` metrics,
    a ``reshard`` flight event, and (when the step-log is on) a JSONL
    event record the launch.py run aggregator passes through into the
    ``mxtpu-run/1`` timeline."""
    from .. import telemetry
    from ..telemetry import flight as _flight
    telemetry.counter("mxtpu_reshard_total").labels(kind=str(kind)).inc()
    telemetry.counter("mxtpu_reshard_params_total").inc(
        plan.get("n_params", 0))
    telemetry.counter("mxtpu_reshard_bytes_total").inc(
        plan.get("bytes", 0))
    if seconds is not None:
        telemetry.histogram("mxtpu_reshard_seconds").observe(seconds)
    fields = {"reshard_kind": str(kind), "src": plan.get("src"),
              "dst": plan.get("dst"),
              "n_params": plan.get("n_params", 0),
              "n_resharded": plan.get("n_resharded", 0),
              "bytes": plan.get("bytes", 0)}
    if epoch is not None:
        fields["epoch"] = int(epoch)
    if seconds is not None:
        fields["seconds"] = round(seconds, 6)
    _flight.record("reshard", **fields)
    telemetry.jsonl_event("reshard", **fields)


def note_world_change(old_world, new_world, kind="load"):
    """Record a rank join/leave (world-size change across a resume):
    ``rank_join``/``rank_leave`` flight + JSONL events and the
    ``mxtpu_elastic_resizes_total`` counter.  No-op when the world is
    unchanged.  Returns the event name, or None."""
    old_world, new_world = int(old_world), int(new_world)
    if old_world == new_world:
        return None
    event = "rank_join" if new_world > old_world else "rank_leave"
    direction = "join" if new_world > old_world else "leave"
    from .. import telemetry
    from ..telemetry import flight as _flight
    telemetry.counter("mxtpu_elastic_resizes_total").labels(
        direction=direction).inc()
    fields = {"from_world": old_world, "to_world": new_world,
              "via": str(kind)}
    _flight.record(event, **fields)
    telemetry.jsonl_event(event, **fields)
    return event
