"""Communication overlap: bucketed async gradient allreduce.

Reference: the threaded dependency engine overlapped kvstore gradient
pushes with the still-running backward pass (SURVEY §3.1 — engine
pushes are asynchronous, so ``kv.push`` of layer N's gradient runs
while layer N-1's backward still computes).  The TPU port lost that:
``DistKVStore.push`` became a fleet-wide barrier-then-allreduce paid
synchronously at step end, and the PR 5/7 instruments prove the cost —
fast ranks pay ``mxtpu_collective_wait_seconds`` while idle and the
costdb roofline marks fused blocks bandwidth-bound, so cross-host
gradient traffic sits on the critical path (ROADMAP item 4).

This module restores the overlap, DDP-style (bucketed allreduce as in
PyTorch DistributedDataParallel, arXiv:1909.02061 ZeRO lineage):

* :func:`plan_buckets` groups gradients into size-targeted buckets
  (``MXNET_TPU_BUCKET_BYTES``, default 4 MiB) in push order — the
  order backward materializes cotangents;
* :class:`BucketQueue` launches each FULL bucket's cross-host
  allreduce the moment its last gradient lands (JAX dispatch is
  asynchronous, so the collective chains behind the still-running
  backward program instead of blocking the host), and drains all
  in-flight buckets only at the optimizer boundary;
* :class:`OverlapScheduler` orders the buckets still pending at drain
  time slowest-to-produce first, using the measured skew history.

CROSS-RANK DETERMINISM INVARIANT: every rank must launch the SAME
bucket sequence in the SAME order — mismatched collective order across
ranks deadlocks the fleet (the defect class MXG011 exists for; the
verifier models this module's schedule via ``build_config(kv_buckets=
...)``).  Two rules enforce it here:

1. the bucket plan derives only from the (deterministic) push order
   and per-key sizes, identical on every rank;
2. the scheduler's ordering consumes ONLY fleet-agreed measurements:
   the skew values returned by ``distview.pre_collective_barrier`` are
   allgathered timestamps, so every rank computes identical EWMAs and
   identical orders.  Rank-local wall clocks feed costdb/metrics but
   never the order.

Fault contract (the ``kvstore.collective`` seam): a collective fault
mid-drain raises a descriptive :class:`~mxnet_tpu.base.MXNetError`
BEFORE any result is handed to the caller — the caller applies
optimizer updates only after :meth:`BucketQueue.drain` returns, so a
failed drain leaves optimizer state untouched (no partially-applied
buckets).

Metrics (docs/api/telemetry.md): ``mxtpu_overlap_buckets_total{phase}``
(buckets launched — ``phase="backward"`` means the launch overlapped
gradient production, ``phase="drain"`` means it waited for the
optimizer boundary), ``mxtpu_overlap_bucket_bytes`` (payload size
distribution), ``mxtpu_overlap_drain_seconds`` (optimizer-boundary
drain wall), ``mxtpu_overlap_inflight_buckets`` (gauge).  Each launch
leaves an ``overlap`` flight event; each drained bucket leaves a
costdb ``collective`` record (blocked-wait wall + bytes + mesh, keyed
per launch phase) — the un-hidden network cost on the critical path,
which is the cost the roofline consumers should attribute.

Knobs: ``MXNET_TPU_OVERLAP`` (default on) gates the bucketed path in
``DistKVStore``/``model._update_params*``; ``MXNET_TPU_BUCKET_BYTES``
sets the bucket size target.  See docs/api/overlap.md.
"""
from __future__ import annotations

import time

from ..base import MXNetError

__all__ = [
    "overlap_enabled", "bucket_bytes", "max_inflight", "plan_buckets",
    "OverlapScheduler", "BucketQueue",
]

DEFAULT_BUCKET_BYTES = 4 << 20

#: byte-scale histogram buckets for the bucket-payload distribution
BYTE_BUCKETS = (1 << 10, 16 << 10, 256 << 10, 1 << 20, 4 << 20,
                16 << 20, 64 << 20, 256 << 20, 1 << 30)


def overlap_enabled():
    """Whether the bucketed-overlap path is on (``MXNET_TPU_OVERLAP``,
    default enabled — bit-parity with the per-push path is tested, so
    overlap is not an accuracy trade)."""
    import os
    return os.environ.get("MXNET_TPU_OVERLAP", "1") not in \
        ("0", "false", "False")


def bucket_bytes():
    """Bucket size target in bytes (``MXNET_TPU_BUCKET_BYTES``,
    default 4 MiB — the DDP default neighborhood; smaller buckets
    start communication earlier, larger ones amortize per-collective
    overhead)."""
    import os
    try:
        n = int(os.environ.get("MXNET_TPU_BUCKET_BYTES",
                               str(DEFAULT_BUCKET_BYTES)))
    except ValueError:
        n = DEFAULT_BUCKET_BYTES
    return max(1, n)


def max_inflight():
    """Launch-window cap (``MXNET_TPU_OVERLAP_INFLIGHT``, default 0 =
    unlimited): with a positive cap, a bucket that fills while the cap
    is reached is deferred instead of launched — the deferred buckets
    launch at the optimizer boundary in the scheduler's
    slowest-to-produce-first order.  Bounding in-flight collectives
    trades some backward overlap for less network contention; the
    default keeps every launch eager."""
    import os
    try:
        n = int(os.environ.get("MXNET_TPU_OVERLAP_INFLIGHT", "0"))
    except ValueError:
        n = 0
    return max(0, n)


def plan_buckets(sizes, target_bytes=None):
    """Greedy size-targeted bucket plan over ``sizes`` (an ordered
    ``[(key, nbytes)]`` in gradient-production order).  Returns a list
    of buckets, each a list of keys; a bucket closes once its payload
    reaches ``target_bytes`` (single oversized keys get their own
    bucket).  Deterministic: the plan is a pure function of the input
    order and sizes, so every rank computes the same plan — the first
    half of the cross-rank determinism invariant."""
    target = bucket_bytes() if target_bytes is None else \
        max(1, int(target_bytes))
    buckets, cur, cur_bytes = [], [], 0
    for key, nbytes in sizes:
        cur.append(key)
        cur_bytes += max(0, int(nbytes))
        if cur_bytes >= target:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return buckets


class OverlapScheduler:
    """Slowest-to-produce-first drain ordering from the skew history.

    Each measured bucket boundary (the sampled
    ``pre_collective_barrier``) yields a fleet-agreed skew value — the
    straggler's lead at that bucket.  The scheduler keeps a per-bucket
    EWMA of those values and orders the buckets pending at drain time
    by descending EWMA (ties by bucket id): the bucket that
    historically arrives last starts first, so its transfer gets the
    longest window to hide under the others' completion.

    DETERMINISM: feed :meth:`observe_skew` only fleet-identical values
    (allgathered skews).  Rank-local wall times must not enter — a
    rank-divergent order deadlocks the fleet (see the module
    docstring and MXG011).
    """

    def __init__(self, alpha=0.3):
        self._alpha = float(alpha)
        self._ewma = {}

    def observe_skew(self, bucket_id, skew_s):
        """Fold one fleet-agreed skew measurement into the EWMA of
        ``bucket_id``."""
        if skew_s is None:
            return
        prev = self._ewma.get(bucket_id)
        v = float(skew_s)
        self._ewma[bucket_id] = v if prev is None else \
            (1 - self._alpha) * prev + self._alpha * v

    def cost(self, bucket_id):
        return self._ewma.get(bucket_id, 0.0)

    def order(self, bucket_ids):
        """Drain order for the pending buckets: slowest (highest skew
        EWMA) first, bucket id breaking ties — identical on every rank
        because the EWMAs are."""
        return sorted(bucket_ids,
                      key=lambda b: (-self._ewma.get(b, 0.0), b))


class _Bucket:
    __slots__ = ("bucket_id", "keys", "values", "nbytes", "handle",
                 "phase")

    def __init__(self, bucket_id):
        self.bucket_id = bucket_id
        self.keys = []
        self.values = {}
        self.nbytes = 0
        self.handle = None
        self.phase = None


class BucketQueue:
    """Size-targeted gradient buckets with async launch + ordered drain.

    ``reduce_fn(bucket_dict)`` launches one bucket's cross-host
    allreduce and returns a zero-argument callable that materializes
    ``{key: reduced}`` — for ``DistKVStore`` the launch is the jitted
    pytree allreduce (async JAX dispatch: the call returns while the
    collective chains behind the in-flight backward) and the handle
    just converts the already-dispatched arrays.  Alternative
    transports (the 2-process dry-run gate uses a filesystem
    allreduce) plug in the same way.

    :meth:`push` appends one (key, value) in production order and
    launches the bucket once it reaches the byte target.  :meth:`drain`
    launches the remainder in scheduler order, materializes EVERY
    in-flight handle, and only then returns the merged results — the
    all-or-nothing contract the chaos test pins: a collective fault
    mid-drain (the ``kvstore.collective`` seam, or any transport error)
    raises a descriptive MXNetError with nothing handed to the caller,
    so optimizer state is untouched.
    """

    def __init__(self, reduce_fn, target_bytes=None, site="kvstore.push",
                 scheduler=None, skew_probe=None, inflight_cap=None):
        from ..telemetry.registry import counter, gauge, histogram
        self._reduce = reduce_fn
        self._target = bucket_bytes() if target_bytes is None else \
            max(1, int(target_bytes))
        self._site = site
        self.scheduler = scheduler or OverlapScheduler()
        # the sampled bucket-boundary skew measurement; overridable so
        # transports without a jax.distributed fleet (tests, the
        # ci_check file-transport worker) can supply their own
        self._skew_probe = skew_probe or self._default_skew_probe
        # launch window (0 = unlimited): buckets that fill while the
        # window is closed defer to the drain, where the scheduler
        # orders them — the reachable half of slowest-first draining
        self._cap = max_inflight() if inflight_cap is None else \
            max(0, int(inflight_cap))
        self._next_id = 0
        self._open = None            # the bucket currently filling
        self._ready = []             # full, deferred by the launch cap
        self._inflight = []          # launched, not yet materialized
        self._step_keys = set()      # keys pushed since the last drain
        self._launched = counter("mxtpu_overlap_buckets_total")
        self._bytes_h = histogram("mxtpu_overlap_bucket_bytes",
                                  buckets=BYTE_BUCKETS)
        self._drain_h = histogram("mxtpu_overlap_drain_seconds")
        self._inflight_g = gauge("mxtpu_overlap_inflight_buckets")
        self.last_skew = None

    def _default_skew_probe(self):
        from ..telemetry import distview
        return distview.pre_collective_barrier(self._site)

    def _reset_step(self):
        """Discard every bucket of the current step — open, deferred,
        and in-flight — so the queue is reusable after a failure.
        In-flight handles are dropped unmaterialized: a step that
        errored must never have its partial buckets applied later."""
        self._open = None
        self._ready = []
        self._inflight = []
        self._inflight_g.set(0)
        self._next_id = 0
        self._step_keys = set()

    # ------------------------------------------------------------ filling
    def push(self, key, value, nbytes):
        """Append one gradient in production order; a bucket reaching
        the byte target launches immediately (``phase="backward"`` —
        the transfer overlaps the rest of gradient production) unless
        the launch window (``MXNET_TPU_OVERLAP_INFLIGHT``) is closed,
        in which case it defers to the drain's scheduler ordering.

        A launch failure here resets the whole step (same contract as
        a failed drain): the error propagates before the optimizer
        boundary, nothing was applied, and the queue is reusable."""
        if key in self._step_keys:
            # checked against EVERY bucket this step, not just the open
            # one — a duplicate straddling a bucket boundary would
            # otherwise allreduce twice and silently keep one result
            raise MXNetError(
                "a bucket already holds key %r this step — push each "
                "gradient key once per step, then drain()" % (key,))
        self._step_keys.add(key)
        if self._open is None:
            self._open = _Bucket(self._next_id)
            self._next_id += 1
        b = self._open
        b.keys.append(key)
        b.values[key] = value
        b.nbytes += max(0, int(nbytes))
        if b.nbytes >= self._target:
            self._open = None
            if self._cap and len(self._inflight) >= self._cap:
                self._ready.append(b)
            else:
                try:
                    self._launch(b, phase="backward")
                except BaseException:  # mxlint: allow-broad-except(reset-then-reraise — nothing is swallowed)
                    # a poisoned step must not leak its keys (the next
                    # attempt would see false duplicates) or its
                    # in-flight buckets (a later drain would apply a
                    # dead step's partial gradients)
                    self._reset_step()
                    raise

    @property
    def pending(self):
        """Buckets launched, deferred, or filling — not yet drained."""
        return len(self._inflight) + len(self._ready) + \
            (1 if self._open else 0)

    # ----------------------------------------------------------- launching
    def _launch(self, bucket, phase):
        """Dispatch one bucket's allreduce.  The sampled skew probe runs
        FIRST (the bucket boundary is the measurement point the
        per-push path used to have at every key) and its fleet-agreed
        skew feeds the scheduler; the ``kvstore.collective`` seam fires
        here so chaos specs can fault any launch, including mid-drain."""
        from .. import resilience
        from ..telemetry import flight as _flight
        bucket.phase = phase
        info = None
        try:
            info = self._skew_probe()
        except Exception:  # mxlint: allow-broad-except(the skew probe is optional instrumentation; a failed barrier degrades to unmeasured skew, never a dead drain)
            info = None
        if info is not None:
            self.last_skew = info
            self.scheduler.observe_skew(bucket.bucket_id,
                                        info.get("skew_s"))
        ev = {"op": "bucket_launch", "site": self._site,
              "bucket": bucket.bucket_id, "keys": len(bucket.keys),
              "bytes": bucket.nbytes, "phase": phase}
        if info is not None:
            ev["skew_s"] = round(info["skew_s"], 6)
            ev["wait_s"] = round(info["wait_s"], 6)
        _flight.record("overlap", **ev)
        try:
            resilience.fault_point("kvstore.collective")
            bucket.handle = self._reduce(dict(bucket.values))
        except MXNetError:
            raise
        except Exception as e:  # mxlint: allow-broad-except(re-raised as a descriptive MXNetError naming the bucket — any transport/backend failure must carry the drain contract, not a raw traceback)
            raise MXNetError(
                "bucketed allreduce launch failed for bucket %d "
                "(%d key(s), %d bytes) at %s: %s"
                % (bucket.bucket_id, len(bucket.keys), bucket.nbytes,
                   self._site, e)) from e
        self._launched.labels(phase=phase).inc()
        self._bytes_h.observe(float(bucket.nbytes))
        self._inflight.append(bucket)
        self._inflight_g.set(len(self._inflight))

    # ------------------------------------------------------------ draining
    def drain(self, mesh=None):
        """Launch every still-pending bucket (scheduler order,
        slowest-to-produce first), materialize ALL in-flight handles,
        and return the merged ``{key: reduced}``.

        All-or-nothing: any failure — an armed ``kvstore.collective``
        fault, a transport error, a dead peer — discards every bucket
        and raises a descriptive MXNetError naming the bucket; nothing
        is returned, so a caller that applies optimizer updates only
        from the return value leaves its state untouched.  The queue
        itself is reset and reusable after a failed drain (the next
        step pushes into fresh buckets)."""
        from ..telemetry import costdb as _costdb
        from ..telemetry import flight as _flight
        t0 = time.perf_counter()
        # the drain tail: buckets the launch window deferred, plus the
        # partial open bucket — the set the scheduler actually orders
        tail = list(self._ready)
        self._ready = []
        if self._open is not None and self._open.keys:
            tail.append(self._open)
        self._open = None
        order = {b.bucket_id: b for b in tail}
        try:
            for bid in self.scheduler.order(sorted(order)):
                self._launch(order[bid], phase="drain")
            results = {}
            for b in self._inflight:
                t_wait = time.perf_counter()
                try:
                    reduced = b.handle()
                except MXNetError:
                    raise
                except Exception as e:  # mxlint: allow-broad-except(re-raised as a descriptive MXNetError carrying the all-or-nothing drain contract; the raw transport error is chained)
                    raise MXNetError(
                        "bucketed allreduce failed for bucket %d "
                        "(%d key(s), %d bytes) at %s: %s — no buckets "
                        "were applied; optimizer state is untouched"
                        % (b.bucket_id, len(b.keys), b.nbytes,
                           self._site, e)) from e
                # the record's wall is the time the drain sat BLOCKED
                # on this bucket — the network cost overlap failed to
                # hide (a bucket that finished behind backward reads
                # ~0).  launch-to-materialize would span the whole
                # overlapped backward for phase="backward" buckets, so
                # the better the overlap worked the more
                # bandwidth-bound the roofline would wrongly read the
                # collectives.  block_kind keys backward-launched and
                # drain-launched buckets to separate records: a hidden
                # bucket's ~0 wall must not become the min-wall of the
                # unhidden drain tail's roofline estimate.
                wall = time.perf_counter() - t_wait
                _costdb.record(
                    "collective", "%s.bucket" % self._site,
                    wall_s=wall, bytes_accessed=float(b.nbytes),
                    shapes=[[len(b.keys)]], mesh=mesh,
                    block_kind=b.phase,
                    source="overlap-drain")
                results.update(reduced)
            dt = time.perf_counter() - t0
            self._drain_h.observe(dt)
            _flight.record("overlap", op="drain", site=self._site,
                           buckets=len(self._inflight),
                           seconds=round(dt, 6))
            return results
        except MXNetError as e:
            # a mid-drain fault must be explicit about the state
            # contract even when it fired at a launch seam
            if "optimizer state" not in str(e):
                e = MXNetError(
                    "%s — drain aborted before any result was handed "
                    "to the caller; no buckets were applied and "
                    "optimizer state is untouched" % e)
            raise e
        finally:
            # per-STEP bucket ids: the plan is deterministic, so bucket
            # N holds the same key set every step — resetting them here
            # is what lets the scheduler's EWMA accumulate a history
            # per bucket (monotonic ids would key every skew
            # observation to a fresh id, leaving every EWMA a single
            # sample)
            self._reset_step()
