"""RecordIO: the reference's binary record container.

Reference: ``python/mxnet/recordio.py`` + dmlc-core recordio (magic-framed
records, `.idx` sidecar for random seek — SURVEY §2.1 Data IO row; C API
MXRecordIO* `src/c_api/c_api.cc:710-787`).  Pure-python implementation
writing the SAME on-disk format so `.rec` datasets interop with the
reference's tools (im2rec).

Format per record: [uint32 magic][uint32 lrecord][data][padding to 4B]
where lrecord encodes cflag (upper 3 bits) and length (lower 29 bits).
"""
from __future__ import annotations

import os
import struct
import time
from collections import namedtuple

import numpy as np

from .base import MXNetError
from . import telemetry
from .telemetry import ioview as _ioview

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

# per-record counters, bound once (hot path: one locked add per record)
_REC_READS = telemetry.counter(
    "mxtpu_io_records_total").labels(source="recordio")
_REC_BAD = telemetry.counter(
    "mxtpu_io_bad_records_total").labels(source="recordio")
_REC_RESYNCS = telemetry.counter(
    "mxtpu_io_resyncs_total").labels(source="recordio")
_REC_SKIPPED = telemetry.counter(
    "mxtpu_io_skipped_bytes_total").labels(source="recordio")

_MAGIC = 0xced7230a
_LENGTH_MASK = (1 << 29) - 1


class MXRecordIO:
    """Sequential record reader/writer (reference MXRecordIO).

    ``skip_bad_records`` (or the ``MXNET_TPU_BAD_RECORD_QUOTA`` env)
    arms tolerant reads: a corrupt or truncated record is skipped by
    scanning forward to the next 4-aligned magic word instead of raising
    ``IOError``, up to that many records per reader.  Skips are counted
    on ``bad_records``/``skipped_bytes``/``resyncs`` so callers can
    surface data loss; exceeding the quota raises ``IOError`` naming
    the file and count.  Default quota 0 = strict (reference behavior).
    """

    def __init__(self, uri, flag, skip_bad_records=None):
        self.uri = uri
        self.flag = flag
        self.fid = None
        if skip_bad_records is None:
            from . import config
            skip_bad_records = config.get_int("MXNET_TPU_BAD_RECORD_QUOTA")
        self._bad_quota = int(skip_bad_records)
        self.bad_records = 0
        self.skipped_bytes = 0
        self.resyncs = 0
        self.records_read = 0
        self._epochs = 0
        self.open()

    def open(self):
        if self.flag == "w":
            self.fid = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fid = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self.fid.close()
            self.is_open = False

    def __del__(self):
        # interpreter-teardown close: only swallow the I/O errors a
        # half-constructed or already-closed reader can raise — a bare
        # ``except Exception`` here used to hide real parse bugs
        try:
            self.close()
        except (OSError, ValueError, AttributeError):
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["fid"] = None
        d["is_open"] = False
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()
        if self.flag == "r":
            pass

    def reset(self):
        self.close()
        self.open()
        self._epochs += 1
        self.records_read = 0

    def position(self):
        """Advisory reader position for the data-plane observability
        layer (``telemetry.ioview``): records read this epoch, the
        byte offset, and the corruption-resync count."""
        pos = {"epoch": self._epochs, "offset": self.records_read,
               "resyncs": self.resyncs}
        try:
            pos["byte"] = self.fid.tell() if self.is_open else None
        except (OSError, ValueError, AttributeError):
            pass
        return pos

    def state(self):
        """Durable reader state (``mxnet_tpu.io_resume`` contract):
        epoch, records read, and the exact byte offset of the next
        unread record."""
        if self.writable:
            return None
        from . import io_resume
        return {"v": io_resume.STATE_VERSION, "kind": "recordio",
                "epoch": self._epochs, "offset": self.records_read,
                "byte": int(self.fid.tell())}

    def restore(self, state):
        """Reopen at the recorded byte offset (validate-then-commit: a
        rejected state leaves the open reader untouched)."""
        from . import io_resume
        io_resume.check_state(state, "recordio")
        if self.writable:
            raise MXNetError("cannot restore a writable MXRecordIO")
        byte = int(state["byte"])
        if byte < 0 or byte % 4 != 0:
            raise MXNetError(
                "recordio byte offset %d is not a 4-aligned record "
                "boundary in %s" % (byte, self.uri))
        self.close()
        self.open()
        self.fid.seek(byte)
        self._epochs = int(state["epoch"])
        self.records_read = int(state["offset"])

    def tell(self):
        return self.fid.tell()

    def seek(self, pos):
        assert not self.writable
        self.fid.seek(pos)

    def _write_part(self, part, cflag):
        lrec = (len(part) & _LENGTH_MASK) | (cflag << 29)
        self.fid.write(struct.pack("<II", _MAGIC, lrec))
        self.fid.write(part)
        pad = (4 - (len(part) % 4)) % 4
        if pad:
            self.fid.write(b"\x00" * pad)

    def write(self, buf):
        """Write one framed record.

        dmlc recordio semantics: the payload is split at 4-aligned
        occurrences of the magic word (the occurrence is dropped and
        re-inserted by the reader), with continuation flags 1/2/3 in the
        upper bits of lrec — so payloads containing the magic (JPEG bytes
        can) stay seekable and round-trip with the reference reader.
        """
        assert self.writable
        magic_bytes = struct.pack("<I", _MAGIC)
        parts = []
        start = 0
        # bytes.find skips straight to candidate matches (the magic almost
        # never occurs); only 4-aligned hits are split points
        pos = buf.find(magic_bytes)
        while pos != -1:
            if pos % 4 == 0:
                parts.append(buf[start:pos])
                start = pos + 4
                pos = buf.find(magic_bytes, start)
            else:
                pos = buf.find(magic_bytes, pos + 1)
        parts.append(buf[start:])
        if len(parts) == 1:
            self._write_part(buf, 0)
            return
        for i, part in enumerate(parts):
            cflag = 1 if i == 0 else (3 if i == len(parts) - 1 else 2)
            self._write_part(part, cflag)

    def _read_part(self):
        header = self.fid.read(8)
        if len(header) < 8:
            return None, None
        magic, lrec = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise IOError("Invalid magic number in %s" % self.uri)
        length = lrec & _LENGTH_MASK
        buf = self.fid.read(length)
        if len(buf) < length:
            # a corrupt length field reads to EOF silently otherwise,
            # losing the rest of the file behind a garbage record
            raise IOError("truncated record in %s: header claims %d "
                          "bytes, file has %d"
                          % (self.uri, length, len(buf)))
        pad = (4 - (length % 4)) % 4
        if pad:
            self.fid.read(pad)
        return lrec >> 29, buf

    def _read_record(self):
        """One framed record, or None at EOF (re-joins continuation
        parts with the magic word re-inserted).  Strict: corruption
        raises IOError."""
        cflag, buf = self._read_part()
        if buf is None:
            return None
        if cflag == 0:
            return buf
        if cflag != 1:
            raise IOError("continuation part without start in %s" % self.uri)
        magic_bytes = struct.pack("<I", _MAGIC)
        parts = [buf]
        while True:
            cflag, part = self._read_part()
            if part is None or cflag not in (2, 3):
                raise IOError("truncated continuation record in %s"
                              % self.uri)
            parts.append(magic_bytes)
            parts.append(part)
            if cflag == 3:
                return b"".join(parts)

    def _note_bad_record(self, exc):
        """Count one corrupt/truncated record against the quota;
        re-raises when no quota is configured, IOError when the quota
        is exhausted."""
        if self._bad_quota <= 0:
            raise exc
        self.bad_records += 1
        _REC_BAD.inc()
        if self.bad_records > self._bad_quota:
            raise IOError(
                "%s: bad-record quota exhausted (%d corrupt/truncated "
                "records > quota %d); last error: %s"
                % (self.uri, self.bad_records, self._bad_quota,
                   exc)) from exc
        import logging
        logging.warning("%s: skipping corrupt record (%d/%d under "
                        "quota): %s", self.uri, self.bad_records,
                        self._bad_quota, exc)

    def _resync(self):
        """Scan forward to the next 4-aligned magic word (dmlc recordio
        framing makes every record boundary one).  Returns False at EOF.
        Skipped bytes are accounted on ``skipped_bytes``."""
        magic_bytes = struct.pack("<I", _MAGIC)
        start = self.fid.tell()
        start += (-start) % 4
        self.fid.seek(start)
        base, tail = start, b""
        while True:
            chunk = self.fid.read(1 << 16)
            if not chunk:
                self.skipped_bytes += base + len(tail) - start
                _REC_SKIPPED.inc(max(0, base + len(tail) - start))
                return False
            buf = tail + chunk
            i = buf.find(magic_bytes)
            while i != -1:
                off = base + i
                if off % 4 == 0 and off >= start:
                    self.fid.seek(off)
                    self.resyncs += 1
                    self.skipped_bytes += off - start
                    _REC_RESYNCS.inc()
                    _REC_SKIPPED.inc(off - start)
                    return True
                i = buf.find(magic_bytes, i + 1)
            keep = min(3, len(buf))
            base += len(buf) - keep
            tail = buf[len(buf) - keep:]

    def read(self):
        """Read the next record, or None at EOF.

        With a bad-record quota (see the constructor) corrupt or
        truncated records are skipped by magic-resync and counted
        instead of raising; the ``recordio.read`` fault seam
        (resilience.py) injects per-record corruption here for chaos
        tests — an injected fault drops the record it would have
        returned, exactly like real corruption."""
        assert not self.writable
        from . import resilience
        t0 = time.perf_counter()
        while True:
            # remember where this record starts: a corrupt length field
            # can drag the file position to EOF, so resync must restart
            # just past THIS record's magic, not from wherever the
            # failed read left off
            start = self.fid.tell()
            try:
                resilience.fault_point("recordio.read")
                rec = self._read_record()
                if rec is not None:
                    _REC_READS.inc()
                    self.records_read += 1
                    # ioview "read" stage: framing + file IO wall time
                    # per record (resync scans after corruption included
                    # — they ARE read-stage work)
                    _ioview.account("read", time.perf_counter() - t0,
                                    items=1, nbytes=len(rec))
                return rec
            except resilience.FaultInjected as e:
                self._note_bad_record(e)
                try:
                    # the injected fault stands for a corrupt payload:
                    # consume and drop one record, continue with the next
                    if self._read_record() is None:
                        return None
                except (IOError, OSError, struct.error, ValueError) as e2:
                    self._note_bad_record(e2)
                    self.fid.seek(start + 4)
                    if not self._resync():
                        return None
            except (IOError, OSError, struct.error, ValueError) as e:
                self._note_bad_record(e)
                self.fid.seek(start + 4)
                if not self._resync():
                    return None


class MXIndexedRecordIO(MXRecordIO):
    """Random-access reader/writer with `.idx` sidecar
    (reference MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for lineno, raw in enumerate(fin, 1):
                    try:
                        line = raw.strip().split("\t")
                        key = self.key_type(line[0])
                        self.idx[key] = int(line[1])
                    except (ValueError, IndexError) as e:
                        raise IOError(
                            "corrupt index %s:%d (%r): %s"
                            % (self.idx_path, lineno, raw.strip(), e)) \
                            from e
                    self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write("%s\t%d\n" % (str(k), self.idx[k]))
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


# header of an image record (reference recordio.py IRHeader)
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack an IRHeader + payload into a record string
    (reference recordio.pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        header = header._replace(label=float(header.label))
        packed = struct.pack(_IR_FORMAT, 0, header.label, header.id,
                             header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        packed = struct.pack(_IR_FORMAT, header.flag, header.label,
                             header.id, header.id2) + label.tobytes()
    return packed + s


def unpack(s):
    """Unpack a record into (IRHeader, payload).

    The flag/header parse catches only ``struct.error``/``ValueError``
    (truncated or malformed headers) and re-raises with the original
    message preserved — anything else is a real bug and propagates."""
    try:
        header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
        s = s[_IR_SIZE:]
        if header.flag > 0:
            label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
            header = header._replace(label=label)
            s = s[header.flag * 4:]
    except (struct.error, ValueError) as e:
        raise ValueError("invalid IRHeader in %d-byte record: %s"
                         % (len(s), e)) from e
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (reference recordio.pack_img; PIL instead of
    OpenCV)."""
    import io as _pyio
    from PIL import Image
    im = Image.fromarray(img.astype(np.uint8))
    buf = _pyio.BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    kwargs = {"quality": quality} if fmt == "JPEG" else {}
    im.save(buf, format=fmt, **kwargs)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1):
    """Unpack a record into (IRHeader, image array)."""
    import io as _pyio
    from PIL import Image
    header, img_bytes = unpack(s)
    im = Image.open(_pyio.BytesIO(img_bytes))
    if iscolor == 0:
        im = im.convert("L")
    elif iscolor == 1:
        im = im.convert("RGB")
    return header, np.asarray(im)
