"""RecordIO: the reference's binary record container.

Reference: ``python/mxnet/recordio.py`` + dmlc-core recordio (magic-framed
records, `.idx` sidecar for random seek — SURVEY §2.1 Data IO row; C API
MXRecordIO* `src/c_api/c_api.cc:710-787`).  Pure-python implementation
writing the SAME on-disk format so `.rec` datasets interop with the
reference's tools (im2rec).

Format per record: [uint32 magic][uint32 lrecord][data][padding to 4B]
where lrecord encodes cflag (upper 3 bits) and length (lower 29 bits).
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LENGTH_MASK = (1 << 29) - 1


class MXRecordIO:
    """Sequential record reader/writer (reference MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.fid = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.fid = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fid = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self.fid.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["fid"] = None
        d["is_open"] = False
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()
        if self.flag == "r":
            pass

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.fid.tell()

    def seek(self, pos):
        assert not self.writable
        self.fid.seek(pos)

    def _write_part(self, part, cflag):
        lrec = (len(part) & _LENGTH_MASK) | (cflag << 29)
        self.fid.write(struct.pack("<II", _MAGIC, lrec))
        self.fid.write(part)
        pad = (4 - (len(part) % 4)) % 4
        if pad:
            self.fid.write(b"\x00" * pad)

    def write(self, buf):
        """Write one framed record.

        dmlc recordio semantics: the payload is split at 4-aligned
        occurrences of the magic word (the occurrence is dropped and
        re-inserted by the reader), with continuation flags 1/2/3 in the
        upper bits of lrec — so payloads containing the magic (JPEG bytes
        can) stay seekable and round-trip with the reference reader.
        """
        assert self.writable
        magic_bytes = struct.pack("<I", _MAGIC)
        parts = []
        start = 0
        # bytes.find skips straight to candidate matches (the magic almost
        # never occurs); only 4-aligned hits are split points
        pos = buf.find(magic_bytes)
        while pos != -1:
            if pos % 4 == 0:
                parts.append(buf[start:pos])
                start = pos + 4
                pos = buf.find(magic_bytes, start)
            else:
                pos = buf.find(magic_bytes, pos + 1)
        parts.append(buf[start:])
        if len(parts) == 1:
            self._write_part(buf, 0)
            return
        for i, part in enumerate(parts):
            cflag = 1 if i == 0 else (3 if i == len(parts) - 1 else 2)
            self._write_part(part, cflag)

    def _read_part(self):
        header = self.fid.read(8)
        if len(header) < 8:
            return None, None
        magic, lrec = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise IOError("Invalid magic number in %s" % self.uri)
        length = lrec & _LENGTH_MASK
        buf = self.fid.read(length)
        pad = (4 - (length % 4)) % 4
        if pad:
            self.fid.read(pad)
        return lrec >> 29, buf

    def read(self):
        """Read the next record, or None at EOF (re-joins continuation
        parts with the magic word re-inserted)."""
        assert not self.writable
        cflag, buf = self._read_part()
        if buf is None:
            return None
        if cflag == 0:
            return buf
        if cflag != 1:
            raise IOError("continuation part without start in %s" % self.uri)
        magic_bytes = struct.pack("<I", _MAGIC)
        parts = [buf]
        while True:
            cflag, part = self._read_part()
            if part is None or cflag not in (2, 3):
                raise IOError("truncated continuation record in %s"
                              % self.uri)
            parts.append(magic_bytes)
            parts.append(part)
            if cflag == 3:
                return b"".join(parts)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access reader/writer with `.idx` sidecar
    (reference MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write("%s\t%d\n" % (str(k), self.idx[k]))
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


# header of an image record (reference recordio.py IRHeader)
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack an IRHeader + payload into a record string
    (reference recordio.pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        header = header._replace(label=float(header.label))
        packed = struct.pack(_IR_FORMAT, 0, header.label, header.id,
                             header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        packed = struct.pack(_IR_FORMAT, header.flag, header.label,
                             header.id, header.id2) + label.tobytes()
    return packed + s


def unpack(s):
    """Unpack a record into (IRHeader, payload)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (reference recordio.pack_img; PIL instead of
    OpenCV)."""
    import io as _pyio
    from PIL import Image
    im = Image.fromarray(img.astype(np.uint8))
    buf = _pyio.BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    kwargs = {"quality": quality} if fmt == "JPEG" else {}
    im.save(buf, format=fmt, **kwargs)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1):
    """Unpack a record into (IRHeader, image array)."""
    import io as _pyio
    from PIL import Image
    header, img_bytes = unpack(s)
    im = Image.open(_pyio.BytesIO(img_bytes))
    if iscolor == 0:
        im = im.convert("L")
    elif iscolor == 1:
        im = im.convert("RGB")
    return header, np.asarray(im)
