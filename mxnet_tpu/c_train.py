"""Python driver for the C training ABI (src/c_train_api.cc).

The reference exposes ~150 flat C functions over its C++ executor
(include/mxnet/c_api.h); here the executor is jax/XLA reached through
Python, so the native training library embeds CPython and drives THIS
class — the same architecture as the predict ABI
(`mxnet_tpu/predictor.py` ↔ src/c_predict_api.cc).  The slice covers
what a non-Python embedding needs for a train loop: bind (with
initialization), set inputs, forward, backward, read outputs/grads/
args, and an SGD-momentum update — the
`cpp-package/include/mxnet-cpp/executor.h` Forward/Backward + optimizer
Update flow.
"""
from __future__ import annotations

import numpy as np

from . import symbol as sym_mod
from . import ndarray as nd
from .base import MXNetError
from .context import Context
from .initializer import Xavier, InitDesc

__all__ = ["TrainSession"]


class TrainSession:
    """One bound training executor + optimizer state for the C ABI."""

    def __init__(self, symbol_json, input_shapes, dev_type="cpu",
                 dev_id=0, seed=0):
        self.symbol = sym_mod.load_json(symbol_json)
        ctx = Context(dev_type, dev_id)
        self._input_names = list(input_shapes)
        arg_names = self.symbol.list_arguments()
        # inputs get grad_req null; parameters write
        reqs = {n: ("null" if n in input_shapes else "write")
                for n in arg_names}
        self.executor = self.symbol.simple_bind(
            ctx=ctx, grad_req=reqs,
            **{k: tuple(v) for k, v in input_shapes.items()})

        self._param_names = [n for n in arg_names
                             if n not in input_shapes]
        init = Xavier(rnd_type="gaussian", factor_type="in", magnitude=2)
        np.random.seed(seed)
        attrs = self.symbol.attr_dict()
        # initializers write NDArrays in place (Module.init_params does
        # the same); aux states too — the name-pattern rules set
        # moving_var to 1, moving_mean to 0
        for name in self._param_names:
            init(InitDesc(name, attrs.get(name)),
                 self.executor.arg_dict[name])
        for name, arr in zip(self.symbol.list_auxiliary_states(),
                             self.executor.aux_arrays):
            init(InitDesc(name, attrs.get(name)), arr)
        self._momentum = {}

    # ------------------------------------------------------------- inputs
    def set_input(self, name, value):
        if name not in self._input_names:
            raise MXNetError("unknown input %r (have %s)"
                             % (name, self._input_names))
        arr = self.executor.arg_dict[name]
        value = np.asarray(value, np.float32).reshape(arr.shape)
        arr[:] = value

    # -------------------------------------------------------------- steps
    def forward(self, is_train):
        self.executor.forward(is_train=bool(is_train))

    def backward(self):
        self.executor.backward()

    def sgd_update(self, lr, momentum=0.0, wd=0.0, rescale_grad=1.0):
        """Apply one SGD(-momentum) step to every bound parameter from
        its gradient (the reference cpp-package optimizer Update loop,
        executor-granular rather than fused).  Loss heads emit
        per-example gradient SUMS (reference convention), so callers
        normally pass rescale_grad = 1/batch — exactly the
        Module.init_optimizer default."""
        for name in self._param_names:
            w = self.executor.arg_dict[name]
            g = self.executor.grad_dict[name].asnumpy() * rescale_grad
            if wd:
                g = g + wd * w.asnumpy()
            if momentum:
                m = momentum * self._momentum.get(
                    name, np.zeros(w.shape, np.float32)) - lr * g
                self._momentum[name] = m
                w[:] = w.asnumpy() + m
            else:
                w[:] = w.asnumpy() - lr * g

    # ------------------------------------------------------------ readout
    def num_outputs(self):
        return len(self.executor.outputs)

    def get_output(self, index):
        return np.ascontiguousarray(
            self.executor.outputs[index].asnumpy(), np.float32)

    def get_output_shape(self, index):
        return tuple(self.executor.outputs[index].shape)

    def get_array(self, name, kind):
        d = (self.executor.arg_dict if kind == "arg"
             else self.executor.grad_dict)
        if name not in d or d[name] is None:
            raise MXNetError("no %s array %r" % (kind, name))
        return np.ascontiguousarray(d[name].asnumpy(), np.float32)

    def arg_names(self):
        return list(self._param_names)
