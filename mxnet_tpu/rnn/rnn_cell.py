"""RNN cells and explicit unrolling.

Reference: ``python/mxnet/rnn/rnn_cell.py`` — cell zoo whose ``unroll``
builds the time-major/batch-major symbol graph, plus ``FusedRNNCell``
wrapping the fused RNN op (cuDNN in the reference, a ``lax.scan`` kernel
here — see mxnet_tpu/ops/rnn.py) with pack/unpack weight conversion between
the fused flat vector and per-gate matrices.

TPU note (SURVEY §5.7): explicit unroll emits length-many static steps and
relies on bucketing to bound recompiles; FusedRNNCell is one lax.scan —
prefer it for long sequences.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .. import symbol
from .. import ndarray as nd
from ..ops.rnn import _GATES, _layer_param_shapes, rnn_param_size

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell", "ModifierCell"]


class RNNParams:
    """Container for cell parameter symbols (reference RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Abstract RNN cell (reference rnn_cell.py BaseRNNCell)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, **kwargs):
        """Initial-state symbols (reference begin_state)."""
        assert not self._modified, \
            "this cell has been wrapped by a modifier (dropout/zoneout/"\
            "residual); invoke the wrapper, not the wrapped base cell"
        states = []
        for info in self.state_info:
            self._init_counter += 1
            if info is None:
                state = func(name="%sbegin_state_%d"
                             % (self._prefix, self._init_counter), **kwargs)
            else:
                kwargs.update(info)
                state = func(name="%sbegin_state_%d"
                             % (self._prefix, self._init_counter), **kwargs)
            states.append(state)
        return states

    def unpack_weights(self, args):
        """fused-layout dict -> per-gate dict (reference unpack_weights)."""
        args = dict(args)
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ["i2h", "h2h"]:
            weight = args.pop("%s%s_weight" % (self._prefix, group_name))
            bias = args.pop("%s%s_bias" % (self._prefix, group_name))
            for j, gate in enumerate(self._gate_names):
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                w = weight.asnumpy() if isinstance(weight, nd.NDArray) \
                    else np.asarray(weight)
                args[wname] = nd.array(w[j * h:(j + 1) * h])
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                b = bias.asnumpy() if isinstance(bias, nd.NDArray) \
                    else np.asarray(bias)
                args[bname] = nd.array(b[j * h:(j + 1) * h])
        return args

    def pack_weights(self, args):
        """per-gate dict -> fused-layout dict (reference pack_weights)."""
        args = dict(args)
        if not self._gate_names:
            return args
        for group_name in ["i2h", "h2h"]:
            ws = []
            bs = []
            for gate in self._gate_names:
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                w = args.pop(wname)
                b = args.pop(bname)
                ws.append(w.asnumpy() if isinstance(w, nd.NDArray) else w)
                bs.append(b.asnumpy() if isinstance(b, nd.NDArray) else b)
            args["%s%s_weight" % (self._prefix, group_name)] = \
                nd.array(np.concatenate(ws))
            args["%s%s_bias" % (self._prefix, group_name)] = \
                nd.array(np.concatenate(bs))
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell over ``length`` steps (reference unroll)."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """Split/merge sequence symbols (reference rnn_cell._normalize_sequence)."""
    assert inputs is not None
    axis = layout.find("T")
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, symbol.Symbol):
        if merge is False:
            assert len(inputs) == 1
            inputs = list(symbol.SliceChannel(
                inputs, axis=in_axis, num_outputs=length, squeeze_axis=1))
    else:
        assert length is None or len(inputs) == length
        if merge is True:
            inputs = [symbol.expand_dims(i, axis=axis) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=axis)
    return inputs, axis


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell (reference RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = symbol.Activation(i2h + h2h, act_type=self._activation,
                                   name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference LSTMCell; gate order i, f, g(c), o as cuDNN)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get(
            "i2h_bias",
            init=_lstm_bias_init(forget_bias))
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(gates, num_outputs=4, axis=1,
                                          name="%sslice" % name)
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid",
                                    name="%si" % name)
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid",
                                        name="%sf" % name)
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh",
                                         name="%sc" % name)
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid",
                                     name="%so" % name)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh",
                                              name="%sstate" % name)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference GRUCell; gate order r, z, n as cuDNN)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=prev_state_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_r, i2h_z, i2h = symbol.SliceChannel(
            i2h, num_outputs=3, axis=1, name="%si2h_slice" % name)
        h2h_r, h2h_z, h2h = symbol.SliceChannel(
            h2h, num_outputs=3, axis=1, name="%sh2h_slice" % name)
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid",
                                       name="%sr_act" % name)
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid",
                                        name="%sz_act" % name)
        next_h_tmp = symbol.Activation(i2h + reset_gate * h2h,
                                       act_type="tanh",
                                       name="%sh_act" % name)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN over the whole sequence (reference
    FusedRNNCell over the cuDNN RNN op; here one lax.scan kernel)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._directions = 2 if bidirectional else 1
        self._parameter = self.params.get("parameters")

    def _parameter_name(self):
        return self._prefix + "parameters"

    @property
    def state_info(self):
        b = self._directions
        n = (self._mode == "lstm") + 1
        return [{"shape": (b * self._num_layers, 0, self._num_hidden),
                 "__layout__": "LNC"} for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def unpack_weights(self, args):
        """Flat fused vector -> per-layer per-gate dict
        (reference FusedRNNCell.unpack_weights)."""
        args = dict(args)
        arr = args.pop(self._parameter_name())
        vec = arr.asnumpy().reshape(-1) if isinstance(arr, nd.NDArray) \
            else np.asarray(arr).reshape(-1)
        h = self._num_hidden
        gates = self._num_gates
        dirs = self._directions
        # first all weights in cuDNN order, then all biases
        off = 0
        mats = []
        for layer, d, wsh, rsh in _layer_param_shapes(
                self._mode, self._infer_input_size(vec), h,
                self._num_layers, self._bidirectional):
            w = vec[off:off + wsh[0] * wsh[1]].reshape(wsh)
            off += wsh[0] * wsh[1]
            r = vec[off:off + rsh[0] * rsh[1]].reshape(rsh)
            off += rsh[0] * rsh[1]
            mats.append((layer, d, w, r))
        for (layer, d, w, r) in mats:
            pre = "%s%s%d_" % (self._prefix,
                               "r_" if d else "l_", layer)
            for j, g in enumerate(self._gate_names):
                args[pre + "i2h%s_weight" % g] = nd.array(
                    w[j * h:(j + 1) * h])
                args[pre + "h2h%s_weight" % g] = nd.array(
                    r[j * h:(j + 1) * h])
        bsz = gates * h
        for i in range(self._num_layers * dirs):
            layer, d = divmod(i, dirs) if dirs > 1 else (i, 0)
            pre = "%s%s%d_" % (self._prefix, "r_" if d else "l_", layer)
            bw = vec[off:off + bsz]
            off += bsz
            br = vec[off:off + bsz]
            off += bsz
            for j, g in enumerate(self._gate_names):
                args[pre + "i2h%s_bias" % g] = nd.array(
                    bw[j * h:(j + 1) * h])
                args[pre + "h2h%s_bias" % g] = nd.array(
                    br[j * h:(j + 1) * h])
        return args

    def pack_weights(self, args):
        """Inverse of unpack_weights."""
        args = dict(args)
        h = self._num_hidden
        dirs = self._directions
        chunks = []
        biases = []
        input_size = None
        for layer in range(self._num_layers):
            for d in range(dirs):
                pre = "%s%s%d_" % (self._prefix, "r_" if d else "l_", layer)
                ws, rs, bws, brs = [], [], [], []
                for g in self._gate_names:
                    w = args.pop(pre + "i2h%s_weight" % g)
                    r = args.pop(pre + "h2h%s_weight" % g)
                    ws.append(w.asnumpy() if isinstance(w, nd.NDArray)
                              else np.asarray(w))
                    rs.append(r.asnumpy() if isinstance(r, nd.NDArray)
                              else np.asarray(r))
                    bw = args.pop(pre + "i2h%s_bias" % g)
                    br = args.pop(pre + "h2h%s_bias" % g)
                    bws.append(bw.asnumpy() if isinstance(bw, nd.NDArray)
                               else np.asarray(bw))
                    brs.append(br.asnumpy() if isinstance(br, nd.NDArray)
                               else np.asarray(br))
                chunks.append(np.concatenate(ws).reshape(-1))
                chunks.append(np.concatenate(rs).reshape(-1))
                biases.append(np.concatenate(bws))
                biases.append(np.concatenate(brs))
        vec = np.concatenate(chunks + biases)
        args[self._parameter_name()] = nd.array(vec)
        return args

    def _infer_input_size(self, vec):
        """Solve for input_size from the flat param vector length."""
        h, gates = self._num_hidden, self._num_gates
        dirs = self._directions
        L = self._num_layers
        total = vec.size
        # total = gates*h*in + gates*h*h (layer0 per dir)
        #       + (L-1)*dirs*(gates*h*dirs*h + gates*h*h) + biases
        biases = L * dirs * 2 * gates * h
        rest = total - biases - (L - 1) * dirs * (
            gates * h * dirs * h + gates * h * h)
        per_dir = rest // dirs
        return (per_dir - gates * h * h) // (gates * h)

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell has no single-step form - it is a "
                         "whole-sequence lax.scan; call unroll() instead")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, True)
        if axis == 1:  # NTC -> TNC (the RNN op is time-major)
            inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state
        if self._mode == "lstm":
            states = {"state": states[0], "state_cell": states[1]}
        else:
            states = {"state": states[0]}
        rnn = symbol.RNN(data=inputs, parameters=self._parameter,
                         state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional, p=self._dropout,
                         state_outputs=self._get_next_state,
                         mode=self._mode, name=self._prefix + "rnn",
                         **states)
        attr = {"__layout__": "LNC"}
        if not self._get_next_state:
            outputs, states = rnn, []
        elif self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]
        if axis == 1:
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs = list(symbol.SliceChannel(
                outputs, axis=axis, num_outputs=length, squeeze_axis=1))
        return outputs, states

    def unfuse(self):
        """Equivalent SequentialRNNCell of unfused cells (reference
        unfuse)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl_%d_" % (self._prefix, i)),
                    get_cell("%sr_%d_" % (self._prefix, i)),
                    output_prefix="%sbi_%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl_%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(
                    self._dropout, prefix="%s_dropout%d_" % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells (reference SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                "parameter containers conflict: pass params to the "\
                "SequentialRNNCell or let each child own its params, not both"
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)
        return self

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._cells, **kwargs)

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        num_cells = len(self._cells)
        if begin_state is None:
            begin_state = self.begin_state()
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class BidirectionalCell(BaseRNNCell):
    """Forward + backward cells over a sequence (reference
    BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        raise MXNetError("Bidirectional cannot be stepped. Please use unroll")

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._cells, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state
        l_cell, r_cell = self._cells
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info)],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info):],
            layout=layout, merge_outputs=False)
        outputs = [symbol.Concat(l_o, r_o, dim=1,
                                 name="%st%d" % (self._output_prefix, i))
                   for i, (l_o, r_o) in enumerate(
                       zip(l_outputs, reversed(r_outputs)))]
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        states = l_states + r_states
        return outputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (reference ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class DropoutCell(BaseRNNCell):
    """Dropout on cell outputs (reference DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell doesn't support zoneout. Please unfuse first."
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout since it doesn't "\
            "support step. Please add ZoneoutCell to the cells underneath "\
            "instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = (lambda p, like: symbol.Dropout(
            symbol.ones_like(like), p=p))
        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(next_output)
        output = (symbol.where(mask(p_outputs, next_output), next_output,
                               prev_output)
                  if p_outputs != 0.0 else next_output)
        states = ([symbol.where(mask(p_states, new_s), new_s, old_s)
                   for new_s, old_s in zip(next_states, states)]
                  if p_states != 0.0 else next_states)
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Adds residual connection (reference ResidualCell)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs,
                                     name="%s_plus_residual" % output.name)
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        merge_outputs = isinstance(outputs, symbol.Symbol) \
            if merge_outputs is None else merge_outputs
        inputs, _ = _normalize_sequence(length, inputs, layout,
                                        merge_outputs)
        if merge_outputs:
            outputs = symbol.elemwise_add(outputs, inputs)
        else:
            outputs = [symbol.elemwise_add(o, i)
                       for o, i in zip(outputs, inputs)]
        return outputs, states


def _lstm_bias_init(forget_bias):
    from ..initializer import LSTMBias
    return LSTMBias(forget_bias=forget_bias).dumps()


def _cells_state_info(cells):
    return sum([c.state_info for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _cells_unpack_weights(cells, args):
    for cell in cells:
        args = cell.unpack_weights(args)
    return args


def _cells_pack_weights(cells, args):
    for cell in cells:
        args = cell.pack_weights(args)
    return args
