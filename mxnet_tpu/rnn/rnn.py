"""RNN checkpoint helpers (reference: python/mxnet/rnn/rnn.py) —
save/load with cell-aware weight (un)packing so fused and unfused layouts
interop on disk."""
from __future__ import annotations

from ..model import save_checkpoint, load_checkpoint

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]


def _as_list(cells):
    return cells if isinstance(cells, (list, tuple)) else [cells]


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params,
                        aux_params):
    """Unpack fused weights before saving (reference rnn.py)."""
    args = dict(arg_params)
    for cell in _as_list(cells):
        args = cell.unpack_weights(args)
    save_checkpoint(prefix, epoch, symbol, args, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load and re-pack weights for the given cells."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    for cell in _as_list(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback (reference rnn.do_rnn_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback
