"""Object-registry helpers (reference: ``python/mxnet/registry.py``).

Provides register/create/alias factories used by initializer, optimizer,
metric and lr_scheduler registries.  ``create`` accepts a name, a
``json.dumps([name, kwargs])`` string (the reference's cross-process
serialization used to ship optimizers to kvstore servers), or an instance.
"""
from __future__ import annotations

import json
import logging

from .base import MXNetError

_REGISTRIES = {}


def _registry(base_class):
    return _REGISTRIES.setdefault(id(base_class), {})


def get_register_func(base_class, nickname):
    registry = _registry(base_class)

    def register(klass, name=None):
        name = (name or klass.__name__).lower()
        if name in registry:
            logging.warning("New %s %s registered with name %s is overriding "
                            "existing %s", nickname, klass, name, nickname)
        registry[name] = klass
        return klass

    register.__doc__ = "Register %s to the %s factory" % (nickname, nickname)
    return register


def get_alias_func(base_class, nickname):
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for a in aliases:
                register(klass, a)
            return klass
        return reg
    return alias


def get_create_func(base_class, nickname):
    registry = _registry(base_class)

    def create(*args, **kwargs):
        if len(args):
            name = args[0]
            args = args[1:]
        else:
            name = kwargs.pop(nickname)
        if not isinstance(name, str):
            return name  # already an instance
        if name.startswith("["):
            assert not args and not kwargs
            name, kwargs = json.loads(name)
            return create(name, **kwargs)
        if name.lower() not in registry:
            raise MXNetError("%s is not registered as a %s factory"
                             % (name, nickname))
        return registry[name.lower()](*args, **kwargs)

    create.__doc__ = "Create a %s instance from config" % nickname
    return create
