"""Image IO + augmentation pipeline.

Reference: ``python/mxnet/image.py`` (724 L python-side pipeline) and the
C++ iterators/augmenters (`src/io/iter_image_recordio_2.cc`,
`image_aug_default.cc` — SURVEY §2.1 Data IO row).  Decode uses PIL
(the environment has no OpenCV); the augmenter list protocol
(``CreateAugmenter``) and ``ImageIter`` over ``.rec``/list files keep the
reference's shapes and semantics.  Host-side numpy feeding the device
pipeline; PrefetchingIter overlaps decode with device compute.
"""
from __future__ import annotations

import logging
import os
import random
import time

import numpy as np

from .base import MXNetError
from . import io as io_mod
from . import ndarray as nd
from . import recordio
from .telemetry import ioview as _ioview

__all__ = ["imdecode", "scale_down", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "ResizeAug", "ForceResizeAug",
           "RandomCropAug", "RandomSizedCropAug", "CenterCropAug",
           "RandomOrderAug", "ColorJitterAug", "LightingAug",
           "ColorNormalizeAug", "HorizontalFlipAug", "CastAug",
           "CreateAugmenter", "ImageIter"]


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image byte buffer to HWC uint8 (reference image.imdecode,
    backed by the imdecode op / OpenCV there, PIL here).

    Accounted as the ioview ``decode`` stage (wall per image, input
    bytes); the ``io.decode`` fault seam fires per image — a
    ``kind=delay`` spec is a seeded slow decoder for bottleneck-
    attribution drills (docs/api/telemetry.md)."""
    import io as _pyio
    from PIL import Image
    from . import resilience
    t0 = time.perf_counter()
    resilience.fault_point("io.decode")
    im = Image.open(_pyio.BytesIO(buf if isinstance(buf, (bytes, bytearray))
                                  else bytes(buf)))
    im = im.convert("RGB" if flag else "L")
    arr = np.asarray(im)
    if not to_rgb and arr.ndim == 3:
        arr = arr[:, :, ::-1]  # RGB -> BGR (OpenCV convention)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    _ioview.account("decode", time.perf_counter() - t0, items=1,
                    nbytes=len(buf))
    return arr


def _resize(src, w, h, interp=2):
    from PIL import Image
    resample = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                3: Image.NEAREST, 4: Image.LANCZOS}.get(interp,
                                                        Image.BILINEAR)
    im = Image.fromarray(src.squeeze().astype(np.uint8))
    im = im.resize((w, h), resample)
    arr = np.asarray(im)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def scale_down(src_size, size):
    """Scale size down to fit in src_size (reference image.scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize the shorter edge to ``size`` (reference image.resize_short)."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return _resize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = _resize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src.astype(np.float32) - np.asarray(mean, np.float32)
    if std is not None:
        src /= np.asarray(std, np.float32)
    return src


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Random area+aspect crop (reference image.random_size_crop)."""
    h, w = src.shape[:2]
    area = w * h
    for _ in range(10):
        new_area = random.uniform(min_area, 1.0) * area
        new_ratio = random.uniform(*ratio)
        new_w = int(np.sqrt(new_area * new_ratio))
        new_h = int(np.sqrt(new_area / new_ratio))
        if random.random() < 0.5:
            new_w, new_h = new_h, new_w
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


# ----------------------------------------------------------- augmenters
def ResizeAug(size, interp=2):
    def aug(src):
        return [resize_short(src, size, interp)]
    return aug


def ForceResizeAug(size, interp=2):
    def aug(src):
        return [_resize(src, size[0], size[1], interp)]
    return aug


def RandomCropAug(size, interp=2):
    def aug(src):
        return [random_crop(src, size, interp)[0]]
    return aug


def RandomSizedCropAug(size, min_area, ratio, interp=2):
    def aug(src):
        return [random_size_crop(src, size, min_area, ratio, interp)[0]]
    return aug


def CenterCropAug(size, interp=2):
    def aug(src):
        return [center_crop(src, size, interp)[0]]
    return aug


def RandomOrderAug(ts):
    def aug(src):
        srcs = [src]
        random.shuffle(ts)
        for t in ts:
            srcs = [j for i in srcs for j in t(i)]
        return srcs
    return aug


def ColorJitterAug(brightness, contrast, saturation):
    ts = []
    coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)
    if brightness > 0:
        def baug(src):
            alpha = 1.0 + random.uniform(-brightness, brightness)
            return [np.clip(src * alpha, 0, 255)]
        ts.append(baug)
    if contrast > 0:
        def caug(src):
            alpha = 1.0 + random.uniform(-contrast, contrast)
            gray = (src * coef).sum(axis=2, keepdims=True)
            return [np.clip(src * alpha + gray.mean() * (1 - alpha), 0, 255)]
        ts.append(caug)
    if saturation > 0:
        def saug(src):
            alpha = 1.0 + random.uniform(-saturation, saturation)
            gray = (src * coef).sum(axis=2, keepdims=True)
            return [np.clip(src * alpha + gray * (1 - alpha), 0, 255)]
        ts.append(saug)
    return RandomOrderAug(ts)


def LightingAug(alphastd, eigval, eigvec):
    """PCA noise (reference image.LightingAug)."""
    def aug(src):
        alpha = np.random.normal(0, alphastd, size=(3,))
        rgb = np.dot(eigvec * alpha, eigval)
        return [src + rgb]
    return aug


def ColorNormalizeAug(mean, std):
    def aug(src):
        return [color_normalize(src, mean, std)]
    return aug


def HorizontalFlipAug(p):
    def aug(src):
        if random.random() < p:
            return [src[:, ::-1]]
        return [src]
    return aug


def CastAug():
    def aug(src):
        return [src.astype(np.float32)]
    return aug


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Standard augmenter list (reference image.CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.3, (3.0 / 4.0,
                                                           4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None:
        assert std is not None
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(io_mod.DataIter):
    """Image iterator over .rec files or image lists (reference
    image.ImageIter; C++ analogue ImageRecordIOParser2)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or (isinstance(imglist, list))
        if path_imgrec:
            logging.info("loading recordio %s...", path_imgrec)
            if path_imgidx:
                self.imgrec = recordio.MXIndexedRecordIO(
                    path_imgidx, path_imgrec, "r")
                self.imgidx = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.imgidx = None
        else:
            self.imgrec = None

        if path_imglist:
            logging.info("loading image list %s...", path_imglist)
            with open(path_imglist) as fin:
                imglist = {}
                imgkeys = []
                for line in iter(fin.readline, ""):
                    line = line.strip().split("\t")
                    label = np.array([float(i) for i in line[1:-1]],
                                     dtype=np.float32)
                    key = int(line[0])
                    imglist[key] = (label, line[-1])
                    imgkeys.append(key)
                self.imglist = imglist
        elif isinstance(imglist, list):
            logging.info("loading image list...")
            result = {}
            imgkeys = []
            index = 1
            for img in imglist:
                key = str(index)
                index += 1
                if len(img) > 2:
                    label = np.array(img[:-1], dtype=np.float32)
                else:
                    label = np.array([img[0]], dtype=np.float32)
                result[key] = (label, img[-1])
                imgkeys.append(str(key))
            self.imglist = result
        else:
            self.imglist = None
        self.path_root = path_root

        self.check_data_shape(data_shape)
        self.provide_data = [io_mod.DataDesc(data_name,
                                             (batch_size,) + data_shape)]
        if label_width > 1:
            self.provide_label = [io_mod.DataDesc(
                label_name, (batch_size, label_width))]
        else:
            self.provide_label = [io_mod.DataDesc(label_name, (batch_size,))]
        self.batch_size = batch_size
        self.data_shape = data_shape
        self.label_width = label_width

        self.shuffle = shuffle
        if self.imgrec is None:
            self.seq = imgkeys
        elif shuffle or num_parts > 1:
            if not self.imgidx:
                # an absent/empty .idx silently yields 0-batch epochs;
                # shuffle and sharding need random access, so fail loud
                raise MXNetError(
                    "ImageIter(shuffle/num_parts) needs a non-empty "
                    "index: pass path_imgidx to a .idx built alongside "
                    "the .rec (tools/im2rec)")
            self.seq = self.imgidx
        else:
            self.seq = None

        if num_parts > 1:
            assert part_index < num_parts
            N = len(self.seq)
            C = N // num_parts
            self.seq = self.seq[part_index * C:(part_index + 1) * C]
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.part_index = int(part_index)
        self.num_parts = int(num_parts)
        self.cur = 0
        self._epochs = -1           # the constructor reset brings it to 0
        self.reset()

    def reset(self):
        if self.shuffle:
            random.shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0
        self._epochs += 1

    def position(self):
        """{"epoch", "shard", "num_shards", "offset", "resyncs"} —
        the advisory iterator position (``telemetry.ioview``): record
        offset within this shard's epoch, plus the underlying reader's
        corruption-resync count."""
        if self.seq is not None:
            offset = self.cur
        elif self.imgrec is not None:
            offset = self.imgrec.records_read
        else:
            offset = 0
        return {"epoch": self._epochs, "shard": self.part_index,
                "num_shards": self.num_parts, "offset": int(offset),
                "resyncs": int(getattr(self.imgrec, "resyncs", 0) or 0)}

    def state(self):
        """Durable state.  Key-list mode records the epoch's key ORDER
        explicitly when shuffling (``reset`` shuffles from the global
        ``random`` RNG, which no seed in this state could replay);
        sequential-``.rec`` mode delegates to the reader's byte-exact
        state.  Augmentation randomness is NOT part of the contract —
        the durable thing is the sample stream, not the pixels."""
        from . import io_resume
        st = {"v": io_resume.STATE_VERSION, "kind": "image",
              "epoch": self._epochs, "shard": self.part_index,
              "num_shards": self.num_parts, "cur": int(self.cur)}
        if self.seq is not None:
            if self.shuffle:
                st["seq"] = list(self.seq)
        else:
            st["rec"] = self.imgrec.state()
        return st

    def restore(self, state):
        from . import io_resume
        io_resume.check_state(state, "image")
        if int(state["shard"]) != self.part_index or \
                int(state["num_shards"]) != self.num_parts:
            raise MXNetError(
                "image state is for shard %s/%s, iterator is %d/%d"
                % (state["shard"], state["num_shards"],
                   self.part_index, self.num_parts))
        if self.seq is not None:
            seq = state.get("seq")
            if seq is not None and len(seq) != len(self.seq):
                raise MXNetError(
                    "image state key list has %d entries, iterator has "
                    "%d — different dataset?" % (len(seq),
                                                 len(self.seq)))
            cur = int(state["cur"])
            limit = len(seq if seq is not None else self.seq)
            if not 0 <= cur <= limit:
                raise MXNetError("image cursor %d out of range [0, %d]"
                                 % (cur, limit))
            if seq is not None:
                self.seq = list(seq)
            self.cur = cur
        else:
            self.imgrec.restore(state["rec"])
            self.cur = int(state["cur"])
        self._epochs = int(state["epoch"])

    def next_sample(self):
        """Read + decode one sample."""
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            return label, self.read_image(fname)
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((batch_size, c, h, w), dtype=np.float32)
        batch_label = np.zeros((batch_size, self.label_width),
                               dtype=np.float32) \
            if self.label_width > 1 else np.zeros(batch_size,
                                                  dtype=np.float32)
        i = 0
        t_batch = 0.0
        try:
            while i < batch_size:
                label, s = self.next_sample()
                data = [imdecode(s) if isinstance(s, (bytes, bytearray))
                        else s]
                try:
                    self.check_valid_image(data)
                except RuntimeError as e:
                    logging.debug("Invalid image, skipping:  %s", str(e))
                    continue
                data = self.augmentation_transform(data)
                t0 = time.perf_counter()
                for datum in data:
                    assert i < batch_size, \
                        "Batch size must be multiple of augmenter output"
                    batch_data[i] = np.transpose(
                        datum.astype(np.float32), (2, 0, 1))
                    if self.label_width > 1:
                        batch_label[i] = label
                    else:
                        batch_label[i] = label if np.isscalar(label) \
                            else np.asarray(label).reshape(-1)[0]
                    i += 1
                t_batch += time.perf_counter() - t0
        except StopIteration:
            if not i:
                raise StopIteration
        # batch-assembly stage: the cast/transpose/copy into the batch
        # buffer (decode and augment account themselves above)
        _ioview.account("batch", t_batch, items=i,
                        nbytes=batch_data.nbytes)
        return io_mod.DataBatch([nd.array(batch_data)],
                                [nd.array(batch_label)],
                                pad=batch_size - i)

    def check_data_shape(self, data_shape):
        if not len(data_shape) == 3:
            raise ValueError("data_shape should have length 3, with "
                             "dimensions CxHxW")
        if not data_shape[0] == 3 and not data_shape[0] == 1:
            raise ValueError("This iterator expects inputs to have 3 or 1 "
                             "channels.")

    def check_valid_image(self, data):
        if len(data[0].shape) == 0:
            raise RuntimeError("Data shape is wrong")

    def read_image(self, fname):
        with open(os.path.join(self.path_root or "", fname), "rb") as fin:
            return fin.read()

    def augmentation_transform(self, data):
        t0 = time.perf_counter()
        for aug in self.auglist:
            data = [ret for src in data for ret in aug(src)]
        _ioview.account("augment", time.perf_counter() - t0,
                        items=len(data))
        return data
