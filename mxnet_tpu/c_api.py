"""Python driver for the widened flat C ABI (``src/c_api.cc``).

Reference: the ``MXNDArray*`` / ``MXSymbol*`` subsets of
``include/mxnet/c_api.h`` (impl ``src/c_api/c_api.cc``) — the seam
every reference language binding hangs off.  The native library embeds
CPython and calls the helpers here; handles on the C side are owned
references to the objects these helpers return (NDArray / Symbol /
composable op stubs), so the ABI manipulates real framework objects,
not session-local copies.

Kept deliberately thin: the logic lives in ``ndarray.py`` /
``symbol.py``; this module only adapts calling conventions (flat
key/value string lists, opaque creator indices) to them.
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError
from .context import Context
from .ops.registry import get_op, has_op, list_ops


# ---------------------------------------------------------------- ndarray

def nd_create(shape, dtype, dev_type, dev_id):
    ctx = Context(dev_type if isinstance(dev_type, str) else
                  {1: "cpu", 2: "gpu", 3: "tpu"}.get(int(dev_type), "cpu"),
                  int(dev_id))
    return nd.zeros(tuple(int(d) for d in shape), ctx=ctx, dtype=dtype)


def nd_from_bytes(arr, buf):
    """SyncCopyFromCPU: write caller bytes into the array in place."""
    host = np.frombuffer(buf, dtype=arr.dtype).reshape(arr.shape)
    arr[:] = host
    return arr


def nd_to_bytes(arr):
    """SyncCopyToCPU: contiguous host bytes of the array."""
    return np.ascontiguousarray(arr.asnumpy()).tobytes()


def nd_shape(arr):
    return tuple(int(d) for d in arr.shape)


def nd_dtype(arr):
    return str(np.dtype(arr.dtype))


def nd_context(arr):
    dev = arr.context
    code = {"cpu": 1, "gpu": 2, "tpu": 3}.get(dev.device_type, 1)
    return (code, int(dev.device_id))


def nd_reshape(arr, dims):
    return arr.reshape(tuple(int(d) for d in dims))


def nd_slice(arr, begin, end):
    return arr[int(begin):int(end)]


def nd_save(fname, arrays, keys):
    """Reference ``MXNDArraySave``: keyed dict when keys given, else a
    positional list — both in the reference binary container."""
    if keys:
        nd.save(fname, {k: a for k, a in zip(keys, arrays)})
    else:
        nd.save(fname, list(arrays))


def nd_load(fname):
    """-> (names_or_None, [NDArray]) in file order."""
    loaded = nd.load(fname)
    if isinstance(loaded, dict):
        names = list(loaded.keys())
        return names, [loaded[k] for k in names]
    return None, list(loaded)


# ---------------------------------------------------------------- symbol

def op_names():
    """Stable op-name list; index+1 is the C-side AtomicSymbolCreator."""
    return sorted(list_ops())


def op_info(name):
    op = get_op(name)
    params = op.params or {}
    return (name, getattr(op, "doc", "") or "", sorted(params.keys()))


class _AtomicStub:
    """An op + attrs awaiting composition (the reference's atomic
    symbol: created by MXSymbolCreateAtomicSymbol, inputs bound later
    by MXSymbolCompose)."""

    def __init__(self, op_name, attrs):
        self.op_name = op_name
        self.attrs = dict(attrs)


def create_atomic(op_name, keys, vals):
    if not has_op(op_name):
        raise MXNetError("unknown operator %r" % (op_name,))
    return _AtomicStub(op_name, dict(zip(keys, vals)))


def create_variable(name):
    return sym_mod.Variable(name)


def compose(stub, name, keys, args):
    """MXSymbolCompose: bind inputs into an atomic stub -> Symbol.
    ``keys`` empty means positional args (the common case)."""
    if not isinstance(stub, _AtomicStub):
        raise MXNetError("compose target is not an atomic symbol")
    import mxnet_tpu as _mx
    fn = getattr(_mx.sym, stub.op_name)
    attrs = dict(stub.attrs)
    if name:
        attrs["name"] = name
    if keys:
        return fn(**dict(zip(keys, args)), **attrs)
    return fn(*args, **attrs)


def sym_from_json(json_str):
    return sym_mod.load_json(json_str)


def sym_from_file(fname):
    return sym_mod.load(fname)


def sym_to_json(sym):
    return sym.tojson()


def sym_save(sym, fname):
    sym.save(fname)


def sym_name(sym):
    entries = sym._entries
    node = entries[0][0]
    return node.name or ""


def sym_list_arguments(sym):
    return list(sym.list_arguments())


def sym_list_outputs(sym):
    return list(sym.list_outputs())


def sym_list_aux(sym):
    return list(sym.list_auxiliary_states())


def sym_get_attr(sym, key):
    """-> (found, value): absent and empty-string attrs are distinct
    (the reference returns success=1 with an empty value)."""
    v = sym.attr(key)
    return (False, "") if v is None else (True, str(v))


def sym_set_attr(sym, key, value):
    if key == "name":
        # attr("name") resolves to the node's name, so a raw_attr
        # write would be unobservable through the paired Get — refuse
        # rather than silently no-op (names are fixed at compose time)
        raise MXNetError("cannot set the reserved attr 'name'; node "
                         "names are fixed when the symbol is composed")
    sym._set_attr(**{key: value})


def sym_list_attr(sym):
    """Flat [k0, v0, k1, v1, ...]: operator params AND user raw attrs
    of the head node (the reference's ListAttrShallow covers both, and
    GetAttr's param fallback must agree with the listing)."""
    node = sym._entries[0][0]
    merged = {}
    if node.op is not None:
        for k, v in (node.attrs or {}).items():
            merged[str(k)] = str(v)
    for k, v in sym.list_attr().items():
        merged[str(k)] = str(v)
    out = []
    for k in sorted(merged):
        out.append(k)
        out.append(merged[k])
    return out


# ---------------------------------------------------------------- kvstore

def kv_create(kv_type):
    from . import kvstore as kv_mod
    return kv_mod.create(kv_type)


def kv_type(kv):
    return str(kv.type)


def kv_rank(kv):
    return int(kv.rank)


def kv_num_workers(kv):
    return int(kv.num_workers)


def kv_init(kv, keys, vals):
    kv.init(list(keys), list(vals))


def kv_push(kv, keys, vals, priority):
    kv.push(list(keys), list(vals), priority=int(priority))


def kv_pull(kv, keys, outs, priority):
    kv.pull(list(keys), out=list(outs), priority=int(priority))


# ---------------------------------------------------------------- recordio

def recordio_writer(uri):
    from . import recordio
    return recordio.MXRecordIO(uri, "w")


def recordio_reader(uri):
    from . import recordio
    return recordio.MXRecordIO(uri, "r")


def recordio_write(rec, buf):
    rec.write(buf)


def recordio_read(rec):
    """-> bytes or None at end of file."""
    return rec.read()


def recordio_close(rec):
    rec.close()


# library registered for trampoline symbol resolution when the C side
# predates the address-passing MXKVStoreSetUpdater protocol (see
# register_library / kv_set_updater)
_REGISTERED_LIB = {"path": None}


def register_library(path):
    """Register the path of the loaded ``libmxtpu.so`` so python-side
    trampolines (``kv_set_updater``) can resolve its symbols via an
    explicit ``ctypes.PyDLL(path)`` handle instead of the process
    GLOBAL symbol table — which does not contain the library when the
    host application dlopen()ed it with the default ``RTLD_LOCAL``.
    Embedders that cannot pass trampoline addresses should call this
    once at init (see include/mxnet_tpu/c_api.h)."""
    _REGISTERED_LIB["path"] = path


def _trampoline_lib():
    """The ctypes handle to resolve MXTPUWrapNDArray/MXNDArrayFree
    from: the registered library path when one was announced, else the
    global symbol table (works only under RTLD_GLOBAL / static link —
    the legacy behavior, kept as the last resort)."""
    import ctypes
    path = _REGISTERED_LIB["path"]
    # PyDLL in both cases: these helpers manipulate Python refcounts,
    # so the GIL must stay held across the call
    return ctypes.PyDLL(path) if path else ctypes.PyDLL(None)


def kv_set_updater(kv, fnptr, user_handle, wrap_addr=0, free_addr=0):
    """Install a C callback updater (reference MXKVStoreSetUpdater).

    ``fnptr`` is the address of a ``void (int key, NDArrayHandle recv,
    NDArrayHandle local, void *user)`` function; each push invokes it
    with freshly wrapped handles onto the REAL stored arrays, so the
    callback's in-place writes (SyncCopyFromCPU) update the store —
    the reference worker-protocol seam, C side in charge of the rule.

    ``wrap_addr``/``free_addr`` are the addresses of the library's own
    ``MXTPUWrapNDArray`` / ``MXNDArrayFree`` trampolines, passed by
    ``src/c_api.cc`` so resolution never depends on global symbol
    visibility (a host app's plain ``dlopen`` defaults to
    ``RTLD_LOCAL``, under which ``ctypes.PyDLL(None)`` cannot see this
    library).  When absent (older C side / direct embedding) the
    symbols are resolved from the library registered via
    :func:`register_library`, else from the global table as a last
    resort — the contract is documented in include/mxnet_tpu/c_api.h.
    """
    import ctypes

    UPDATER = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                               ctypes.c_void_p, ctypes.c_void_p)
    cb = UPDATER(int(fnptr))
    if wrap_addr and free_addr:
        # PYFUNCTYPE: the GIL stays held across the trampoline (they
        # manipulate Python refcounts); the user callback itself goes
        # through CFUNCTYPE above, which releases the GIL, and its
        # re-entries into MXNDArray* entry points re-ensure it
        wrap = ctypes.PYFUNCTYPE(ctypes.c_void_p,
                                 ctypes.py_object)(int(wrap_addr))
        free = ctypes.PYFUNCTYPE(ctypes.c_int,
                                 ctypes.c_void_p)(int(free_addr))
    else:
        lib = _trampoline_lib()
        wrap = lib.MXTPUWrapNDArray
        wrap.restype = ctypes.c_void_p
        wrap.argtypes = [ctypes.py_object]
        free = lib.MXNDArrayFree
        free.restype = ctypes.c_int
        free.argtypes = [ctypes.c_void_p]
    user = ctypes.c_void_p(int(user_handle))

    def _updater(key, recv, local):
        rh = wrap(recv)
        lh = wrap(local)
        try:
            cb(int(key), rh, lh, user)
        finally:
            free(rh)
            free(lh)

    kv.set_updater(_updater)
