"""mxnet_tpu: a TPU-native deep-learning framework with the capability
surface of Apache MXNet 0.10 (reference: daiab/mxnet @ v0.10.1), built on
JAX/XLA/Pallas/pjit.

Import convention mirrors the reference's ``import mxnet as mx``::

    import mxnet_tpu as mx
    x = mx.nd.zeros((2, 3), ctx=mx.tpu(0))
"""
from __future__ import annotations

import os as _os

if _os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # Honor an explicit CPU pin even where a site TPU plugin prepends
    # itself to jax_platforms regardless of the env var.  This must run
    # before anything below touches jax: embedded ABI consumers import
    # this package with no conftest, and a lazily-initialized remote
    # accelerator client would hang the whole process when its tunnel
    # is down.
    import jax as _jax
    try:
        _jax.config.update("jax_platforms", "cpu")
    except Exception as _e:  # mxlint: allow-broad-except(site plugins fail the pin in arbitrary ways; a warning beats failing every import)
        import logging as _logging
        _logging.getLogger(__name__).warning(
            "JAX_PLATFORMS=cpu requested but the pin failed (%s); a "
            "site accelerator plugin may still be selected", _e)

__version__ = "0.1.0"

from .base import MXNetError
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import base
from . import ndarray
from . import ndarray as nd
from . import random
from . import autograd
from . import ops
from . import operator  # registers the "Custom" op before codegen below
from . import name
from .attribute import AttrScope
from . import symbol
from . import symbol as sym
from .executor import Executor

# generate mx.nd.<op> functions from the registry (reference:
# python/mxnet/ndarray.py:2281-2423 codegen over the C op registry)
ndarray._register_op_functions(ops.generate_nd_functions())

# training stack (imported after op injection: optimizer uses nd.sgd_update
# et al., which only exist once the codegen above has run)
from . import registry
from . import initializer
from . import initializer as init  # reference alias (python/mxnet/__init__.py)
from .initializer import InitDesc
from . import lr_scheduler
from . import optimizer
from . import metric
from . import io
from . import io_resume
from . import callback
from . import kvstore
from . import kvstore as kv
from . import model
from . import module
from . import module as mod  # reference alias (python/mxnet/__init__.py)
from .module import Module
from . import rnn
from . import profiler
from . import telemetry
from . import monitor
from . import monitor as mon  # reference alias (python/mxnet/__init__.py)
from .monitor import Monitor
from . import recordio
from . import resilience
from . import visualization
from . import visualization as viz
from . import test_utils
from . import analysis
from . import autotune
from . import contrib
from . import config
from . import predictor
from .predictor import Predictor
from . import serving

# optional: image pipelines need PIL
try:
    from . import image
    from . import image_det
except ImportError:  # pragma: no cover
    image = None
    image_det = None

from . import rtc

# optional: torch interop (plugin/torch + python/mxnet/torch.py parity)
try:
    from . import torch as th
    sym.TorchModule = th.torch_module_symbol
    sym.TorchCriterion = th.torch_criterion_symbol
except ImportError:  # pragma: no cover
    th = None

