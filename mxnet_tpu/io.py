"""Data iterators.

Reference: ``python/mxnet/io.py`` (859 L: DataDesc/DataBatch/DataIter,
NDArrayIter, ResizeIter, PrefetchingIter) plus the C++ iterators in
``src/io/`` (MNIST: iter_mnist.cc, CSV: iter_csv.cc; the RecordIO image
pipeline lives in :mod:`mxnet_tpu.image`).  TPU-native notes: batches are
host numpy until Module scatters them to devices; PrefetchingIter overlaps
host IO with device compute (the role of dmlc::ThreadedIter,
iter_prefetcher.h).
"""
from __future__ import annotations

import gzip
import os
import struct
import threading
import time
from collections import namedtuple

import numpy as np

from .base import MXNetError
from . import ndarray
from . import resilience
from . import telemetry
from .telemetry import ioview as _ioview
from .ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "DevicePrefetchIter",
           "ResizeIter",
           "PrefetchingIter", "NDArrayIter", "MNISTIter", "CSVIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name + shape (+ dtype/layout) of one input (reference io.py:19-75)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """One mini-batch (reference io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Base iterator (reference io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError

    def position(self):
        """Advisory iterator position for the data-plane observability
        layer (``telemetry.ioview``): a JSON-able dict — by convention
        ``{"epoch", "shard", "num_shards", "offset", "resyncs"}``, any
        subset — or None when the iterator tracks nothing.  Rides each
        sampled step's telemetry JSONL record and the checkpoint
        manifest meta.  Wrappers MUST report the next-UNDELIVERED
        sample, not the inner reader's read-ahead position: a
        prefetcher holding staged-but-undelivered batches reports the
        position captured BEFORE those batches were fetched (the
        ``restore(state()) => identical remaining stream`` contract
        depends on it)."""
        return None

    def state(self):
        """Durable iterator state (``mxnet_tpu.io_resume``): a
        JSON-able versioned dict ``{"v", "kind", ...}`` describing the
        next-undelivered sample, or None when this iterator declares no
        durable state.  ``restore(state())`` into a compatible iterator
        must reproduce the identical remaining sample stream.  Wrappers
        delegate inward, compensating for any prefetched-but-
        undelivered batches they hold."""
        return None

    def restore(self, state):
        """Restore a ``state()`` dict.  Validate-then-commit: a
        rejected or failing restore must leave the iterator restartable
        from the same state (the ``io.resume`` chaos seam in
        ``io_resume.restore_iterator`` tests exactly that).  The base
        accepts only None (nothing to restore)."""
        if state is None:
            return
        raise MXNetError(
            "%s declares no durable state and cannot restore %r — "
            "resume with the iterator class that produced the state"
            % (type(self).__name__, state.get("kind")
               if isinstance(state, dict) else state))


class ResizeIter(DataIter):
    """Resize another iterator to ``size`` batches per epoch
    (reference io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    def position(self):
        return self.data_iter.position()

    def state(self):
        from . import io_resume
        return {"v": io_resume.STATE_VERSION, "kind": "resize",
                "cur": self.cur, "inner": self.data_iter.state()}

    def restore(self, state):
        from . import io_resume
        io_resume.check_state(state, "resize")
        cur = int(state["cur"])
        if not 0 <= cur <= self.size:
            raise MXNetError("resize cursor %d out of range [0, %d]"
                             % (cur, self.size))
        # inner first (it validates its own state), cursor commits last
        self.data_iter.restore(state["inner"])
        self.cur = cur
        self.current_batch = None


def _safe_state(it):
    """``it.state()`` when the duck-type fits, else None (raw values
    that are not dicts are advisory noise, not durable state)."""
    fn = getattr(it, "state", None)
    st = fn() if callable(fn) else None
    return st if isinstance(st, dict) else None


def _safe_position(it):
    fn = getattr(it, "position", None)
    pos = fn() if callable(fn) else None
    return pos if isinstance(pos, dict) else None


class PrefetchingIter(DataIter):
    """Thread-prefetch over one or more iterators (reference io.py:319;
    C++ analogue iter_prefetcher.h)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0].shape[0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]
        self.prefetch_errors = [None for _ in range(self.n_iter)]
        # inner state/position captured BEFORE each fetch: while the
        # fetched batch is staged-but-undelivered, the wrapper's
        # state()/position() must describe that batch (the next
        # UNDELIVERED sample), not the reader's read-ahead point
        self.next_state = [None for _ in range(self.n_iter)]
        self.next_position = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                # producer-starved time: this thread is idle because the
                # consumer has not taken the previous batch — a slow
                # consumer must not be misread as a healthy pipeline
                # (the consumer-bound half of the bottleneck verdict)
                t_wait = time.perf_counter()
                self.data_taken[i].wait()
                _ioview.note_starved("host",
                                     time.perf_counter() - t_wait)
                if not self.started:
                    break
                try:
                    self.next_state[i] = _safe_state(self.iters[i])
                    self.next_position[i] = _safe_position(self.iters[i])
                except Exception:  # mxlint: allow-broad-except(advisory capture from arbitrary user iterators must not kill the producer thread and hang the consumer on data_ready)
                    self.next_state[i] = None
                    self.next_position[i] = None
                try:
                    # the io.prefetch fault seam: injected faults retry
                    # with backoff (transient-read semantics); a real —
                    # or exhausted — error is surfaced on the consumer
                    # in iter_next instead of killing this thread and
                    # hanging the consumer on data_ready forever.  The
                    # host_prefetch stage is this window EXCLUSIVE of
                    # the inner stages the upstream next() accounts on
                    # this same thread (read/decode/augment/batch) —
                    # charging them twice would make host_prefetch >=
                    # their sum by construction, so the slowest-stage
                    # verdict could never name the real culprit.  A
                    # kind=delay seam fault (a seeded slow stage) is
                    # outside the inner stages and lands here
                    t_work = time.perf_counter()
                    inner0 = _ioview.thread_accounted()
                    resilience.retry_call(
                        resilience.fault_point, args=("io.prefetch",),
                        retries=2, base_delay=0.01, max_delay=0.1,
                        exceptions=(resilience.FaultInjected,),
                        name="io.prefetch")
                    self.next_batch[i] = self.iters[i].next()
                    inner = _ioview.thread_accounted() - inner0
                    _ioview.account(
                        "host_prefetch",
                        max(0.0, time.perf_counter() - t_work - inner),
                        items=1)
                except StopIteration:
                    self.next_batch[i] = None
                except BaseException as e:  # mxlint: allow-broad-except(stored and re-raised on the consumer thread, not swallowed)
                    self.next_batch[i] = None
                    self.prefetch_errors[i] = e
                self.data_taken[i].clear()
                self.data_ready[i].set()
                # a composite batch counts as staged once EVERY slot is
                # ready; the occupancy tracker owns the depth value (the
                # consumer zeroes it when it takes the batch) and holds
                # it between iter_next calls so scrapes/snapshots see it
                if all(e.is_set() for e in self.data_ready):
                    _ioview.queue_tracker("host").set_depth(1)

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i], daemon=True)
            for i in range(self.n_iter)]
        for t in self.prefetch_threads:
            t.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for i in range(self.n_iter):
            # pre-fetch captures are from the finished epoch; position()
            # falls back to the live inner until the first new fetch
            self.next_state[i] = None
            self.next_position[i] = None
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        # the staged composite (if any) was discarded above
        _ioview.queue_tracker("host").set_depth(0)

    def iter_next(self):
        # consumer stall: time blocked on the prefetch threads — nonzero
        # totals mean the pipeline (not the device) bounds throughput
        t0 = time.perf_counter()
        for e in self.data_ready:
            e.wait()
        _ioview.note_stall("host", time.perf_counter() - t0)
        errs = [e for e in self.prefetch_errors if e is not None]
        if errs:
            # re-arm EVERY slot before raising so a caller that treats
            # the error as transient can keep iterating: the whole
            # composite batch is dropped (re-arming only the errored
            # slot would leave the other iterators one batch ahead —
            # silently mismatched data/labels for the rest of the epoch)
            for i in range(self.n_iter):
                self.prefetch_errors[i] = None
                self.next_state[i] = None
                self.next_position[i] = None
                self.data_ready[i].clear()
                self.data_taken[i].set()
            raise errs[0]
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iters"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, \
                "Number of entry mismatches between iters"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad, self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        # the captures described the batch just taken; until the
        # producer re-captures, the live inner position IS the next
        # undelivered sample.  Nulled BEFORE data_taken re-arms the
        # producer, so a fresh capture is never clobbered
        for i in range(self.n_iter):
            self.next_state[i] = None
            self.next_position[i] = None
        for e in self.data_taken:
            e.set()
        _ioview.queue_tracker("host").set_depth(0)
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    def position(self):
        """Position of the next-UNDELIVERED batch: the first wrapped
        iterator's position captured BEFORE the staged (or in-flight)
        fetch — the producer thread runs one batch ahead of the
        consumer, so the live inner position would over-report by that
        batch.  Falls back to the live inner position before the first
        fetch of an epoch (nothing is staged then)."""
        pos = self.next_position[0]
        return pos if pos is not None else self.iters[0].position()

    def state(self):
        """Durable state of the next-undelivered batch: the inner
        state(s) captured before the staged fetch.  Quiesces first
        (waits for the producers to finish staging, like ``reset``), so
        the captures are stable."""
        from . import io_resume
        for e in self.data_ready:
            e.wait()
        if self.n_iter == 1:
            return self.next_state[0]
        return {"v": io_resume.STATE_VERSION, "kind": "prefetch",
                "inner": list(self.next_state)}

    def restore(self, state):
        """Restore the wrapped iterator(s) and discard any staged
        batch (it belongs to the abandoned stream).  The producer
        threads then refetch from the restored state."""
        from . import io_resume
        if state is None:
            return
        if self.n_iter == 1:
            states = [state]
        else:
            io_resume.check_state(state, "prefetch")
            states = list(state["inner"])
            if len(states) != self.n_iter:
                raise MXNetError(
                    "prefetch state has %d inner entries, wrapper has "
                    "%d iterators" % (len(states), self.n_iter))
        for e in self.data_ready:
            e.wait()                 # quiesce: producers are parked
        for it, st in zip(self.iters, states):
            it.restore(st)           # each tier validates-then-commits
        for i in range(self.n_iter):
            self.prefetch_errors[i] = None
            self.next_state[i] = None
            self.next_position[i] = None
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        # the staged composite (if any) was discarded above
        _ioview.queue_tracker("host").set_depth(0)


def _init_data(data, allow_empty, default_name):
    """Normalize input data to a list of (name, numpy) (reference io.py)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {default_name + "_%d" % i: d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    out = {}
    for k, v in data.items():
        out[k] = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
    return list(sorted(out.items()))


def tunnel_limited_backend():
    """True when the accelerator is reached over a remote tunnel (the
    axon PJRT proxy): host->device bandwidth is a shared WAN-ish link,
    so background staging threads contend with step dispatch instead
    of overlapping it."""
    try:
        import jax
        dev = jax.devices()[0]
        return "axon" in getattr(dev.client, "platform_version", "")
    except (ImportError, RuntimeError, IndexError, AttributeError):
        return False


class DevicePrefetchIter:
    """Stage up to ``depth`` batches AHEAD onto the devices.

    The reference prefetcher's role (``src/io/iter_prefetcher.h:27-130``)
    extended across the device boundary: a background thread pulls host
    batches from ``it`` and runs ``stage_fn`` — typically
    ``ShardedTrainer.put_batch`` (device-side transpose/normalize +
    transfer) — so JPEG decode AND host→device transfer overlap the
    previous step's compute instead of serializing with it.  Iterating
    yields whatever ``stage_fn`` returns (a dict of staged device
    arrays, feedable straight to ``trainer.step``).
    """

    _warned_tunnel = False

    def __init__(self, it, stage_fn, depth=2):
        import queue as _queue
        if tunnel_limited_backend() and not DevicePrefetchIter._warned_tunnel:
            import logging
            DevicePrefetchIter._warned_tunnel = True
            logging.warning(
                "DevicePrefetchIter on a tunnel-limited accelerator "
                "backend: background staging contends with step "
                "dispatch on the same host link and measured 0.63x "
                "plain staging there (docs/perf.md) — prefer the "
                "inline put_batch path on such hosts")
        self._it = it
        self._stage = stage_fn
        self._depth = max(1, int(depth))
        self._queue = _queue.Queue(maxsize=self._depth)
        self._thread = None
        self._stop = False
        self._exhausted = False
        # (state, position) of the inner iterator captured BEFORE each
        # fetched-but-undelivered batch, oldest first: the wrapper's
        # state()/position() report pending[0] — the next UNDELIVERED
        # sample — never the inner reader's read-ahead point
        from collections import deque
        self._pending = deque()
        self._plock = threading.Lock()
        self._start()

    def depth(self):
        """Current staging-queue depth bound (a backpressure knob)."""
        return self._depth

    def set_depth(self, depth):
        """Retune the staging depth at runtime — the backpressure
        controller's actuator (io_resume.BackpressureController).
        Raising it lets the worker run further ahead; lowering it takes
        effect as the consumer drains below the new bound (staged
        batches are never discarded)."""
        depth = max(1, int(depth))
        with self._queue.mutex:
            self._queue.maxsize = depth
            self._queue.not_full.notify_all()
        self._depth = depth

    def _to_host_dict(self, batch):
        out = {}
        for desc, arr in zip(self._it.provide_data, batch.data):
            out[desc[0] if not hasattr(desc, "name") else desc.name] = \
                arr.asnumpy()
        for desc, arr in zip(self._it.provide_label or [], batch.label):
            out[desc[0] if not hasattr(desc, "name") else desc.name] = \
                arr.asnumpy()
        return out

    def _put(self, item):
        """Bounded put that gives up when reset() cancels the worker."""
        import queue as _queue
        while not self._stop:
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def _try_put(self, item):
        """Non-blocking put; False when the queue is full (the caller
        holds the item in its double-buffer slot instead)."""
        import queue as _queue
        try:
            self._queue.put_nowait(item)
            return True
        except _queue.Full:
            return False

    def _start(self):
        self._stop = False
        self._exhausted = False

        def worker():
            # payloads are tagged, so a stage_fn returning None or a
            # tuple is never mistaken for a control message.
            #
            # DOUBLE-BUFFERED staging (ISSUE 15): the worker holds up
            # to one staged batch ASIDE of the bounded queue, so when
            # the queue is full (backpressure) the NEXT batch's decode
            # + H2D staging dispatch still proceeds instead of waiting
            # behind the blocked put — the transfer overlaps the
            # current step's compute, and the moment the consumer takes
            # a batch the replacement is already staged (no pipeline
            # bubble of one decode+transfer per take).  An empty queue
            # flushes immediately, so consumer-bound pipelines see no
            # added latency.
            tracker = _ioview.queue_tracker("device")
            held = []        # staged, tracked, awaiting queue space
            # MXNET_TPU_OVERLAP=0 restores the strictly serial
            # decode -> stage -> blocking-put worker (held_cap 0)
            import os as _os
            held_cap = 0 if _os.environ.get(
                "MXNET_TPU_OVERLAP", "1") in ("0", "false", "False") \
                else 1
            try:
                src = iter(self._it)
                while True:
                    if self._stop:
                        return
                    # speculative pre-capture: every fetched-but-
                    # undelivered batch must have its BEFORE-state on
                    # the pending deque while it is in flight, or a
                    # state() read during the fetch would skip it; the
                    # entry is popped right back off when the fetch
                    # turns out to be the end of the epoch
                    pre = (_safe_state(self._it),
                           _safe_position(self._it))
                    with self._plock:
                        self._pending.append(pre)
                    try:
                        batch = next(src)
                    except StopIteration:
                        with self._plock:
                            self._pending.pop()
                        break
                    # opportunistic flush: hand over anything the
                    # consumer made room for, without blocking
                    while held and self._try_put(held[0]):
                        held.pop(0)
                    # io.prefetch fault seam: injected staging faults
                    # retry with backoff; exhaustion surfaces on the
                    # consumer like any other staging error (a
                    # kind=delay fault is a seeded slow device_stage)
                    t_work = time.perf_counter()
                    resilience.retry_call(
                        resilience.fault_point, args=("io.prefetch",),
                        retries=2, base_delay=0.01, max_delay=0.1,
                        exceptions=(resilience.FaultInjected,),
                        name="io.prefetch")
                    host = self._to_host_dict(batch)
                    nbytes = sum(getattr(v, "nbytes", 0)
                                 for v in host.values())
                    staged = self._stage(host)
                    _ioview.account("device_stage",
                                    time.perf_counter() - t_work,
                                    items=1, nbytes=nbytes)
                    # the tracker owns the depth counter: the old
                    # producer/consumer set(qsize()) pair raced and the
                    # exported depth flapped (ISSUE 14 satellite).
                    # Increment BEFORE the put: the consumer decrements
                    # after its take, so depth transiently over-reads by
                    # one instead of under-reading — an underflow would
                    # hit the tracker's 0-clamp and leave a permanent +1
                    # offset (a put that loses the race to a cancelled
                    # reset is settled by reset's set_depth(0))
                    tracker.adjust(+1)
                    held.append(("item", staged))
                    # hand the fresh batch over NOW if the queue has
                    # room — holding it until the next upstream fetch
                    # would add one upstream-production latency to
                    # every take on a producer-bound pipeline
                    while held and self._try_put(held[0]):
                        held.pop(0)
                    # block only once BOTH double-buffer slots are
                    # occupied; the blocked time is producer-starved —
                    # the consumer (the training step) is the slow side
                    while len(held) > held_cap:
                        t_put = time.perf_counter()
                        if not self._put(held[0]):
                            return
                        held.pop(0)
                        _ioview.note_starved(
                            "device", time.perf_counter() - t_put)
                while held:
                    if not self._put(held[0]):
                        return
                    held.pop(0)
            except BaseException as e:  # mxlint: allow-broad-except(surfaced on the consumer via the error queue item)
                # deliver any already-staged batch first: the serial
                # path (held_cap 0) put it before the failing fetch,
                # so the double-buffer must not silently drop it
                while held:
                    if not self._put(held[0]):
                        return
                    held.pop(0)
                self._put(("error", e))
                return
            self._put(("end", None))
        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration     # iterator protocol: stays exhausted
        t0 = time.perf_counter()
        kind, val = self._queue.get()
        _ioview.note_stall("device", time.perf_counter() - t0)
        if kind == "end":
            self._exhausted = True
            raise StopIteration
        if kind == "error":
            self._exhausted = True
            raise val
        # only staged items count toward occupancy (end/error control
        # messages were never tracked in)
        _ioview.queue_tracker("device").adjust(-1)
        with self._plock:
            if self._pending:
                self._pending.popleft()
        return val

    next = __next__

    def position(self):
        """Position of the next-UNDELIVERED batch: the inner position
        captured before the oldest staged (or in-flight) batch — the
        worker runs up to ``depth``+held batches ahead of the consumer,
        so the live inner position would over-report by that much.
        Falls back to the live inner position when nothing is staged."""
        with self._plock:
            if self._pending:
                return self._pending[0][1]
        return self._it.position() if hasattr(self._it, "position") \
            else None

    def state(self):
        """Durable state of the next-undelivered batch (pending[0]'s
        pre-fetch capture), compensating for every staged batch the
        worker ran ahead."""
        with self._plock:
            if self._pending:
                return self._pending[0][0]
        return _safe_state(self._it)

    def restore(self, state):
        """Cancel the worker, discard staged batches (they belong to
        the abandoned stream — a stale worker error goes with them),
        restore the wrapped iterator, restart.  The inner restore
        validates-then-commits, so a failure here leaves the wrapped
        iterator restorable from the same state (the worker is simply
        stopped; a follow-up restore or reset revives it)."""
        if state is None:
            return
        self._cancel_worker()
        if not callable(getattr(self._it, "restore", None)):
            raise MXNetError(
                "%s wraps %s, which has no restore()"
                % (type(self).__name__, type(self._it).__name__))
        self._it.restore(state)
        self._exhausted = False
        self._start()

    def _cancel_worker(self):
        """Stop the worker and drain the queue (staged batches are
        discarded); returns a worker error the consumer never saw."""
        import queue as _queue
        self._stop = True
        pending_error = None
        while self._thread.is_alive() or not self._queue.empty():
            try:
                kind, val = self._queue.get(timeout=0.1)
                if kind == "error":
                    pending_error = val
            except _queue.Empty:
                pass
        self._thread.join()
        with self._plock:
            self._pending.clear()
        _ioview.queue_tracker("device").set_depth(0)
        return pending_error

    def reset(self):
        """Cancel the worker (at most ``depth`` staged batches are
        discarded — a mid-epoch reset must not decode the rest of the
        epoch), rewind the wrapped iterator, restart.  A worker error
        that the consumer never saw is re-raised here rather than
        silently dropped."""
        pending_error = self._cancel_worker()
        if pending_error is not None:
            self._exhausted = True
            raise pending_error
        self._it.reset()
        self._start()


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference io.py NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)

        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
            self.data = [(k, v[self.idx]) for k, v in self.data]
            self.label = [(k, v[self.idx]) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - \
                self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]

        self.data_list = [x[1] for x in self.data] + \
            [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle
        self._epochs = 0

    @property
    def provide_data(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        self._epochs += 1
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + \
                (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def position(self):
        """{"epoch", "offset"}: samples consumed this epoch (advisory —
        see :meth:`DataIter.position`)."""
        return {"epoch": self._epochs,
                "offset": int(min(max(0, self.cursor + self.batch_size),
                                  self.num_data))}

    def state(self):
        """Durable state.  NOTE: a ``shuffle=True`` iterator permutes
        ONCE at construction from the global numpy RNG — an exact
        restore into a fresh process requires seeding ``np.random``
        identically before reconstructing (the order is part of the
        arrays, not of this state)."""
        from . import io_resume
        pos = self.position()
        return {"v": io_resume.STATE_VERSION, "kind": "ndarray",
                "epoch": pos["epoch"], "offset": pos["offset"],
                "num_data": int(self.num_data)}

    def restore(self, state):
        from . import io_resume
        io_resume.check_state(state, "ndarray")
        if int(state["num_data"]) != int(self.num_data):
            raise MXNetError(
                "ndarray state is for %s samples, iterator has %d"
                % (state["num_data"], self.num_data))
        offset = int(state["offset"])
        if not 0 <= offset <= self.num_data:
            raise MXNetError("ndarray offset %d out of range [0, %d]"
                             % (offset, self.num_data))
        self._epochs = int(state["epoch"])
        self.cursor = offset - self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [array(x[1][self.cursor:self.cursor + self.batch_size])
                    for x in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [array(np.concatenate((x[1][self.cursor:], x[1][:pad]),
                                     axis=0)) for x in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


def _read_idx_file(path, expect_magic):
    """Read an MNIST idx-ubyte file, optionally gzipped
    (reference src/io/iter_mnist.cc:71-150)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        buf = f.read()
    magic, = struct.unpack(">i", buf[:4])
    ndim = magic % 256
    dims = struct.unpack(">" + "i" * ndim, buf[4:4 + 4 * ndim])
    data = np.frombuffer(buf, dtype=np.uint8, offset=4 + 4 * ndim)
    return data.reshape(dims)


class MNISTIter(DataIter):
    """MNIST idx-ubyte reader (reference src/io/iter_mnist.cc:21-254)."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, seed=0, silent=False,
                 num_parts=1, part_index=0, **kwargs):
        super().__init__(batch_size)
        img = _read_idx_file(image, 2051).astype(np.float32) / 255.0
        lab = _read_idx_file(label, 2049).astype(np.float32)
        if num_parts > 1:  # sharded read for data-parallel workers
            n = img.shape[0] // num_parts
            img = img[part_index * n:(part_index + 1) * n]
            lab = lab[part_index * n:(part_index + 1) * n]
        if shuffle:
            rng = np.random.RandomState(seed)
            order = rng.permutation(img.shape[0])
            img, lab = img[order], lab[order]
        if flat:
            img = img.reshape(img.shape[0], -1)
        else:
            img = img.reshape(img.shape[0], 1, img.shape[1], img.shape[2])
        self._part_index = int(part_index)
        self._num_parts = int(num_parts)
        self._inner = NDArrayIter(img, lab, batch_size=batch_size,
                                  last_batch_handle="discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    def position(self):
        pos = self._inner.position()
        pos.update(shard=self._part_index, num_shards=self._num_parts)
        return pos

    def state(self):
        return self._inner.state()

    def restore(self, state):
        self._inner.restore(state)


class CSVIter(DataIter):
    """CSV reader (reference src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32,
                          ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = np.zeros(data.shape[0], dtype=np.float32)
        self._inner = NDArrayIter(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    def position(self):
        return self._inner.position()

    def state(self):
        return self._inner.state()

    def restore(self, state):
        self._inner.restore(state)


def ImageRecordIter(*args, **kwargs):
    """Native JPEG record iterator (reference io.ImageRecordIter,
    src/io/iter_image_recordio_2.cc) — see
    :class:`mxnet_tpu.io_native.ImageRecordIter`.  Requires the native
    library built with libjpeg; use :class:`mxnet_tpu.image.ImageIter`
    as the pure-python fallback."""
    from .io_native import ImageRecordIter as _Native
    return _Native(*args, **kwargs)
