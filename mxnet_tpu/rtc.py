"""Runtime kernel compilation (``mx.rtc``) — Pallas edition.

Reference: ``python/mxnet/rtc.py`` + ``src/common/mxrtc.cc`` — the
reference takes CUDA C source at runtime, NVRTC-compiles it, caches by
source, and launches via the engine.  The TPU-native equivalent takes a
**Pallas kernel body** (python source or a callable) at runtime,
Mosaic-compiles it on first launch (jit tracing = the NVRTC step), and
runs it on NDArrays.

API shape mirrors the reference::

    x = mx.nd.zeros((1000, 10))
    y = mx.nd.zeros((1000, 10))
    rtc = mx.rtc.Rtc('abs', [('x', x)], [('y', y)], '''
        y_ref[:] = jnp.abs(x_ref[:])
    ''')
    rtc.push([x], [y], (1, 1, 1), (1, 1, 1))

The kernel body sees ``<name>_ref`` for every input/output (Pallas
``pl.Ref``), plus ``pl`` / ``pltpu`` / ``jnp`` / ``jax`` and
``grid_dims``/``block_dims`` are accepted for API parity (the TPU grid
is derived from ``grid_dims[0]`` when > 1: the kernel is then launched
over a 1-d grid with ``pl.program_id(0)`` available, like blockIdx.x).
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from . import ndarray as _nd

_CACHE = {}


class Rtc:
    """Runtime-compiled kernel over NDArrays (reference rtc.py Rtc)."""

    def __init__(self, name, inputs, outputs, kernel):
        self.name = name
        self._in_names = [n for n, _ in inputs]
        self._out_names = [n for n, _ in outputs]
        if callable(kernel):
            self._kernel = kernel
        else:
            # cache by (name, source, arg names), as mxrtc.cc caches PTX
            # by source: re-creating an Rtc with identical source skips
            # the compile.  Arg names are part of the key because the
            # compiled function's parameters are built from them — same
            # source with different variable names is a different kernel.
            key = (name, kernel,
                   tuple(self._in_names), tuple(self._out_names))
            cached = _CACHE.get(key)
            if cached is None:
                cached = self._compile_source(kernel)
                _CACHE[key] = cached
            self._kernel = cached
        self._call_cache = {}

    def _compile_source(self, source):
        """'NVRTC' step: build a python kernel function from the body
        source with the ref-naming convention."""
        args = ", ".join("%s_ref" % n
                         for n in self._in_names + self._out_names)
        body = "\n".join("    " + line
                         for line in source.strip("\n").split("\n"))
        code = "def _rtc_kernel(%s):\n%s\n" % (args, body)
        ns = {}
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        try:
            from jax.experimental.pallas import tpu as pltpu
        except ImportError:  # pragma: no cover
            pltpu = None
        glb = {"jax": jax, "jnp": jnp, "pl": pl, "pltpu": pltpu,
               "np": np}
        try:
            exec(compile(code, "<mx.rtc:%s>" % self.name, "exec"),
                 glb, ns)
        except SyntaxError as e:
            raise MXNetError("rtc kernel %r failed to compile: %s"
                             % (self.name, e))
        return ns["_rtc_kernel"]

    def push(self, ins, outs, grid_dims=(1, 1, 1), block_dims=(1, 1, 1)):
        """Launch on the given NDArrays; results are written into
        ``outs`` (reference push semantics).  ``grid_dims[0] > 1`` runs a
        1-d Pallas grid (blockIdx.x ≙ pl.program_id(0)); block_dims is
        accepted for parity (the VPU has no thread blocks)."""
        import jax
        from jax.experimental import pallas as pl

        if len(ins) != len(self._in_names) or \
                len(outs) != len(self._out_names):
            raise MXNetError("rtc push: argument count mismatch")
        grid = int(grid_dims[0]) if grid_dims and grid_dims[0] > 1 else None
        out_shapes = tuple(jax.ShapeDtypeStruct(o.shape, o.data.dtype)
                           for o in outs)
        key = (tuple((i.shape, str(i.data.dtype)) for i in ins),
               tuple((o.shape, str(o.data.dtype)) for o in outs), grid)
        fn = self._call_cache.get(key)
        if fn is None:
            interpret = ins[0].context.device_type == "cpu" if ins else True
            kw = {"grid": grid} if grid is not None else {}
            call = pl.pallas_call(self._kernel,
                                  out_shape=list(out_shapes),
                                  interpret=interpret, **kw)
            fn = jax.jit(lambda *a: call(*a))
            self._call_cache[key] = fn
        results = fn(*[i.data for i in ins])
        if not isinstance(results, (list, tuple)):
            results = [results]
        for o, r in zip(outs, results):
            # on-device writeback (no host roundtrip) — same pattern as
            # the imperative aux writeback in ops/__init__.py
            o._set_data(r)
        return outs
