"""Device contexts: ``mx.cpu() / mx.gpu() / mx.tpu()``.

Reference: ``include/mxnet/base.h:117-208`` (Context{dev_type, dev_id}) and
``python/mxnet/context.py``.  TPU-native design: a Context is a *name* for a
JAX device.  ``tpu`` is first class; ``gpu`` resolves to an accelerator if one
exists (so reference scripts written against ``mx.gpu(0)`` run unchanged on a
TPU chip); ``cpu`` is the host platform.  Multi-device placement and sharding
live in :mod:`mxnet_tpu.parallel`; a plain Context maps to exactly one
``jax.Device``.
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus"]

_DEVTYPE2ID = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}
_ID2DEVTYPE = {v: k for k, v in _DEVTYPE2ID.items()}


class Context:
    """A device context.  With-statement scoping matches the reference."""

    _default = threading.local()
    devtype2str = _ID2DEVTYPE
    devstr2type = _DEVTYPE2ID

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_type, self.device_id = device_type.device_type, device_type.device_id
        else:
            if device_type not in _DEVTYPE2ID:
                raise MXNetError(f"unknown device type {device_type}")
            self.device_type = device_type
            self.device_id = int(device_id)
        self._old_ctx = None

    @property
    def device_typeid(self):
        return _DEVTYPE2ID[self.device_type]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __str__(self):
        return self.__repr__()

    def __enter__(self):
        self._old_ctx = getattr(Context._default, "value", None)
        Context._default.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default.value = self._old_ctx

    # -- JAX device resolution -------------------------------------------
    def jax_device(self):
        """Resolve this context to a concrete jax.Device.

        ``tpu``/``gpu`` -> i-th accelerator (any non-cpu platform, so code
        written for ``mx.gpu()`` runs on a TPU chip); ``cpu`` -> host device.
        """
        import jax
        # local_devices: in a multi-process job (dist kvstore) the global
        # enumeration starts with process 0's devices, which other ranks
        # cannot address — a context always means a device THIS host owns
        # (reference: Context device ids are per-node)
        if self.device_type in ("cpu", "cpu_pinned"):
            devs = (jax.local_devices(backend="cpu") if _has_platform("cpu")
                    else jax.local_devices())
        else:
            devs = _accelerators()
            if not devs:  # CPU-only host: impersonate devices (SURVEY §4.2)
                devs = jax.local_devices()
        if self.device_id >= len(devs):
            raise MXNetError(
                f"context {self} out of range: only {len(devs)} device(s) available")
        return devs[self.device_id]

    @staticmethod
    def from_string(s):
        """Parse 'tpu(0)' / 'cpu' style strings (reference Context::FromString)."""
        s = s.strip()
        if "(" in s:
            name, _, rest = s.partition("(")
            return Context(name.strip(), int(rest.rstrip(")")))
        return Context(s, 0)


def _has_platform(name):
    import jax
    try:
        return bool(jax.devices(name))
    except RuntimeError:
        return False


def _accelerators():
    """This host's non-cpu jax devices, in enumeration order."""
    import jax
    return [d for d in jax.local_devices() if d.platform != "cpu"] or []


def cpu(device_id=0):
    return Context("cpu", device_id)


def gpu(device_id=0):
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def current_context():
    ctx = getattr(Context._default, "value", None)
    return ctx if ctx is not None else Context("cpu", 0)


def num_gpus():
    return len(_accelerators())


def num_tpus():
    return len(_accelerators())
