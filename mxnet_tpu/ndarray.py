"""NDArray: the imperative tensor, backed by an immutable ``jax.Array``.

Reference: ``include/mxnet/ndarray.h`` + ``src/ndarray/ndarray.cc`` +
``python/mxnet/ndarray.py``.  TPU-native re-design:

* The reference NDArray is a mutable buffer whose reads/writes are ordered by
  the threaded dependency engine (``ndarray.h:366-427`` Chunk{storage, var}).
  Here an NDArray is a *mutable handle to an immutable jax.Array*: every
  "in-place" op rebinds the handle.  XLA's async dispatch plays the engine's
  role — ops return immediately, ``wait_to_read`` == ``block_until_ready``
  (reference ``WaitToRead``, ``engine.h:186``).
* Views (``Slice/At/Reshape``, ``ndarray.h:297-331``) share their parent
  handle: writes through a view functionally update the parent and are seen by
  all other views, matching the reference's shared-Chunk semantics.
* ``save``/``load`` keep the reference's name-prefixed container layout
  (``src/c_api/c_api.cc:204-252``, ``ndarray.cc`` NDArray::Save) so Module
  checkpoints interop at the file level.

Op functions (``mx.nd.conv2d`` style) are generated from the op registry at
import time, mirroring ``python/mxnet/ndarray.py:2281-2423``'s codegen over the
C op registry.
"""
from __future__ import annotations

import struct
import sys

import numpy as _np

from .base import MXNetError, dtype_np, dtype_id, DTYPE_ID_TO_NP, numeric_types
from .context import Context, current_context

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concatenate", "save", "load", "waitall", "onehot_encode", "imdecode"]


def _jnp():
    import jax.numpy as jnp
    return jnp


# Generated op functions (mx.nd.slice, mx.nd.sum, ...) are injected into this
# module's namespace and would shadow python builtins for code below — capture
# the builtins we use first.
_py_slice = slice


class NDArray:
    """Multi-dimensional array on a device context."""

    __slots__ = ("_data", "_view_of", "_index", "_writable", "__weakref__")
    # numpy should defer binary ops to us
    __array_priority__ = 100.0

    def __init__(self, data, view_of=None, index=None, writable=True):
        self._data = data          # jax.Array (None when this is a view)
        self._view_of = view_of    # parent NDArray for writeback views
        self._index = index        # basic-index tuple into parent
        self._writable = writable

    # ------------------------------------------------------------------ core
    @property
    def data(self):
        """The underlying jax.Array (resolving views lazily)."""
        if self._view_of is not None:
            return self._view_of.data[self._index]
        return self._data

    def _set_data(self, new_data):
        """Rebind the handle (the 'write' half of the engine var protocol)."""
        if not self._writable:
            raise MXNetError("NDArray is not writable")
        if self._view_of is not None:
            parent = self._view_of
            parent._set_data(parent.data.at[self._index].set(new_data))
        else:
            self._data = new_data

    @property
    def shape(self):
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return _np.dtype(self.data.dtype)

    @property
    def ndim(self):
        return self.data.ndim

    @property
    def size(self):
        return int(self.data.size)

    @property
    def context(self):
        dev = next(iter(self.data.devices()))
        import jax
        # report the LOCAL index (multi-process global device ids are not
        # valid per-node context ids; reference ctx ids are per-node)
        if dev.platform == "cpu":
            local = jax.local_devices(backend="cpu")
            return Context("cpu", local.index(dev) if dev in local else dev.id)
        # single accelerator platform: report as tpu (gpu alias resolves there)
        accels = [d for d in jax.local_devices() if d.platform != "cpu"]
        idx = accels.index(dev) if dev in accels else dev.id
        return Context("tpu", idx)

    ctx = context

    @property
    def T(self):
        return NDArray(self.data.T)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of an NDArray with multiple "
                             "elements is ambiguous.")
        return bool(self.asscalar())

    def __repr__(self):
        return f"<NDArray {'x'.join(map(str, self.shape))} @{self.context} " \
               f"{self.dtype.name}>\n{self.asnumpy()!r}"

    # -------------------------------------------------------------- host sync
    def asnumpy(self):
        """Copy to host numpy array (blocks; reference WaitToRead + SyncCopyToCPU)."""
        return _np.asarray(self.data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        self.data.block_until_ready()

    wait_to_write = wait_to_read

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # ------------------------------------------------------------- conversion
    def astype(self, dtype):
        return NDArray(self.data.astype(dtype_np(dtype)))

    def copy(self):
        return NDArray(_jnp().array(self.data))

    def copyto(self, other):
        """Copy into an existing NDArray (in-place write) or to a Context."""
        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise MXNetError(
                    f"copyto shape mismatch {self.shape} vs {other.shape}")
            import jax
            src = self.data.astype(other.dtype)
            other._set_data(jax.device_put(src, other._target_device()))
            return other
        if isinstance(other, Context):
            import jax
            return NDArray(jax.device_put(self.data, other.jax_device()))
        raise TypeError(f"copyto does not support type {type(other)}")

    def _target_device(self):
        return next(iter(self.data.devices()))

    def as_in_context(self, ctx):
        if self.context == ctx:
            return self
        return self.copyto(ctx)

    def reshape(self, shape, **kwargs):
        if isinstance(shape, int):
            shape = (shape,)
        from . import ops
        return ops.imperative_invoke("Reshape", self, shape=tuple(shape))

    def broadcast_to(self, shape):
        return NDArray(_jnp().broadcast_to(self.data, tuple(shape)))

    # --------------------------------------------------------------- indexing
    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key.asnumpy()
        basic = isinstance(key, (int, _py_slice)) or (
            isinstance(key, tuple) and all(isinstance(k, (int, _py_slice))
                                           for k in key))
        if basic and self._view_of is None:
            # basic indexing -> writeback view (reference Slice/At share Chunk)
            return NDArray(None, view_of=self, index=key,
                           writable=self._writable)
        # nested view or advanced indexing: plain copy (reads only)
        return NDArray(self.data[key])

    def __setitem__(self, key, value):
        if isinstance(key, NDArray):
            key = key.asnumpy()
        if isinstance(value, NDArray):
            value = value.data
        elif isinstance(value, numeric_types):
            pass
        else:
            value = _np.asarray(value)
        if self._view_of is not None:
            parent = self._view_of
            sub = parent.data[self._index]
            sub = sub.at[key].set(value) if not _is_full_slice(key, sub.ndim) \
                else _jnp().broadcast_to(_jnp().asarray(value, sub.dtype), sub.shape)
            parent._set_data(parent.data.at[self._index].set(sub))
        else:
            if _is_full_slice(key, self.ndim):
                self._set_data(_jnp().broadcast_to(
                    _jnp().asarray(value, self.dtype), self.shape).astype(self.dtype))
            else:
                self._set_data(self.data.at[key].set(value))

    def slice(self, start, stop):
        return self[int(start):int(stop)]

    def at(self, idx):
        return self[int(idx)]

    # ------------------------------------------------------------- arithmetic
    # Routed through the op registry so the autograd tape sees them
    # (reference: python operators dispatch to registered ops,
    # python/mxnet/ndarray.py _ufunc_helper).
    def _binary_op(self, other, op, scalar_op, rscalar_op=None, reverse=False):
        from . import ops
        if isinstance(other, numeric_types):
            name = (rscalar_op or scalar_op) if reverse else scalar_op
            return ops.imperative_invoke(name, self, scalar=float(other))
        if not isinstance(other, NDArray):
            other = array(other)
        a, b = (other, self) if reverse else (self, other)
        return ops.imperative_invoke(op, a, b)

    def _binary(self, other, fn, reverse=False):
        if isinstance(other, NDArray):
            other = other.data
        a, b = (other, self.data) if reverse else (self.data, other)
        return NDArray(fn(a, b))

    def __add__(self, o): return self._binary_op(o, "broadcast_add", "_plus_scalar")
    def __radd__(self, o): return self.__add__(o)
    def __sub__(self, o): return self._binary_op(o, "broadcast_sub", "_minus_scalar", "_rminus_scalar")
    def __rsub__(self, o): return self._binary_op(o, "broadcast_sub", "_minus_scalar", "_rminus_scalar", True)
    def __mul__(self, o): return self._binary_op(o, "broadcast_mul", "_mul_scalar")
    def __rmul__(self, o): return self.__mul__(o)
    def __truediv__(self, o): return self._binary_op(o, "broadcast_div", "_div_scalar", "_rdiv_scalar")
    def __rtruediv__(self, o): return self._binary_op(o, "broadcast_div", "_div_scalar", "_rdiv_scalar", True)
    def __div__(self, o): return self.__truediv__(o)
    def __rdiv__(self, o): return self.__rtruediv__(o)
    def __mod__(self, o): return self._binary_op(o, "broadcast_mod", "_mod_scalar")
    def __pow__(self, o): return self._binary_op(o, "broadcast_power", "_power_scalar", "_rpower_scalar")
    def __rpow__(self, o): return self._binary_op(o, "broadcast_power", "_power_scalar", "_rpower_scalar", True)

    def __neg__(self):
        from . import ops
        return ops.imperative_invoke("_mul_scalar", self, scalar=-1.0)

    def __abs__(self):
        from . import ops
        return ops.imperative_invoke("abs", self)

    def __iadd__(self, o):
        self._set_data((self + o).data.astype(self.dtype))
        return self

    def __isub__(self, o):
        self._set_data((self - o).data.astype(self.dtype))
        return self

    def __imul__(self, o):
        self._set_data((self * o).data.astype(self.dtype))
        return self

    def __itruediv__(self, o):
        self._set_data((self / o).data.astype(self.dtype))
        return self

    def __eq__(self, o):
        if isinstance(o, (NDArray,) + numeric_types) or isinstance(o, _np.ndarray):
            return self._binary(o, _jnp().equal)
        return NotImplemented

    def __ne__(self, o):
        if isinstance(o, (NDArray,) + numeric_types) or isinstance(o, _np.ndarray):
            return self._binary(o, _jnp().not_equal)
        return NotImplemented

    def __gt__(self, o): return self._binary(o, _jnp().greater)
    def __ge__(self, o): return self._binary(o, _jnp().greater_equal)
    def __lt__(self, o): return self._binary(o, _jnp().less)
    def __le__(self, o): return self._binary(o, _jnp().less_equal)
    __hash__ = None

    # ---------------------------------------------------------- reduce sugar
    def _reduce(self, op, axis, keepdims):
        from . import ops
        return ops.imperative_invoke(op, self, axis=axis, keepdims=keepdims)

    def sum(self, axis=None, keepdims=False):
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._reduce("mean", axis, keepdims)

    def max(self, axis=None, keepdims=False):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce("min", axis, keepdims)

    def argmax(self, axis=None):
        return NDArray(_jnp().argmax(self.data, axis=axis))

    def argmin(self, axis=None):
        return NDArray(_jnp().argmin(self.data, axis=axis))

    def flatten(self):
        return self.reshape((self.shape[0], -1)) if self.ndim > 1 \
            else self.reshape((self.size,))

    # ------------------------------------------------------------- autograd
    def attach_grad(self, grad_req="write"):
        from . import autograd
        autograd.mark_variables([self], [zeros_like(self)], grad_req)

    @property
    def grad(self):
        from . import autograd
        return autograd._get_grad(self)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from . import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)


def _is_full_slice(key, ndim):
    return key == _py_slice(None) or (
        isinstance(key, tuple) and len(key) == 0)


# ---------------------------------------------------------------- creation

def _device_for(ctx):
    ctx = ctx or current_context()
    return ctx.jax_device()


def array(source, ctx=None, dtype=None):
    """Create an NDArray from any array-like."""
    import jax
    if isinstance(source, NDArray):
        source = source.asnumpy()
    keep_dtype = isinstance(source, _np.ndarray)
    arr = _np.asarray(source, dtype=dtype_np(dtype) if dtype is not None else None)
    if dtype is None:
        # reference default: python lists become float32; numpy arrays keep
        # their dtype except float64 -> float32 (mx default real type)
        if arr.dtype == _np.float64 or not keep_dtype:
            arr = arr.astype(_np.float32)
    return NDArray(jax.device_put(arr, _device_for(ctx)))


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=None):
    import jax
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    with jax.default_device(_device_for(ctx)):
        return NDArray(_jnp().zeros(shape, dtype_np(dtype)))


def ones(shape, ctx=None, dtype=None):
    import jax
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    with jax.default_device(_device_for(ctx)):
        return NDArray(_jnp().ones(shape, dtype_np(dtype)))


def full(shape, val, ctx=None, dtype=None):
    import jax
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    with jax.default_device(_device_for(ctx)):
        return NDArray(_jnp().full(shape, val, dtype_np(dtype)))


def zeros_like(arr):
    return NDArray(_jnp().zeros_like(arr.data))


def ones_like(arr):
    return NDArray(_jnp().ones_like(arr.data))


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    import jax
    with jax.default_device(_device_for(ctx)):
        out = _jnp().arange(start, stop, step, dtype_np(dtype))
        if repeat > 1:
            out = _jnp().repeat(out, repeat)
        return NDArray(out)


def concatenate(arrays, axis=0, always_copy=True):
    return NDArray(_jnp().concatenate([a.data for a in arrays], axis=axis))


def onehot_encode(indices, out):
    depth = out.shape[1]
    import jax.nn as jnn
    out._set_data(jnn.one_hot(indices.data.astype(_np.int32), depth,
                              dtype=out.dtype))
    return out


def imdecode(buf, **kwargs):  # minimal parity hook; full version in image.py
    from . import image
    return image.imdecode(buf, **kwargs)


def waitall():
    """Block until all async work is done (reference Engine::WaitForAll)."""
    import jax
    jax.effects_barrier()


# ------------------------------------------------------------------ save/load
# Container layout follows the reference (`c_api.cc:204-252`):
#   u64 magic, u64 reserved, u64 n_arrays, arrays..., u64 n_names, names...
# Each array (`ndarray.cc` NDArray::Save):
#   u32 ndim, u32*ndim shape, i32 dev_type, i32 dev_id, i32 type_flag, raw data
_LIST_MAGIC = 0x112


def _write_str(f, s):
    b = s.encode("utf-8")
    f.write(struct.pack("<Q", len(b)))
    f.write(b)


def _read_str(f):
    n, = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8")


def _save_one(f, arr: NDArray):
    np_arr = _np.ascontiguousarray(arr.asnumpy())
    tid = dtype_id(np_arr.dtype)
    f.write(struct.pack("<I", np_arr.ndim))
    f.write(struct.pack(f"<{np_arr.ndim}I", *np_arr.shape))
    ctx = arr.context
    f.write(struct.pack("<ii", ctx.device_typeid, ctx.device_id))
    f.write(struct.pack("<i", tid))
    if np_arr.dtype.name == "bfloat16":
        f.write(np_arr.view(_np.uint16).tobytes())
    else:
        f.write(np_arr.tobytes())


def _load_one(f):
    ndim, = struct.unpack("<I", f.read(4))
    shape = struct.unpack(f"<{ndim}I", f.read(4 * ndim)) if ndim else ()
    dev_type, dev_id = struct.unpack("<ii", f.read(8))
    tid, = struct.unpack("<i", f.read(4))
    np_dt = dtype_np(DTYPE_ID_TO_NP[tid])
    count = 1
    for s in shape:
        count *= s
    if np_dt.name == "bfloat16":
        raw = _np.frombuffer(f.read(count * 2), dtype=_np.uint16)
        data = raw.view(np_dt).reshape(shape)
    else:
        data = _np.frombuffer(f.read(count * np_dt.itemsize),
                              dtype=np_dt).reshape(shape)
    return array(data, dtype=np_dt)


def save(fname, data):
    """Save a list of NDArrays or dict of str->NDArray (reference MXNDArraySave)."""
    if isinstance(data, NDArray):
        data = [data]
    names, arrays = [], []
    if isinstance(data, dict):
        for k in sorted(data):
            names.append(k)
            arrays.append(data[k])
    else:
        arrays = list(data)
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            _save_one(f, a)
        f.write(struct.pack("<Q", len(names)))
        for n in names:
            _write_str(f, n)


def _load_stream(f, what):
    magic, _ = struct.unpack("<QQ", f.read(16))
    if magic != _LIST_MAGIC:
        raise MXNetError(f"invalid NDArray {what}")
    n, = struct.unpack("<Q", f.read(8))
    arrays = [_load_one(f) for _ in range(n)]
    m, = struct.unpack("<Q", f.read(8))
    names = [_read_str(f) for _ in range(m)]
    if names:
        return dict(zip(names, arrays))
    return arrays


def load(fname):
    """Load from :func:`save`'s format; returns list or dict matching input."""
    with open(fname, "rb") as f:
        return _load_stream(f, f"file {fname}")


def load_buffer(buf):
    """Load NDArrays from in-memory bytes (reference
    MXNDArrayLoadFromBuffer, c_api.cc) — the C predict API hands the
    .params content as a buffer, not a path."""
    import io
    return _load_stream(io.BytesIO(buf), "buffer")


# Op functions (mx.nd.relu etc.) are attached by ops/__init__ at import time.
def _register_op_functions(fns):
    mod = sys.modules[__name__]
    for name, fn in fns.items():
        setattr(mod, name, fn)
