/*!
 * Flat C ABI: the MXNDArray* / MXSymbol* subsets of the reference
 * include/mxnet/c_api.h (impl src/c_api/c_api.cc) — the seam the
 * reference language bindings (cpp/R/Scala/Perl/JNI) hang off.
 *
 * Build: `make libmxtpu.so` (src/Makefile).  Error convention: every
 * function returns 0 on success, -1 on failure with the message
 * available from MXGetLastError() (reference API_BEGIN/API_END role).
 *
 * Handles are opaque; NDArray handles wrap real mxnet_tpu NDArrays and
 * Symbol handles real Symbols (not session-local copies), so files
 * written here are byte-compatible with the python side and vice
 * versa.  Returned const char* / array pointers stay valid until the
 * next ABI call on the same handle (string lists) or the next
 * MXNDArrayLoad / MXSymbolListAtomicSymbolCreators on the same thread
 * (global scratch), matching the reference's ret-store semantics.
 */
#ifndef MXNET_TPU_C_API_H_
#define MXNET_TPU_C_API_H_

#ifdef __cplusplus
extern "C" {
#endif

#include <stddef.h>

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *AtomicSymbolCreator;
typedef void *KVStoreHandle;
typedef void *RecordIOHandle;

const char *MXGetLastError();

/* dtype codes follow the reference: 0=float32 1=float64 2=float16
 * 3=uint8 4=int32 5=int8 6=int64 (7=bfloat16, TPU extension) */

/* ---------------------------------------------------------- ndarray */
int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle *out);
int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle *out);
/* size is the ELEMENT count (reference convention) */
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t size);
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                      const mx_uint **out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype);
int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                        int *out_dev_id);
int MXNDArrayReshape(NDArrayHandle handle, int ndim, int *dims,
                     NDArrayHandle *out);
int MXNDArraySlice(NDArrayHandle handle, mx_uint slice_begin,
                   mx_uint slice_end, NDArrayHandle *out);
/* reference binary container (arg:/aux: keyed or positional) */
int MXNDArraySave(const char *fname, mx_uint num_args,
                  NDArrayHandle *args, const char **keys);
int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names);
int MXNDArrayWaitAll();
int MXNDArrayFree(NDArrayHandle handle);

/* ----------------------------------------------------------- symbol */
int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                     AtomicSymbolCreator **out_array);
int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char **name);
int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator,
                               mx_uint num_param, const char **keys,
                               const char **vals, SymbolHandle *out);
int MXSymbolCreateVariable(const char *name, SymbolHandle *out);
/* binds args into an atomic symbol IN PLACE (reference semantics) */
int MXSymbolCompose(SymbolHandle sym, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args);
int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out);
int MXSymbolSaveToJSON(SymbolHandle symbol, const char **out_json);
int MXSymbolSaveToFile(SymbolHandle symbol, const char *fname);
int MXSymbolListArguments(SymbolHandle symbol, mx_uint *out_size,
                          const char ***out_str_array);
int MXSymbolListOutputs(SymbolHandle symbol, mx_uint *out_size,
                        const char ***out_str_array);
int MXSymbolListAuxiliaryStates(SymbolHandle symbol, mx_uint *out_size,
                                const char ***out_str_array);
int MXSymbolGetAttr(SymbolHandle symbol, const char *key,
                    const char **out, int *success);
int MXSymbolSetAttr(SymbolHandle symbol, const char *key,
                    const char *value);
/* flat [k0, v0, k1, v1, ...] pairs (reference ListAttrShallow) */
int MXSymbolListAttrShallow(SymbolHandle symbol, mx_uint *out_size,
                            const char ***out_str_array);
int MXSymbolGetName(SymbolHandle symbol, const char **out, int *success);
int MXSymbolFree(SymbolHandle symbol);

/* ---------------------------------------------------------- kvstore */
int MXKVStoreCreate(const char *type, KVStoreHandle *out);
int MXKVStoreGetType(KVStoreHandle handle, const char **type);
int MXKVStoreGetRank(KVStoreHandle handle, int *rank);
int MXKVStoreGetGroupSize(KVStoreHandle handle, int *size);
int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals);
int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
/* per-push update rule, C side in charge (reference contract):
 * mutate `local` in place via MXNDArraySyncCopyFromCPU.
 *
 * Symbol-visibility contract: the python-side trampoline receives the
 * addresses of this library's MXTPUWrapNDArray / MXNDArrayFree from
 * MXKVStoreSetUpdater itself, so installing an updater does NOT
 * require the library's symbols to be globally visible — a host
 * application may dlopen(libmxtpu.so) with the default RTLD_LOCAL.
 * Embedders that drive mxnet_tpu.c_api directly (without this entry
 * point) must either load the library with RTLD_GLOBAL or announce
 * its path once via mxnet_tpu.c_api.register_library(path). */
typedef void (MXKVStoreUpdater)(int key, NDArrayHandle recv,
                                NDArrayHandle local, void *handle);
int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle);
int MXKVStoreFree(KVStoreHandle handle);

/* --------------------------------------------------------- recordio */
int MXRecordIOWriterCreate(const char *uri, RecordIOHandle *out);
int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char *buf,
                                size_t size);
int MXRecordIOWriterFree(RecordIOHandle handle);
int MXRecordIOReaderCreate(const char *uri, RecordIOHandle *out);
/* *out_buf=NULL and *size=0 at end of file; the buffer stays valid
 * until the next read on the same handle */
int MXRecordIOReaderReadRecord(RecordIOHandle handle,
                               char const **out_buf, size_t *size);
int MXRecordIOReaderFree(RecordIOHandle handle);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* MXNET_TPU_C_API_H_ */
