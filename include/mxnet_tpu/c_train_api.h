/*
 * C TRAINING ABI slice for mxnet_tpu — the native seam beyond inference.
 *
 * Role parity: the executor/optimizer subset of include/mxnet/c_api.h
 * (MXSymbolCreateFromJSON + MXExecutorForward/Backward + the update
 * loop the reference cpp-package drives, cpp-package/include/mxnet-cpp/
 * executor.h).  The reference ABI is ~150 functions; this slice is the
 * minimum a non-Python embedding needs to TRAIN a net: create a bound
 * executor from symbol JSON (parameters initialized in-library), feed
 * inputs, run forward/backward, apply SGD(-momentum), and read
 * outputs/arguments/gradients.  Under the hood an embedded CPython
 * drives mxnet_tpu.c_train.TrainSession — the same architecture as the
 * predict ABI (libmxtpu_predict.so).
 *
 * Flow:
 *   MXTrainCreate(json, "cpu", 0, seed, ins, indptr, data, n, &h)
 *   loop: MXTrainSetInput(h, "data", x, nx)
 *         MXTrainSetInput(h, "softmax_label", y, ny)
 *         MXTrainForward(h, 1)
 *         MXTrainBackward(h)
 *         MXTrainSGDUpdate(h, lr, momentum, wd, 1.0f/batch)
 *   MXTrainGetOutput(h, 0, probs, n)       (inference: Forward(h, 0))
 *   MXTrainFree(h)
 *
 * Every entry point returns 0 on success, -1 on failure; see
 * MXTrainGetLastError().
 */
#ifndef MXNET_TPU_C_TRAIN_API_H_
#define MXNET_TPU_C_TRAIN_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *TrainHandle;

const char *MXTrainGetLastError();

/* Bind a training executor over symbol JSON.
 * dev_type 1 = cpu, 2 = gpu, 3 = tpu; parameters are Xavier-initialized
 * with `seed`; inputs (data + labels) are named in input_keys with
 * shapes packed CSR-style as in MXPredCreate. */
int MXTrainCreate(const char *symbol_json_str,
                  int dev_type, int dev_id, int seed,
                  mx_uint num_input_nodes,
                  const char **input_keys,
                  const mx_uint *input_shape_indptr,
                  const mx_uint *input_shape_data,
                  TrainHandle *out);

/* Copy `size` floats into input `key`. */
int MXTrainSetInput(TrainHandle handle, const char *key,
                    const mx_float *data, mx_uint size);

/* Forward pass; is_train != 0 runs the training graph (dropout etc.). */
int MXTrainForward(TrainHandle handle, int is_train);

/* Backward pass (loss heads seed their own gradients, as in the
 * reference Executor::Backward with no out_grads). */
int MXTrainBackward(TrainHandle handle);

/* SGD(-momentum) update of every parameter from its gradient.
 * Loss heads produce per-example gradient SUMS (reference
 * convention), so pass rescale_grad = 1/batch for averaged updates
 * (1.0f applies the raw sums). */
int MXTrainSGDUpdate(TrainHandle handle, mx_float lr, mx_float momentum,
                     mx_float wd, mx_float rescale_grad);

/* Output count / shape / data.  Shape pointers are valid until the next
 * call on this handle. */
int MXTrainGetOutputCount(TrainHandle handle, mx_uint *out);
int MXTrainGetOutputShape(TrainHandle handle, mx_uint index,
                          mx_uint **shape_data, mx_uint *shape_ndim);
int MXTrainGetOutput(TrainHandle handle, mx_uint index, mx_float *data,
                     mx_uint size);

/* Read a named argument ("arg") or gradient ("grad") array. */
int MXTrainGetArray(TrainHandle handle, const char *kind,
                    const char *name, mx_float *data, mx_uint size);

int MXTrainFree(TrainHandle handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXNET_TPU_C_TRAIN_API_H_ */
