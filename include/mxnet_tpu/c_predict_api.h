/*
 * C prediction ABI for mxnet_tpu — the deployment boundary.
 *
 * Role parity: include/mxnet/c_predict_api.h + src/c_api/c_predict_api.cc
 * in the reference (and the amalgamation's libmxnet_predict).  The same
 * flat MXPred* entry points are exported from libmxtpu_predict.so; under
 * the hood an embedded CPython drives mxnet_tpu.predictor.Predictor, so
 * a C/C++ application links one shared library and never touches Python
 * itself.
 *
 * Flow (identical to the reference):
 *   MXPredCreate(symbol_json, params_bytes, ...) -> handle
 *   MXPredSetInput(handle, "data", floats, n)
 *   MXPredForward(handle)
 *   MXPredGetOutputShape(handle, 0, &shape, &ndim)
 *   MXPredGetOutput(handle, 0, out_floats, n)
 *   MXPredFree(handle)
 */
#ifndef MXNET_TPU_C_PREDICT_API_H_
#define MXNET_TPU_C_PREDICT_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *PredictorHandle;

/* Last error message of the calling thread (empty string if none). */
const char *MXGetLastError();

/* Create a predictor.
 * symbol_json_str : contents of the *-symbol.json file
 * param_bytes     : contents of the *.params file
 * param_size      : byte length of param_bytes
 * dev_type        : 1 = cpu, 2 = gpu (accelerator), 3 = tpu
 * dev_id          : device ordinal
 * num_input_nodes : number of input nodes (usually 1, "data")
 * input_keys      : input names
 * input_shape_indptr : length num_input_nodes+1; input i's shape is
 *                      input_shape_data[indptr[i] .. indptr[i+1])
 * input_shape_data   : concatenated input shapes
 * Returns 0 on success, -1 on failure (see MXGetLastError). */
int MXPredCreate(const char *symbol_json_str,
                 const void *param_bytes, int param_size,
                 int dev_type, int dev_id,
                 mx_uint num_input_nodes,
                 const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data,
                 PredictorHandle *out);

/* Output shape of output node `index`; pointers are valid until the
 * next call on this handle. */
int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim);

/* Copy `size` floats into input `key` (row-major, shape from create). */
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const mx_float *data, mx_uint size);

/* Run the forward pass. */
int MXPredForward(PredictorHandle handle);

/* Copy output node `index` into `data` (`size` floats, row-major). */
int MXPredGetOutput(PredictorHandle handle, mx_uint index,
                    mx_float *data, mx_uint size);

/* Re-bind with new input shapes (same keys/layout as create). */
int MXPredReshape(PredictorHandle handle,
                  mx_uint num_input_nodes,
                  const char **input_keys,
                  const mx_uint *input_shape_indptr,
                  const mx_uint *input_shape_data,
                  PredictorHandle *out);

/* Release the predictor. */
int MXPredFree(PredictorHandle handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXNET_TPU_C_PREDICT_API_H_ */
