"""LSTM + CTC sequence recognition on synthetic digit strips.

Reference: ``example/warpctc/lstm_ocr.py`` — an LSTM reads an image
column-by-column and CTC aligns the unsegmented label sequence
(`_contrib_CTCLoss`, the warpctc plugin's role).  Data here is
synthetic: each digit paints a column band with a characteristic
pattern, so the task is learnable in seconds without a captcha
generator.

    python lstm_ocr.py --epochs 5
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx

NUM_CLASSES = 10          # digits; CTC blank is class 0 => labels 1..10
SEQ_LEN = 20              # image columns / LSTM steps
NUM_LABEL = 4             # digits per strip
FEAT = 16                 # rows per column


def gen_strip(rng):
    """(SEQ_LEN, FEAT) image + NUM_LABEL digit labels in 1..10."""
    digits = rng.randint(0, NUM_CLASSES, NUM_LABEL)
    img = rng.rand(SEQ_LEN, FEAT).astype("f") * 0.1
    cols = SEQ_LEN // NUM_LABEL
    for i, d in enumerate(digits):
        band = img[i * cols:(i + 1) * cols]
        band[:, d:d + 6] += 1.0   # digit-dependent stripe position
    return img, digits + 1        # shift: 0 is the CTC blank


def make_net(num_hidden=64):
    data = mx.sym.Variable("data")          # (B, SEQ_LEN, FEAT)
    label = mx.sym.Variable("label")        # (B, NUM_LABEL)
    cell = mx.rnn.LSTMCell(num_hidden, prefix="lstm_")
    outputs, _ = cell.unroll(SEQ_LEN, inputs=data, merge_outputs=True,
                             layout="NTC")
    pred = mx.sym.Reshape(outputs, shape=(-1, num_hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=NUM_CLASSES + 1,
                                 name="pred")
    pred = mx.sym.Reshape(pred, shape=(-4, -1, SEQ_LEN, 0))
    pred = mx.sym.transpose(pred, axes=(1, 0, 2))   # (T, B, V)
    loss = mx.contrib.sym.CTCLoss(pred, label, name="ctc")
    return mx.sym.Group([mx.sym.MakeLoss(loss),
                         mx.sym.BlockGrad(pred, name="pred_out")])


def greedy_decode(pred):
    """Collapse repeated argmaxes and drop blanks (class 0)."""
    seq = pred.argmax(-1)
    out = []
    for b in range(seq.shape[1]):
        prev, dec = -1, []
        for t in range(seq.shape[0]):
            c = int(seq[t, b])
            if c != prev and c != 0:
                dec.append(c)
            prev = c
        out.append(dec)
    return out


def train(epochs=5, batch_size=32, n_train=512, lr=0.01, ctx=None,
          log_every=8):
    rng = np.random.RandomState(0)
    xs, ys = zip(*[gen_strip(rng) for _ in range(n_train)])
    x = np.stack(xs)
    y = np.stack(ys).astype("f")
    it = mx.io.NDArrayIter({"data": x}, {"label": y},
                           batch_size=batch_size, shuffle=True)
    net = make_net()
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=ctx or mx.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": lr})

    acc = 0.0
    for epoch in range(epochs):
        it.reset()
        losses = []
        for t, batch in enumerate(it):
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            losses.append(float(mod.get_outputs()[0].asnumpy().mean()))
        # exact-sequence accuracy via greedy decode
        it.reset()
        hit = tot = 0
        for batch in it:
            mod.forward(batch, is_train=False)
            pred = mod.get_outputs()[1].asnumpy()
            dec = greedy_decode(pred)
            labs = batch.label[0].asnumpy().astype(int)
            for d, l in zip(dec, labs):
                tot += 1
                hit += int(d == [c for c in l.tolist() if c > 0])
        acc = hit / max(tot, 1)
        logging.info("epoch %d ctc-loss %.3f exact-match %.3f", epoch,
                     np.mean(losses), acc)
    return mod, acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description="LSTM+CTC OCR toy")
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args()
    train(epochs=args.epochs, batch_size=args.batch_size)
