"""Profiler demo: Chrome-trace capture of a training step.

Reference: ``example/profiler/profiler_executor.py`` — set the profiler
state around a few executor steps and dump a trace-event JSON that
chrome://tracing (or Perfetto) loads.  On TPU, set
``MXNET_PROFILER_XLA_DIR`` to also capture an xprof trace of the device
timeline.

    python profiler_demo.py [--output profile.json]
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import models


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--output", default="profile.json")
    p.add_argument("--steps", type=int, default=5)
    args = p.parse_args()

    net = models.get_model("lenet", num_classes=10)
    ex = net.simple_bind(mx.current_context(), data=(32, 1, 28, 28),
                         softmax_label=(32,))
    for k, v in ex.arg_dict.items():
        if k not in ("data", "softmax_label"):
            mx.initializer.Xavier()(k, v)

    mx.profiler.profiler_set_config(mode="all", filename=args.output)
    mx.profiler.profiler_set_state("run")
    x = np.random.rand(32, 1, 28, 28).astype(np.float32)
    y = np.random.randint(0, 10, 32).astype(np.float32)
    for _ in range(args.steps):
        ex.forward_backward(data=x, softmax_label=y)
    ex.outputs[0].wait_to_read()
    mx.profiler.profiler_set_state("stop")
    mx.profiler.dump_profile()
    print("wrote", args.output)


if __name__ == "__main__":
    main()
