"""Stochastic-depth residual network (Huang et al. 2016).

Reference: ``example/stochastic-depth/{sd_module.py,sd_mnist.py,
sd_cifar10.py}`` — residual blocks whose transform branch is randomly
dropped per sample during training.  The reference implements the skip
at the module level (one Module per block, a python coin flip deciding
whether to execute it); under XLA the graph is compiled once, so the
TPU-native formulation puts the coin flip *in* the graph: a per-sample
Bernoulli gate = ``Dropout`` on a ones-vector (inverted scaling makes
inference the identity, matching the expected-depth rule).

    python sd_mnist.py --epochs 4
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def get_conv(name, data, num_filter, kernel, stride, pad, with_relu=True):
    conv = mx.sym.Convolution(name=name, data=data, num_filter=num_filter,
                              kernel=kernel, stride=stride, pad=pad,
                              no_bias=True)
    bn = mx.sym.BatchNorm(name=name + "_bn", data=conv, fix_gamma=False,
                          eps=2e-5)
    return (mx.sym.Activation(name=name + "_relu", data=bn,
                              act_type="relu") if with_relu else bn)


def sd_block(name, data, num_filter, death_rate):
    """Residual block with a per-sample stochastic-depth gate."""
    branch = get_conv(name + "_c1", data, num_filter, (3, 3), (1, 1),
                      (1, 1), with_relu=True)
    branch = get_conv(name + "_c2", branch, num_filter, (3, 3), (1, 1),
                      (1, 1), with_relu=False)
    if death_rate > 0:
        # (batch, 1, 1, 1) inverted-Bernoulli gate: 1/(1-p) with prob
        # 1-p at train time, exactly 1 at inference.
        ones = mx.sym.ones_like(
            mx.sym.slice_axis(
                mx.sym.slice_axis(
                    mx.sym.slice_axis(branch, axis=1, begin=0, end=1),
                    axis=2, begin=0, end=1),
                axis=3, begin=0, end=1))
        gate = mx.sym.Dropout(ones, p=death_rate,
                              name=name + "_gate")
        branch = mx.sym.broadcast_mul(branch, gate)
    out = data + branch
    return mx.sym.Activation(out, act_type="relu",
                             name=name + "_out_relu")


def make_sd_net(num_blocks=3, num_filter=16, final_death_rate=0.5,
                num_classes=10):
    data = mx.sym.Variable("data")
    net = get_conv("conv0", data, num_filter, (3, 3), (1, 1), (1, 1))
    for i in range(num_blocks):
        # linearly increasing death rate, as in the paper / reference
        rate = final_death_rate * (i + 1) / num_blocks
        net = sd_block("block%d" % i, net, num_filter, rate)
    pool = mx.sym.Pooling(net, pool_type="avg", kernel=(7, 7),
                          global_pool=True)
    flat = mx.sym.Flatten(pool)
    fc = mx.sym.FullyConnected(flat, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def synthetic_mnist(n, side=14, classes=10, seed=0):
    protos = np.random.RandomState(42).rand(
        classes, 1, side, side).astype("f")
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = protos[y] + 0.2 * rng.randn(n, 1, side, side).astype("f")
    return x.astype("f"), y.astype("f")


def train(epochs=8, batch_size=100, num_blocks=3, ctx=None):
    ctx = ctx or mx.context.current_context()
    xtr, ytr = synthetic_mnist(2000, seed=0)
    xte, yte = synthetic_mnist(500, seed=1)
    train_iter = mx.io.NDArrayIter(xtr, ytr, batch_size, shuffle=True)
    test_iter = mx.io.NDArrayIter(xte, yte, batch_size)

    net = make_sd_net(num_blocks=num_blocks)
    mod = mx.module.Module(net, context=ctx)
    mod.fit(train_iter, eval_data=test_iter, num_epoch=epochs,
            initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "wd": 1e-4},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(batch_size, 10))
    acc = mod.score(test_iter, mx.metric.Accuracy())[0][1]
    logging.info("test accuracy %.3f (%d stochastic blocks)",
                 acc, num_blocks)
    return acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    a = p.parse_args()
    train(epochs=a.epochs)
