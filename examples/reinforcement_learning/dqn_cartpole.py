"""DQN on a self-contained CartPole environment.

Reference: ``example/reinforcement-learning/dqn/`` — Q-network +
target network, epsilon-greedy acting, uniform replay memory, TD
targets from the frozen copy.  The reference plays ALE Atari through
OpenCV; neither is available offline, so the classic CartPole dynamics
(Barto-Sutton-Anderson) are implemented here in ~30 lines of numpy —
the DQN mechanics are identical.

    python dqn_cartpole.py --episodes 150
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


class CartPole:
    """Classic cart-pole balancing; episode ends on |x|>2.4, |θ|>12°,
    or 200 steps."""

    def __init__(self, seed=0):
        self.rng = np.random.RandomState(seed)
        self.state = None
        self.steps = 0

    def reset(self):
        self.state = self.rng.uniform(-0.05, 0.05, 4).astype("f")
        self.steps = 0
        return self.state.copy()

    def step(self, action):
        x, x_dot, th, th_dot = self.state
        force = 10.0 if action == 1 else -10.0
        costh, sinth = np.cos(th), np.sin(th)
        temp = (force + 0.05 * th_dot ** 2 * sinth) / 1.1
        th_acc = (9.8 * sinth - costh * temp) / (
            0.5 * (4.0 / 3.0 - 0.1 * costh ** 2 / 1.1))
        x_acc = temp - 0.05 * th_acc * costh / 1.1
        dt = 0.02
        self.state = np.array([x + dt * x_dot, x_dot + dt * x_acc,
                               th + dt * th_dot, th_dot + dt * th_acc],
                              dtype="f")
        self.steps += 1
        done = (abs(self.state[0]) > 2.4 or
                abs(self.state[2]) > 12 * np.pi / 180 or
                self.steps >= 200)
        return self.state.copy(), 1.0, done


class ReplayMemory:
    """Uniform-sampling circular buffer (reference replay_memory.py)."""

    def __init__(self, capacity, state_dim, seed=0):
        self.capacity = capacity
        self.s = np.zeros((capacity, state_dim), "f")
        self.a = np.zeros(capacity, np.int64)
        self.r = np.zeros(capacity, "f")
        self.s2 = np.zeros((capacity, state_dim), "f")
        self.done = np.zeros(capacity, "f")
        self.size = self.pos = 0
        self.rng = np.random.RandomState(seed)

    def push(self, s, a, r, s2, done):
        i = self.pos
        self.s[i], self.a[i], self.r[i] = s, a, r
        self.s2[i], self.done[i] = s2, float(done)
        self.pos = (self.pos + 1) % self.capacity
        self.size = min(self.size + 1, self.capacity)

    def sample(self, n):
        idx = self.rng.randint(0, self.size, n)
        return (self.s[idx], self.a[idx], self.r[idx], self.s2[idx],
                self.done[idx])


def q_network(num_actions):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    act1 = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=64)
    act2 = mx.sym.Activation(fc2, act_type="relu")
    return mx.sym.FullyConnected(act2, name="qvals",
                                 num_hidden=num_actions)


class DQNAgent:
    """Q + frozen target module pair; TD(0) regression on sampled
    transitions (reference base.py/dqn_demo.py training loop)."""

    def __init__(self, state_dim, num_actions, batch_size, ctx,
                 lr=1e-3, gamma=0.99):
        self.num_actions = num_actions
        self.gamma = gamma
        self.batch_size = batch_size
        qsym = q_network(num_actions)
        # training head: MSE on the chosen action's Q via act_mask
        data = mx.sym.Variable("data")
        target = mx.sym.Variable("target")      # (batch, num_actions)
        mask = mx.sym.Variable("mask")          # one-hot chosen action
        q = q_network(num_actions)
        loss = mx.sym.LinearRegressionOutput(
            data=q * mask + (1 - mask) * mx.sym.BlockGrad(q),
            label=target, name="td")
        self.train_mod = mx.module.Module(
            loss, context=ctx, data_names=("data", "mask"),
            label_names=("target",))
        self.train_mod.bind(
            data_shapes=[("data", (batch_size, state_dim)),
                         ("mask", (batch_size, num_actions))],
            label_shapes=[("target", (batch_size, num_actions))])
        self.train_mod.init_params(mx.init.Xavier())
        self.train_mod.init_optimizer(
            optimizer="adam", optimizer_params={"learning_rate": lr})

        self.act_mod = mx.module.Module(qsym, context=ctx,
                                        label_names=[])
        self.act_mod.bind(data_shapes=[("data", (1, state_dim))],
                          for_training=False)
        self.target_mod = mx.module.Module(qsym, context=ctx,
                                           label_names=[])
        self.target_mod.bind(
            data_shapes=[("data", (batch_size, state_dim))],
            for_training=False)
        self.sync_acting()
        self.sync_target()

    def sync_acting(self):
        self.act_mod.set_params(*self.train_mod.get_params())

    def sync_target(self):
        self.target_mod.set_params(*self.train_mod.get_params())

    def act(self, state, eps, rng):
        if rng.rand() < eps:
            return rng.randint(self.num_actions)
        self.act_mod.forward(mx.io.DataBatch(
            data=[mx.nd.array(state[None])]), is_train=False)
        return int(self.act_mod.get_outputs()[0].asnumpy().argmax())

    def learn(self, replay):
        s, a, r, s2, done = replay.sample(self.batch_size)
        self.target_mod.forward(mx.io.DataBatch(
            data=[mx.nd.array(s2)]), is_train=False)
        q2 = self.target_mod.get_outputs()[0].asnumpy()
        td = r + self.gamma * (1 - done) * q2.max(1)
        mask = np.zeros((self.batch_size, self.num_actions), "f")
        mask[np.arange(self.batch_size), a] = 1
        target = mask * td[:, None]
        self.train_mod.forward(mx.io.DataBatch(
            data=[mx.nd.array(s), mx.nd.array(mask)],
            label=[mx.nd.array(target)]), is_train=True)
        self.train_mod.backward()
        self.train_mod.update()


def train(episodes=150, batch_size=64, ctx=None, seed=0,
          target_sync=200, eps_decay_episodes=100):
    ctx = ctx or mx.context.current_context()
    env = CartPole(seed)
    rng = np.random.RandomState(seed + 1)
    agent = DQNAgent(4, 2, batch_size, ctx)
    replay = ReplayMemory(20000, 4, seed + 2)
    lengths = []
    step_count = 0
    for ep in range(episodes):
        eps = max(0.05, 1.0 - ep / eps_decay_episodes)
        s = env.reset()
        done = False
        ep_len = 0
        agent.sync_acting()
        while not done:
            a = agent.act(s, eps, rng)
            s2, r, done = env.step(a)
            # terminal-by-timeout is not a true failure state
            fail = done and env.steps < 200
            replay.push(s, a, r, s2, fail)
            s = s2
            ep_len += 1
            step_count += 1
            if replay.size >= batch_size and step_count % 4 == 0:
                agent.learn(replay)
            if step_count % target_sync == 0:
                agent.sync_target()
        lengths.append(ep_len)
        if (ep + 1) % 20 == 0:
            logging.info("episode %d  eps %.2f  mean length (last 20) "
                         "%.1f", ep + 1, eps, np.mean(lengths[-20:]))
    return lengths


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--episodes", type=int, default=150)
    a = p.parse_args()
    train(episodes=a.episodes)
