"""Memory-cost study: mirroring (recompute) vs activation memory.

Reference: ``example/memcost/`` — compares training memory with
``MXNET_BACKWARD_DO_MIRROR`` on and off.  Here the comparison reads the
compiled program's own memory analysis (temp/argument/output bytes) for
the fused ShardedTrainer step, plus the trace-level saved-residual count
(what the remat policy actually controls).  Measurements on v5e are
discussed in docs/perf.md.

    python memcost.py [--batch 32] [--layers 50] [--image 224]
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def measure(mirror, batch, layers, image):
    """The fused step's memory plan with mirroring on/off, via the
    shared version-tolerant accessor (telemetry.memory.plan_of) —
    no private memory_analysis() probing here."""
    os.environ["MXNET_BACKWARD_DO_MIRROR"] = "1" if mirror else "0"
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import models
    from mxnet_tpu.parallel import ShardedTrainer, build_mesh
    from mxnet_tpu.telemetry import memory as tmem

    net = models.get_model("resnet%d" % layers, num_classes=1000,
                           image_shape="3,%d,%d" % (image, image))
    mesh = build_mesh(tp=1)
    t = ShardedTrainer(net, mesh,
                       data_shapes={"data": (batch, 3, image, image)},
                       label_shapes={"softmax_label": (batch,)},
                       dtype="bfloat16")
    x = np.zeros((batch, 3, image, image), np.float32)
    y = np.zeros((batch,), np.float32)
    db = t.put_batch({"data": x, "softmax_label": y})
    lowered = t._step_fn.lower(t.params, t.opt_state, t.aux, db,
                               jax.random.PRNGKey(0), jnp.float32(0.1),
                               jnp.float32(1))
    return tmem.plan_of(lowered.compile(),
                        "memcost.mirror=%s" % mirror)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--layers", type=int, default=50)
    p.add_argument("--image", type=int, default=224)
    args = p.parse_args()
    for mirror in (False, True):
        plan = measure(mirror, args.batch, args.layers, args.image)
        if plan is None or not plan.memory:
            print("mirror=%s: backend reports no memory analysis" % mirror)
            continue
        m = plan.memory
        print("mirror=%-5s temp=%8.1f MB  args=%8.1f MB  out=%8.1f MB"
              "  total=%8.1f MB"
              % (mirror, m.get("temp", 0) / 1e6,
                 m.get("argument", 0) / 1e6,
                 m.get("output", 0) / 1e6, plan.total_bytes / 1e6))


if __name__ == "__main__":
    main()
