"""Memory-cost study: mirroring (recompute) vs activation memory.

Reference: ``example/memcost/`` — compares training memory with
``MXNET_BACKWARD_DO_MIRROR`` on and off.  Here the comparison reads the
compiled program's own memory analysis (temp/argument/output bytes) for
the fused ShardedTrainer step, plus the trace-level saved-residual count
(what the remat policy actually controls).  Measurements on v5e are
discussed in docs/perf.md.

    python memcost.py [--batch 32] [--layers 50] [--image 224]
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def measure(mirror, batch, layers, image):
    os.environ["MXNET_BACKWARD_DO_MIRROR"] = "1" if mirror else "0"
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import models
    from mxnet_tpu.parallel import ShardedTrainer, build_mesh

    net = models.get_model("resnet%d" % layers, num_classes=1000,
                           image_shape="3,%d,%d" % (image, image))
    mesh = build_mesh(tp=1)
    t = ShardedTrainer(net, mesh,
                       data_shapes={"data": (batch, 3, image, image)},
                       label_shapes={"softmax_label": (batch,)},
                       dtype="bfloat16")
    x = np.zeros((batch, 3, image, image), np.float32)
    y = np.zeros((batch,), np.float32)
    db = t.put_batch({"data": x, "softmax_label": y})
    lowered = t._step_fn.lower(t.params, t.opt_state, t.aux, db,
                               jax.random.PRNGKey(0), jnp.float32(0.1),
                               jnp.float32(1))
    ma = lowered.compile().memory_analysis()
    return ma


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--layers", type=int, default=50)
    p.add_argument("--image", type=int, default=224)
    args = p.parse_args()
    for mirror in (False, True):
        ma = measure(mirror, args.batch, args.layers, args.image)
        if ma is None:
            print("mirror=%s: backend reports no memory analysis" % mirror)
            continue
        print("mirror=%-5s temp=%8.1f MB  args=%8.1f MB  out=%8.1f MB"
              % (mirror, ma.temp_size_in_bytes / 1e6,
                 ma.argument_size_in_bytes / 1e6,
                 ma.output_size_in_bytes / 1e6))


if __name__ == "__main__":
    main()
