"""Fully-convolutional network for per-pixel segmentation (FCN-xs).

Reference: ``example/fcn-xs/{symbol_fcnxs.py,fcn_xs.py,init_fcnxs.py}``
— conv trunk downsamples, a 1x1 score layer maps to classes, a
``Deconvolution`` initialized as bilinear upsampling restores input
resolution, ``Crop`` aligns the upsampled map, and a skip branch from a
shallower stage sharpens boundaries (the 32s -> 16s refinement);
training is per-pixel ``SoftmaxOutput(multi_output=True)``.

Data: synthetic images of rectangles of distinct classes on background,
so CI can assert pixel accuracy well above the background-majority
baseline.

    python fcn_xs.py --epochs 6
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def fcn_symbol(num_classes=3, with_skip=True):
    data = mx.sym.Variable("data")
    # stage 1: /2
    c1 = mx.sym.Convolution(data, kernel=(3, 3), stride=(2, 2),
                            pad=(1, 1), num_filter=16, name="conv1")
    r1 = mx.sym.Activation(c1, act_type="relu")
    # stage 2: /4
    c2 = mx.sym.Convolution(r1, kernel=(3, 3), stride=(2, 2),
                            pad=(1, 1), num_filter=32, name="conv2")
    r2 = mx.sym.Activation(c2, act_type="relu")

    score4 = mx.sym.Convolution(r2, kernel=(1, 1), num_filter=num_classes,
                                name="score4")  # /4 resolution
    if with_skip:
        # FCN-16s-style refinement: upsample deep score x2, add the
        # shallow stage's score, then upsample the sum the rest of the way
        up2 = mx.sym.Deconvolution(score4, kernel=(4, 4), stride=(2, 2),
                                   pad=(1, 1), num_filter=num_classes,
                                   name="up2", no_bias=True)
        score2 = mx.sym.Convolution(r1, kernel=(1, 1),
                                    num_filter=num_classes, name="score2")
        up2 = mx.sym.Crop(up2, score2, name="crop2")
        fused = up2 + score2
        up = mx.sym.Deconvolution(fused, kernel=(4, 4), stride=(2, 2),
                                  pad=(1, 1), num_filter=num_classes,
                                  name="upfinal", no_bias=True)
    else:
        up = mx.sym.Deconvolution(score4, kernel=(8, 8), stride=(4, 4),
                                  pad=(2, 2), num_filter=num_classes,
                                  name="upfinal", no_bias=True)
    up = mx.sym.Crop(up, data, name="crop_final")
    return mx.sym.SoftmaxOutput(up, multi_output=True, use_ignore=True,
                                ignore_label=255, name="softmax")


def synthetic_shapes(n, side=32, num_classes=3, seed=0):
    """Background class 0; rectangles of class 1..num_classes-1 whose fill
    intensity channel identifies the class."""
    rng = np.random.RandomState(seed)
    x = np.zeros((n, 1, side, side), "f")
    y = np.zeros((n, side, side), "f")
    for i in range(n):
        for cls in range(1, num_classes):
            h, w = rng.randint(6, 14, 2)
            r, c = rng.randint(0, side - h), rng.randint(0, side - w)
            x[i, 0, r:r + h, c:c + w] = cls / (num_classes - 1)
            y[i, r:r + h, c:c + w] = cls
        x[i] += 0.05 * rng.randn(side, side)
    return x.astype("f"), y


def pixel_accuracy(mod, it, n):
    it.reset()
    correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(1)
        lab = batch.label[0].asnumpy()
        correct += (pred == lab).sum()
        total += lab.size
    return correct / total


def train(epochs=6, batch_size=16, num_classes=3, with_skip=True,
          ctx=None):
    ctx = ctx or mx.context.current_context()
    xtr, ytr = synthetic_shapes(512, seed=0, num_classes=num_classes)
    xte, yte = synthetic_shapes(128, seed=1, num_classes=num_classes)
    train_iter = mx.io.NDArrayIter(xtr, ytr, batch_size, shuffle=True)
    test_iter = mx.io.NDArrayIter(xte, yte, batch_size)

    net = fcn_symbol(num_classes, with_skip)
    mod = mx.module.Module(net, context=ctx)
    # bilinear-initialized upsampling, as init_fcnxs.py does for deconvs
    mod.fit(train_iter, num_epoch=epochs,
            initializer=mx.init.Mixed(
                [".*up.*_weight", ".*"],
                [mx.init.Bilinear(), mx.init.Xavier()]),
            optimizer="adam", optimizer_params={"learning_rate": 3e-3},
            eval_metric=mx.metric.Accuracy(axis=1),
            batch_end_callback=mx.callback.Speedometer(batch_size, 10))
    acc = pixel_accuracy(mod, test_iter, len(xte))
    bg = float((yte == 0).mean())
    logging.info("pixel accuracy %.3f (all-background baseline %.3f)",
                 acc, bg)
    return acc, bg


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=6)
    a = p.parse_args()
    train(epochs=a.epochs)
