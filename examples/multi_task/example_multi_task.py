"""Multi-task training: one trunk, two softmax heads, per-head metrics.

Reference: ``example/multi-task/example_multi_task.py`` — a Group of two
``SoftmaxOutput`` heads trained jointly, a wrapping iterator that serves
one label per head, and a multi-accuracy metric indexed per output.

    python example_multi_task.py --epochs 5
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def build_network(num_classes=10):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=128)
    act1 = mx.sym.Activation(data=fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(data=act1, name="fc2", num_hidden=64)
    act2 = mx.sym.Activation(data=fc2, name="relu2", act_type="relu")
    fc3 = mx.sym.FullyConnected(data=act2, name="fc3",
                                num_hidden=num_classes)
    sm1 = mx.sym.SoftmaxOutput(data=fc3, name="softmax1")
    # second task: coarse parity of the digit (num_classes//2 way)
    fc4 = mx.sym.FullyConnected(data=act2, name="fc4", num_hidden=2)
    sm2 = mx.sym.SoftmaxOutput(data=fc4, name="softmax2")
    return mx.sym.Group([sm1, sm2])


class MultiTaskIter(mx.io.DataIter):
    """Serves (label, label % 2) for the two heads."""

    def __init__(self, data_iter):
        super().__init__()
        self.data_iter = data_iter
        self.batch_size = data_iter.batch_size

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        name, shape = (self.data_iter.provide_label[0].name,
                       self.data_iter.provide_label[0].shape)
        return [mx.io.DataDesc("softmax1_label", shape),
                mx.io.DataDesc("softmax2_label", shape)]

    def reset(self):
        self.data_iter.reset()

    def next(self):
        batch = self.data_iter.next()
        label = batch.label[0]
        parity = mx.nd.array(label.asnumpy() % 2)
        return mx.io.DataBatch(data=batch.data, label=[label, parity],
                               pad=batch.pad, index=batch.index)


class MultiAccuracy(mx.metric.EvalMetric):
    """Per-output accuracy vector (reference Multi_Accuracy)."""

    def __init__(self, num):
        super().__init__("multi-accuracy", num=num)

    def reset(self):
        self.sum_metric = [0.0] * self.num
        self.num_inst = [0] * self.num

    def update(self, labels, preds):
        assert len(labels) == self.num == len(preds)
        for i in range(self.num):
            pred = np.argmax(preds[i].asnumpy(), axis=1)
            lab = labels[i].asnumpy().astype(np.int64)
            self.sum_metric[i] += (pred.ravel() == lab.ravel()).sum()
            self.num_inst[i] += len(lab.ravel())

    def get(self):
        accs = [s / max(n, 1) for s, n in
                zip(self.sum_metric, self.num_inst)]
        return (["task%d-accuracy" % i for i in range(self.num)], accs)


def synthetic(n, dim=64, classes=10, seed=0):
    protos = np.random.RandomState(42).randn(
        classes, dim).astype(np.float32) * 1.5
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = protos[y] + rng.randn(n, dim).astype(np.float32) * 0.5
    return x.astype(np.float32), y.astype(np.float32)


def train(epochs=5, batch_size=100, ctx=None):
    ctx = ctx or mx.context.current_context()
    x, y = synthetic(4000)
    xv, yv = synthetic(1000, seed=1)
    train_iter = MultiTaskIter(mx.io.NDArrayIter(x, y, batch_size,
                                                 shuffle=True))
    val_iter = MultiTaskIter(mx.io.NDArrayIter(xv, yv, batch_size))

    mod = mx.module.Module(build_network(), context=ctx,
                           label_names=("softmax1_label",
                                        "softmax2_label"))
    metric = MultiAccuracy(num=2)
    mod.fit(train_iter, eval_data=val_iter, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            eval_metric=metric,
            batch_end_callback=mx.callback.Speedometer(batch_size, 20))
    val_metric = MultiAccuracy(num=2)
    res = dict(mod.score(val_iter, val_metric))
    logging.info("validation: %s", res)
    return res


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    a = p.parse_args()
    train(epochs=a.epochs)
