"""CNN for sentence classification (Kim 2014).

Reference: ``example/cnn_text_classification/text_cnn.py`` — token
embeddings, parallel Convolutions with filter widths (3,4,5) over the
full embedding width, max-pool-over-time, concat, dropout, softmax.

Data: synthetic sentences; class 1 sentences contain one of a few
"signal" trigrams somewhere, class 0 sentences don't — exactly the
pattern a width-3 filter + max-over-time detects.

    python text_cnn.py --epochs 6
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def make_text_cnn(sentence_size, num_embed, vocab_size, num_label=2,
                  filter_list=(3, 4, 5), num_filter=32, dropout=0.25):
    data = mx.sym.Variable("data")
    embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                             output_dim=num_embed, name="vocab_embed")
    # (batch, 1, sentence, embed) image for the conv layers
    conv_input = mx.sym.Reshape(
        data=embed, shape=(-1, 1, sentence_size, num_embed))

    pooled = []
    for i, w in enumerate(filter_list):
        conv = mx.sym.Convolution(data=conv_input, kernel=(w, num_embed),
                                  num_filter=num_filter,
                                  name="conv%d" % i)
        act = mx.sym.Activation(conv, act_type="relu")
        pool = mx.sym.Pooling(act, pool_type="max",
                              kernel=(sentence_size - w + 1, 1),
                              stride=(1, 1))
        pooled.append(pool)

    concat = mx.sym.Concat(*pooled, dim=1)
    h = mx.sym.Reshape(concat,
                       shape=(-1, num_filter * len(filter_list)))
    if dropout > 0:
        h = mx.sym.Dropout(h, p=dropout)
    fc = mx.sym.FullyConnected(h, num_hidden=num_label, name="cls")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def synthetic_sentences(n, sentence_size=24, vocab_size=200,
                        n_signals=4, seed=0):
    signals = np.random.RandomState(42).randint(
        5, vocab_size, (n_signals, 3))
    rng = np.random.RandomState(seed)
    x = rng.randint(5, vocab_size, (n, sentence_size))
    y = (rng.rand(n) < 0.5).astype(np.int64)
    for i in np.where(y == 1)[0]:
        pos = rng.randint(0, sentence_size - 3)
        x[i, pos:pos + 3] = signals[rng.randint(n_signals)]
    return x.astype(np.float32), y.astype(np.float32)


def train(epochs=6, batch_size=100, sentence_size=24, vocab_size=200,
          num_embed=32, ctx=None):
    ctx = ctx or mx.context.current_context()
    xtr, ytr = synthetic_sentences(4000, sentence_size, vocab_size,
                                   seed=0)
    xte, yte = synthetic_sentences(1000, sentence_size, vocab_size,
                                   seed=1)
    train_iter = mx.io.NDArrayIter(xtr, ytr, batch_size, shuffle=True)
    test_iter = mx.io.NDArrayIter(xte, yte, batch_size)

    net = make_text_cnn(sentence_size, num_embed, vocab_size)
    mod = mx.module.Module(net, context=ctx)
    mod.fit(train_iter, eval_data=test_iter, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="adam", optimizer_params={"learning_rate": 1e-3},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(batch_size, 20))
    acc = mod.score(test_iter, mx.metric.Accuracy())[0][1]
    logging.info("test accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=6)
    a = p.parse_args()
    train(epochs=a.epochs)
