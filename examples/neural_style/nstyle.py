"""Neural style transfer: optimize the *input image* through a frozen
feature network.

Reference: ``example/neural-style/nstyle.py`` — content features + style
Gram matrices from conv activations define the loss; the executor's
gradient w.r.t. the data argument (everything else ``grad_req='null'``)
drives plain gradient descent on the pixels.  The reference extracts
features from downloaded VGG19 weights; offline, a fixed random conv
net plays that role — random projections still define Gram/content
targets, and the optimization mechanics (the point of the example) are
identical.  Swap in converted VGG19 weights via ``set_params`` for real
stylization.

    python nstyle.py --iters 60
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def feature_net():
    """Small conv stack; relu1/relu2 = style taps, relu3 = content tap
    (the VGG19 relphases 1_1/2_1 vs 4_2 in the reference)."""
    data = mx.sym.Variable("data")
    taps = []
    x = data
    for i, nf in enumerate((16, 32, 64)):
        x = mx.sym.Convolution(x, kernel=(3, 3), pad=(1, 1),
                               num_filter=nf, name="conv%d" % i)
        x = mx.sym.Activation(x, act_type="relu")
        taps.append(x)
        if i < 2:
            x = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2),
                               pool_type="avg")
    return taps[:2], taps[2]


def gram(feat):
    """(1,C,H,W) -> (C,C) Gram matrix symbol."""
    c = mx.sym.Reshape(feat, shape=(0, -1))      # drop batch=1 -> (C, HW)
    return mx.sym.dot(c, c, transpose_b=True)


def style_content_loss(style_w, content_w):
    style_taps, content_tap = feature_net()
    losses = []
    for i, s in enumerate(style_taps):
        target = mx.sym.Variable("style_target%d" % i)
        g = gram(mx.sym.Reshape(s, shape=(-3, -2)))  # merge batch into C
        losses.append(style_w * mx.sym.sum(mx.sym.square(g - target)))
    ct = mx.sym.Variable("content_target")
    losses.append(content_w * mx.sym.sum(
        mx.sym.square(content_tap - ct)))
    return mx.sym.Group([mx.sym.MakeLoss(l) for l in losses])


def run(iters=60, size=48, lr=0.2, style_w=1e-6, content_w=1e-3,
        ctx=None, seed=0):
    ctx = ctx or mx.context.current_context()
    rng = np.random.RandomState(seed)
    style_img = rng.rand(1, 3, size, size).astype("f")
    content_img = rng.rand(1, 3, size, size).astype("f")

    # --- extract targets with a forward-only executor ------------------
    style_taps, content_tap = feature_net()
    extract = mx.sym.Group(list(style_taps) + [content_tap])
    fixed_args = {
        name: mx.nd.array(rng.randn(*shape).astype("f") * 0.3)
        for name, shape in zip(
            extract.list_arguments(),
            extract.infer_shape(data=(1, 3, size, size))[0])
        if name != "data"}
    ex = extract.bind(ctx, dict(fixed_args,
                                data=mx.nd.array(style_img)),
                      grad_req="null")
    ex.forward()
    style_targets = []
    for o in ex.outputs[:2]:
        f = o.asnumpy().reshape(o.shape[1], -1)
        style_targets.append(f @ f.T)
    ex2 = extract.bind(ctx, dict(fixed_args,
                                 data=mx.nd.array(content_img)),
                       grad_req="null")
    ex2.forward()
    content_target = ex2.outputs[2].asnumpy()

    # --- optimization executor: grad only w.r.t. data ------------------
    loss_sym = style_content_loss(style_w, content_w)
    img = mx.nd.array(rng.rand(1, 3, size, size).astype("f"))
    args = dict(fixed_args)
    args["data"] = img
    args["style_target0"] = mx.nd.array(style_targets[0])
    args["style_target1"] = mx.nd.array(style_targets[1])
    args["content_target"] = mx.nd.array(content_target)
    grad_img = mx.nd.zeros(img.shape, ctx=ctx)
    exo = loss_sym.bind(ctx, args, args_grad={"data": grad_img},
                        grad_req={"data": "write"})

    history = []
    for it in range(iters):
        exo.forward(is_train=True)
        loss = sum(float(o.asnumpy()) for o in exo.outputs)
        exo.backward()
        g = grad_img.asnumpy()
        new = np.clip(args["data"].asnumpy() - lr * g, 0, 1)
        args["data"][:] = new
        history.append(loss)
        if (it + 1) % 20 == 0:
            logging.info("iter %d  loss %.5f", it + 1, loss)
    return history


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=60)
    a = p.parse_args()
    run(iters=a.iters)
