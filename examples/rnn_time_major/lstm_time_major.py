"""Time-major (TNC) LSTM language model.

Reference: ``example/rnn-time-major/`` — the same bucketing LM as
``example/rnn`` but with time-major data layout, which avoids the
per-step batch-major slicing ("up to 1.5x faster" in the reference's
README on cuDNN).  Here the unroll's ``layout="TNC"`` drives
``lax.scan`` directly over the leading time axis — the natural scan
layout on TPU as well.

    python lstm_time_major.py --epochs 3
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


class TimeMajorIter(mx.io.DataIter):
    """Serves (seq_len, batch) token arrays + shifted targets."""

    def __init__(self, sentences, batch_size, seq_len, vocab_size,
                 seed=0):
        super().__init__()
        self.batch_size = batch_size
        self.seq_len = seq_len
        flat = np.concatenate(sentences)
        n_batches = len(flat) // (batch_size * seq_len + 1)
        self.n_batches = n_batches
        self.data = flat[: n_batches * batch_size * seq_len].reshape(
            batch_size, n_batches * seq_len)
        self.target = flat[1: n_batches * batch_size * seq_len + 1] \
            .reshape(batch_size, n_batches * seq_len)
        self.provide_data = [mx.io.DataDesc("data",
                                            (seq_len, batch_size))]
        self.provide_label = [mx.io.DataDesc("softmax_label",
                                             (seq_len, batch_size))]
        self.cur = 0

    def reset(self):
        self.cur = 0

    def next(self):
        if self.cur >= self.n_batches:
            raise StopIteration
        s = self.cur * self.seq_len
        self.cur += 1
        # (batch, T) slice -> time-major (T, batch)
        d = self.data[:, s:s + self.seq_len].T
        t = self.target[:, s:s + self.seq_len].T
        return mx.io.DataBatch(
            data=[mx.nd.array(d.astype("f"))],
            label=[mx.nd.array(t.astype("f"))],
            pad=0, index=None,
            provide_data=self.provide_data,
            provide_label=self.provide_label)


def make_sym(seq_len, vocab_size, num_hidden=64, num_embed=32,
             num_layers=1):
    data = mx.sym.Variable("data")          # (T, N)
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                             output_dim=num_embed, name="embed")
    stack = mx.rnn.SequentialRNNCell()
    for i in range(num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=num_hidden,
                                  prefix="lstm_l%d_" % i))
    outputs, _ = stack.unroll(seq_len, inputs=embed, layout="TNC",
                              merge_outputs=True)
    pred = mx.sym.Reshape(outputs, shape=(-1, num_hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size,
                                 name="pred")
    label = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, label=label, name="softmax")


def synthetic_corpus(n=500, vocab_size=60, seed=0):
    """Markov-ish token stream: next token depends on the previous one,
    so an LSTM beats the unigram baseline measurably."""
    rng = np.random.RandomState(seed)
    trans = rng.dirichlet(np.ones(vocab_size) * 0.1, size=vocab_size)
    out = []
    for _ in range(n):
        sent = [rng.randint(vocab_size)]
        for _ in range(rng.randint(10, 30)):
            sent.append(rng.choice(vocab_size, p=trans[sent[-1]]))
        out.append(np.array(sent))
    return out


def train(epochs=3, batch_size=16, seq_len=16, vocab_size=60,
          ctx=None):
    ctx = ctx or mx.context.current_context()
    corpus = synthetic_corpus(vocab_size=vocab_size)
    it = TimeMajorIter(corpus, batch_size, seq_len, vocab_size)
    net = make_sym(seq_len, vocab_size)
    mod = mx.module.Module(net, context=ctx)
    mod.fit(it, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="adam", optimizer_params={"learning_rate": 5e-3},
            eval_metric=mx.metric.Perplexity(None),
            batch_end_callback=mx.callback.Speedometer(batch_size, 20))
    ppl = mod.score(it, mx.metric.Perplexity(None))[0][1]
    logging.info("train perplexity %.1f (vocab %d)", ppl, vocab_size)
    return ppl


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    a = p.parse_args()
    train(epochs=a.epochs)
