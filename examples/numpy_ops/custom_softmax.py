"""A softmax output layer written in numpy as a CustomOp.

Reference: ``example/numpy-ops/custom_softmax.py`` — the operator's
forward/backward run as host callbacks (numpy), while everything around
them stays compiled; same flow the reference drives through
``MXCustomOpRegister`` engine callbacks.

    python custom_softmax.py --epochs 5
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


class Softmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        l = in_data[1].asnumpy().ravel().astype(np.int64)
        y = out_data[0].asnumpy().copy()
        y[np.arange(l.shape[0]), l] -= 1.0
        self.assign(in_grad[0], req[0], y)


@mx.operator.register("demo_softmax")
class SoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        output_shape = in_shape[0]
        return [data_shape, label_shape], [output_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return Softmax()


def make_net():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    fc1 = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=64)
    act1 = mx.sym.Activation(data=fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(data=act1, name="fc2", num_hidden=10)
    return mx.sym.Custom(data=fc2, label=label, name="softmax",
                         op_type="demo_softmax")


def synthetic(n, dim=64, classes=10, seed=0):
    protos = np.random.RandomState(42).randn(
        classes, dim).astype(np.float32)
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = protos[y] + 0.3 * rng.randn(n, dim).astype(np.float32)
    return x.astype(np.float32), y.astype(np.float32)


def train(epochs=5, batch_size=64, ctx=None):
    ctx = ctx or mx.context.current_context()
    x, y = synthetic(2560)
    xv, yv = synthetic(512, seed=1)
    mod = mx.module.Module(make_net(), context=ctx,
                           label_names=("softmax_label",))
    mod.fit(mx.io.NDArrayIter(x, y, batch_size, shuffle=True),
            eval_data=mx.io.NDArrayIter(xv, yv, batch_size),
            num_epoch=epochs, initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    acc = mod.score(mx.io.NDArrayIter(xv, yv, batch_size),
                    mx.metric.Accuracy())[0][1]
    logging.info("validation accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    a = p.parse_args()
    train(epochs=a.epochs)
