"""Class-weighted logistic regression as a CustomOp.

Reference: ``example/numpy-ops/weighted_logistic_regression.py`` — a
logistic output whose backward scales positive/negative gradients by
per-class weights, something the stock ops don't expose.

    python weighted_logistic_regression.py
"""
from __future__ import annotations

import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


class WeightedLogistic(mx.operator.CustomOp):
    def __init__(self, pos_w, neg_w):
        super().__init__()
        self.pos_w = pos_w
        self.neg_w = neg_w

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], 1.0 / (1.0 + np.exp(-x)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        l = in_data[1].asnumpy().reshape(y.shape)
        grad = (y - l) * (self.pos_w * l + self.neg_w * (1 - l))
        self.assign(in_grad[0], req[0], grad)


@mx.operator.register("weighted_logistic")
class WeightedLogisticProp(mx.operator.CustomOpProp):
    def __init__(self, pos_w=1.0, neg_w=1.0):
        super().__init__(need_top_grad=False)
        self.pos_w = float(pos_w)
        self.neg_w = float(neg_w)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return [in_shape[0], in_shape[0]], [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return WeightedLogistic(self.pos_w, self.neg_w)


def train(epochs=10, batch_size=64, pos_w=3.0, ctx=None):
    """Imbalanced binary problem; the positive-class weight pulls recall up."""
    ctx = ctx or mx.context.current_context()
    rng = np.random.RandomState(0)
    n = 2560
    y = (rng.rand(n) < 0.15).astype(np.float32)       # 15% positives
    x = (y[:, None] * 1.5 + rng.randn(n, 32) * 1.0).astype(np.float32)

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    fc = mx.sym.FullyConnected(data=data, num_hidden=1, name="fc")
    net = mx.sym.Custom(data=fc, label=label, name="wlogit",
                        op_type="weighted_logistic", pos_w=pos_w, neg_w=1.0)

    mod = mx.module.Module(net, context=ctx,
                           label_names=("softmax_label",))
    it = mx.io.NDArrayIter(x, y.reshape(n, 1), batch_size, shuffle=True)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    for _ in range(epochs):
        it.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()

    # recall on positives
    it.reset()
    preds, labels = [], []
    for batch in it:
        mod.forward(batch, is_train=False)
        preds.append(mod.get_outputs()[0].asnumpy().ravel())
        labels.append(batch.label[0].asnumpy().ravel())
    preds = np.concatenate(preds)[:n] > 0.5
    labels = np.concatenate(labels)[:n] > 0.5
    recall = (preds & labels).sum() / max(labels.sum(), 1)
    logging.info("positive-class recall %.3f (pos_w=%.1f)", recall, pos_w)
    return recall


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    train()
