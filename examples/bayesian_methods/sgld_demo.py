"""Stochastic Gradient Langevin Dynamics posterior sampling.

Reference: ``example/bayesian-methods/{sgld.ipynb,bdk_demo.py,algos.py}``
— the classic Welling-Teh toy: sample a small Bayesian NN's posterior
with the ``sgld`` optimizer (SGD + per-step Gaussian noise scaled by the
learning rate) and average the sampled predictions.  The posterior mean
is a better predictor than the last noisy iterate, which this script
(and its CI test) measures.

    python sgld_demo.py
"""
from __future__ import annotations

import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def make_net():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    act1 = mx.sym.Activation(fc1, act_type="tanh")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=1)
    return mx.sym.LinearRegressionOutput(fc2, name="reg")


def toy_regression(n, seed=0, noise=0.1):
    """y = x^3 on [-1,1] plus noise (BDK toy problem family)."""
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, (n, 1)).astype("f")
    y = (x[:, 0] ** 3 + noise * rng.randn(n)).astype("f")
    return x, y


def train(total_epochs=60, burn_in=30, batch_size=50, lr=5e-5,
          ctx=None):
    ctx = ctx or mx.context.current_context()
    xtr, ytr = toy_regression(1000, seed=0)
    xte, yte = toy_regression(400, seed=1, noise=0.0)
    train_iter = mx.io.NDArrayIter(xtr, ytr.reshape(-1, 1), batch_size,
                                   shuffle=True, label_name="reg_label")
    test_iter = mx.io.NDArrayIter(xte, None, batch_size)

    mod = mx.module.Module(make_net(), context=ctx,
                           label_names=("reg_label",))
    mod.bind(data_shapes=train_iter.provide_data,
             label_shapes=train_iter.provide_label)
    mod.init_params(mx.init.Xavier())
    # SGLD samples the posterior of the FULL dataset: the gradient must
    # be the full-data scale (sum over N), so undo the default 1/batch
    # mean-rescale with N/batch (Welling-Teh eq. 4; the noise N(0, lr)
    # then matches the posterior temperature).
    mod.init_optimizer(optimizer="sgld",
                       optimizer_params={"learning_rate": lr,
                                         "wd": 1e-3,
                                         "rescale_grad":
                                             len(xtr) / batch_size})

    def predict():
        test_iter.reset()
        out = []
        for batch in test_iter:
            mod.forward(batch, is_train=False)
            out.append(mod.get_outputs()[0].asnumpy())
        return np.concatenate(out)[: len(xte)].ravel()

    posterior_sum = np.zeros(len(xte))
    n_samples = 0
    for epoch in range(total_epochs):
        train_iter.reset()
        for batch in train_iter:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        if epoch >= burn_in:
            posterior_sum += predict()
            n_samples += 1

    last_rmse = float(np.sqrt(np.mean((predict() - yte) ** 2)))
    post_mean = posterior_sum / n_samples
    post_rmse = float(np.sqrt(np.mean((post_mean - yte) ** 2)))
    logging.info("last-sample RMSE %.4f, posterior-mean RMSE %.4f "
                 "(%d samples)", last_rmse, post_rmse, n_samples)
    return last_rmse, post_rmse


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    train()
