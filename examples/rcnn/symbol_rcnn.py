"""Faster R-CNN train/test symbols (toy-scale backbone).

Reference: ``example/rcnn/rcnn/symbol/symbol_vgg.py`` — shared conv
trunk, RPN (3x3 conv -> 2A cls + 4A bbox), Proposal op, proposal-target
sampler, ROIPooling, and the two Fast-RCNN heads with
``SoftmaxOutput(normalization='batch')`` + weighted ``smooth_l1``.

Channel conventions follow the framework Proposal op
(`mxnet_tpu/ops/spatial.py`): cls channels [bg_0..bg_{A-1},
fg_0..fg_{A-1}], bbox channels a*4+k, box enumeration h, w, a.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
import rcnn_lib  # noqa: F401  (registers the proposal_target CustomOp)

FEAT_STRIDE = 8
ANCHOR_SCALES = (2, 4)
ANCHOR_RATIOS = (1.0,)
NUM_ANCHORS = len(ANCHOR_SCALES) * len(ANCHOR_RATIOS)


def get_trunk(data):
    """Three stride-2 conv stages -> feature stride 8."""
    x = data
    for i, nf in enumerate((16, 32, 64)):
        x = mx.sym.Convolution(x, kernel=(3, 3), stride=(2, 2),
                               pad=(1, 1), num_filter=nf,
                               name="conv%d" % (i + 1))
        x = mx.sym.Activation(x, act_type="relu",
                              name="relu%d" % (i + 1))
    return x


def rpn_heads(feat, num_anchors):
    rpn_conv = mx.sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                                  num_filter=64, name="rpn_conv_3x3")
    rpn_relu = mx.sym.Activation(rpn_conv, act_type="relu")
    cls = mx.sym.Convolution(rpn_relu, kernel=(1, 1),
                             num_filter=2 * num_anchors,
                             name="rpn_cls_score")
    bbox = mx.sym.Convolution(rpn_relu, kernel=(1, 1),
                              num_filter=4 * num_anchors,
                              name="rpn_bbox_pred")
    return cls, bbox


def rcnn_heads(feat, rois, num_classes, pooled=(4, 4)):
    pool = mx.sym.ROIPooling(data=feat, rois=rois, pooled_size=pooled,
                             spatial_scale=1.0 / FEAT_STRIDE,
                             name="roi_pool")
    flat = mx.sym.Flatten(pool)
    fc6 = mx.sym.FullyConnected(flat, num_hidden=128, name="fc6")
    relu6 = mx.sym.Activation(fc6, act_type="relu")
    cls_score = mx.sym.FullyConnected(relu6, num_hidden=num_classes,
                                      name="cls_score")
    bbox_pred = mx.sym.FullyConnected(relu6,
                                      num_hidden=4 * num_classes,
                                      name="bbox_pred")
    return cls_score, bbox_pred


def get_rcnn_train(num_classes=3, num_anchors=NUM_ANCHORS,
                   rpn_batch_size=64, batch_rois=32,
                   rpn_pre_nms=400, rpn_post_nms=64):
    data = mx.sym.Variable("data")
    im_info = mx.sym.Variable("im_info")
    gt_boxes = mx.sym.Variable("gt_boxes")
    rpn_label = mx.sym.Variable("label")
    rpn_bbox_target = mx.sym.Variable("bbox_target")
    rpn_bbox_weight = mx.sym.Variable("bbox_weight")

    feat = get_trunk(data)
    rpn_cls, rpn_bbox = rpn_heads(feat, num_anchors)

    # per-anchor 2-way softmax: (1, 2A, H, W) -> (1, 2, A, H, W)
    rpn_cls_reshape = mx.sym.Reshape(rpn_cls, shape=(0, -4, 2, -1, -2),
                                     name="rpn_cls_reshape")
    rpn_cls_prob = mx.sym.SoftmaxOutput(
        data=rpn_cls_reshape, label=rpn_label, multi_output=True,
        normalization="valid", use_ignore=True, ignore_label=-1,
        name="rpn_cls_prob")
    rpn_bbox_loss_ = rpn_bbox_weight * mx.sym.smooth_l1(
        data=(rpn_bbox_pred_minus_target(rpn_bbox, rpn_bbox_target)),
        scalar=3.0, name="rpn_bbox_loss_")
    rpn_bbox_loss = mx.sym.MakeLoss(rpn_bbox_loss_,
                                    grad_scale=1.0 / rpn_batch_size,
                                    name="rpn_bbox_loss")

    # proposals from the softmaxed scores, channel-major (1, 2A, H, W)
    rpn_cls_act = mx.sym.SoftmaxActivation(rpn_cls_reshape,
                                           mode="channel",
                                           name="rpn_cls_act")
    rpn_cls_act = mx.sym.Reshape(rpn_cls_act, shape=(0, -3, -2),
                                 name="rpn_cls_act_reshape")
    rois = mx.sym.Proposal(
        cls_prob=rpn_cls_act, bbox_pred=rpn_bbox, im_info=im_info,
        name="rois", feature_stride=FEAT_STRIDE,
        scales=ANCHOR_SCALES, ratios=ANCHOR_RATIOS,
        rpn_pre_nms_top_n=rpn_pre_nms, rpn_post_nms_top_n=rpn_post_nms,
        threshold=0.7, rpn_min_size=4)

    gt_reshape = mx.sym.Reshape(gt_boxes, shape=(-1, 5),
                                name="gt_boxes_reshape")
    group = mx.sym.Custom(rois=rois, gt_boxes=gt_reshape,
                          op_type="proposal_target",
                          num_classes=num_classes,
                          batch_rois=batch_rois, name="ptarget")
    rois = group[0]
    label = group[1]
    bbox_target = group[2]
    bbox_weight = group[3]

    cls_score, bbox_pred = rcnn_heads(feat, rois, num_classes)
    cls_prob = mx.sym.SoftmaxOutput(data=cls_score, label=label,
                                    normalization="batch",
                                    name="cls_prob")
    bbox_loss_ = bbox_weight * mx.sym.smooth_l1(
        data=(bbox_pred - bbox_target), scalar=1.0, name="bbox_loss_")
    bbox_loss = mx.sym.MakeLoss(bbox_loss_,
                                grad_scale=1.0 / batch_rois,
                                name="bbox_loss")
    return mx.sym.Group([rpn_cls_prob, rpn_bbox_loss, cls_prob,
                         bbox_loss, mx.sym.BlockGrad(label)])


def rpn_bbox_pred_minus_target(pred, target):
    return pred - target


def get_rcnn_test(num_classes=3, num_anchors=NUM_ANCHORS,
                  rpn_pre_nms=400, rpn_post_nms=32):
    data = mx.sym.Variable("data")
    im_info = mx.sym.Variable("im_info")
    feat = get_trunk(data)
    rpn_cls, rpn_bbox = rpn_heads(feat, num_anchors)
    rpn_cls_reshape = mx.sym.Reshape(rpn_cls, shape=(0, -4, 2, -1, -2))
    rpn_cls_act = mx.sym.SoftmaxActivation(rpn_cls_reshape,
                                           mode="channel")
    rpn_cls_act = mx.sym.Reshape(rpn_cls_act, shape=(0, -3, -2))
    rois = mx.sym.Proposal(
        cls_prob=rpn_cls_act, bbox_pred=rpn_bbox, im_info=im_info,
        name="rois", feature_stride=FEAT_STRIDE,
        scales=ANCHOR_SCALES, ratios=ANCHOR_RATIOS,
        rpn_pre_nms_top_n=rpn_pre_nms, rpn_post_nms_top_n=rpn_post_nms,
        threshold=0.7, rpn_min_size=4)
    cls_score, bbox_pred = rcnn_heads(feat, rois, num_classes)
    cls_prob = mx.sym.SoftmaxActivation(cls_score, name="cls_prob")
    return mx.sym.Group([rois, cls_prob, bbox_pred])
