"""Faster R-CNN building blocks: anchors, box transforms, RPN anchor
targets, and the proposal-target sampler.

Reference: ``example/rcnn/rcnn/processing/{generate_anchor.py,
bbox_transform.py}``, ``rcnn/io/rpn.py`` (assign_anchor) and
``rcnn/symbol/proposal_target.py`` — the host-side half of the detector;
the device-side ops (Proposal, ROIPooling, smooth_l1) are framework ops.
"""
from __future__ import annotations

import numpy as np

import mxnet_tpu as mx


# -------------------------------------------------------------- anchors
def generate_anchors(base_size=16, ratios=(0.5, 1, 2), scales=(8, 16, 32)):
    """Window-centered anchor set (generate_anchor.py semantics)."""
    base = np.array([0, 0, base_size - 1, base_size - 1], np.float32)
    w, h = base[2] - base[0] + 1, base[3] - base[1] + 1
    cx, cy = base[0] + 0.5 * (w - 1), base[1] + 0.5 * (h - 1)
    anchors = []
    size = w * h
    for r in ratios:
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                            cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return np.array(anchors, np.float32)


# ------------------------------------------------------ box transforms
def bbox_transform(ex_rois, gt_rois):
    """Regression targets (dx, dy, dw, dh) from ex boxes to gt boxes."""
    ew = ex_rois[:, 2] - ex_rois[:, 0] + 1.0
    eh = ex_rois[:, 3] - ex_rois[:, 1] + 1.0
    ecx = ex_rois[:, 0] + 0.5 * (ew - 1)
    ecy = ex_rois[:, 1] + 0.5 * (eh - 1)
    gw = gt_rois[:, 2] - gt_rois[:, 0] + 1.0
    gh = gt_rois[:, 3] - gt_rois[:, 1] + 1.0
    gcx = gt_rois[:, 0] + 0.5 * (gw - 1)
    gcy = gt_rois[:, 1] + 0.5 * (gh - 1)
    return np.stack([(gcx - ecx) / ew, (gcy - ecy) / eh,
                     np.log(gw / ew), np.log(gh / eh)], axis=1)


def bbox_pred(boxes, deltas):
    """Inverse transform: apply (dx, dy, dw, dh) deltas to boxes."""
    if boxes.shape[0] == 0:
        return np.zeros((0, deltas.shape[1]), deltas.dtype)
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (w - 1)
    cy = boxes[:, 1] + 0.5 * (h - 1)
    pred = np.zeros_like(deltas)
    for k in range(deltas.shape[1] // 4):
        dx, dy, dw, dh = (deltas[:, 4 * k + i] for i in range(4))
        pcx, pcy = dx * w + cx, dy * h + cy
        pw, ph = np.exp(dw) * w, np.exp(dh) * h
        pred[:, 4 * k] = pcx - 0.5 * (pw - 1)
        pred[:, 4 * k + 1] = pcy - 0.5 * (ph - 1)
        pred[:, 4 * k + 2] = pcx + 0.5 * (pw - 1)
        pred[:, 4 * k + 3] = pcy + 0.5 * (ph - 1)
    return pred


def clip_boxes(boxes, im_shape):
    """Clip (x1, y1, x2, y2[, ...]) to image (h, w)."""
    boxes = boxes.copy()
    boxes[:, 0::4] = np.clip(boxes[:, 0::4], 0, im_shape[1] - 1)
    boxes[:, 1::4] = np.clip(boxes[:, 1::4], 0, im_shape[0] - 1)
    boxes[:, 2::4] = np.clip(boxes[:, 2::4], 0, im_shape[1] - 1)
    boxes[:, 3::4] = np.clip(boxes[:, 3::4], 0, im_shape[0] - 1)
    return boxes


def iou_matrix(a, b):
    """(len(a), len(b)) IoU with the +1 pixel convention."""
    if len(a) == 0 or len(b) == 0:
        return np.zeros((len(a), len(b)), np.float32)
    area_a = (a[:, 2] - a[:, 0] + 1) * (a[:, 3] - a[:, 1] + 1)
    area_b = (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)
    iw = (np.minimum(a[:, None, 2], b[None, :, 2]) -
          np.maximum(a[:, None, 0], b[None, :, 0]) + 1).clip(0)
    ih = (np.minimum(a[:, None, 3], b[None, :, 3]) -
          np.maximum(a[:, None, 1], b[None, :, 1]) + 1).clip(0)
    inter = iw * ih
    return inter / (area_a[:, None] + area_b[None, :] - inter)


def nms(dets, thresh):
    """Greedy NMS on (x1, y1, x2, y2, score) rows; returns kept indices."""
    if len(dets) == 0:
        return []
    order = dets[:, 4].argsort()[::-1]
    iou = iou_matrix(dets[:, :4], dets[:, :4])
    keep = []
    suppressed = np.zeros(len(dets), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > thresh
    return keep


# ------------------------------------------------- RPN anchor targets
def assign_anchor(feat_shape, gt_boxes, im_info, feat_stride,
                  scales, ratios, allowed_border=0, rpn_batch_size=64,
                  fg_fraction=0.5, pos_thresh=0.7, neg_thresh=0.3,
                  rng=None):
    """RPN training targets for one image (rpn.py assign_anchor):
    label 1 = fg (IoU >= pos_thresh or argmax per gt), 0 = bg, -1 =
    ignore; subsampled to rpn_batch_size; bbox targets toward the
    best-overlap gt.

    Returns label (A*H*W,), bbox_target (A*H*W, 4), bbox_weight
    (A*H*W, 4) in index order h*(W*A) + w*A + a (the Proposal op's
    enumeration and the (2A, H, W) channel layout's flattening).
    """
    rng = rng or np.random
    height, width = feat_shape
    base = generate_anchors(feat_stride, ratios, scales)
    A = len(base)
    sx = np.arange(width) * feat_stride
    sy = np.arange(height) * feat_stride
    shift = np.stack(np.broadcast_arrays(
        sx[None, :, None], sy[:, None, None],
        sx[None, :, None], sy[:, None, None]), axis=-1).astype(np.float32)
    anchors = (base[None, None] + shift).reshape(-1, 4)   # h, w, a order
    total = len(anchors)

    inside = ((anchors[:, 0] >= -allowed_border) &
              (anchors[:, 1] >= -allowed_border) &
              (anchors[:, 2] < im_info[1] + allowed_border) &
              (anchors[:, 3] < im_info[0] + allowed_border))
    label = np.full(total, -1, np.float32)
    bbox_target = np.zeros((total, 4), np.float32)
    bbox_weight = np.zeros((total, 4), np.float32)

    valid_gt = gt_boxes[gt_boxes[:, 4] >= 0][:, :4] if len(gt_boxes) \
        else np.zeros((0, 4), np.float32)
    if len(valid_gt):
        iou = iou_matrix(anchors, valid_gt)
        best_gt = iou.argmax(1)
        best_iou = iou.max(1)
        label[inside & (best_iou < neg_thresh)] = 0
        # anchors with best overlap per gt are fg even below pos_thresh
        per_gt_best = iou.argmax(0)
        label[per_gt_best] = 1
        label[inside & (best_iou >= pos_thresh)] = 1
        label[~inside] = -1
        fg_idx = np.where(label == 1)[0]
        bbox_target[fg_idx] = bbox_transform(anchors[fg_idx],
                                             valid_gt[best_gt[fg_idx]])
        bbox_weight[fg_idx] = 1.0
    else:
        label[inside] = 0

    # subsample to the rpn batch
    fg = np.where(label == 1)[0]
    max_fg = int(fg_fraction * rpn_batch_size)
    if len(fg) > max_fg:
        label[rng.choice(fg, len(fg) - max_fg, replace=False)] = -1
    bg = np.where(label == 0)[0]
    max_bg = rpn_batch_size - min(len(fg), max_fg)
    if len(bg) > max_bg:
        label[rng.choice(bg, len(bg) - max_bg, replace=False)] = -1
    bbox_weight[label != 1] = 0.0
    return label, bbox_target, bbox_weight


# --------------------------------------------- proposal-target sampler
class ProposalTarget(mx.operator.CustomOp):
    """Sample rois into a fixed Fast-RCNN batch with class labels and
    per-class bbox regression targets (proposal_target.py)."""

    def __init__(self, num_classes, batch_rois, fg_fraction, fg_thresh,
                 bg_thresh_hi):
        super().__init__()
        self.num_classes = num_classes
        self.batch_rois = batch_rois
        self.fg_fraction = fg_fraction
        self.fg_thresh = fg_thresh
        self.bg_thresh_hi = bg_thresh_hi
        self.rng = np.random.RandomState(0)

    def forward(self, is_train, req, in_data, out_data, aux):
        rois = in_data[0].asnumpy()          # (N, 5) batch_idx, x1..y2
        gt = in_data[1].asnumpy()            # (G, 5) x1..y2, cls (pad<0)
        gt = gt[gt[:, 4] >= 0]
        R = self.batch_rois
        all_boxes = rois[:, 1:5]
        if len(gt):
            # gt boxes are candidate rois too (proposal_target.py)
            all_boxes = np.vstack([all_boxes, gt[:, :4]])
            iou = iou_matrix(all_boxes, gt[:, :4])
            best = iou.argmax(1)
            best_iou = iou.max(1)
            cls = gt[best, 4] + 1            # 0 reserved for background
        else:
            best_iou = np.zeros(len(all_boxes), np.float32)
            best = np.zeros(len(all_boxes), np.int64)
            cls = np.zeros(len(all_boxes), np.float32)

        fg = np.where(best_iou >= self.fg_thresh)[0]
        bg = np.where(best_iou < min(self.bg_thresh_hi,
                                     self.fg_thresh))[0]
        n_fg = min(len(fg), int(self.fg_fraction * R))
        if len(fg) > n_fg:
            fg = self.rng.choice(fg, n_fg, replace=False)
        n_bg = R - n_fg
        if len(bg) > n_bg:
            bg = self.rng.choice(bg, n_bg, replace=False)
        elif len(bg) > 0:
            bg = self.rng.choice(bg, n_bg, replace=True)
        else:
            bg = np.zeros(n_bg, np.int64)
        keep = np.concatenate([fg, bg]).astype(np.int64)

        out_rois = np.zeros((R, 5), np.float32)
        out_rois[:, 1:5] = all_boxes[keep]
        label = cls[keep].copy()
        label[n_fg:] = 0
        target = np.zeros((R, 4 * self.num_classes), np.float32)
        weight = np.zeros((R, 4 * self.num_classes), np.float32)
        if len(gt) and n_fg > 0:
            t = bbox_transform(all_boxes[keep[:n_fg]],
                               gt[best[keep[:n_fg]], :4])
            for i in range(n_fg):
                c = int(label[i])
                target[i, 4 * c:4 * c + 4] = t[i]
                weight[i, 4 * c:4 * c + 4] = 1.0
        self.assign(out_data[0], req[0], out_rois)
        self.assign(out_data[1], req[1], label)
        self.assign(out_data[2], req[2], target)
        self.assign(out_data[3], req[3], weight)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        for i in range(len(in_grad)):
            self.assign(in_grad[i], req[i],
                        np.zeros(in_grad[i].shape, np.float32))


@mx.operator.register("proposal_target")
class ProposalTargetProp(mx.operator.CustomOpProp):
    def __init__(self, num_classes, batch_rois=32, fg_fraction=0.5,
                 fg_thresh=0.5, bg_thresh_hi=0.5):
        super().__init__(need_top_grad=False)
        self.num_classes = int(num_classes)
        self.batch_rois = int(batch_rois)
        self.fg_fraction = float(fg_fraction)
        self.fg_thresh = float(fg_thresh)
        self.bg_thresh_hi = float(bg_thresh_hi)

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["rois_output", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        R = self.batch_rois
        return (in_shape,
                [(R, 5), (R,), (R, 4 * self.num_classes),
                 (R, 4 * self.num_classes)], [])

    def create_operator(self, ctx, shapes, dtypes):
        return ProposalTarget(self.num_classes, self.batch_rois,
                              self.fg_fraction, self.fg_thresh,
                              self.bg_thresh_hi)
