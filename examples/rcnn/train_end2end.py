"""Faster R-CNN end-to-end training + evaluation on a toy detection set.

Reference: ``example/rcnn/train_end2end.py`` + ``rcnn/core/loader.py``
(AnchorLoader: RPN targets computed host-side per batch) and
``test.py``/``rcnn/core/tester.py`` (Proposal -> heads -> bbox_pred ->
per-class NMS -> VOC mAP).

Data: rectangles on background where fill intensity encodes the class
(same family the SSD example trains on), images 96x96, one image per
batch as the reference trains VOC.

    python train_end2end.py --epochs 4
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.join(_HERE, "..", ".."))
sys.path.insert(0, os.path.join(_HERE, "..", "ssd"))

import mxnet_tpu as mx  # noqa: E402

import rcnn_lib  # noqa: E402
import symbol_rcnn  # noqa: E402
from symbol_rcnn import (ANCHOR_RATIOS, ANCHOR_SCALES, FEAT_STRIDE,
                         NUM_ANCHORS)  # noqa: E402


IM_SIZE = 96
NUM_CLASSES = 3        # background + 2 foreground classes
MAX_GT = 4


def synthetic_detection(n, size=IM_SIZE, seed=0):
    """Images + gt arrays (MAX_GT, 5) [x1, y1, x2, y2, cls-1], pad -1."""
    rng = np.random.RandomState(seed)
    images = np.zeros((n, 1, size, size), "f")
    gts = -np.ones((n, MAX_GT, 5), "f")
    for i in range(n):
        n_obj = rng.randint(1, 3)
        for j in range(n_obj):
            cls = rng.randint(0, NUM_CLASSES - 1)
            w, h = rng.randint(20, 44, 2)
            x1 = rng.randint(0, size - w)
            y1 = rng.randint(0, size - h)
            intensity = 0.4 + 0.5 * cls
            images[i, 0, y1:y1 + h, x1:x1 + w] = intensity
            gts[i, j] = [x1, y1, x1 + w - 1, y1 + h - 1, cls]
        images[i, 0] += 0.05 * rng.randn(size, size)
    return images.astype("f"), gts


class AnchorLoader(mx.io.DataIter):
    """Per-image iterator emitting RPN anchor targets alongside the
    image (reference core/loader.py AnchorLoader)."""

    def __init__(self, images, gts, shuffle=False, seed=0):
        super().__init__()
        self.images, self.gts = images, gts
        self.batch_size = 1
        self.shuffle = shuffle
        self.rng = np.random.RandomState(seed)
        self.hf = IM_SIZE // FEAT_STRIDE
        self.wf = IM_SIZE // FEAT_STRIDE
        A = NUM_ANCHORS
        self.provide_data = [
            mx.io.DataDesc("data", (1, 1, IM_SIZE, IM_SIZE)),
            mx.io.DataDesc("im_info", (1, 3)),
            mx.io.DataDesc("gt_boxes", (1, MAX_GT, 5))]
        self.provide_label = [
            mx.io.DataDesc("label", (1, A, self.hf, self.wf)),
            mx.io.DataDesc("bbox_target", (1, 4 * A, self.hf, self.wf)),
            mx.io.DataDesc("bbox_weight", (1, 4 * A, self.hf, self.wf))]
        self.reset()

    def reset(self):
        self.order = (self.rng.permutation(len(self.images))
                      if self.shuffle else np.arange(len(self.images)))
        self.cur = 0

    def next(self):
        if self.cur >= len(self.order):
            raise StopIteration
        i = self.order[self.cur]
        self.cur += 1
        gt = self.gts[i]
        label, t, w = rcnn_lib.assign_anchor(
            (self.hf, self.wf), gt, (IM_SIZE, IM_SIZE), FEAT_STRIDE,
            ANCHOR_SCALES, ANCHOR_RATIOS, rng=self.rng)
        A = NUM_ANCHORS
        # h,w,a order -> (A, H, W) / (4A, H, W) channel layouts
        label = label.reshape(self.hf, self.wf, A).transpose(2, 0, 1)
        t = t.reshape(self.hf, self.wf, A, 4).transpose(2, 3, 0, 1) \
             .reshape(4 * A, self.hf, self.wf)
        w = w.reshape(self.hf, self.wf, A, 4).transpose(2, 3, 0, 1) \
             .reshape(4 * A, self.hf, self.wf)
        im_info = np.array([[IM_SIZE, IM_SIZE, 1.0]], "f")
        return mx.io.DataBatch(
            data=[mx.nd.array(self.images[i][None]),
                  mx.nd.array(im_info),
                  mx.nd.array(gt[None])],
            label=[mx.nd.array(label[None]), mx.nd.array(t[None]),
                   mx.nd.array(w[None])],
            pad=0, index=None,
            provide_data=self.provide_data,
            provide_label=self.provide_label)


class RPNAccuracy(mx.metric.EvalMetric):
    """RPN fg/bg accuracy over non-ignored anchors."""

    def __init__(self):
        super().__init__("rpn-acc")

    def update(self, labels, preds):
        pred = preds[0].asnumpy().argmax(1).ravel()
        lab = labels[0].asnumpy().ravel()
        keep = lab != -1
        self.sum_metric += (pred[keep] == lab[keep]).sum()
        self.num_inst += keep.sum()


class RCNNAccuracy(mx.metric.EvalMetric):
    """Fast-RCNN head accuracy on the sampled rois (label from the
    in-graph proposal_target output, preds[4])."""

    def __init__(self):
        super().__init__("rcnn-acc")

    def update(self, labels, preds):
        pred = preds[2].asnumpy().argmax(1).ravel()
        lab = preds[4].asnumpy().ravel()
        self.sum_metric += (pred == lab).sum()
        self.num_inst += lab.size


def train(epochs=4, n_train=200, lr=2e-3, ctx=None, seed=0):
    ctx = ctx or mx.context.current_context()
    images, gts = synthetic_detection(n_train, seed=seed)
    it = AnchorLoader(images, gts, shuffle=True, seed=seed + 1)
    net = symbol_rcnn.get_rcnn_train(NUM_CLASSES)
    mod = mx.module.Module(net, context=ctx,
                           data_names=("data", "im_info", "gt_boxes"),
                           label_names=("label", "bbox_target",
                                        "bbox_weight"))
    metric = mx.metric.CompositeEvalMetric(
        metrics=[RPNAccuracy(), RCNNAccuracy()])
    mod.fit(it, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": lr, "momentum": 0.9,
                              "wd": 5e-4, "clip_gradient": 5.0},
            eval_metric=metric,
            batch_end_callback=mx.callback.Speedometer(1, 50))
    return mod


def detect(mod_params, images, nms_thresh=0.3, score_thresh=0.1,
           ctx=None):
    """Run the test symbol; per-class bbox decode + NMS.
    Returns per-image arrays (m, 6) [cls, score, x1, y1, x2, y2]."""
    ctx = ctx or mx.context.current_context()
    net = symbol_rcnn.get_rcnn_test(NUM_CLASSES)
    mod = mx.module.Module(net, context=ctx,
                           data_names=("data", "im_info"),
                           label_names=[])
    mod.bind(data_shapes=[("data", (1, 1, IM_SIZE, IM_SIZE)),
                          ("im_info", (1, 3))], for_training=False)
    mod.set_params(*mod_params)
    im_info = np.array([[IM_SIZE, IM_SIZE, 1.0]], "f")
    results = []
    for img in images:
        mod.forward(mx.io.DataBatch(
            data=[mx.nd.array(img[None]), mx.nd.array(im_info)]),
            is_train=False)
        rois, cls_prob, deltas = (o.asnumpy() for o in
                                  mod.get_outputs())
        boxes = rcnn_lib.bbox_pred(rois[:, 1:5], deltas)
        boxes = rcnn_lib.clip_boxes(boxes, (IM_SIZE, IM_SIZE))
        dets = []
        for c in range(1, NUM_CLASSES):
            score = cls_prob[:, c]
            keep = score > score_thresh
            if not keep.any():
                continue
            cdet = np.hstack([boxes[keep, 4 * c:4 * c + 4],
                              score[keep, None]])
            kept = rcnn_lib.nms(cdet, nms_thresh)
            for k in kept:
                dets.append([c - 1, cdet[k, 4], *cdet[k, :4]])
        results.append(np.array(dets, "f").reshape(-1, 6))
    return results


def evaluate(mod, n_test=50, seed=99, ctx=None):
    """VOC-style mAP at IoU 0.5 using the SSD example's metric."""
    from metric import MApMetric  # examples/ssd/metric.py
    images, gts = synthetic_detection(n_test, seed=seed)
    dets = detect(mod.get_params(), images, ctx=ctx)
    metric = MApMetric(ovp_thresh=0.5)
    for img_dets, gt in zip(dets, gts):
        valid = gt[gt[:, 4] >= 0]
        label = -np.ones((1, MAX_GT, 6), "f")
        label[0, :len(valid), 0] = valid[:, 4]
        label[0, :len(valid), 1:5] = valid[:, :4] / IM_SIZE
        pred = img_dets.copy().reshape(1, -1, 6)
        if pred.size:
            pred[0, :, 2:6] = pred[0, :, 2:6] / IM_SIZE
        metric.update([mx.nd.array(label)], [mx.nd.array(pred)])
    name, value = metric.get()
    mean_ap = value[-1] if isinstance(value, list) else value
    logging.info("toy VOC mAP@0.5 = %.3f", mean_ap)
    return mean_ap


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    a = p.parse_args()
    mod = train(epochs=a.epochs)
    evaluate(mod)
