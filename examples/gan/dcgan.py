"""DCGAN: adversarial training through the Module API.

Reference: ``example/gan/dcgan.py`` — generator/discriminator Modules,
discriminator gradients accumulated over the fake+real passes, generator
updated through the discriminator's input gradients
(``inputs_need_grad=True`` + ``get_input_grads``).  Data: MNIST-shaped
synthetic blobs by default (no dataset download in this environment), or
any ``.rec`` via --data-rec.

    python dcgan.py --epochs 2 --size 32
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def make_dcgan_sym(ngf, ndf, nc, n_up=4, no_bias=True, fix_gamma=True,
                   eps=1e-5 + 1e-12):
    """Generator (rand -> tanh image) and discriminator (image -> logistic)
    symbols; ``n_up`` upsampling stages give image size 4 * 2**(n_up-1)."""
    BatchNorm = mx.sym.BatchNorm
    rand = mx.sym.Variable("rand")

    g = mx.sym.Deconvolution(rand, name="g1", kernel=(4, 4),
                             num_filter=ngf * 2 ** (n_up - 1),
                             no_bias=no_bias)
    g = BatchNorm(g, name="gbn1", fix_gamma=fix_gamma, eps=eps)
    g = mx.sym.Activation(g, name="gact1", act_type="relu")
    for i in range(n_up - 1):
        filters = nc if i == n_up - 2 else ngf * 2 ** (n_up - 2 - i)
        g = mx.sym.Deconvolution(g, name="g%d" % (i + 2), kernel=(4, 4),
                                 stride=(2, 2), pad=(1, 1),
                                 num_filter=filters, no_bias=no_bias)
        if i == n_up - 2:
            gout = mx.sym.Activation(g, name="gact_out", act_type="tanh")
        else:
            g = BatchNorm(g, name="gbn%d" % (i + 2), fix_gamma=fix_gamma,
                          eps=eps)
            g = mx.sym.Activation(g, name="gact%d" % (i + 2),
                                  act_type="relu")

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    d = mx.sym.Convolution(data, name="d1", kernel=(4, 4), stride=(2, 2),
                           pad=(1, 1), num_filter=ndf, no_bias=no_bias)
    d = mx.sym.LeakyReLU(d, name="dact1", act_type="leaky", slope=0.2)
    for i in range(n_up - 2):
        d = mx.sym.Convolution(d, name="d%d" % (i + 2), kernel=(4, 4),
                               stride=(2, 2), pad=(1, 1),
                               num_filter=ndf * 2 ** (i + 1),
                               no_bias=no_bias)
        d = BatchNorm(d, name="dbn%d" % (i + 2), fix_gamma=fix_gamma,
                      eps=eps)
        d = mx.sym.LeakyReLU(d, name="dact%d" % (i + 2), act_type="leaky",
                             slope=0.2)
    d = mx.sym.Convolution(d, name="d_out", kernel=(4, 4), num_filter=1,
                           no_bias=no_bias)
    d = mx.sym.Flatten(d)
    dloss = mx.sym.LogisticRegressionOutput(data=d, label=label,
                                            name="dloss")
    return gout, dloss


def synthetic_images(n, nc, size, seed=0):
    """Blob-on-background images in [-1, 1] (MNIST stand-in)."""
    rng = np.random.RandomState(seed)
    x = np.full((n, nc, size, size), -1.0, np.float32)
    for i in range(n):
        cx, cy = rng.randint(size // 4, 3 * size // 4, 2)
        r = rng.randint(size // 8, size // 4)
        yy, xx = np.mgrid[:size, :size]
        mask = (yy - cy) ** 2 + (xx - cx) ** 2 < r * r
        x[i, :, mask] = 1.0
    return x


def facc(label, pred):
    return ((pred.ravel() > 0.5) == label.ravel()).mean()


def train(epochs=2, batch_size=32, size=32, ngf=32, ndf=32, nc=1, z=64,
          lr=2e-4, beta1=0.5, n_images=256, ctx=None, log_every=4):
    import math
    n_up = int(math.log2(size // 4)) + 1
    assert n_up >= 2 and 4 * 2 ** (n_up - 1) == size, \
        "size must be 4*2^k with k >= 1 (>= 8)"
    symG, symD = make_dcgan_sym(ngf, ndf, nc, n_up=n_up)
    ctx = ctx or mx.current_context()

    x = synthetic_images(n_images, nc, size)
    train_iter = mx.io.NDArrayIter(x, batch_size=batch_size)
    rng = np.random.RandomState(1)

    modG = mx.mod.Module(symG, data_names=("rand",), label_names=None,
                         context=ctx)
    modG.bind(data_shapes=[("rand", (batch_size, z, 1, 1))])
    modG.init_params(initializer=mx.init.Normal(0.02))
    modG.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": lr, "wd": 0.0,
                                          "beta1": beta1})

    modD = mx.mod.Module(symD, data_names=("data",), label_names=("label",),
                         context=ctx)
    modD.bind(data_shapes=[("data", (batch_size, nc, size, size))],
              label_shapes=[("label", (batch_size,))],
              inputs_need_grad=True)
    modD.init_params(initializer=mx.init.Normal(0.02))
    modD.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": lr, "wd": 0.0,
                                          "beta1": beta1})

    mACC = mx.metric.CustomMetric(facc)
    history = []
    for epoch in range(epochs):
        train_iter.reset()
        for t, batch in enumerate(train_iter):
            rbatch = mx.io.DataBatch(
                [mx.nd.array(rng.normal(0, 1,
                                        (batch_size, z, 1, 1)).astype("f"))],
                [])
            modG.forward(rbatch, is_train=True)
            outG = modG.get_outputs()

            # discriminator on fake (label 0); stash the gradients
            label = mx.nd.zeros((batch_size,))
            modD.forward(mx.io.DataBatch(outG, [label]), is_train=True)
            modD.backward()
            gradD = [[g.copy() for g in grads]
                     for grads in modD._exec_group.grad_arrays]
            modD.update_metric(mACC, [label])

            # discriminator on real (label 1); accumulate fake grads
            label = mx.nd.ones((batch_size,))
            modD.forward(mx.io.DataBatch(batch.data, [label]),
                         is_train=True)
            modD.backward()
            for gr, gf in zip(modD._exec_group.grad_arrays, gradD):
                for a, b in zip(gr, gf):
                    a += b
            modD.update()
            modD.update_metric(mACC, [label])

            # generator: push D toward calling fakes real, backprop the
            # input gradient into G
            label = mx.nd.ones((batch_size,))
            modD.forward(mx.io.DataBatch(outG, [label]), is_train=True)
            modD.backward()
            diffD = modD.get_input_grads()
            modG.backward(diffD)
            modG.update()

            if (t + 1) % log_every == 0:
                name, acc = mACC.get()
                history.append(acc)
                logging.info("epoch %d iter %d d-acc %.3f", epoch, t + 1,
                             acc)
                mACC.reset()
    return modG, modD, history


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description="train DCGAN")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--size", type=int, default=32)
    args = p.parse_args()
    train(epochs=args.epochs, batch_size=args.batch_size, size=args.size)
