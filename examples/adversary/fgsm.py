"""Fast-gradient-sign adversarial examples through Module input gradients.

Reference: ``example/adversary/adversary_generation.ipynb`` — train a small
classifier, then perturb inputs along the sign of the input gradient
(Goodfellow et al., FGSM) and watch accuracy collapse.  The mechanism this
exercises is ``Module.bind(inputs_need_grad=True)`` + ``get_input_grads``,
the same path the notebook uses via ``executor.grad_arrays``.

Data: synthetic class-prototype "digits" (no dataset download in this
environment); each sample is a class prototype plus Gaussian noise, so a
small MLP separates clean data near-perfectly and the adversarial
perturbation has a clean signal to invert.

    python fgsm.py --epochs 5 --eps 0.3
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def make_mlp(num_classes=10):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=128)
    act1 = mx.sym.Activation(data=fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(data=act1, name="fc2", num_hidden=64)
    act2 = mx.sym.Activation(data=fc2, name="relu2", act_type="relu")
    fc3 = mx.sym.FullyConnected(data=act2, name="fc3",
                                num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(data=fc3, name="softmax")


def synthetic_digits(n, dim=196, num_classes=10, noise=0.25, seed=0):
    protos = np.random.RandomState(42).uniform(
        0, 1, (num_classes, dim)).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n)
    x = protos[labels] + noise * rng.randn(n, dim).astype(np.float32)
    return np.clip(x, 0, 1).astype(np.float32), labels.astype(np.float32)


def accuracy(mod, x, y, batch_size):
    it = mx.io.NDArrayIter(x, y, batch_size)
    return mod.score(it, mx.metric.Accuracy())[0][1]


def fgsm_perturb(mod, x, y, eps, batch_size):
    """One FGSM step: x_adv = clip(x + eps * sign(dL/dx))."""
    it = mx.io.NDArrayIter(x, y, batch_size, label_name="softmax_label")
    out = []
    for batch in it:
        mod.forward(batch, is_train=True)
        mod.backward()
        g = mod.get_input_grads()[0].asnumpy()
        xb = batch.data[0].asnumpy()
        out.append(np.clip(xb + eps * np.sign(g), 0, 1))
    return np.concatenate(out)[: len(x)]


def train(epochs=5, batch_size=100, eps=0.3, n_train=4000, n_test=1000,
          dim=196, ctx=None):
    ctx = ctx or mx.context.current_context()
    xtr, ytr = synthetic_digits(n_train, dim=dim, seed=0)
    xte, yte = synthetic_digits(n_test, dim=dim, seed=1)

    net = make_mlp()
    mod = mx.module.Module(net, context=ctx)
    train_iter = mx.io.NDArrayIter(xtr, ytr, batch_size, shuffle=True)
    mod.fit(train_iter, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(batch_size, 20))

    # re-bind with inputs_need_grad so backward fills dL/d(data)
    adv_mod = mx.module.Module(net, context=ctx)
    adv_mod.bind(data_shapes=[("data", (batch_size, dim))],
                 label_shapes=[("softmax_label", (batch_size,))],
                 for_training=True, inputs_need_grad=True)
    adv_mod.set_params(*mod.get_params())

    clean_acc = accuracy(mod, xte, yte, batch_size)
    x_adv = fgsm_perturb(adv_mod, xte, yte, eps, batch_size)
    adv_acc = accuracy(mod, x_adv, yte, batch_size)
    logging.info("clean accuracy %.3f -> adversarial accuracy %.3f "
                 "(eps=%.2f)", clean_acc, adv_acc, eps)
    return clean_acc, adv_acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--eps", type=float, default=0.3)
    a = p.parse_args()
    train(epochs=a.epochs, batch_size=a.batch_size, eps=a.eps)
