"""Noise-contrastive estimation loss layer and metrics.

Reference: ``example/nce-loss/nce.py`` — score the true label plus k
noise labels against the hidden vector via a shared label-embedding
matrix, and train logistic outputs with the true/noise indicator as the
target.  Avoids the full-vocab softmax matmul.
"""
from __future__ import annotations

import numpy as np

import mxnet_tpu as mx


def nce_loss(data, label, label_weight, embed_weight, vocab_size,
             num_hidden):
    """data: (batch, num_hidden); label: (batch, num_label) candidate ids;
    label_weight: (batch, num_label) 1 for the true label, 0 for noise."""
    label_embed = mx.sym.Embedding(data=label, input_dim=vocab_size,
                                   weight=embed_weight,
                                   output_dim=num_hidden,
                                   name="label_embed")
    data = mx.sym.Reshape(data=data, shape=(-1, 1, num_hidden))
    pred = mx.sym.broadcast_mul(data, label_embed)
    pred = mx.sym.sum(data=pred, axis=2)
    return mx.sym.LogisticRegressionOutput(data=pred, label=label_weight)


class NceAuc(mx.metric.EvalMetric):
    """AUC over (indicator, score) pairs pooled across the batch."""

    def __init__(self):
        super().__init__("nce-auc")

    def update(self, labels, preds):
        w = labels[1].asnumpy().ravel()
        p = preds[0].asnumpy().ravel()
        order = np.argsort(-p)
        w = w[order]
        n_pos = w.sum()
        n_neg = len(w) - n_pos
        if n_pos == 0 or n_neg == 0:
            return
        # rank-sum AUC
        ranks = np.arange(1, len(w) + 1)
        auc = (ranks[w > 0.5].sum() - n_pos * (n_pos + 1) / 2)
        auc = 1.0 - auc / (n_pos * n_neg)
        self.sum_metric += auc
        self.num_inst += 1
