"""Toy NCE training: learn a many-class mapping without a full softmax.

Reference: ``example/nce-loss/toy_nce.py`` — a feature vector maps to one
of ``vocab_size`` classes; the NCE head scores the true class against
sampled noise classes.  The AUC metric over true-vs-noise scores should
approach 1 as the embedding learns.

    python toy_nce.py --epochs 8
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from nce import nce_loss, NceAuc


def get_net(vocab_size, num_label, num_hidden=64):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    label_weight = mx.sym.Variable("label_weight")
    embed_weight = mx.sym.Variable("embed_weight")
    pred = mx.sym.FullyConnected(data=data, num_hidden=num_hidden,
                                 name="trunk")
    return nce_loss(data=pred, label=label, label_weight=label_weight,
                    embed_weight=embed_weight, vocab_size=vocab_size,
                    num_hidden=num_hidden)


class ToyNCEIter(mx.io.DataIter):
    """Feature = noisy one-hot-ish projection of the class; label row =
    [true_class, noise...] with weight [1, 0, ...]."""

    def __init__(self, count, batch_size, vocab_size, num_label,
                 feature_size, seed=0):
        super().__init__()
        self.batch_size = batch_size
        self.count = count
        self.vocab_size = vocab_size
        self.num_label = num_label
        self.feature_size = feature_size
        self.rng = np.random.RandomState(seed)
        # class->feature projection shared across train/val iterators
        self.proj = np.random.RandomState(42).randn(
            vocab_size, feature_size).astype("f")
        self.provide_data = [mx.io.DataDesc("data",
                                            (batch_size, feature_size))]
        self.provide_label = [
            mx.io.DataDesc("label", (batch_size, num_label)),
            mx.io.DataDesc("label_weight", (batch_size, num_label))]
        self._i = 0

    def reset(self):
        self._i = 0

    def next(self):
        if self._i >= self.count:
            raise StopIteration
        self._i += 1
        cls = self.rng.randint(0, self.vocab_size, self.batch_size)
        data = self.proj[cls] + 0.1 * self.rng.randn(
            self.batch_size, self.feature_size).astype("f")
        noise = self.rng.randint(0, self.vocab_size,
                                 (self.batch_size, self.num_label - 1))
        label = np.concatenate([cls[:, None], noise], axis=1)
        weight = np.zeros_like(label, dtype="f")
        weight[:, 0] = 1.0
        return mx.io.DataBatch(
            data=[mx.nd.array(data.astype("f"))],
            label=[mx.nd.array(label.astype("f")),
                   mx.nd.array(weight)],
            pad=0, index=None,
            provide_data=self.provide_data,
            provide_label=self.provide_label)


def train(epochs=8, batch_size=128, vocab_size=100, num_label=6,
          feature_size=32, ctx=None):
    ctx = ctx or mx.context.current_context()
    data_train = ToyNCEIter(60, batch_size, vocab_size, num_label,
                            feature_size)
    data_val = ToyNCEIter(10, batch_size, vocab_size, num_label,
                          feature_size, seed=1)
    net = get_net(vocab_size, num_label)
    mod = mx.module.Module(net, context=ctx,
                           data_names=("data",),
                           label_names=("label", "label_weight"))
    metric = NceAuc()
    mod.fit(data_train, eval_data=data_val, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "wd": 1e-5},
            eval_metric=metric)
    val_auc = mod.score(data_val, NceAuc())[0][1]
    logging.info("validation NCE AUC %.3f", val_auc)
    return val_auc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    a = p.parse_args()
    train(epochs=a.epochs)
