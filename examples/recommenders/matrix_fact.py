"""Matrix-factorization recommender (MovieLens-style).

Reference: ``example/recommenders/matrix_fact.py`` — user/item Embedding
lookups, elementwise product + sum as the predicted rating, trained with
``LinearRegressionOutput``.  Data is a synthetic low-rank rating matrix
(MovieLens is a download; none here), so the model can be validated by
driving RMSE well below the rating variance.

    python matrix_fact.py --epochs 10
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def matrix_fact_net(factor_size, num_users, num_items):
    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    score = mx.sym.Variable("score")
    user_w = mx.sym.Embedding(data=user, input_dim=num_users,
                              output_dim=factor_size, name="user_weight")
    item_w = mx.sym.Embedding(data=item, input_dim=num_items,
                              output_dim=factor_size, name="item_weight")
    pred = user_w * item_w
    pred = mx.sym.sum(data=pred, axis=1)
    pred = mx.sym.Flatten(data=pred)
    return mx.sym.LinearRegressionOutput(data=pred, label=score,
                                         name="lro")


def synthetic_ratings(num_users=200, num_items=300, rank=8, n=20000,
                      noise=0.1, seed=0):
    rng = np.random.RandomState(seed)
    u_f = rng.randn(num_users, rank).astype(np.float32) / np.sqrt(rank)
    i_f = rng.randn(num_items, rank).astype(np.float32)
    users = rng.randint(0, num_users, n)
    items = rng.randint(0, num_items, n)
    scores = (u_f[users] * i_f[items]).sum(1) + noise * rng.randn(n)
    return (users.astype(np.float32), items.astype(np.float32),
            scores.astype(np.float32))


def train(epochs=10, batch_size=200, factor_size=16, ctx=None):
    ctx = ctx or mx.context.current_context()
    num_users, num_items = 200, 300
    users, items, scores = synthetic_ratings(num_users, num_items)
    n_train = int(0.9 * len(users))

    def make_iter(sl, shuffle=False):
        return mx.io.NDArrayIter(
            data={"user": users[sl], "item": items[sl]},
            label={"score": scores[sl]},
            batch_size=batch_size, shuffle=shuffle)

    train_iter = make_iter(slice(0, n_train), shuffle=True)
    val_iter = make_iter(slice(n_train, None))

    net = matrix_fact_net(factor_size, num_users, num_items)
    mod = mx.module.Module(net, context=ctx,
                           data_names=("user", "item"),
                           label_names=("score",))
    mod.fit(train_iter, eval_data=val_iter, num_epoch=epochs,
            initializer=mx.init.Normal(0.1),
            optimizer="adam", optimizer_params={"learning_rate": 0.01},
            eval_metric="rmse",
            batch_end_callback=mx.callback.Speedometer(batch_size, 50))
    rmse = mod.score(val_iter, mx.metric.RMSE())[0][1]
    base = float(np.std(scores[n_train:]))
    logging.info("val RMSE %.3f (predict-mean baseline %.3f)", rmse, base)
    return rmse, base


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    a = p.parse_args()
    train(epochs=a.epochs)
