"""SSD metrics: training losses + detection mAP.

Reference: example/ssd/train/metric.py (MultiBoxMetric) and
example/ssd/evaluate/eval_metric.py (MApMetric, VOC07MApMetric).
"""
from __future__ import annotations

import numpy as np

import mxnet_tpu as mx


class MultiBoxMetric(mx.metric.EvalMetric):
    """Training cross-entropy + SmoothL1 over the SSD heads
    (train/metric.py:5-52)."""

    def __init__(self, eps=1e-8):
        super().__init__("multibox")
        self.eps = eps
        self.reset()

    def reset(self):
        self.ce_sum = 0.0
        self.ce_n = 0
        self.l1_sum = 0.0
        self.l1_n = 0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        cls_prob = preds[0].asnumpy()
        loc_loss = preds[1].asnumpy()
        cls_label = preds[2].asnumpy()
        valid = int(np.sum(cls_label >= 0))
        flat = cls_label.flatten()
        mask = np.where(flat >= 0)[0]
        idx = flat[mask].astype(np.int64)
        prob = cls_prob.transpose(0, 2, 1).reshape(-1, cls_prob.shape[1])
        prob = prob[mask, idx]
        self.ce_sum += float((-np.log(prob + self.eps)).sum())
        self.ce_n += valid
        self.l1_sum += float(np.sum(loc_loss))
        self.l1_n += valid
        self.num_inst = 1
        self.sum_metric = self.ce_sum / max(self.ce_n, 1)

    def get(self):
        return (["CrossEntropy", "SmoothL1"],
                [self.ce_sum / max(self.ce_n, 1),
                 self.l1_sum / max(self.l1_n, 1)])


class MApMetric(mx.metric.EvalMetric):
    """Mean average precision for detection
    (evaluate/eval_metric.py:4-228).

    labels: (n, 5|6) [cls, xmin, ymin, xmax, ymax, (difficult)];
    preds[pred_idx]: (m, 6) [cls, score, xmin, ymin, xmax, ymax].
    """

    def __init__(self, ovp_thresh=0.5, use_difficult=False,
                 class_names=None, pred_idx=0):
        name = "mAP" if class_names is None else class_names + ["mAP"]
        super().__init__(name if isinstance(name, str) else "mAP")
        self.records = {}
        self.counts = {}
        self.ovp_thresh = ovp_thresh
        self.use_difficult = use_difficult
        self.class_names = class_names
        self.pred_idx = int(pred_idx)

    def reset(self):
        self.records = {}
        self.counts = {}
        self.num_inst = 0
        self.sum_metric = 0.0

    @staticmethod
    def _iou(x, ys):
        ixmin = np.maximum(ys[:, 0], x[0])
        iymin = np.maximum(ys[:, 1], x[1])
        ixmax = np.minimum(ys[:, 2], x[2])
        iymax = np.minimum(ys[:, 3], x[3])
        iw = np.maximum(ixmax - ixmin, 0.0)
        ih = np.maximum(iymax - iymin, 0.0)
        inters = iw * ih
        uni = (x[2] - x[0]) * (x[3] - x[1]) + \
            (ys[:, 2] - ys[:, 0]) * (ys[:, 3] - ys[:, 1]) - inters
        ious = inters / np.maximum(uni, 1e-12)
        ious[uni < 1e-12] = 0
        return ious

    def _gt_count(self, gts):
        if not self.use_difficult and gts.shape[1] >= 6:
            return int(np.sum(gts[:, 5] < 1))
        return gts.shape[0]

    def update(self, labels, preds):
        for i in range(labels[0].shape[0]):
            label = labels[0][i].asnumpy()
            label = label[label[:, 0] >= 0]  # drop -1 padding rows
            pred = preds[self.pred_idx][i].asnumpy()
            processed = set()
            while pred.shape[0] > 0:
                cid = int(pred[0, 0])
                indices = np.where(pred[:, 0].astype(int) == cid)[0]
                if cid < 0:
                    pred = np.delete(pred, indices, axis=0)
                    continue
                dets = pred[indices]
                pred = np.delete(pred, indices, axis=0)
                processed.add(cid)
                dets = dets[dets[:, 1].argsort()[::-1]]
                records = np.hstack((dets[:, 1][:, np.newaxis],
                                     np.zeros((dets.shape[0], 1))))
                gts = label[label[:, 0].astype(int) == cid]
                if gts.size > 0:
                    found = [False] * gts.shape[0]
                    for j in range(dets.shape[0]):
                        ious = self._iou(dets[j, 2:6], gts[:, 1:5])
                        am = int(np.argmax(ious))
                        if ious[am] > self.ovp_thresh:
                            if (not self.use_difficult and
                                    gts.shape[1] >= 6 and gts[am, 5] > 0):
                                pass  # difficult gt: neither tp nor fp
                            elif not found[am]:
                                records[j, -1] = 1  # tp
                                found[am] = True
                            else:
                                records[j, -1] = 2  # duplicate: fp
                        else:
                            records[j, -1] = 2
                else:
                    records[:, -1] = 2
                gt_count = self._gt_count(gts)
                records = records[records[:, -1] > 0]
                if records.size > 0:
                    self._insert(cid, records, gt_count)
                elif gt_count > 0:
                    # every det matched a difficult gt: the real gts still
                    # count toward recall (sentinel row, neither tp nor fp)
                    self._insert(cid, np.array([[-1.0, 0.0]]), gt_count)
            # label classes with no detections at all still contribute
            # their gt count — a wholly-missed class must drag recall to 0,
            # not drop out of the mean (reference eval_metric.py's
            # missing-class sentinel)
            for cid in np.unique(label[:, 0].astype(int)):
                if cid < 0 or cid in processed:
                    continue
                gts = label[label[:, 0].astype(int) == cid]
                gt_count = self._gt_count(gts)
                if gt_count > 0:
                    self._insert(int(cid), np.array([[-1.0, 0.0]]), gt_count)

    def _insert(self, key, records, count):
        if key not in self.records:
            self.records[key] = records
            self.counts[key] = count
        else:
            self.records[key] = np.vstack((self.records[key], records))
            self.counts[key] += count

    def _recall_prec(self, record, count):
        srt = record[record[:, 0].argsort()[::-1]]
        tp = np.cumsum(srt[:, 1].astype(int) == 1)
        fp = np.cumsum(srt[:, 1].astype(int) == 2)
        recall = tp / float(count) if count > 0 else tp * 0.0
        prec = tp.astype(float) / np.maximum(tp + fp, 1)
        return recall, prec

    def _average_precision(self, rec, prec):
        mrec = np.concatenate(([0.0], rec, [1.0]))
        mpre = np.concatenate(([0.0], prec, [0.0]))
        for i in range(mpre.size - 1, 0, -1):
            mpre[i - 1] = max(mpre[i - 1], mpre[i])
        i = np.where(mrec[1:] != mrec[:-1])[0]
        return float(np.sum((mrec[i + 1] - mrec[i]) * mpre[i + 1]))

    def get(self):
        aps = {}
        for k, v in self.records.items():
            recall, prec = self._recall_prec(v, self.counts[k])
            aps[k] = self._average_precision(recall, prec)
        if not aps:
            return ("mAP", float("nan"))
        mean_ap = float(np.mean(list(aps.values())))
        if self.class_names is None:
            return ("mAP", mean_ap)
        names = [self.class_names[k] if k < len(self.class_names) else str(k)
                 for k in sorted(aps)] + ["mAP"]
        values = [aps[k] for k in sorted(aps)] + [mean_ap]
        return (names, values)


class VOC07MApMetric(MApMetric):
    """11-point interpolated AP (eval_metric.py:230-258)."""

    def _average_precision(self, rec, prec):
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = 0.0 if np.sum(rec >= t) == 0 else float(np.max(prec[rec >= t]))
            ap += p / 11.0
        return ap
