"""Train SSD on a detection .rec.

Reference: example/ssd/train.py + train/train_net.py — Module.fit over
the multibox training symbol with DetRecordIter data and the
CrossEntropy/SmoothL1 training metric.

    python train.py --train-rec data.rec --network mini --num-classes 3
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "symbol"))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.image_det import DetRecordIter  # noqa: E402

from metric import MultiBoxMetric  # noqa: E402


def get_net(network, num_classes, train=True):
    if network == "mini":
        import ssd_mini as m
    else:
        import ssd_vgg16 as m
    return (m.get_symbol_train if train else m.get_symbol)(
        num_classes=num_classes)


def train_net(train_rec, network="vgg16_reduced", num_classes=20,
              batch_size=32, data_shape=(3, 300, 300), num_epochs=1,
              lr=0.004, momentum=0.9, wd=5e-4, ctx=None, seed=0,
              model_prefix=None, mean_pixels=(123.68, 116.779, 103.939),
              rand_mirror=True, frequent=20):
    """The train_net.py flow: iterator -> Module.fit with multibox
    metric; returns the fitted module."""
    net = get_net(network, num_classes, train=True)
    train_iter = DetRecordIter(train_rec, batch_size, data_shape,
                               mean_pixels=mean_pixels, shuffle=True,
                               rand_mirror=rand_mirror, seed=seed)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=ctx or mx.cpu())
    mod.fit(train_iter,
            eval_metric=MultiBoxMetric(),
            optimizer="sgd",
            optimizer_params={"learning_rate": lr, "momentum": momentum,
                              "wd": wd, "rescale_grad": 1.0 / batch_size,
                              "clip_gradient": 4.0},
            initializer=mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="out", magnitude=2),
            num_epoch=num_epochs,
            batch_end_callback=mx.callback.Speedometer(batch_size,
                                                       frequent))
    if model_prefix:
        mod.save_checkpoint(model_prefix, num_epochs)
    return mod


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description="train SSD")
    p.add_argument("--train-rec", required=True)
    p.add_argument("--network", default="vgg16_reduced",
                   choices=["vgg16_reduced", "mini"])
    p.add_argument("--num-classes", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--data-shape", type=int, default=300)
    p.add_argument("--num-epochs", type=int, default=240)
    p.add_argument("--lr", type=float, default=0.004)
    p.add_argument("--model-prefix", default="ssd")
    args = p.parse_args()
    train_net(args.train_rec, args.network, args.num_classes,
              args.batch_size, (3, args.data_shape, args.data_shape),
              args.num_epochs, lr=args.lr, model_prefix=args.model_prefix)
