"""Small SSD over a 3-stage convnet backbone.

A CI-scale detector using the same multibox head machinery as the VGG16
model (ssd_vgg16.build_train_symbol/build_symbol) — the role of the
reference's smaller legacy configs for quick experiments; converges on
the synthetic rectangle dataset (tools/synth_dataset.py) in minutes on
CPU.
"""
from __future__ import annotations

from mxnet_tpu import symbol as sym

from ssd_vgg16 import build_symbol, build_train_symbol

MINI_SIZES = [[0.2, 0.3], [0.4, 0.55], [0.7, 0.85]]
MINI_RATIOS = [[1, 2, 0.5]] * 3


def _conv_bn(body, name, f):
    body = sym.Convolution(body, kernel=(3, 3), pad=(1, 1), num_filter=f,
                           name="conv%s" % name)
    body = sym.BatchNorm(body, name="bn%s" % name)
    return sym.Activation(body, act_type="relu", name="relu%s" % name)


def _backbone(data):
    body = data
    layers = []
    for i, f in enumerate([32, 64, 128]):
        body = _conv_bn(body, "m%d_a" % i, f)
        body = _conv_bn(body, "m%d_b" % i, f)
        body = sym.Pooling(body, pool_type="max", kernel=(2, 2),
                           stride=(2, 2), name="mpool%d" % i)
        layers.append(body)
    return layers[-3:]


def get_symbol_train(num_classes=3, **kwargs):
    data = sym.Variable("data")
    layers = _backbone(data)
    return build_train_symbol(layers, num_classes, MINI_SIZES, MINI_RATIOS,
                              nms_thresh=0.45, nms_topk=100)


def get_symbol(num_classes=3, nms_thresh=0.45, **kwargs):
    data = sym.Variable("data")
    layers = _backbone(data)
    return build_symbol(layers, num_classes, MINI_SIZES, MINI_RATIOS,
                        nms_thresh=nms_thresh, nms_topk=100)
