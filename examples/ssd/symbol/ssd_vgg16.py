"""SSD detector symbol over reduced VGG-16.

Reference: ``example/ssd/symbol/`` (VGG16-reduced backbone + per-scale
multibox heads; contrib MultiBoxPrior/Target/Detection ops,
src/operator/contrib/multibox_*.cc).  Structure follows the reference's
multi-scale head wiring with the TPU-native contrib ops.
"""
from __future__ import annotations

import sys

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def conv_act_layer(from_layer, name, num_filter, kernel=(3, 3), pad=(1, 1),
                   stride=(1, 1), act_type="relu"):
    conv = sym.Convolution(data=from_layer, kernel=kernel, pad=pad,
                           stride=stride, num_filter=num_filter,
                           name="conv{}".format(name))
    relu = sym.Activation(data=conv, act_type=act_type,
                          name="{}{}".format(act_type, name))
    return relu


def vgg16_reduced(data):
    """VGG16 body with reduced fc6/fc7 as convs (reference
    symbol/vgg16_reduced.py)."""
    body = data
    filters = [64, 128, 256, 512, 512]
    layers = [2, 2, 3, 3, 3]
    feat = {}
    for i, (f, n) in enumerate(zip(filters, layers)):
        for j in range(n):
            body = sym.Convolution(data=body, kernel=(3, 3), pad=(1, 1),
                                   num_filter=f,
                                   name="conv%d_%d" % (i + 1, j + 1))
            body = sym.Activation(data=body, act_type="relu",
                                  name="relu%d_%d" % (i + 1, j + 1))
        feat["relu%d_%d" % (i + 1, n)] = body
        if i < 4:
            body = sym.Pooling(data=body, pool_type="max", kernel=(2, 2),
                               stride=(2, 2), name="pool%d" % (i + 1))
        else:
            body = sym.Pooling(data=body, pool_type="max", kernel=(3, 3),
                               stride=(1, 1), pad=(1, 1),
                               name="pool%d" % (i + 1))
    # fc6/fc7 as dilated convs
    body = sym.Convolution(data=body, kernel=(3, 3), pad=(6, 6),
                           dilate=(6, 6), num_filter=1024, name="fc6")
    body = sym.Activation(data=body, act_type="relu", name="relu6")
    body = sym.Convolution(data=body, kernel=(1, 1), num_filter=1024,
                           name="fc7")
    body = sym.Activation(data=body, act_type="relu", name="relu7")
    feat["relu7"] = body
    return feat


def multi_layer_feature(feat):
    """Extra SSD feature scales (reference common.multi_layer_feature)."""
    layers = [feat["relu4_3"], feat["relu7"]]
    body = feat["relu7"]
    for i, (f1, f2, s) in enumerate([(256, 512, 2), (128, 256, 2),
                                     (128, 256, 2), (128, 256, 2)]):
        body = conv_act_layer(body, "8_%d_1x1" % i, f1, kernel=(1, 1),
                              pad=(0, 0))
        body = conv_act_layer(body, "8_%d_3x3" % i, f2, kernel=(3, 3),
                              pad=(1, 1), stride=(s, s))
        layers.append(body)
    return layers


def multibox_layer(from_layers, num_classes, sizes, ratios):
    """Per-scale loc/cls heads + priors (reference common.multibox_layer)."""
    cls_preds = []
    loc_preds = []
    anchors = []
    for k, from_layer in enumerate(from_layers):
        size, ratio = sizes[k], ratios[k]
        num_anchors = len(size) + len(ratio) - 1
        # location prediction
        loc = sym.Convolution(data=from_layer, kernel=(3, 3), pad=(1, 1),
                              num_filter=num_anchors * 4,
                              name="loc_pred%d_conv" % k)
        loc = sym.transpose(loc, axes=(0, 2, 3, 1))
        loc = sym.Flatten(data=loc)
        loc_preds.append(loc)
        # class prediction
        cls = sym.Convolution(data=from_layer, kernel=(3, 3), pad=(1, 1),
                              num_filter=num_anchors * (num_classes + 1),
                              name="cls_pred%d_conv" % k)
        cls = sym.transpose(cls, axes=(0, 2, 3, 1))
        cls = sym.Reshape(cls, shape=(0, -1, num_classes + 1))
        cls_preds.append(cls)
        # anchors
        anchor = mx.contrib.sym.MultiBoxPrior(
            from_layer, sizes=tuple(size), ratios=tuple(ratio),
            name="anchor%d" % k)
        anchors.append(sym.Reshape(anchor, shape=(0, -1, 4)))
    loc_preds = sym.Concat(*loc_preds, dim=1, name="multibox_loc_pred")
    cls_preds = sym.Concat(*cls_preds, dim=1, name="multibox_cls_pred")
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1))
    anchors = sym.Concat(*anchors, dim=1, name="multibox_anchors")
    return [loc_preds, cls_preds, anchors]


DEFAULT_SIZES = [[0.1, 0.141], [0.2, 0.272], [0.37, 0.447], [0.54, 0.619],
                 [0.71, 0.79], [0.88, 0.961]]
DEFAULT_RATIOS = [[1, 2, 0.5], [1, 2, 0.5, 3, 1.0 / 3],
                  [1, 2, 0.5, 3, 1.0 / 3], [1, 2, 0.5, 3, 1.0 / 3],
                  [1, 2, 0.5], [1, 2, 0.5]]


def build_train_symbol(layers, num_classes, sizes, ratios,
                       nms_thresh=0.45, nms_topk=400):
    """Training head over prepared feature scales: multibox target +
    losses (reference symbol_builder.get_symbol_train) — shared by every
    SSD backbone."""
    label = sym.Variable("label")
    loc_preds, cls_preds, anchors = multibox_layer(
        layers, num_classes, sizes, ratios)

    tmp = mx.contrib.sym.MultiBoxTarget(
        anchors, label, cls_preds, overlap_threshold=0.5,
        ignore_label=-1, negative_mining_ratio=3, minimum_negative_samples=0,
        negative_mining_thresh=0.5, variances=(0.1, 0.1, 0.2, 0.2),
        name="multibox_target")
    loc_target = tmp[0]
    loc_target_mask = tmp[1]
    cls_target = tmp[2]

    cls_prob = sym.SoftmaxOutput(data=cls_preds, label=cls_target,
                                 ignore_label=-1, use_ignore=True,
                                 multi_output=True,
                                 normalization="valid", name="cls_prob")
    loc_diff = loc_target_mask * (loc_preds - loc_target)
    loc_loss = sym.MakeLoss(sym.smooth_l1(loc_diff, scalar=1.0),
                            grad_scale=1.0, normalization="valid",
                            name="loc_loss")
    cls_label = sym.BlockGrad(cls_target, name="cls_label")
    det = mx.contrib.sym.MultiBoxDetection(
        cls_prob, loc_preds, anchors, nms_threshold=nms_thresh,
        force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2),
        nms_topk=nms_topk, name="detection")
    det = sym.BlockGrad(det, name="det_out")
    return sym.Group([cls_prob, loc_loss, cls_label, det])


def build_symbol(layers, num_classes, sizes, ratios, nms_thresh=0.5,
                 force_suppress=False, nms_topk=400):
    """Inference head over prepared feature scales (reference
    symbol_builder.get_symbol)."""
    loc_preds, cls_preds, anchors = multibox_layer(
        layers, num_classes, sizes, ratios)
    cls_prob = sym.SoftmaxActivation(data=cls_preds, mode="channel",
                                     name="cls_prob")
    out = mx.contrib.sym.MultiBoxDetection(
        cls_prob, loc_preds, anchors, nms_threshold=nms_thresh,
        force_suppress=force_suppress, variances=(0.1, 0.1, 0.2, 0.2),
        nms_topk=nms_topk, name="detection")
    return out


def get_symbol_train(num_classes=20, **kwargs):
    """Training net over reduced VGG-16 (reference
    symbol_builder.get_symbol_train)."""
    data = sym.Variable("data")
    feat = vgg16_reduced(data)
    layers = multi_layer_feature(feat)
    return build_train_symbol(layers, num_classes, DEFAULT_SIZES,
                              DEFAULT_RATIOS)


def get_symbol(num_classes=20, nms_thresh=0.5, force_suppress=False,
               nms_topk=400, **kwargs):
    """Inference net (reference symbol_builder.get_symbol)."""
    data = sym.Variable("data")
    feat = vgg16_reduced(data)
    layers = multi_layer_feature(feat)
    return build_symbol(layers, num_classes, DEFAULT_SIZES, DEFAULT_RATIOS,
                        nms_thresh=nms_thresh, force_suppress=force_suppress,
                        nms_topk=nms_topk)
