"""Evaluate SSD detections with VOC mAP.

Reference: example/ssd/evaluate.py + evaluate/evaluate_net.py — run the
inference symbol over a detection .rec and score with
MApMetric/VOC07MApMetric.
"""
from __future__ import annotations

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "symbol"))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.image_det import DetRecordIter  # noqa: E402

from metric import MApMetric, VOC07MApMetric  # noqa: E402


def evaluate_net(module_or_params, val_rec, num_classes, network="mini",
                 batch_size=8, data_shape=(3, 96, 96), ctx=None,
                 ovp_thresh=0.5, use_voc07=True, class_names=None,
                 mean_pixels=(123.68, 116.779, 103.939)):
    """Score a trained SSD on a detection .rec; returns (names, values).

    ``module_or_params``: a fitted training Module (its weights are
    rebound onto the inference symbol) or a param dict.
    """
    from train import get_net
    net = get_net(network, num_classes, train=False)
    if hasattr(module_or_params, "get_params"):
        arg_params, aux_params = module_or_params.get_params()
    else:
        arg_params, aux_params = module_or_params

    val_iter = DetRecordIter(val_rec, batch_size, data_shape,
                             mean_pixels=mean_pixels)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=ctx or mx.cpu())
    mod.bind(data_shapes=val_iter.provide_data,
             label_shapes=val_iter.provide_label, for_training=False)
    mod.set_params(arg_params, aux_params, allow_missing=False)
    metric = (VOC07MApMetric if use_voc07 else MApMetric)(
        ovp_thresh=ovp_thresh, class_names=class_names)
    res = mod.score(val_iter, metric)
    return res


if __name__ == "__main__":
    p = argparse.ArgumentParser(description="evaluate SSD")
    p.add_argument("--val-rec", required=True)
    p.add_argument("--network", default="vgg16_reduced",
                   choices=["vgg16_reduced", "mini"])
    p.add_argument("--num-classes", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--data-shape", type=int, default=300)
    p.add_argument("--model-prefix", default="ssd")
    p.add_argument("--epoch", type=int, default=240)
    args = p.parse_args()
    _, arg_params, aux_params = mx.model.load_checkpoint(
        args.model_prefix, args.epoch)
    res = evaluate_net((arg_params, aux_params), args.val_rec,
                       args.num_classes, args.network, args.batch_size,
                       (3, args.data_shape, args.data_shape))
    for n, v in res:  # Module.score returns a list of (name, value) pairs
        print("%s=%f" % (n, v))
