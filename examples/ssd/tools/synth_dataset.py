"""Toy detection dataset: colored rectangles on noise backgrounds.

Role of the reference's VOC download+prepare tooling
(example/ssd/tools/prepare_dataset.py) for environments without the
dataset: generates a .rec in the detection record format
(mxnet_tpu.image_det.pack_det_label) whose classes are distinguishable
by color, so a small SSD must learn localization + classification.
"""
from __future__ import annotations

import io as _pyio
import os

import numpy as np

CLASS_COLORS = [(220, 40, 40), (40, 220, 40), (40, 40, 220)]
CLASS_NAMES = ["red", "green", "blue"]


def make_record_file(path, num_images=64, image_size=96, max_objects=2,
                     seed=0):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "..", "..", ".."))
    import mxnet_tpu as mx
    from mxnet_tpu.image_det import pack_det_label
    from PIL import Image

    rng = np.random.RandomState(seed)
    w = mx.recordio.MXRecordIO(path, "w")
    for i in range(num_images):
        img = (rng.rand(image_size, image_size, 3) * 40 + 100).astype(
            np.uint8)
        objs = []
        for _ in range(rng.randint(1, max_objects + 1)):
            cls = rng.randint(len(CLASS_COLORS))
            bw = rng.randint(image_size // 4, image_size // 2)
            bh = rng.randint(image_size // 4, image_size // 2)
            x0 = rng.randint(0, image_size - bw)
            y0 = rng.randint(0, image_size - bh)
            img[y0:y0 + bh, x0:x0 + bw] = CLASS_COLORS[cls]
            objs.append([cls, x0 / image_size, y0 / image_size,
                         (x0 + bw) / image_size, (y0 + bh) / image_size, 0])
        buf = _pyio.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG", quality=95)
        w.write(mx.recordio.pack(
            mx.recordio.IRHeader(0, pack_det_label(objs), i, 0),
            buf.getvalue()))
    w.close()
    return path


if __name__ == "__main__":
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="synth_det.rec")
    p.add_argument("--num-images", type=int, default=64)
    p.add_argument("--image-size", type=int, default=96)
    args = p.parse_args()
    make_record_file(args.out, args.num_images, args.image_size)
    print("wrote", args.out)
