"""Multi-digit captcha recognition: one CNN, four digit heads.

Reference: ``example/captcha`` — a convnet reads a 4-character captcha
image; the head emits 4x10 logits softmaxed per position (label is the
4-digit string).  Images here are synthetic: four prototype digit
patches side by side with noise/jitter (the reference generates them
with the ImageCaptcha library, unavailable offline).

    python train_captcha.py --epochs 8
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx

N_DIGITS = 4
SIDE = 16


def captcha_net(num_digits=N_DIGITS, num_classes=10):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")    # (batch, num_digits)
    net = mx.sym.Convolution(data, kernel=(5, 5), num_filter=16,
                             name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=32,
                             name="conv2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=256, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=num_digits * num_classes,
                                name="fc2")
    net = mx.sym.Reshape(net, shape=(-1, num_classes))
    label = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(net, label=label, name="softmax")


def synthetic_captchas(n, seed=0, noise=0.2):
    protos = np.random.RandomState(42).rand(10, SIDE, SIDE).astype("f")
    rng = np.random.RandomState(seed)
    x = np.zeros((n, 1, SIDE, SIDE * N_DIGITS), "f")
    y = rng.randint(0, 10, (n, N_DIGITS))
    for i in range(n):
        for j in range(N_DIGITS):
            jitter = rng.randint(-1, 2)
            patch = np.roll(protos[y[i, j]], jitter, axis=0)
            x[i, 0, :, j * SIDE:(j + 1) * SIDE] = patch
        x[i] += noise * rng.randn(SIDE, SIDE * N_DIGITS)
    return x.astype("f"), y.astype("f")


def exact_match(mod, it, n):
    it.reset()
    hits = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy()
        pred = pred.reshape(-1, N_DIGITS, 10).argmax(-1)
        lab = batch.label[0].asnumpy().astype(int)
        hits += (pred == lab).all(axis=1).sum()
        total += len(lab)
    return hits / total


def train(epochs=8, batch_size=64, ctx=None):
    ctx = ctx or mx.context.current_context()
    np.random.seed(17)
    mx.random.seed(17)
    xtr, ytr = synthetic_captchas(4000, seed=0)
    xte, yte = synthetic_captchas(800, seed=1)
    train_iter = mx.io.NDArrayIter(xtr, ytr, batch_size, shuffle=True)
    test_iter = mx.io.NDArrayIter(xte, yte, batch_size)

    mod = mx.module.Module(captcha_net(), context=ctx)
    mod.fit(train_iter, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="adam", optimizer_params={"learning_rate": 1e-3},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(batch_size, 20))
    per_digit = mod.score(test_iter, mx.metric.Accuracy())[0][1]
    exact = exact_match(mod, test_iter, len(xte))
    logging.info("per-digit accuracy %.3f, exact-captcha accuracy %.3f",
                 per_digit, exact)
    return per_digit, exact


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    a = p.parse_args()
    train(epochs=a.epochs)
