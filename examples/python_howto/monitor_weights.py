"""Monitor weights/gradients/outputs during FeedForward training.

Reference: example/python-howto/monitor_weights.py — install a Monitor
with a custom statistic and watch per-array norms stream past during
``model.fit``.  Runs on synthetic digits so it needs no download.
"""
import logging

import numpy as np

import mxnet_tpu as mx


def mlp():
    data = mx.symbol.Variable("data")
    fc1 = mx.symbol.FullyConnected(data=data, name="fc1", num_hidden=128)
    act1 = mx.symbol.Activation(data=fc1, name="relu1", act_type="relu")
    fc2 = mx.symbol.FullyConnected(data=act1, name="fc2", num_hidden=64)
    act2 = mx.symbol.Activation(data=fc2, name="relu2", act_type="relu")
    fc3 = mx.symbol.FullyConnected(data=act2, name="fc3", num_hidden=10)
    return mx.symbol.SoftmaxOutput(data=fc3, name="softmax")


def synthetic_digits(n, seed=0):
    protos = np.random.RandomState(42).rand(10, 784).astype("f")
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = (protos[y] + rng.randn(n, 784).astype("f") * 0.3).astype("f")
    return x, y.astype("f")


def norm_stat(d):
    return mx.nd.norm(d) / np.sqrt(d.size)


def main(num_epoch=2, batch_size=100):
    logging.basicConfig(level=logging.INFO)
    xt, yt = synthetic_digits(1000, seed=0)
    xv, yv = synthetic_digits(300, seed=1)
    train = mx.io.NDArrayIter(xt, yt, batch_size, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(xv, yv, batch_size,
                            label_name="softmax_label")

    model = mx.model.FeedForward(
        ctx=mx.cpu(), symbol=mlp(), num_epoch=num_epoch,
        learning_rate=0.1, momentum=0.9, wd=0.00001)
    mon = mx.mon.Monitor(5, norm_stat)
    model.fit(X=train, eval_data=val, monitor=mon,
              batch_end_callback=mx.callback.Speedometer(batch_size, 5))
    return model


if __name__ == "__main__":
    main()
