"""Multiple-output symbols with Group.

Reference: example/python-howto/multiple_outputs.py — group an internal
layer with the loss head so one executor returns both.
"""
import numpy as np

import mxnet_tpu as mx


def main():
    net = mx.symbol.Variable("data")
    fc1 = mx.symbol.FullyConnected(data=net, name="fc1", num_hidden=128)
    net = mx.symbol.Activation(data=fc1, name="relu1", act_type="relu")
    net = mx.symbol.FullyConnected(data=net, name="fc2", num_hidden=64)
    out = mx.symbol.SoftmaxOutput(data=net, name="softmax")
    # group fc1 and out together
    group = mx.symbol.Group([fc1, out])
    print(group.list_outputs())

    # bind on the group: outputs[0] is fc1's value, outputs[1] softmax's
    executor = group.simple_bind(ctx=mx.cpu(), data=(4, 32),
                                 softmax_label=(4,))
    for name, arr in executor.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = np.random.RandomState(0).uniform(
                -0.1, 0.1, arr.shape).astype("f")
    executor.arg_dict["data"][:] = np.random.RandomState(1).rand(4, 32)
    executor.forward(is_train=False)
    fc1_val, softmax_val = executor.outputs
    print("fc1:", fc1_val.shape, "softmax:", softmax_val.shape)
    return group, executor


if __name__ == "__main__":
    main()
