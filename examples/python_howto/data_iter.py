"""Create a record-file data iterator with augmentation and threaded IO.

Reference: example/python-howto/data_iter.py — ImageRecordIter over a
.rec file with augmentation parameters and a backend thread hiding IO.
This version packs a tiny synthetic .rec in-place first (the reference
assumes a pre-downloaded cifar rec), so the walkthrough runs anywhere.
"""
import os
import tempfile

import numpy as np

import mxnet_tpu as mx


def make_toy_rec(path, n=24, edge=32):
    """Pack n random JPEGs into path (tools/im2rec role, in-process)."""
    from PIL import Image
    import io as _io

    rng = np.random.RandomState(0)
    rec = mx.recordio.MXIndexedRecordIO(path[:-4] + ".idx", path, "w")
    for i in range(n):
        img = Image.fromarray(
            rng.randint(0, 255, (edge, edge, 3), dtype=np.uint8))
        buf = _io.BytesIO()
        img.save(buf, format="JPEG")
        header = mx.recordio.IRHeader(0, float(i % 10), i, 0)
        rec.write_idx(i, mx.recordio.pack(header, buf.getvalue()))
    rec.close()


def main():
    tmpdir = tempfile.TemporaryDirectory()
    tmp = tmpdir.name
    rec_path = os.path.join(tmp, "toy.rec")
    make_toy_rec(rec_path)

    dataiter = mx.image.ImageIter(
        # Dataset parameters: the record file and decoded shape
        path_imgrec=rec_path,
        path_imgidx=rec_path[:-4] + ".idx",
        data_shape=(3, 28, 28),
        # Batch parameter
        batch_size=8,
        # Augmentation parameters
        rand_crop=True,
        rand_mirror=True,
        shuffle=True,
    )
    batches = 0
    for batch in dataiter:
        assert batch.data[0].shape == (8, 3, 28, 28)
        batches += 1
    print("read %d augmented batches" % batches)
    tmpdir.cleanup()
    return batches


if __name__ == "__main__":
    main()
