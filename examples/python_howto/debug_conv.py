"""Debug a single Convolution through Module with an install_monitor.

Reference: example/python-howto/debug_conv.py — a one-op module, a
Monitor installed on the executor group, one forward on ones.
"""
import numpy as np

import mxnet_tpu as mx


class SimpleData(object):
    def __init__(self, data):
        self.data = data


def main():
    data_shape = (1, 3, 5, 5)
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data=data, kernel=(3, 3), pad=(1, 1),
                              stride=(1, 1), num_filter=1)
    mon = mx.mon.Monitor(1)

    mod = mx.mod.Module(conv, label_names=[])
    mod.bind(data_shapes=[("data", data_shape)])
    mod.install_monitor(mon)   # (the reference reaches into _exec_group)
    mod.init_params()

    input_data = mx.nd.ones(data_shape)
    mon.tic()
    mod.forward(data_batch=SimpleData([input_data]))
    res = mod.get_outputs()[0].asnumpy()
    mon.toc_print()
    print(res)
    return res


if __name__ == "__main__":
    main()
