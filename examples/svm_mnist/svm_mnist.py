"""L2-SVM / L1-SVM output layer on an MNIST-like task.

Reference: ``example/svm_mnist/svm_mnist.py`` — an MLP trained with the
``SVMOutput`` large-margin objective instead of softmax cross-entropy
(src/operator/svm_output.cc).  Data is a synthetic PCA-like Gaussian
mixture (the reference runs sklearn PCA over downloaded MNIST; no
downloads in this environment).

    python svm_mnist.py --epochs 8 [--use-linear]
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def make_net(use_linear=False, num_classes=10):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=512)
    act1 = mx.sym.Activation(data=fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(data=act1, name="fc2", num_hidden=512)
    act2 = mx.sym.Activation(data=fc2, name="relu2", act_type="relu")
    fc3 = mx.sym.FullyConnected(data=act2, name="fc3",
                                num_hidden=num_classes)
    return mx.sym.SVMOutput(data=fc3, name="svm", use_linear=use_linear)


def synthetic_pca_mnist(n, dim=70, classes=10, seed=0):
    """Gaussian clusters + noise, mirroring the reference's noisy PCA input."""
    protos = np.random.RandomState(42).randn(
        classes, dim).astype(np.float32) * 2.0
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = protos[y] + rng.randn(n, dim).astype(np.float32)
    return x.astype(np.float32), y.astype(np.float32)


def train(epochs=8, batch_size=200, use_linear=False, ctx=None):
    ctx = ctx or mx.context.current_context()
    xtr, ytr = synthetic_pca_mnist(6000, seed=0)
    xte, yte = synthetic_pca_mnist(1000, seed=1)

    train_iter = mx.io.NDArrayIter(xtr, ytr, batch_size, shuffle=True,
                                   label_name="svm_label")
    test_iter = mx.io.NDArrayIter(xte, yte, batch_size,
                                  label_name="svm_label")
    mod = mx.module.Module(make_net(use_linear), context=ctx,
                           label_names=("svm_label",))
    mod.fit(train_iter, eval_data=test_iter, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.02, "momentum": 0.9,
                              "wd": 1e-5},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(batch_size, 20))
    acc = mod.score(test_iter, mx.metric.Accuracy())[0][1]
    logging.info("%s-SVM test accuracy %.3f",
                 "L1" if use_linear else "L2", acc)
    return acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--use-linear", action="store_true")
    a = p.parse_args()
    train(epochs=a.epochs, use_linear=a.use_linear)
