"""Stacked autoencoder with layer-wise pretraining + fine-tuning.

Reference: ``example/autoencoder/{autoencoder.py,mnist_sae.py}`` — each
encoder layer is pretrained as a one-layer denoising AE on the features
of the stack below it, then the full symmetric network is fine-tuned to
minimize reconstruction error (``LinearRegressionOutput``).

Data: synthetic low-dimensional-manifold images (random smooth basis
combinations), so reconstruction through a narrow bottleneck is
learnable and CI can assert MSE << input variance.

    python mnist_sae.py --dims 128,64,16
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


class StackedAutoEncoder:
    """Greedy layerwise pretrain, then end-to-end finetune."""

    def __init__(self, input_dim, dims, ctx=None):
        self.input_dim = input_dim
        self.dims = list(dims)
        self.ctx = ctx or mx.context.current_context()
        self.params = {}

    # -- symbols -------------------------------------------------------
    def _encoder(self, depth):
        x = mx.sym.Variable("data")
        for i in range(depth):
            x = mx.sym.FullyConnected(data=x, num_hidden=self.dims[i],
                                      name="enc%d" % i)
            x = mx.sym.Activation(x, act_type="relu", name="enc%d_act" % i)
        return x

    def _full(self):
        """encoder stack + mirrored decoder + reconstruction loss."""
        x = self._encoder(len(self.dims))
        widths = [self.input_dim] + self.dims[:-1]
        for i in reversed(range(len(self.dims))):
            x = mx.sym.FullyConnected(data=x, num_hidden=widths[i],
                                      name="dec%d" % i)
            if i != 0:
                x = mx.sym.Activation(x, act_type="relu",
                                      name="dec%d_act" % i)
        return mx.sym.LinearRegressionOutput(data=x, label=mx.sym.Variable(
            "ae_label"), name="recon")

    # -- training ------------------------------------------------------
    def _fit_module(self, sym, x, y, epochs, batch_size, lr,
                    label_name="ae_label", arg_params=None):
        it = mx.io.NDArrayIter(x, y, batch_size, shuffle=True,
                               label_name=label_name)
        mod = mx.module.Module(sym, context=self.ctx,
                               label_names=(label_name,))
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        mod.init_params(mx.init.Xavier())
        if arg_params:
            mod.set_params(arg_params, {}, allow_missing=True,
                           allow_extra=True)
        mod.init_optimizer(optimizer="adam",
                           optimizer_params={"learning_rate": lr})
        for _ in range(epochs):
            it.reset()
            for batch in it:
                mod.forward(batch, is_train=True)
                mod.backward()
                mod.update()
        return mod

    def _features(self, depth, x, batch_size):
        if depth == 0:
            return x
        sym = self._encoder(depth)
        mod = mx.module.Module(sym, context=self.ctx, label_names=())
        it = mx.io.NDArrayIter(x, None, batch_size)
        mod.bind(data_shapes=it.provide_data, for_training=False)
        mod.set_params(self.params, {}, allow_missing=False,
                       allow_extra=True)
        out = []
        for batch in it:
            mod.forward(batch, is_train=False)
            out.append(mod.get_outputs()[0].asnumpy())
        return np.concatenate(out)[: len(x)]

    def pretrain(self, x, epochs=4, batch_size=100, lr=1e-3):
        """Greedy layerwise: train layer i to reconstruct features_{i}."""
        for i in range(len(self.dims)):
            feats = self._features(i, x, batch_size)
            data = mx.sym.Variable("data")
            enc = mx.sym.FullyConnected(data=data,
                                        num_hidden=self.dims[i],
                                        name="enc%d" % i)
            enc = mx.sym.Activation(enc, act_type="relu",
                                    name="enc%d_act" % i)
            dec = mx.sym.FullyConnected(data=enc,
                                        num_hidden=feats.shape[1],
                                        name="pre_dec%d" % i)
            sym = mx.sym.LinearRegressionOutput(
                data=dec, label=mx.sym.Variable("ae_label"))
            mod = self._fit_module(sym, feats, feats, epochs, batch_size,
                                   lr)
            args, _ = mod.get_params()
            self.params["enc%d_weight" % i] = args["enc%d_weight" % i]
            self.params["enc%d_bias" % i] = args["enc%d_bias" % i]
            logging.info("pretrained layer %d (%d -> %d)", i,
                         feats.shape[1], self.dims[i])

    def finetune(self, x, epochs=6, batch_size=100, lr=1e-3):
        mod = self._fit_module(self._full(), x, x, epochs, batch_size, lr,
                               arg_params=self.params)
        args, _ = mod.get_params()
        self.params = dict(args)
        self._final_mod = mod
        return mod

    def reconstruction_mse(self, x, batch_size=100):
        mod = self._final_mod
        it = mx.io.NDArrayIter(x, x, batch_size, label_name="ae_label")
        out = []
        for batch in it:
            mod.forward(batch, is_train=False)
            out.append(mod.get_outputs()[0].asnumpy())
        recon = np.concatenate(out)[: len(x)]
        return float(np.mean((recon - x) ** 2))


def smooth_images(n, dim=196, rank=10, seed=0):
    """Images on a rank-`rank` manifold + small noise."""
    rng = np.random.RandomState(seed)
    basis = rng.randn(rank, dim).astype("f")
    coef = rng.randn(n, rank).astype("f")
    x = coef @ basis / np.sqrt(rank)
    return (x + 0.05 * rng.randn(n, dim)).astype("f")


def train(dims=(128, 64, 16), n=4000, pre_epochs=3, fine_epochs=6,
          batch_size=100, ctx=None):
    x = smooth_images(n)
    sae = StackedAutoEncoder(x.shape[1], dims, ctx=ctx)
    sae.pretrain(x, epochs=pre_epochs, batch_size=batch_size)
    sae.finetune(x, epochs=fine_epochs, batch_size=batch_size)
    mse = sae.reconstruction_mse(x)
    var = float(np.var(x))
    logging.info("reconstruction MSE %.4f vs input variance %.4f",
                 mse, var)
    return mse, var, sae


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--dims", default="128,64,16")
    a = p.parse_args()
    train(dims=tuple(int(d) for d in a.dims.split(",")))
