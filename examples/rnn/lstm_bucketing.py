"""LSTM language model with bucketing.

Reference: ``example/rnn/lstm_bucketing.py`` — PennTreeBank LSTM with
BucketingModule (the dynamic-shape acid test, SURVEY §5.7: one jit cache
entry per bucket).  Reads PTB-format text if present, else synthesizes a
corpus.
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx

parser = argparse.ArgumentParser(
    description="Train RNN on Penn Tree Bank",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--data-dir", type=str, default="data/ptb/")
parser.add_argument("--num-layers", type=int, default=2)
parser.add_argument("--num-hidden", type=int, default=200)
parser.add_argument("--num-embed", type=int, default=200)
parser.add_argument("--gpus", type=str)
parser.add_argument("--kv-store", type=str, default="device")
parser.add_argument("--num-epochs", type=int, default=25)
parser.add_argument("--lr", type=float, default=0.01)
parser.add_argument("--optimizer", type=str, default="sgd")
parser.add_argument("--mom", type=float, default=0.0)
parser.add_argument("--wd", type=float, default=0.00001)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--disp-batches", type=int, default=50)
parser.add_argument("--stack-rnn", default=False, action="store_true")
parser.add_argument("--bidirectional", default=False, action="store_true")

buckets = [10, 20, 30, 40, 50, 60]
start_label = 1
invalid_label = 0


def tokenize_text(fname, vocab=None, invalid_label=-1, start_label=0):
    """Reference lstm_bucketing.tokenize_text."""
    with open(fname) as f:
        lines = f.readlines()
    lines = [filter(None, i.split(" ")) for i in lines]
    sentences, vocab = mx.rnn.encode_sentences(
        lines, vocab=vocab, invalid_label=invalid_label,
        start_label=start_label)
    return sentences, vocab


def synthetic_corpus(n=2000, vocab_size=500, seed=0):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(start_label, vocab_size,
                             rng.randint(5, 60)))
            for _ in range(n)], vocab_size


if __name__ == "__main__":
    args = parser.parse_args()

    train_file = os.path.join(args.data_dir, "ptb.train.txt")
    if os.path.exists(train_file):
        train_sent, vocab = tokenize_text(
            train_file, start_label=start_label,
            invalid_label=invalid_label)
        val_sent, _ = tokenize_text(
            os.path.join(args.data_dir, "ptb.test.txt"), vocab=vocab,
            invalid_label=invalid_label)
        vocab_size = len(vocab) + start_label
    else:
        train_sent, vocab_size = synthetic_corpus(2000)
        val_sent, _ = synthetic_corpus(200, vocab_size, seed=1)

    data_train = mx.rnn.BucketSentenceIter(train_sent, args.batch_size,
                                           buckets=buckets,
                                           invalid_label=invalid_label)
    data_val = mx.rnn.BucketSentenceIter(val_sent, args.batch_size,
                                         buckets=buckets,
                                         invalid_label=invalid_label)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, states = stack.unroll(seq_len, inputs=embed,
                                       merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab_size,
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label, name="softmax")
        return pred, ("data",), ("softmax_label",)

    if args.gpus:
        contexts = [mx.tpu(int(i)) for i in args.gpus.split(",")]
    else:
        contexts = mx.cpu(0)

    model = mx.module.BucketingModule(
        sym_gen=sym_gen,
        default_bucket_key=data_train.default_bucket_key,
        context=contexts)

    model.fit(
        train_data=data_train, eval_data=data_val,
        eval_metric=mx.metric.Perplexity(invalid_label),
        kvstore=args.kv_store, optimizer=args.optimizer,
        optimizer_params={"learning_rate": args.lr, "momentum": args.mom,
                          "wd": args.wd},
        initializer=mx.initializer.Xavier(factor_type="in", magnitude=2.34),
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches))
