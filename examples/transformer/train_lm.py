"""Decoder-only transformer language model (GPT-mini).

Beyond-reference capability demo: the 0.10.1 reference predates
attention, but this framework treats long-context as first-class —
``_contrib_FlashAttention`` (Pallas block-streaming kernel on TPU, jnp
fallback elsewhere), ``LayerNorm``, and (for multi-chip) the ring
attention in ``mxnet_tpu.parallel.sequence``.  This example trains a
causal LM through the standard Module API on a synthetic Markov corpus,
where the learnable structure gives a crisp perplexity target.

    python train_lm.py --epochs 5
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def transformer_block(x, d_model, n_heads, prefix,
                      ffn_mult=4, dropout=0.1, attention="flash"):
    """Pre-norm block: x + Attn(LN(x)); x + FFN(LN(x)).

    attention="ring" swaps in ``_contrib_RingAttention`` — identical
    math single-chip, and under ShardedTrainer(sequence_parallel=True)
    the sequence dim shards over the mesh and K/V ride the ICI ring.
    """
    h = mx.sym.LayerNorm(x, name=prefix + "_ln1")
    qkv = mx.sym.FullyConnected(h, num_hidden=3 * d_model, flatten=False,
                                name=prefix + "_qkv")
    qkv = mx.sym.Reshape(qkv, shape=(0, 0, 3, n_heads, -1))
    # each slice: (B, S, 1, H, hd) -> (B, S, H, hd), the attention layout
    q = mx.sym.Reshape(mx.sym.slice_axis(qkv, axis=2, begin=0, end=1),
                       shape=(0, 0, -3, -2))
    k = mx.sym.Reshape(mx.sym.slice_axis(qkv, axis=2, begin=1, end=2),
                       shape=(0, 0, -3, -2))
    v = mx.sym.Reshape(mx.sym.slice_axis(qkv, axis=2, begin=2, end=3),
                       shape=(0, 0, -3, -2))
    attn_op = (mx.sym._contrib_RingAttention if attention == "ring"
               else mx.sym._contrib_FlashAttention)
    att = attn_op(q, k, v, causal=True, name=prefix + "_attn")
    att = mx.sym.Reshape(att, shape=(0, 0, -3))
    att = mx.sym.FullyConnected(att, num_hidden=d_model, flatten=False,
                                name=prefix + "_proj")
    if dropout > 0:
        att = mx.sym.Dropout(att, p=dropout)
    x = x + att

    h = mx.sym.LayerNorm(x, name=prefix + "_ln2")
    h = mx.sym.FullyConnected(h, num_hidden=ffn_mult * d_model,
                              flatten=False, name=prefix + "_ffn1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=d_model, flatten=False,
                              name=prefix + "_ffn2")
    if dropout > 0:
        h = mx.sym.Dropout(h, p=dropout)
    return x + h


def gpt_symbol(vocab_size, seq_len, d_model=128, n_heads=4, n_layers=2,
               dropout=0.1, attention="flash"):
    data = mx.sym.Variable("data")              # (batch, seq)
    label = mx.sym.Variable("softmax_label")
    tok = mx.sym.Embedding(data, input_dim=vocab_size,
                           output_dim=d_model, name="tok_embed")
    # learned positional embedding, looked up with a constant iota
    pos_ids = mx.sym.arange(start=0, stop=seq_len, name="pos_ids")
    pos = mx.sym.Embedding(pos_ids, input_dim=seq_len,
                           output_dim=d_model, name="pos_embed")
    x = mx.sym.broadcast_add(tok, mx.sym.expand_dims(pos, axis=0))
    for i in range(n_layers):
        x = transformer_block(x, d_model, n_heads, "block%d" % i,
                              dropout=dropout, attention=attention)
    x = mx.sym.LayerNorm(x, name="ln_f")
    x = mx.sym.Reshape(x, shape=(-1, d_model))
    logits = mx.sym.FullyConnected(x, num_hidden=vocab_size,
                                   name="lm_head")
    label = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(logits, label=label, name="softmax")


def build_bench_trainer(vocab=16384, seq=1024, d_model=1024, heads=16,
                        layers=12, batch=16, dtype="bfloat16",
                        auto_layouts=False):
    """(fused trainer, staged synthetic batch) at benchmark scale — ONE
    definition shared by tools/transformer_mfu.py and tools/xprof_top.py
    so the profiled program and the benchmarked program are identical
    by construction."""
    from mxnet_tpu.parallel import ShardedTrainer, build_mesh

    net = gpt_symbol(vocab, seq, d_model, heads, layers, dropout=0.0,
                     attention="flash")
    trainer = ShardedTrainer(
        net, build_mesh(n_devices=1),
        data_shapes={"data": (batch, seq)},
        label_shapes={"softmax_label": (batch, seq)},
        optimizer="adam", learning_rate=1e-4, dtype=dtype,
        auto_layouts=auto_layouts)
    rng = np.random.RandomState(0)
    x = rng.randint(0, vocab, (batch, seq)).astype("f")
    staged = trainer.put_batch({"data": x,
                                "softmax_label": np.roll(x, -1, 1).copy()})
    return trainer, staged


def markov_batches(n_tokens, vocab_size, seq_len, batch_size, seed=0):
    rng = np.random.RandomState(seed)
    trans = np.random.RandomState(42).dirichlet(
        np.ones(vocab_size) * 0.05, size=vocab_size)
    toks = [rng.randint(vocab_size)]
    for _ in range(n_tokens):
        toks.append(rng.choice(vocab_size, p=trans[toks[-1]]))
    toks = np.array(toks)
    n_seq = (len(toks) - 1) // seq_len
    x = toks[: n_seq * seq_len].reshape(n_seq, seq_len)
    y = toks[1: n_seq * seq_len + 1].reshape(n_seq, seq_len)
    return (mx.io.NDArrayIter(x.astype("f"), y.astype("f"), batch_size,
                              shuffle=True),
            trans)


def train(epochs=5, batch_size=16, seq_len=64, vocab_size=64,
          d_model=64, n_heads=4, n_layers=2, ctx=None):
    ctx = ctx or mx.context.current_context()
    it, trans = markov_batches(40000, vocab_size, seq_len, batch_size)
    net = gpt_symbol(vocab_size, seq_len, d_model, n_heads, n_layers)
    mod = mx.module.Module(net, context=ctx)
    mod.fit(it, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="adam", optimizer_params={"learning_rate": 3e-3},
            eval_metric=mx.metric.Perplexity(None),
            batch_end_callback=mx.callback.Speedometer(batch_size, 20))
    ppl = mod.score(it, mx.metric.Perplexity(None))[0][1]
    # entropy floor of the generating chain (best achievable ppl)
    stat = np.linalg.matrix_power(trans.T, 50)[:, 0]
    h = -np.sum(stat[:, None] * trans * np.log(np.maximum(trans, 1e-12)))
    logging.info("train perplexity %.2f (chain floor %.2f, vocab %d)",
                 ppl, float(np.exp(h)), vocab_size)
    return ppl, float(np.exp(h))


def train_sequence_parallel(sp=2, steps=120, batch_size=8, seq_len=64,
                            vocab_size=64, d_model=64, n_heads=4,
                            n_layers=2):
    """Sequence-parallel training: the sequence dim sharded ``sp`` ways
    over the mesh 'model' axis, attention via ``_contrib_RingAttention``
    (K/V blocks rotate over the ICI ring; per-device attention memory is
    O(seq/sp)).  Data parallelism rides the 'data' axis at the same
    time when the mesh has more devices than ``sp``.

    Returns (first_loss, last_loss) of the fused training run.
    """
    from mxnet_tpu.parallel import ShardedTrainer, build_mesh

    net = gpt_symbol(vocab_size, seq_len, d_model, n_heads, n_layers,
                     dropout=0.0, attention="ring")
    mesh = build_mesh(tp=sp)  # 'model' axis carries the sequence shards
    trainer = ShardedTrainer(
        net, mesh,
        data_shapes={"data": (batch_size, seq_len)},
        label_shapes={"softmax_label": (batch_size, seq_len)},
        optimizer="adam", learning_rate=3e-3,
        sequence_parallel=True)

    it, _trans = markov_batches(steps * batch_size * seq_len + seq_len,
                                vocab_size, seq_len, batch_size)
    losses = []
    for epoch in range(2):
        it.reset()
        for b in it:
            losses.append(float(trainer.step(
                {"data": b.data[0].asnumpy(),
                 "softmax_label": b.label[0].asnumpy()})))
            if len(losses) >= steps:
                break
        if len(losses) >= steps:
            break
    logging.info("sequence-parallel (sp=%d): loss %.3f -> %.3f over %d "
                 "steps", sp, losses[0], losses[-1], len(losses))
    return losses[0], losses[-1]


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--ring", type=int, default=0,
                   help="train sequence-parallel with this many "
                        "sequence shards (needs >= that many devices)")
    a = p.parse_args()
    if a.ring > 1:
        train_sequence_parallel(sp=a.ring)
    else:
        train(epochs=a.epochs)
