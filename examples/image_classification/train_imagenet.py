"""Train ImageNet-scale networks.

Reference: ``example/image-classification/train_imagenet.py`` — the
headline ResNet-50 config (BASELINE.md).  Data from .rec files
(--data-train/--data-val, reference format via mxnet_tpu.image.ImageIter)
or --benchmark 1 for synthetic throughput runs, same as the reference flag.
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import models
from common import fit


def get_rec_iter(args, kv):
    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    if args.benchmark:
        rng = np.random.RandomState(0)
        n = args.batch_size * 32
        x = rng.rand(n, *image_shape).astype(np.float32)
        y = rng.randint(0, args.num_classes, n).astype(np.float32)
        train = mx.io.NDArrayIter(x, y, args.batch_size)
        return train, None
    from mxnet_tpu import config, io_native
    # native pipeline (reference ImageRecordIter / ImageRecordIOParser2):
    # C++ reader + N JPEG decode threads, no per-image Python cost.
    # Needs cores to beat the in-process PIL path (docs/perf.md) — let
    # MXNET_USE_NATIVE_REC=0/1 override the auto choice.
    forced = os.environ.get("MXNET_USE_NATIVE_REC")
    use_native = config.get_bool(
        "MXNET_USE_NATIVE_REC",
        io_native.jpeg_available() and (os.cpu_count() or 1) >= 2)
    if forced == "1" and not io_native.jpeg_available():
        # an explicit force must fail loudly, not quietly run 4x slower
        raise RuntimeError("MXNET_USE_NATIVE_REC=1 but the native JPEG "
                           "pipeline is unavailable on this host")
    if use_native and io_native.jpeg_available():
        train = mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=image_shape,
            batch_size=args.batch_size, shuffle=True,
            rand_crop=True, rand_mirror=True,
            num_parts=kv.num_workers, part_index=kv.rank,
            preprocess_threads=args.data_nthreads)
        val = None
        if args.data_val:
            val = mx.io.ImageRecordIter(
                path_imgrec=args.data_val, data_shape=image_shape,
                batch_size=args.batch_size,
                preprocess_threads=args.data_nthreads)
        return train, val
    train = mx.image.ImageIter(
        batch_size=args.batch_size, data_shape=image_shape,
        path_imgrec=args.data_train, path_imgidx=args.data_train_idx or None,
        shuffle=True, rand_crop=True, rand_mirror=True,
        num_parts=kv.num_workers, part_index=kv.rank)
    val = None
    if args.data_val:
        val = mx.image.ImageIter(
            batch_size=args.batch_size, data_shape=image_shape,
            path_imgrec=args.data_val, shuffle=False)
    return train, val


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train imagenet-1k",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--data-train", type=str)
    parser.add_argument("--data-train-idx", type=str, default="")
    parser.add_argument("--data-val", type=str)
    parser.add_argument("--image-shape", type=str, default="3,224,224")
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--num-examples", type=int, default=1281167)
    parser.add_argument("--benchmark", type=int, default=0,
                        help="if 1, run throughput benchmark on synthetic "
                             "data")
    fit.add_fit_args(parser)
    parser.set_defaults(network="resnet", num_layers=50, num_epochs=80,
                        lr_step_epochs="30,60", batch_size=128)
    args = parser.parse_args()

    net = models.get_model(args.network, num_classes=args.num_classes,
                           num_layers=args.num_layers,
                           image_shape=args.image_shape)
    fit.fit(args, net, get_rec_iter)
