"""Train mlp/lenet on MNIST.

Reference: ``example/image-classification/train_mnist.py``.  Reads the
standard idx-ubyte files if present (--data-dir), else generates a
synthetic stand-in so the end-to-end path runs anywhere.
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import models
from common import fit


def get_mnist_iter(args, kv):
    """MNIST iterators (reference train_mnist.py get_mnist_iter)."""
    image = os.path.join(args.data_dir, "train-images-idx3-ubyte")
    label = os.path.join(args.data_dir, "train-labels-idx1-ubyte")
    flat = args.network == "mlp"
    if os.path.exists(image):
        train = mx.io.MNISTIter(image=image, label=label,
                                batch_size=args.batch_size, shuffle=True,
                                flat=flat, num_parts=kv.num_workers,
                                part_index=kv.rank)
        vimage = os.path.join(args.data_dir, "t10k-images-idx3-ubyte")
        vlabel = os.path.join(args.data_dir, "t10k-labels-idx1-ubyte")
        val = mx.io.MNISTIter(image=vimage, label=vlabel,
                              batch_size=args.batch_size, shuffle=False,
                              flat=flat)
        return train, val
    # synthetic fallback: class-separated gaussians shaped like MNIST
    rng = np.random.RandomState(0)
    n = args.num_examples
    y = rng.randint(0, 10, n).astype(np.float32)
    x = rng.rand(n, 784).astype(np.float32) * 0.1
    for i in range(10):
        x[y == i, i * 78:(i + 1) * 78] += 0.8
    if not flat:
        x = x.reshape(n, 1, 28, 28)
    split = int(n * 0.9)
    train = mx.io.NDArrayIter(x[:split], y[:split], args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(x[split:], y[split:], args.batch_size)
    return train, val


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train an image classifier on mnist",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--data-dir", type=str, default="data/mnist/",
                        help="the input data directory")
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--num-examples", type=int, default=60000)
    fit.add_fit_args(parser)
    parser.set_defaults(network="mlp", num_epochs=10,
                        lr=0.05, lr_step_epochs="10", batch_size=64,
                        kv_store="local")
    args = parser.parse_args()

    net = models.get_model(args.network, num_classes=args.num_classes,
                           image_shape="1,28,28")
    fit.fit(args, net, get_mnist_iter)
