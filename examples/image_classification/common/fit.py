"""Shared CLI training harness.

Reference: ``example/image-classification/common/fit.py`` (:45-89 — the
network/num-layers/devices/kv-store/lr-schedule/checkpoint argument set).
Device flag parity: ``--gpus`` retained (maps to accelerator contexts, so
reference commands run unchanged on TPU); ``--tpus`` is the native spelling.
"""
from __future__ import annotations

import argparse
import logging
import os
import time

import mxnet_tpu as mx


def add_fit_args(parser):
    """Reference fit.py:45-89."""
    train = parser.add_argument_group("Training", "model training")
    train.add_argument("--network", type=str, default="mlp",
                       help="the neural network to use")
    train.add_argument("--num-layers", type=int,
                       help="number of layers in the neural network, "
                            "required by some networks such as resnet")
    train.add_argument("--gpus", type=str,
                       help="list of gpus to run, e.g. 0 or 0,2,5. "
                            "empty means using cpu")
    train.add_argument("--tpus", type=str,
                       help="list of tpu cores to run on (native spelling "
                            "of --gpus)")
    train.add_argument("--kv-store", type=str, default="device",
                       help="key-value store type")
    train.add_argument("--num-epochs", type=int, default=100,
                       help="max num of epochs")
    train.add_argument("--lr", type=float, default=0.1,
                       help="initial learning rate")
    train.add_argument("--lr-factor", type=float, default=0.1,
                       help="the ratio to reduce lr on each step")
    train.add_argument("--lr-step-epochs", type=str,
                       help="the epochs to reduce the lr, e.g. 30,60")
    train.add_argument("--optimizer", type=str, default="sgd",
                       help="the optimizer type")
    train.add_argument("--mom", type=float, default=0.9,
                       help="momentum for sgd")
    train.add_argument("--wd", type=float, default=0.0001,
                       help="weight decay for sgd")
    train.add_argument("--batch-size", type=int, default=128,
                       help="the batch size")
    train.add_argument("--disp-batches", type=int, default=20,
                       help="show progress for every n batches")
    train.add_argument("--model-prefix", type=str,
                       help="model prefix for checkpointing")
    train.add_argument("--load-epoch", type=int,
                       help="load the model on an epoch using the "
                            "model-prefix")
    train.add_argument("--top-k", type=int, default=0,
                       help="report the top-k accuracy. 0 means no report.")
    train.add_argument("--data-nthreads", type=int, default=4,
                       help="number of native decode threads "
                            "(reference --data-nthreads)")
    train.add_argument("--test-io", type=int, default=0,
                       help="1 means test reading speed without training")
    train.add_argument("--monitor", dest="monitor", type=int, default=0,
                       help="log network parameters every N iters if larger "
                            "than 0")
    train.add_argument("--fused", type=int, default=-1,
                       help="1: train via the fused ShardedTrainer step "
                            "(the TPU performance path, docs/perf.md); "
                            "0: the Module path (API parity); -1: auto "
                            "(fused on TPU, Module elsewhere)")
    train.add_argument("--dtype", type=str, default="float32",
                       help="compute dtype for the fused path (bfloat16 "
                            "recommended on TPU; master weights stay f32)")
    train.add_argument("--fuse-blocks", type=int, default=-1,
                       help="1: block-granularity fusion on the fused "
                            "trainer path (conv+BN+ReLU / FC+activation "
                            "chains as single regions with layout "
                            "planning, docs/api/fusion.md); 0: off; -1: "
                            "auto (on for the fused path)")
    train.add_argument("--device-queue", type=int, default=-1,
                       help="1: double-buffer real-data batches onto the "
                            "chip with DevicePrefetchIter (decode + "
                            "host->device transfer overlap compute); 0: "
                            "stage inline; -1: auto (on, except on "
                            "tunnel-limited backends where staging "
                            "contends with dispatch — docs/perf.md)")
    return train


def _get_contexts(args):
    spec = args.tpus or args.gpus
    if spec:
        return [mx.tpu(int(i)) for i in spec.split(",")]
    return [mx.cpu()]


def _get_lr_scheduler(args, kv):
    if not args.lr_step_epochs:
        return (args.lr, None)
    epoch_size = args.num_examples // args.batch_size
    if "dist" in args.kv_store:
        epoch_size //= kv.num_workers
    begin_epoch = args.load_epoch if args.load_epoch else 0
    step_epochs = [int(l) for l in args.lr_step_epochs.split(",")]
    lr = args.lr
    for s in step_epochs:
        if begin_epoch >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjust learning rate to %e for epoch %d", lr,
                     begin_epoch)
    steps = [epoch_size * (x - begin_epoch) for x in step_epochs
             if x - begin_epoch > 0]
    return (lr, mx.lr_scheduler.MultiFactorScheduler(step=steps,
                                                     factor=args.lr_factor))


def _use_fused(args):
    if getattr(args, "fused", -1) != -1:
        return bool(args.fused)
    try:
        import jax
        return jax.devices()[0].platform == "tpu"
    except (ImportError, RuntimeError, IndexError):
        return False


def _fit_fused(args, sym, train, val, kv):
    """Train through the fused ShardedTrainer step (one XLA program per
    step: forward+backward+allreduce+optimizer) with the fit-CLI surface
    — lr schedule, checkpoints, Speedometer logging, epoch eval.

    This is the performance path the bench measures (docs/perf.md: 9.5x
    the per-op Module dispatch on a remote TPU backend); the Module path
    (--fused 0) remains the API-parity route.  Batches are staged with
    ``put_batch`` and the step dispatch is async, so host IO for batch
    N+1 overlaps device compute for batch N; the loss value is fetched
    (a device sync) only every --disp-batches.
    """
    import numpy as np
    from mxnet_tpu.parallel import ShardedTrainer, build_mesh

    data_name, data_shape = train.provide_data[0][:2]
    label_name, label_shape = train.provide_label[0][:2]
    lr, lr_scheduler = _get_lr_scheduler(args, kv)
    optimizer_params = {"lr_scheduler": lr_scheduler}

    mesh = build_mesh(tp=1)
    common = dict(
        data_shapes={data_name: tuple(data_shape)},
        label_shapes={label_name: tuple(label_shape)},
        optimizer=args.optimizer, optimizer_params=optimizer_params,
        learning_rate=lr, momentum=args.mom, weight_decay=args.wd,
        dtype=args.dtype, auto_layouts=True,
        # block-granularity fusion (analysis.fusion): on by default for
        # the fused path — conv+BN+ReLU blocks become single regions
        # with a pinned layout per boundary (docs/api/fusion.md)
        fuse_blocks=getattr(args, "fuse_blocks", -1) != 0,
        initializer=mx.initializer.Xavier(
            rnd_type="gaussian", factor_type="in", magnitude=2))
    try:
        trainer = ShardedTrainer(sym, mesh, layout="NHWC", **common)
    except mx.base.MXNetError:
        # nets with NCHW-pinned axis semantics fall back to NCHW
        trainer = ShardedTrainer(sym, mesh, **common)

    begin_epoch = args.load_epoch or 0
    if args.load_epoch and args.model_prefix:
        trainer.load_checkpoint(args.model_prefix, args.load_epoch)

    eval_metrics = [mx.metric.create("accuracy")]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.create("top_k_accuracy",
                                             top_k=args.top_k))

    # --benchmark runs cycle a small synthetic set: stage each distinct
    # batch on device ONCE and reuse it across epochs, so the benchmark
    # measures the training pipeline rather than re-shipping identical
    # bytes over the host link every epoch (bench.py methodology; the
    # real-data path below always transfers)
    staged = {} if getattr(args, "benchmark", 0) else None

    # device queue (VERDICT r4 #4): on the real-data path, a
    # DevicePrefetchIter double-buffers decode + host->device staging
    # behind the async step dispatch, so steady-state training pays no
    # staging wall-time.  Auto-off on tunnel-limited backends, where
    # the background thread contends with dispatch for the one link
    # (measured 0.63x, docs/perf.md).
    dq = getattr(args, "device_queue", -1)
    use_queue = staged is None and (
        bool(dq) if dq != -1 else not mx.io.tunnel_limited_backend())
    if staged is not None and dq == 1:
        # ADVICE r5: an explicit request must not vanish silently
        logging.info(
            "--device-queue 1 is overridden by --benchmark staging: "
            "synthetic batches are staged once and reused on device, so "
            "there is no per-batch host->device transfer for the queue "
            "to overlap")

    def _host_dict(batch):
        return {data_name: batch.data[0].asnumpy(),
                label_name: batch.label[0].asnumpy()}

    for epoch in range(begin_epoch, args.num_epochs):
        train.reset()
        tic = time.time()
        nbatch = 0
        loss = None
        if use_queue:
            source = mx.io.DevicePrefetchIter(train, trainer.put_batch,
                                              depth=2)
        else:
            source = train
        for batch in source:
            if use_queue:
                dev = batch            # already staged by the queue
            elif staged is not None and nbatch in staged:
                dev = staged[nbatch]
            else:
                dev = trainer.put_batch(_host_dict(batch))
                if staged is not None:
                    staged[nbatch] = dev
            loss = trainer.step(dev)
            nbatch += 1
            if nbatch == 1 and epoch == begin_epoch:
                fs = trainer.fusion_summary()
                if fs:
                    logging.info(
                        "fusion plan: %d block(s) %s, %d relayout(s) "
                        "eliminated, fallbacks=%s", fs["blocks"],
                        fs["kinds"], fs["relayouts_eliminated"],
                        fs["fallbacks"] or "none")
            if args.disp_batches and nbatch % args.disp_batches == 0:
                # float(loss) syncs the async chain — the only per-batch
                # device round trip, paid once per disp window
                lval = float(loss)
                speed = args.disp_batches * args.batch_size / \
                    (time.time() - tic)
                logging.info(
                    "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                    "\tcross-entropy=%f", epoch, nbatch, speed, lval)
                tic = time.time()
        if loss is not None:
            logging.info("Epoch[%d] Train-cross-entropy=%f", epoch,
                         float(loss))
        if args.model_prefix and kv.rank == 0:
            trainer.save_checkpoint(args.model_prefix, epoch + 1,
                                    save_optimizer_states=True)
        if val is not None:
            val.reset()
            for m in eval_metrics:
                m.reset()
            for batch in val:
                probs = np.asarray(trainer.forward(
                    {data_name: batch.data[0].asnumpy()})[0])
                n_valid = probs.shape[0] - batch.pad
                lab = mx.nd.array(batch.label[0].asnumpy()[:n_valid])
                for m in eval_metrics:
                    m.update([lab], [mx.nd.array(probs[:n_valid])])
            for m in eval_metrics:
                for name, value in zip(*_metric_get(m)):
                    logging.info("Epoch[%d] Validation-%s=%f", epoch,
                                 name, value)
    return trainer


def _metric_get(m):
    name, value = m.get()
    if not isinstance(name, list):
        name, value = [name], [value]
    return name, value


def fit(args, network, data_loader, **kwargs):
    """Train the model (reference fit.py fit())."""
    kv = mx.kv.create(args.kv_store)
    logging.basicConfig(level=logging.DEBUG,
                        format="%(asctime)-15s Node[" + str(kv.rank) +
                        "] %(message)s")
    logging.info("start with arguments %s", args)

    (train, val) = data_loader(args, kv)
    if args.test_io:
        tic = time.time()
        for i, batch in enumerate(train):
            for j in batch.data:
                j.wait_to_read()
            if (i + 1) % args.disp_batches == 0:
                logging.info("Batch [%d]\tSpeed: %.2f samples/sec", i,
                             args.disp_batches * args.batch_size /
                             (time.time() - tic))
                tic = time.time()
        return

    if _use_fused(args):
        if "dist" in args.kv_store:
            logging.warning("--fused with a dist kv-store: the fused "
                            "trainer allreduces over the device mesh of "
                            "THIS process; use tools/launch.py host "
                            "meshes for multi-process training")
        return _fit_fused(args, network, train, val, kv)

    if args.load_epoch and args.model_prefix:
        sym, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
    else:
        sym, arg_params, aux_params = network, None, None

    devs = _get_contexts(args)
    lr, lr_scheduler = _get_lr_scheduler(args, kv)

    model = mx.module.Module(context=devs, symbol=sym)

    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "lr_scheduler": lr_scheduler,
    }
    if args.optimizer in ("sgd", "nag", "dcasgd"):
        optimizer_params["momentum"] = args.mom

    eval_metrics = ["accuracy"]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.create("top_k_accuracy",
                                             top_k=args.top_k))

    batch_end_callbacks = [mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches)]
    checkpoint = None
    if args.model_prefix:
        checkpoint = mx.callback.do_checkpoint(
            args.model_prefix if kv.rank == 0 else
            "%s-%d" % (args.model_prefix, kv.rank))

    monitor = mx.Monitor(args.monitor, pattern=".*") if args.monitor > 0 \
        else None

    model.fit(train, begin_epoch=args.load_epoch or 0,
              num_epoch=args.num_epochs, eval_data=val,
              eval_metric=eval_metrics, kvstore=kv,
              optimizer=args.optimizer, optimizer_params=optimizer_params,
              initializer=mx.initializer.Xavier(
                  rnd_type="gaussian", factor_type="in", magnitude=2),
              arg_params=arg_params, aux_params=aux_params,
              batch_end_callback=batch_end_callbacks,
              epoch_end_callback=checkpoint, allow_missing=True,
              monitor=monitor)
    return model
