"""Shared CLI training harness.

Reference: ``example/image-classification/common/fit.py`` (:45-89 — the
network/num-layers/devices/kv-store/lr-schedule/checkpoint argument set).
Device flag parity: ``--gpus`` retained (maps to accelerator contexts, so
reference commands run unchanged on TPU); ``--tpus`` is the native spelling.
"""
from __future__ import annotations

import argparse
import logging
import os
import time

import mxnet_tpu as mx


def add_fit_args(parser):
    """Reference fit.py:45-89."""
    train = parser.add_argument_group("Training", "model training")
    train.add_argument("--network", type=str, default="mlp",
                       help="the neural network to use")
    train.add_argument("--num-layers", type=int,
                       help="number of layers in the neural network, "
                            "required by some networks such as resnet")
    train.add_argument("--gpus", type=str,
                       help="list of gpus to run, e.g. 0 or 0,2,5. "
                            "empty means using cpu")
    train.add_argument("--tpus", type=str,
                       help="list of tpu cores to run on (native spelling "
                            "of --gpus)")
    train.add_argument("--kv-store", type=str, default="device",
                       help="key-value store type")
    train.add_argument("--num-epochs", type=int, default=100,
                       help="max num of epochs")
    train.add_argument("--lr", type=float, default=0.1,
                       help="initial learning rate")
    train.add_argument("--lr-factor", type=float, default=0.1,
                       help="the ratio to reduce lr on each step")
    train.add_argument("--lr-step-epochs", type=str,
                       help="the epochs to reduce the lr, e.g. 30,60")
    train.add_argument("--optimizer", type=str, default="sgd",
                       help="the optimizer type")
    train.add_argument("--mom", type=float, default=0.9,
                       help="momentum for sgd")
    train.add_argument("--wd", type=float, default=0.0001,
                       help="weight decay for sgd")
    train.add_argument("--batch-size", type=int, default=128,
                       help="the batch size")
    train.add_argument("--disp-batches", type=int, default=20,
                       help="show progress for every n batches")
    train.add_argument("--model-prefix", type=str,
                       help="model prefix for checkpointing")
    train.add_argument("--load-epoch", type=int,
                       help="load the model on an epoch using the "
                            "model-prefix")
    train.add_argument("--top-k", type=int, default=0,
                       help="report the top-k accuracy. 0 means no report.")
    train.add_argument("--data-nthreads", type=int, default=4,
                       help="number of native decode threads "
                            "(reference --data-nthreads)")
    train.add_argument("--test-io", type=int, default=0,
                       help="1 means test reading speed without training")
    train.add_argument("--monitor", dest="monitor", type=int, default=0,
                       help="log network parameters every N iters if larger "
                            "than 0")
    return train


def _get_contexts(args):
    spec = args.tpus or args.gpus
    if spec:
        return [mx.tpu(int(i)) for i in spec.split(",")]
    return [mx.cpu()]


def _get_lr_scheduler(args, kv):
    if not args.lr_step_epochs:
        return (args.lr, None)
    epoch_size = args.num_examples // args.batch_size
    if "dist" in args.kv_store:
        epoch_size //= kv.num_workers
    begin_epoch = args.load_epoch if args.load_epoch else 0
    step_epochs = [int(l) for l in args.lr_step_epochs.split(",")]
    lr = args.lr
    for s in step_epochs:
        if begin_epoch >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjust learning rate to %e for epoch %d", lr,
                     begin_epoch)
    steps = [epoch_size * (x - begin_epoch) for x in step_epochs
             if x - begin_epoch > 0]
    return (lr, mx.lr_scheduler.MultiFactorScheduler(step=steps,
                                                     factor=args.lr_factor))


def fit(args, network, data_loader, **kwargs):
    """Train the model (reference fit.py fit())."""
    kv = mx.kv.create(args.kv_store)
    logging.basicConfig(level=logging.DEBUG,
                        format="%(asctime)-15s Node[" + str(kv.rank) +
                        "] %(message)s")
    logging.info("start with arguments %s", args)

    (train, val) = data_loader(args, kv)
    if args.test_io:
        tic = time.time()
        for i, batch in enumerate(train):
            for j in batch.data:
                j.wait_to_read()
            if (i + 1) % args.disp_batches == 0:
                logging.info("Batch [%d]\tSpeed: %.2f samples/sec", i,
                             args.disp_batches * args.batch_size /
                             (time.time() - tic))
                tic = time.time()
        return

    if args.load_epoch and args.model_prefix:
        sym, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
    else:
        sym, arg_params, aux_params = network, None, None

    devs = _get_contexts(args)
    lr, lr_scheduler = _get_lr_scheduler(args, kv)

    model = mx.module.Module(context=devs, symbol=sym)

    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "lr_scheduler": lr_scheduler,
    }
    if args.optimizer in ("sgd", "nag", "dcasgd"):
        optimizer_params["momentum"] = args.mom

    eval_metrics = ["accuracy"]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.create("top_k_accuracy",
                                             top_k=args.top_k))

    batch_end_callbacks = [mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches)]
    checkpoint = None
    if args.model_prefix:
        checkpoint = mx.callback.do_checkpoint(
            args.model_prefix if kv.rank == 0 else
            "%s-%d" % (args.model_prefix, kv.rank))

    monitor = mx.Monitor(args.monitor, pattern=".*") if args.monitor > 0 \
        else None

    model.fit(train, begin_epoch=args.load_epoch or 0,
              num_epoch=args.num_epochs, eval_data=val,
              eval_metric=eval_metrics, kvstore=kv,
              optimizer=args.optimizer, optimizer_params=optimizer_params,
              initializer=mx.initializer.Xavier(
                  rnd_type="gaussian", factor_type="in", magnitude=2),
              arg_params=arg_params, aux_params=aux_params,
              batch_end_callback=batch_end_callbacks,
              epoch_end_callback=checkpoint, allow_missing=True,
              monitor=monitor)
    return model
