"""Inference throughput benchmark.

Reference: ``example/image-classification/benchmark_score.py`` — img/s over
the model zoo at batch sizes 1..32 (the numbers in perf.md:40-147).
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import models

logging.basicConfig(level=logging.INFO)


def score(network, batch_size, image_shape=(3, 224, 224), num_batches=20,
          dev=None):
    net = models.get_model(network, num_classes=1000,
                           image_shape=",".join(map(str, image_shape)))
    data_shape = (batch_size,) + image_shape
    if dev is None:
        # bind on the accelerator (reference scores on mx.gpu(0)); a cpu
        # context would re-ship every weight to the chip per call
        import jax
        has_accel = any(d.platform != "cpu" for d in jax.devices())
        dev = mx.tpu(0) if has_accel else mx.cpu()
    ex = net.simple_bind(dev, grad_req="null",
                         data=data_shape,
                         softmax_label=(batch_size,))
    init = mx.initializer.Xavier()
    for k, v in ex.arg_dict.items():
        if k not in ("data", "softmax_label"):
            init(k, v)
    for k, v in ex.aux_dict.items():
        if k.endswith("moving_var"):
            v[:] = 1.0
    x = np.random.rand(*data_shape).astype(np.float32)
    ex.forward(is_train=False, data=x)
    float(ex.outputs[0].asnumpy().sum())  # warm compile
    tic = time.time()
    for _ in range(num_batches):
        out = ex.forward(is_train=False)
    float(out[0].asnumpy().sum())  # value fetch closes the chain
    return num_batches * batch_size / (time.time() - tic)


def score_fused(network, batch_size, image_shape=(3, 224, 224),
                num_batches=20, dtype="bfloat16"):
    """Inference through the fused path: one jitted forward program,
    bf16 NHWC (the TPU-native serving configuration); batch staged once
    so the number isolates device throughput like `score` does."""
    from mxnet_tpu.parallel import ShardedTrainer, build_mesh
    net = models.get_model(network, num_classes=1000,
                           image_shape=",".join(map(str, image_shape)))
    data_shape = (batch_size,) + image_shape
    trainer = ShardedTrainer(
        net, build_mesh(tp=1),
        data_shapes={"data": data_shape},
        label_shapes={"softmax_label": (batch_size,)},
        dtype=dtype, layout="NHWC")
    x = np.random.rand(*data_shape).astype(np.float32)
    dev = trainer.put_batch({"data": x})
    float(np.asarray(trainer.forward(dev)[0]).sum())   # warm compile
    tic = time.time()
    for _ in range(num_batches):
        out = trainer.forward(dev)
    float(np.asarray(out[0]).sum())  # value fetch closes the chain
    return num_batches * batch_size / (time.time() - tic)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="score inference speed")
    parser.add_argument("--networks", type=str,
                        default="alexnet,vgg16,inception_bn,resnet50")
    parser.add_argument("--batch-sizes", type=str, default="1,2,4,8,16,32")
    parser.add_argument("--fused", type=int, default=0,
                        help="1: score the fused bf16 NHWC path")
    parser.add_argument("--dtype", type=str, default="bfloat16")
    args = parser.parse_args()
    for net in args.networks.split(","):
        shape = (3, 299, 299) if net == "inception_v3" else (3, 224, 224)
        logging.info("network: %s", net)
        for b in (int(x) for x in args.batch_sizes.split(",")):
            if args.fused:
                speed = score_fused(net, b, image_shape=shape,
                                    dtype=args.dtype)
            else:
                speed = score(net, b, image_shape=shape)
            logging.info("batch size %2d, image/sec: %f", b, speed)
