"""Train on CIFAR-10.

Reference: ``example/image-classification/train_cifar10.py``.  Reads the
reference's ``cifar10_train.rec`` if present, else synthesizes data.
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import models
from common import fit


def get_cifar_iter(args, kv):
    train_rec = os.path.join(args.data_dir, "cifar10_train.rec")
    if os.path.exists(train_rec):
        train = mx.image.ImageIter(
            batch_size=args.batch_size, data_shape=(3, 28, 28),
            path_imgrec=train_rec, shuffle=True, rand_crop=True,
            rand_mirror=True, num_parts=kv.num_workers, part_index=kv.rank)
        val = mx.image.ImageIter(
            batch_size=args.batch_size, data_shape=(3, 28, 28),
            path_imgrec=os.path.join(args.data_dir, "cifar10_val.rec"))
        return train, val
    rng = np.random.RandomState(0)
    n = args.num_examples
    y = rng.randint(0, 10, n).astype(np.float32)
    x = rng.rand(n, 3, 28, 28).astype(np.float32) * 0.2
    for i in range(10):
        x[y == i, :, i:i + 3, i:i + 3] += 0.7
    split = int(n * 0.9)
    train = mx.io.NDArrayIter(x[:split], y[:split], args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(x[split:], y[split:], args.batch_size)
    return train, val


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train cifar10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--data-dir", type=str, default="data/cifar10/")
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--num-examples", type=int, default=50000)
    fit.add_fit_args(parser)
    parser.set_defaults(network="resnet", num_layers=20, num_epochs=300,
                        lr=0.05, lr_step_epochs="200,250", batch_size=128,
                        kv_store="local")
    args = parser.parse_args()

    net = models.get_model(args.network, num_classes=args.num_classes,
                           num_layers=args.num_layers,
                           image_shape="3,28,28")
    fit.fit(args, net, get_cifar_iter)
