"""Deep Embedded Clustering (DEC).

Reference: ``example/dec/dec.py`` — pretrain a stacked autoencoder,
k-means the bottleneck embedding, then jointly refine encoder weights
and cluster centers by minimizing KL(P || Q) where Q is a student-t
soft assignment and P the sharpened target distribution.  The loss (and
its gradient w.r.t. both the embedding and the centers) is a numpy
CustomOp, like the reference's ``NumpyOp`` DECLoss.

Data: well-separated synthetic blobs in pixel space, so CI can assert
cluster accuracy.

    python dec.py --clusters 4
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "autoencoder"))

import mxnet_tpu as mx
from mnist_sae import StackedAutoEncoder


class DECLoss(mx.operator.CustomOp):
    def __init__(self, alpha=1.0):
        super().__init__()
        self.alpha = alpha

    def _q(self, z, mu):
        d2 = ((z[:, None, :] - mu[None, :, :]) ** 2).sum(-1)
        mask = 1.0 / (1.0 + d2 / self.alpha)
        q = mask ** ((self.alpha + 1.0) / 2.0)
        q = q / q.sum(axis=1, keepdims=True)
        return q, mask

    def forward(self, is_train, req, in_data, out_data, aux):
        z, mu = in_data[0].asnumpy(), in_data[1].asnumpy()
        q, _ = self._q(z, mu)
        self.assign(out_data[0], req[0], q)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        # stateless across calls: recompute the student-t mask here
        z, mu, p = (in_data[i].asnumpy() for i in range(3))
        q, mask = self._q(z, mu)
        m = mask * (self.alpha + 1.0) / self.alpha * (p - q)
        dz = (z.T * m.sum(axis=1)).T - m.dot(mu)
        dmu = (mu.T * m.sum(axis=0)).T - m.T.dot(z)
        self.assign(in_grad[0], req[0], dz)
        self.assign(in_grad[1], req[1], dmu)
        self.assign(in_grad[2], req[2], np.zeros_like(p))


@mx.operator.register("dec_loss")
class DECLossProp(mx.operator.CustomOpProp):
    def __init__(self, num_centers, alpha=1.0):
        super().__init__(need_top_grad=False)
        self.num_centers = int(num_centers)
        self.alpha = float(alpha)

    def list_arguments(self):
        return ["z", "mu", "p"]

    def list_outputs(self):
        return ["q"]

    def infer_shape(self, in_shape):
        n, d = in_shape[0]
        return ([in_shape[0], (self.num_centers, d),
                 (n, self.num_centers)], [(n, self.num_centers)], [])

    def create_operator(self, ctx, shapes, dtypes):
        return DECLoss(self.alpha)


def kmeans(x, k, iters=50, seed=0):
    rng = np.random.RandomState(seed)
    centers = x[rng.choice(len(x), k, replace=False)].copy()
    for _ in range(iters):
        d = ((x[:, None] - centers[None]) ** 2).sum(-1)
        a = d.argmin(1)
        for j in range(k):
            if (a == j).any():
                centers[j] = x[a == j].mean(0)
    return centers, a


def cluster_accuracy(pred, truth, k):
    """Best-permutation accuracy via greedy assignment (blobs are
    well-separated; full Hungarian not needed)."""
    w = np.zeros((k, k))
    for pi, ti in zip(pred, truth.astype(int)):
        w[pi, ti] += 1
    acc = 0
    used_r, used_c = set(), set()
    for _ in range(k):
        r, c = np.unravel_index(
            np.argmax(np.where(
                np.isin(np.arange(k), list(used_r))[:, None] |
                np.isin(np.arange(k), list(used_c))[None, :],
                -1, w)), (k, k))
        acc += w[r, c]
        used_r.add(r)
        used_c.add(c)
    return acc / len(pred)


def blobs(n, dim=64, k=4, seed=0):
    rng = np.random.RandomState(seed)
    protos = rng.randn(k, dim).astype("f") * 3.0
    y = rng.randint(0, k, n)
    return (protos[y] + rng.randn(n, dim).astype("f")).astype("f"), y


def train(clusters=4, n=2000, dims=(64, 16), epochs=40, batch_size=256,
          ctx=None):
    ctx = ctx or mx.context.current_context()
    x, y = blobs(n, k=clusters)

    sae = StackedAutoEncoder(x.shape[1], dims, ctx=ctx)
    sae.pretrain(x, epochs=2, batch_size=100)
    sae.finetune(x, epochs=4, batch_size=100)
    z = sae._features(len(dims), x, 100)
    centers, assign0 = kmeans(z, clusters)
    acc0 = cluster_accuracy(assign0, y, clusters)

    # DEC refinement graph: encoder -> dec_loss(z, mu, p)
    enc = sae._encoder(len(dims))
    dec_sym = mx.sym.Custom(z=enc, mu=mx.sym.Variable("mu"),
                            p=mx.sym.Variable("p"), name="dec",
                            op_type="dec_loss", num_centers=clusters)
    mod = mx.module.Module(dec_sym, context=ctx, data_names=("data",),
                           label_names=("p",))
    mod.bind(data_shapes=[("data", (batch_size, x.shape[1]))],
             label_shapes=[("p", (batch_size, clusters))])
    # all args come from the pretrained encoder + kmeans centers ("mu"
    # has no default-init name pattern, so it must arrive as a param)
    mod.init_params(mx.init.Xavier(),
                    arg_params={**sae.params,
                                "mu": mx.nd.array(centers)},
                    allow_missing=True, allow_extra=True)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01,
                                         "momentum": 0.9})

    def soft_assign(zb, mu):
        d2 = ((zb[:, None] - mu[None]) ** 2).sum(-1)
        q = (1.0 + d2) ** -1.0
        return q / q.sum(1, keepdims=True)

    for epoch in range(epochs):
        mu = mod.get_params()[0]["mu"].asnumpy()
        # full-set target distribution P from current Q (reference updates
        # p every `update_interval`; here once per epoch)
        znow = sae._features(len(dims), x, 100) if epoch else z
        q = soft_assign(znow, mu)
        f = q.sum(0)
        p = (q ** 2 / f) / (q ** 2 / f).sum(1, keepdims=True)
        order = np.random.RandomState(epoch).permutation(n)
        for s in range(0, n - batch_size + 1, batch_size):
            idx = order[s:s + batch_size]
            batch = mx.io.DataBatch(
                data=[mx.nd.array(x[idx])],
                label=[mx.nd.array(p[idx].astype("f"))])
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        # keep the SAE param view fresh for _features
        args, _ = mod.get_params()
        sae.params = {k: v for k, v in args.items() if k != "mu"}

    mu = mod.get_params()[0]["mu"].asnumpy()
    zf = sae._features(len(dims), x, 100)
    pred = soft_assign(zf, mu).argmax(1)
    acc = cluster_accuracy(pred, y, clusters)
    logging.info("cluster accuracy: kmeans %.3f -> DEC %.3f", acc0, acc)
    return acc0, acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--clusters", type=int, default=4)
    a = p.parse_args()
    train(clusters=a.clusters)
