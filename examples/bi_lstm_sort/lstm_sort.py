"""Sorting short integer sequences with a bidirectional LSTM.

Reference: ``example/bi-lstm-sort/lstm_sort.py`` — sequence-to-sequence
sorting (input: k numbers, target: the same numbers sorted), learned by a
``BidirectionalCell`` over embeddings with a shared per-timestep softmax
head.  The task needs both directions: the value at output position t
depends on the whole input.

    python lstm_sort.py --epochs 10
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def make_sym(seq_len, vocab_size, num_hidden=64, num_embed=32):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                             output_dim=num_embed, name="embed")
    bi = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="lstm_l_"),
        mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="lstm_r_"))
    outputs, _ = bi.unroll(seq_len, inputs=embed, merge_outputs=True)
    pred = mx.sym.Reshape(outputs, shape=(-1, 2 * num_hidden))
    pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab_size,
                                 name="pred")
    label = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(data=pred, label=label, name="softmax")


def sort_dataset(n, seq_len, vocab_size, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(0, vocab_size, (n, seq_len))
    y = np.sort(x, axis=1)
    return x.astype(np.float32), y.astype(np.float32)


def element_accuracy(mod, it):
    """Fraction of output positions predicted exactly right."""
    it.reset()
    correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy()
        lab = batch.label[0].asnumpy().ravel().astype(np.int64)
        correct += (np.argmax(pred, axis=1) == lab).sum()
        total += lab.size
    return correct / total


def train(epochs=10, batch_size=50, seq_len=5, vocab_size=30,
          num_hidden=64, ctx=None):
    ctx = ctx or mx.context.current_context()
    xtr, ytr = sort_dataset(5000, seq_len, vocab_size, seed=0)
    xte, yte = sort_dataset(500, seq_len, vocab_size, seed=1)
    train_iter = mx.io.NDArrayIter(xtr, ytr, batch_size, shuffle=True)
    test_iter = mx.io.NDArrayIter(xte, yte, batch_size)

    net = make_sym(seq_len, vocab_size, num_hidden=num_hidden)
    mod = mx.module.Module(net, context=ctx)
    mod.fit(train_iter, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="adam", optimizer_params={"learning_rate": 3e-3},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(batch_size, 50))
    acc = element_accuracy(mod, test_iter)
    logging.info("per-position sort accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    a = p.parse_args()
    train(epochs=a.epochs)
