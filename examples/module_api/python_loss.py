"""A numpy loss head: multiclass hinge gradient via PythonLossModule.

Reference: ``example/module/python_loss.py`` — an MLP Module chained
into a ``PythonLossModule`` whose gradient function is plain numpy; the
SequentialModule routes labels to the loss and the loss's input grads
back into the trunk.

    python python_loss.py
"""
from __future__ import annotations

import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def mc_hinge_grad(scores, labels):
    """Crammer-Singer multiclass hinge subgradient."""
    scores = scores.asnumpy()
    labels = labels.asnumpy().astype(np.int64)
    n, _ = scores.shape
    grad = np.zeros_like(scores)
    for i in range(n):
        score = 1 + scores[i] - scores[i, labels[i]]
        score[labels[i]] = 0
        ind_pred = score.argmax()
        grad[i, labels[i]] -= 1
        grad[i, ind_pred] += 1
    return grad / n


def synthetic(n, dim=196, seed=0):
    protos = np.random.RandomState(42).rand(10, dim).astype("f")
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = protos[y] + 0.25 * rng.randn(n, dim).astype("f")
    return x.astype("f"), y.astype("f")


def train(epochs=4, batch_size=100, ctx=None):
    ctx = ctx or mx.context.current_context()
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=128)
    act1 = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=64)
    act2 = mx.sym.Activation(fc2, name="relu2", act_type="relu")
    fc3 = mx.sym.FullyConnected(act2, name="fc3", num_hidden=10)

    mlp = mx.module.Module(fc3, label_names=[], context=ctx)
    loss = mx.module.PythonLossModule(grad_func=mc_hinge_grad)
    mod = mx.module.SequentialModule() \
        .add(mlp) \
        .add(loss, take_labels=True, auto_wiring=True)

    xtr, ytr = synthetic(2000, seed=0)
    xte, yte = synthetic(500, seed=1)
    train_iter = mx.io.NDArrayIter(xtr, ytr, batch_size, shuffle=True)

    mod.fit(train_iter, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})

    # score by running the trunk alone
    test_iter = mx.io.NDArrayIter(xte, yte, batch_size)
    correct = total = 0
    for batch in test_iter:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy()
        lab = batch.label[0].asnumpy()
        correct += (pred.argmax(1) == lab).sum()
        total += len(lab)
    acc = correct / total
    logging.info("hinge-loss MLP test accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    train()
