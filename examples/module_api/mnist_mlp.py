"""Low-level Module API walkthrough: bind / init / forward / backward /
update driven by hand, plus fit() and checkpointing on the same module.

Reference: ``example/module/mnist_mlp.py`` — demonstrates the
intermediate-level interface under ``fit``.

    python mnist_mlp.py
"""
from __future__ import annotations

import logging
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def make_mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=128)
    act1 = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=64)
    act2 = mx.sym.Activation(fc2, name="relu2", act_type="relu")
    fc3 = mx.sym.FullyConnected(act2, name="fc3", num_hidden=10)
    return mx.sym.SoftmaxOutput(fc3, name="softmax")


def synthetic(n, dim=196, seed=0):
    protos = np.random.RandomState(42).rand(10, dim).astype("f")
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = protos[y] + 0.25 * rng.randn(n, dim).astype("f")
    return x.astype("f"), y.astype("f")


def train(epochs=3, batch_size=100, ctx=None):
    ctx = ctx or mx.context.current_context()
    xtr, ytr = synthetic(2000, seed=0)
    xte, yte = synthetic(500, seed=1)
    train_iter = mx.io.NDArrayIter(xtr, ytr, batch_size, shuffle=True)
    test_iter = mx.io.NDArrayIter(xte, yte, batch_size)

    # ---- intermediate interface: drive the loop yourself -------------
    mod = mx.module.Module(make_mlp(), context=ctx)
    mod.bind(data_shapes=train_iter.provide_data,
             label_shapes=train_iter.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    metric = mx.metric.Accuracy()
    for epoch in range(epochs):
        train_iter.reset()
        metric.reset()
        for batch in train_iter:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        logging.info("epoch %d, train %s", epoch, metric.get())

    acc = mod.score(test_iter, mx.metric.Accuracy())[0][1]

    # ---- checkpoint roundtrip ----------------------------------------
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "mlp")
        mod.save_checkpoint(prefix, epochs)
        mod2 = mx.module.Module.load(prefix, epochs, context=ctx)
        mod2.bind(data_shapes=test_iter.provide_data,
                  label_shapes=test_iter.provide_label,
                  for_training=False)
        acc2 = mod2.score(test_iter, mx.metric.Accuracy())[0][1]
    assert abs(acc - acc2) < 1e-6, (acc, acc2)
    logging.info("test accuracy %.3f (checkpoint reload matches)", acc)
    return acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    train()
