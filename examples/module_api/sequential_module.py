"""SequentialModule: chain independent Modules into one trainable stack.

Reference: ``example/module/sequential_module.py`` — module 1 (feature
trunk, no labels) feeds module 2 (classifier head) with automatic data
wiring and label routing; the chain trains end to end through the
container's fit().

    python sequential_module.py
"""
from __future__ import annotations

import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def build_chain(ctx):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=128)
    act1 = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    mod1 = mx.module.Module(act1, label_names=[], context=ctx)

    data = mx.sym.Variable("data")
    fc2 = mx.sym.FullyConnected(data, name="fc2", num_hidden=64)
    act2 = mx.sym.Activation(fc2, name="relu2", act_type="relu")
    fc3 = mx.sym.FullyConnected(act2, name="fc3", num_hidden=10)
    softmax = mx.sym.SoftmaxOutput(fc3, name="softmax")
    mod2 = mx.module.Module(softmax, context=ctx)

    seq = mx.module.SequentialModule()
    seq.add(mod1).add(mod2, take_labels=True, auto_wiring=True)
    return seq


def synthetic(n, dim=196, seed=0):
    protos = np.random.RandomState(42).rand(10, dim).astype("f")
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = protos[y] + 0.25 * rng.randn(n, dim).astype("f")
    return x.astype("f"), y.astype("f")


def train(epochs=3, batch_size=100, ctx=None):
    ctx = ctx or mx.context.current_context()
    xtr, ytr = synthetic(2000, seed=0)
    xte, yte = synthetic(500, seed=1)
    train_iter = mx.io.NDArrayIter(xtr, ytr, batch_size, shuffle=True)
    test_iter = mx.io.NDArrayIter(xte, yte, batch_size)

    seq = build_chain(ctx)
    seq.fit(train_iter, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    acc = seq.score(test_iter, mx.metric.Accuracy())[0][1]
    logging.info("sequential-module test accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    train()
