"""Model-parallel LSTM: each layer group on its own device.

Reference: ``example/model-parallel-lstm/lstm.py`` (:48-112 layers placed on
different GPUs via ctx_group, :142-205 executors with grad_req='add').
TPU-native: ctx_group maps onto per-device placement in the executor
(SURVEY §2.4 row 'Model parallelism'); XLA async dispatch pipelines the
per-device segments the way the reference's dependency engine overlaps
ctx groups.
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx


def build_lstm(num_layers, seq_len, num_hidden, num_embed, vocab,
               group_per_layer=True):
    """Stacked LSTM with one ctx_group per layer."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    with mx.AttrScope(ctx_group="embed"):
        embed = mx.sym.Embedding(data=data, input_dim=vocab,
                                 output_dim=num_embed, name="embed")
    inputs = embed
    for i in range(num_layers):
        group = "layer%d" % i if group_per_layer else "layers"
        with mx.AttrScope(ctx_group=group):
            cell = mx.rnn.LSTMCell(num_hidden=num_hidden,
                                   prefix="lstm_l%d_" % i)
            outputs, _ = cell.unroll(seq_len, inputs=inputs,
                                     merge_outputs=True)
        inputs = outputs
    with mx.AttrScope(ctx_group="decode"):
        pred = mx.sym.Reshape(outputs, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab,
                                     name="pred")
        lab = mx.sym.Reshape(label, shape=(-1,))
        net = mx.sym.SoftmaxOutput(data=pred, label=lab, name="softmax")
    return net


def main():
    parser = argparse.ArgumentParser(
        description="model-parallel LSTM (reference "
                    "example/model-parallel-lstm)")
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--seq-len", type=int, default=16)
    parser.add_argument("--num-hidden", type=int, default=128)
    parser.add_argument("--num-embed", type=int, default=64)
    parser.add_argument("--vocab", type=int, default=100)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--num-batches", type=int, default=10)
    parser.add_argument("--lr", type=float, default=0.1)
    args = parser.parse_args()

    import jax
    n_dev = len(jax.devices())
    # map layer groups round-robin over available devices
    group2ctx = {"embed": mx.cpu(0) if n_dev == 1 else mx.tpu(0)}
    for i in range(args.num_layers):
        dev = (i + 1) % max(n_dev, 1)
        group2ctx["layer%d" % i] = mx.cpu(dev) if n_dev == 1 \
            else mx.tpu(dev)
    group2ctx["decode"] = group2ctx["layer%d" % (args.num_layers - 1)]

    net = build_lstm(args.num_layers, args.seq_len, args.num_hidden,
                     args.num_embed, args.vocab)

    # grad_req='add' as the reference uses for shared params across
    # ctx groups (example/model-parallel-lstm/lstm.py:199)
    ex = net.simple_bind(mx.cpu(0), grad_req="add",
                         data=(args.batch_size, args.seq_len),
                         softmax_label=(args.batch_size, args.seq_len),
                         group2ctx=group2ctx)
    init = mx.initializer.Xavier()
    for k, v in ex.arg_dict.items():
        if k not in ("data", "softmax_label"):
            init(k, v)

    rng = np.random.RandomState(0)
    opt = mx.optimizer.create("sgd", learning_rate=args.lr,
                              rescale_grad=1.0 / args.batch_size)
    updater = mx.optimizer.get_updater(opt)

    for step in range(args.num_batches):
        x = rng.randint(0, args.vocab,
                        (args.batch_size, args.seq_len)).astype(np.float32)
        y = np.roll(x, -1, axis=1)
        for g in ex.grad_dict.values():
            g[:] = 0.0
        ex.forward(is_train=True, data=x, softmax_label=y)
        ex.backward()
        for i, name in enumerate(k for k in ex.arg_dict
                                 if k not in ("data", "softmax_label")):
            updater(i, ex.grad_dict[name], ex.arg_dict[name])
        probs = ex.outputs[0].asnumpy()
        idx = y.reshape(-1).astype(int)
        nll = -np.log(np.maximum(
            probs[np.arange(probs.shape[0]), idx], 1e-10)).mean()
        print("batch %d  nll %.4f" % (step, nll))


if __name__ == "__main__":
    main()
