// C++ training example over the header-only binding (the reference
// cpp-package's train loop role, e.g. cpp-package/example/mlp.cpp):
// bind from symbol JSON, overfit one batch with SGD-momentum, assert
// learning, all without touching Python source.
#include <cmath>
#include <cstring>
#include <cstdio>
#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

#include "mxnet_tpu_cpp/trainer.hpp"

static std::string slurp(const char *path) {
  std::ifstream f(path, std::ios::binary);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

static std::vector<float> slurp_floats(const char *path) {
  std::string raw = slurp(path);
  std::vector<float> out(raw.size() / sizeof(float));
  std::memcpy(out.data(), raw.data(), out.size() * sizeof(float));
  return out;
}

int main(int argc, char **argv) {
  if (argc != 7) {
    std::fprintf(stderr,
                 "usage: %s symbol.json x.f32 y.f32 batch dim nclass\n",
                 argv[0]);
    return 2;
  }
  const mx_uint batch = std::atoi(argv[4]);
  const mx_uint dim = std::atoi(argv[5]);
  const mx_uint nclass = std::atoi(argv[6]);
  auto x = slurp_floats(argv[2]);
  auto y = slurp_floats(argv[3]);

  try {
    mxnet_tpu_cpp::Trainer trainer(
        slurp(argv[1]),
        {{"data", {batch, dim}}, {"softmax_label", {batch}}},
        /*dev_type=*/1, /*dev_id=*/0, /*seed=*/7);

    float first = -1.f, last = -1.f;
    for (int step = 0; step < 30; ++step) {
      trainer.SetInput("data", x);
      trainer.SetInput("softmax_label", y);
      trainer.Forward(true);
      trainer.Backward();
      auto probs = trainer.GetOutput(0);
      float loss = 0.f;
      for (mx_uint i = 0; i < batch; ++i) {
        float p = probs[i * nclass + static_cast<mx_uint>(y[i])];
        loss += -std::log(p < 1e-10f ? 1e-10f : p);
      }
      loss /= static_cast<float>(batch);
      if (step == 0) first = loss;
      last = loss;
      trainer.SGDUpdate(0.1f, 0.9f, 0.f, 1.0f / batch);
    }
    if (!(last < 0.5f * first)) {
      std::fprintf(stderr, "did not learn: %.4f -> %.4f\n", first, last);
      return 1;
    }
    std::printf("cpp-train OK loss %.4f -> %.4f\n", first, last);
    return 0;
  } catch (const std::exception &e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
