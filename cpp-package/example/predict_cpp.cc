// C++ binding example: load a checkpoint, predict, print outputs.
//
// Role parity: cpp-package/example/inference in the reference.  Build
// (after `make -C src libmxtpu_predict.so`):
//
//   g++ -O2 -std=c++17 cpp-package/example/predict_cpp.cc \
//       -Icpp-package/include -Lsrc -lmxtpu_predict -Wl,-rpath,src \
//       -o predict_cpp
//   PYTHONPATH=. ./predict_cpp net-symbol.json net-0000.params \
//       x.f32 BATCH FEAT
//
// Prints "shape d0 d1 ..." then one output value per line (the same
// contract as tests/c_predict_test.c, so the python test harness can
// drive either binary).
#include <cstdio>
#include <fstream>
#include <iterator>
#include <string>
#include <vector>

#include <mxnet_tpu_cpp/predictor.hpp>

static std::string slurp(const char *path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw mxtpu::Error(std::string("cannot open ") + path);
  return std::string(std::istreambuf_iterator<char>(f),
                     std::istreambuf_iterator<char>());
}

int main(int argc, char **argv) {
  if (argc != 6) {
    std::fprintf(stderr,
                 "usage: %s symbol.json params x.f32 batch feat\n",
                 argv[0]);
    return 2;
  }
  try {
    const std::string symbol = slurp(argv[1]);
    const std::string params = slurp(argv[2]);
    const std::string xbytes = slurp(argv[3]);
    const mx_uint batch = static_cast<mx_uint>(std::stoul(argv[4]));
    const mx_uint feat = static_cast<mx_uint>(std::stoul(argv[5]));

    mxtpu::Predictor pred(symbol, params,
                          {{"data", {batch, feat}}}, mxtpu::kCPU);
    pred.SetInput("data",
                  reinterpret_cast<const float *>(xbytes.data()),
                  xbytes.size() / sizeof(float));
    pred.Forward();

    const auto shape = pred.GetOutputShape(0);
    std::printf("shape");
    for (mx_uint d : shape) std::printf(" %u", d);
    std::printf("\n");
    for (float v : pred.GetOutput(0)) std::printf("%.6f\n", v);
    return 0;
  } catch (const std::exception &e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
