// Header-only C++ TRAINING binding over the C train ABI
// (include/mxnet_tpu/c_train_api.h) — the role of the reference
// cpp-package's Executor + Optimizer training loop
// (cpp-package/include/mxnet-cpp/executor.h): a non-Python application
// links libmxtpu_train.so and trains through this RAII wrapper.
#ifndef MXNET_TPU_CPP_TRAINER_HPP_
#define MXNET_TPU_CPP_TRAINER_HPP_

#include <map>
#include <stdexcept>
#include <string>
#include <vector>

#include "../../../include/mxnet_tpu/c_train_api.h"

namespace mxnet_tpu_cpp {

class Trainer {
 public:
  // input_shapes: name -> shape for every data/label input
  Trainer(const std::string &symbol_json,
          const std::map<std::string, std::vector<mx_uint>> &input_shapes,
          int dev_type = 1, int dev_id = 0, int seed = 0) {
    std::vector<const char *> keys;
    std::vector<mx_uint> indptr{0};
    std::vector<mx_uint> data;
    for (const auto &kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      data.insert(data.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<mx_uint>(data.size()));
    }
    Check(MXTrainCreate(symbol_json.c_str(), dev_type, dev_id, seed,
                        static_cast<mx_uint>(keys.size()), keys.data(),
                        indptr.data(), data.data(), &handle_));
  }

  ~Trainer() {
    if (handle_) MXTrainFree(handle_);
  }
  Trainer(const Trainer &) = delete;
  Trainer &operator=(const Trainer &) = delete;

  void SetInput(const std::string &name, const std::vector<float> &v) {
    Check(MXTrainSetInput(handle_, name.c_str(), v.data(),
                          static_cast<mx_uint>(v.size())));
  }

  void Forward(bool is_train) {
    Check(MXTrainForward(handle_, is_train ? 1 : 0));
  }

  void Backward() { Check(MXTrainBackward(handle_)); }

  // rescale_grad: loss heads emit per-example gradient sums; pass
  // 1/batch for averaged updates (the Module default)
  void SGDUpdate(float lr, float momentum = 0.f, float wd = 0.f,
                 float rescale_grad = 1.f) {
    Check(MXTrainSGDUpdate(handle_, lr, momentum, wd, rescale_grad));
  }

  std::vector<mx_uint> OutputShape(mx_uint index) {
    mx_uint *shape = nullptr;
    mx_uint ndim = 0;
    Check(MXTrainGetOutputShape(handle_, index, &shape, &ndim));
    return std::vector<mx_uint>(shape, shape + ndim);
  }

  std::vector<float> GetOutput(mx_uint index) {
    auto shape = OutputShape(index);
    mx_uint n = 1;
    for (mx_uint d : shape) n *= d;
    std::vector<float> out(n);
    Check(MXTrainGetOutput(handle_, index, out.data(), n));
    return out;
  }

  // kind: "arg" (weights) or "grad" (their gradients)
  std::vector<float> GetArray(const std::string &kind,
                              const std::string &name, mx_uint n) {
    std::vector<float> out(n);
    Check(MXTrainGetArray(handle_, kind.c_str(), name.c_str(),
                          out.data(), n));
    return out;
  }

 private:
  static void Check(int rc) {
    if (rc != 0) throw std::runtime_error(MXTrainGetLastError());
  }

  TrainHandle handle_ = nullptr;
};

}  // namespace mxnet_tpu_cpp

#endif  // MXNET_TPU_CPP_TRAINER_HPP_
