/*
 * C++ inference binding for mxnet_tpu — header-only wrapper over the
 * native prediction ABI (libmxtpu_predict.so).
 *
 * Role parity: the reference's `cpp-package/` (MXNet C++ API) at its
 * deployment scope.  The reference cpp-package also wraps training
 * (~150 C API functions); this framework is python-first for training
 * (PARITY.md), so the C++ surface covers what C++ applications ship:
 * load a checkpoint, feed inputs, run, read outputs — with RAII
 * handles and exceptions instead of manual MXPred* calls.
 *
 *   #include <mxnet_tpu_cpp/predictor.hpp>
 *   mxtpu::Predictor pred(symbol_json, params_blob,
 *                         {{"data", {1, 3, 224, 224}}}, mxtpu::kTPU);
 *   pred.SetInput("data", img.data(), img.size());
 *   pred.Forward();
 *   std::vector<float> probs = pred.GetOutput(0);
 *
 * Link: -lmxtpu_predict (see src/Makefile; the library embeds CPython,
 * so run with MXTPU_PYTHONHOME/PYTHONPATH as tests/test_c_predict.py
 * demonstrates).
 */
#ifndef MXNET_TPU_CPP_PREDICTOR_HPP_
#define MXNET_TPU_CPP_PREDICTOR_HPP_

#include <cstddef>
#include <functional>
#include <numeric>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "../../../include/mxnet_tpu/c_predict_api.h"

namespace mxtpu {

enum DeviceType { kCPU = 1, kGPU = 2, kTPU = 3 };

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string &what) : std::runtime_error(what) {}
};

inline void Check(int rc, const char *op) {
  if (rc != 0) {
    throw Error(std::string(op) + ": " + MXGetLastError());
  }
}

/* One named input and its shape. */
struct InputDesc {
  std::string name;
  std::vector<mx_uint> shape;
};

class Predictor {
 public:
  /* symbol_json: contents of *-symbol.json; params: raw bytes of the
   * *.params file; inputs: name -> shape. */
  Predictor(const std::string &symbol_json, const std::string &params,
            const std::vector<InputDesc> &inputs,
            DeviceType dev = kCPU, int dev_id = 0) {
    std::vector<const char *> keys;
    std::vector<mx_uint> indptr{0};
    std::vector<mx_uint> shapes;
    for (const auto &in : inputs) {
      keys.push_back(in.name.c_str());
      for (mx_uint d : in.shape) shapes.push_back(d);
      indptr.push_back(static_cast<mx_uint>(shapes.size()));
    }
    Check(MXPredCreate(symbol_json.c_str(), params.data(),
                       static_cast<int>(params.size()),
                       static_cast<int>(dev), dev_id,
                       static_cast<mx_uint>(inputs.size()), keys.data(),
                       indptr.data(), shapes.data(), &handle_),
          "MXPredCreate");
  }

  Predictor(const Predictor &) = delete;
  Predictor &operator=(const Predictor &) = delete;
  Predictor(Predictor &&o) noexcept : handle_(o.handle_) {
    o.handle_ = nullptr;
  }
  Predictor &operator=(Predictor &&o) noexcept {
    std::swap(handle_, o.handle_);
    return *this;
  }
  ~Predictor() {
    if (handle_) MXPredFree(handle_);
  }

  void SetInput(const std::string &name, const float *data,
                std::size_t size) {
    Check(MXPredSetInput(handle_, name.c_str(), data,
                         static_cast<mx_uint>(size)),
          "MXPredSetInput");
  }

  void Forward() { Check(MXPredForward(handle_), "MXPredForward"); }

  std::vector<mx_uint> GetOutputShape(mx_uint index = 0) {
    mx_uint *shape = nullptr;
    mx_uint ndim = 0;
    Check(MXPredGetOutputShape(handle_, index, &shape, &ndim),
          "MXPredGetOutputShape");
    return std::vector<mx_uint>(shape, shape + ndim);
  }

  std::vector<float> GetOutput(mx_uint index = 0) {
    auto shape = GetOutputShape(index);
    std::size_t n = std::accumulate(shape.begin(), shape.end(),
                                    std::size_t(1),
                                    std::multiplies<std::size_t>());
    std::vector<float> out(n);
    Check(MXPredGetOutput(handle_, index, out.data(),
                          static_cast<mx_uint>(n)),
          "MXPredGetOutput");
    return out;
  }

  /* New handle bound at new input shapes; this predictor stays usable
   * at its original shapes (weights shared — reference MXPredReshape
   * semantics). */
  Predictor Reshaped(const std::vector<InputDesc> &inputs) {
    std::vector<const char *> keys;
    std::vector<mx_uint> indptr{0};
    std::vector<mx_uint> shapes;
    for (const auto &in : inputs) {
      keys.push_back(in.name.c_str());
      for (mx_uint d : in.shape) shapes.push_back(d);
      indptr.push_back(static_cast<mx_uint>(shapes.size()));
    }
    PredictorHandle fresh = nullptr;
    Check(MXPredReshape(handle_,
                        static_cast<mx_uint>(inputs.size()), keys.data(),
                        indptr.data(), shapes.data(), &fresh),
          "MXPredReshape");
    return Predictor(fresh);
  }

 private:
  explicit Predictor(PredictorHandle h) : handle_(h) {}
  PredictorHandle handle_ = nullptr;
};

}  // namespace mxtpu

#endif  // MXNET_TPU_CPP_PREDICTOR_HPP_
