// Shared embedded-CPython plumbing for the C ABI shims
// (c_predict_api.cc and c_train_api.cc build into separate .so files;
// each gets its own copy of these inline definitions, but the source
// of truth is single so interpreter setup and error normalization
// cannot drift between the libraries).
#ifndef MXNET_TPU_SRC_PY_EMBED_COMMON_H_
#define MXNET_TPU_SRC_PY_EMBED_COMMON_H_

#include <Python.h>

#include <mutex>
#include <string>

namespace mxtpu_embed {

inline thread_local std::string g_last_error;

inline void EnsurePython() {
  static std::once_flag once;
  std::call_once(once, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the GIL acquired by Py_Initialize so PyGILState_Ensure
      // works from any thread (including this one)
      PyEval_SaveThread();
    }
  });
}

class Gil {
 public:
  Gil() { state_ = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// owns one reference
struct Ref {
  PyObject *p;
  explicit Ref(PyObject *o) : p(o) {}
  ~Ref() { Py_XDECREF(p); }
  explicit operator bool() const { return p != nullptr; }
};

inline void SetPyError() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  PyObject *s = value ? PyObject_Str(value) : nullptr;
  g_last_error = (s && PyUnicode_Check(s)) ? PyUnicode_AsUTF8(s)
                                           : "unknown python error";
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

inline const char *DevName(int dev_type) {
  switch (dev_type) {
    case 2: return "gpu";
    case 3: return "tpu";
    default: return "cpu";
  }
}

}  // namespace mxtpu_embed

#endif  // MXNET_TPU_SRC_PY_EMBED_COMMON_H_
