// C prediction ABI (include/mxnet_tpu/c_predict_api.h) — embedded-Python
// implementation.
//
// Role parity: src/c_api/c_predict_api.cc in the reference.  The
// reference's predict library is a thin C shim over its C++ executor;
// here the executor IS jax/XLA reached through python, so the native
// deployment artifact embeds CPython once per process and drives
// mxnet_tpu.predictor.Predictor.  Every entry point follows the
// reference's API_BEGIN/API_END error convention: catch everything,
// stash the message for MXGetLastError, return -1.
//
// Build: `make libmxtpu_predict.so` (links libpython); run with
// MXTPU_PYTHONHOME/PYTHONPATH set so the embedded interpreter finds the
// mxnet_tpu package (see tests/test_c_predict.py for the exact flow).

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "../include/mxnet_tpu/c_predict_api.h"
#include "py_embed_common.h"

namespace {

using mxtpu_embed::DevName;
using mxtpu_embed::EnsurePython;
using mxtpu_embed::Gil;
using mxtpu_embed::SetPyError;
using mxtpu_embed::g_last_error;

struct PredRecord {
  PyObject *predictor = nullptr;          // mxnet_tpu.predictor.Predictor
  std::vector<std::string> input_keys;
  std::vector<mx_uint> out_shape;         // scratch for GetOutputShape
};

// shapes dict {key: (d0, d1, ...)} from the indptr-packed C arrays
PyObject *BuildShapesDict(mx_uint num_input_nodes, const char **input_keys,
                          const mx_uint *input_shape_indptr,
                          const mx_uint *input_shape_data) {
  PyObject *shapes = PyDict_New();
  if (!shapes) return nullptr;
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    PyObject *shape = PyTuple_New(
        input_shape_indptr[i + 1] - input_shape_indptr[i]);
    if (!shape) { Py_DECREF(shapes); return nullptr; }
    for (mx_uint j = input_shape_indptr[i], k = 0;
         j < input_shape_indptr[i + 1]; ++j, ++k) {
      PyTuple_SET_ITEM(shape, k,
                       PyLong_FromUnsignedLong(input_shape_data[j]));
    }
    if (PyDict_SetItemString(shapes, input_keys[i], shape) != 0) {
      Py_DECREF(shape);
      Py_DECREF(shapes);
      return nullptr;
    }
    Py_DECREF(shape);
  }
  return shapes;
}

}  // namespace

extern "C" {

const char *MXGetLastError() { return g_last_error.c_str(); }

int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data, PredictorHandle *out) {
  EnsurePython();
  Gil gil;
  try {
    PyObject *mod = PyImport_ImportModule("mxnet_tpu");
    if (!mod) { SetPyError(); return -1; }
    PyObject *ctx_mod = PyObject_GetAttrString(mod, "context");
    if (!ctx_mod) { SetPyError(); Py_DECREF(mod); return -1; }
    PyObject *ctx = PyObject_CallMethod(ctx_mod, "Context", "si",
                                        DevName(dev_type), dev_id);
    if (!ctx) {
      SetPyError();
      Py_DECREF(ctx_mod);
      Py_DECREF(mod);
      return -1;
    }

    PyObject *shapes = BuildShapesDict(num_input_nodes, input_keys,
                                       input_shape_indptr,
                                       input_shape_data);
    if (!shapes) {
      SetPyError();
      Py_DECREF(ctx);
      Py_DECREF(ctx_mod);
      Py_DECREF(mod);
      return -1;
    }
    auto rec = new PredRecord();
    for (mx_uint i = 0; i < num_input_nodes; ++i) {
      rec->input_keys.emplace_back(input_keys[i]);
    }

    PyObject *pred_mod = PyObject_GetAttrString(mod, "predictor");
    PyObject *cls = pred_mod ? PyObject_GetAttrString(pred_mod,
                                                      "Predictor")
                             : nullptr;
    PyObject *params = PyBytes_FromStringAndSize(
        static_cast<const char *>(param_bytes), param_size);
    PyObject *json = PyUnicode_FromString(symbol_json_str);
    if (!cls || !params || !json) {
      SetPyError();
      Py_XDECREF(json);
      Py_XDECREF(params);
      Py_XDECREF(cls);
      Py_XDECREF(pred_mod);
      Py_DECREF(shapes);
      Py_DECREF(ctx);
      Py_DECREF(ctx_mod);
      Py_DECREF(mod);
      delete rec;
      return -1;
    }
    PyObject *args = PyTuple_Pack(3, json, params, shapes);
    PyObject *kw = PyDict_New();
    PyDict_SetItemString(kw, "ctx", ctx);
    PyObject *pred = PyObject_Call(cls, args, kw);
    Py_DECREF(args);
    Py_DECREF(kw);
    Py_DECREF(json);
    Py_DECREF(params);
    Py_DECREF(shapes);
    Py_DECREF(cls);
    Py_DECREF(pred_mod);
    Py_DECREF(ctx);
    Py_DECREF(ctx_mod);
    Py_DECREF(mod);
    if (!pred) { SetPyError(); delete rec; return -1; }
    rec->predictor = pred;
    *out = rec;
    return 0;
  } catch (const std::exception &e) {
    g_last_error = e.what();
    return -1;
  }
}

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const mx_float *data, mx_uint size) {
  Gil gil;
  auto rec = static_cast<PredRecord *>(handle);
  // hand the floats to python as a flat list-free bytes + frombuffer
  PyObject *np = PyImport_ImportModule("numpy");
  if (!np) { SetPyError(); return -1; }
  PyObject *bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data), size * sizeof(mx_float));
  if (!bytes) { SetPyError(); Py_DECREF(np); return -1; }
  PyObject *flat = PyObject_CallMethod(np, "frombuffer", "Os", bytes,
                                       "float32");
  Py_DECREF(bytes);
  Py_DECREF(np);
  if (!flat) { SetPyError(); return -1; }
  // Predictor.set_input reshapes via the bound arg's shape: pass the
  // flat array reshaped python-side
  PyObject *arr = PyObject_GetAttrString(rec->predictor, "_executor");
  PyObject *arg_dict = arr ? PyObject_GetAttrString(arr, "arg_dict")
                           : nullptr;
  PyObject *target = arg_dict ? PyMapping_GetItemString(arg_dict, key)
                              : nullptr;
  PyObject *shape = target ? PyObject_GetAttrString(target, "shape")
                           : nullptr;
  PyObject *shaped = shape ? PyObject_CallMethod(flat, "reshape", "O",
                                                 shape)
                           : nullptr;
  PyObject *r = shaped ? PyObject_CallMethod(rec->predictor, "set_input",
                                             "sO", key, shaped)
                       : nullptr;
  Py_XDECREF(r);
  Py_XDECREF(shaped);
  Py_XDECREF(shape);
  Py_XDECREF(target);
  Py_XDECREF(arg_dict);
  Py_XDECREF(arr);
  Py_DECREF(flat);
  if (!r) { SetPyError(); return -1; }
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  Gil gil;
  auto rec = static_cast<PredRecord *>(handle);
  PyObject *r = PyObject_CallMethod(rec->predictor, "forward", nullptr);
  if (!r) { SetPyError(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim) {
  Gil gil;
  auto rec = static_cast<PredRecord *>(handle);
  PyObject *out = PyObject_CallMethod(rec->predictor, "get_output", "I",
                                      index);
  if (!out) { SetPyError(); return -1; }
  PyObject *shape = PyObject_GetAttrString(out, "shape");
  Py_ssize_t nd = PyTuple_Size(shape);
  rec->out_shape.resize(nd);
  for (Py_ssize_t i = 0; i < nd; ++i) {
    rec->out_shape[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shape, i)));
  }
  Py_DECREF(shape);
  Py_DECREF(out);
  *shape_data = rec->out_shape.data();
  *shape_ndim = static_cast<mx_uint>(nd);
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float *data,
                    mx_uint size) {
  Gil gil;
  auto rec = static_cast<PredRecord *>(handle);
  PyObject *out = PyObject_CallMethod(rec->predictor, "get_output", "I",
                                      index);
  if (!out) { SetPyError(); return -1; }
  // np.ascontiguousarray(out, float32).tobytes()
  PyObject *np = PyImport_ImportModule("numpy");
  PyObject *contig = PyObject_CallMethod(np, "ascontiguousarray", "Os",
                                         out, "float32");
  Py_DECREF(np);
  Py_DECREF(out);
  if (!contig) { SetPyError(); return -1; }
  PyObject *bytes = PyObject_CallMethod(contig, "tobytes", nullptr);
  Py_DECREF(contig);
  if (!bytes) { SetPyError(); return -1; }
  Py_ssize_t len = PyBytes_Size(bytes);
  if (static_cast<mx_uint>(len / sizeof(mx_float)) < size) {
    g_last_error = "MXPredGetOutput: requested size exceeds output";
    Py_DECREF(bytes);
    return -1;
  }
  std::memcpy(data, PyBytes_AsString(bytes), size * sizeof(mx_float));
  Py_DECREF(bytes);
  return 0;
}

int MXPredReshape(PredictorHandle handle, mx_uint num_input_nodes,
                  const char **input_keys,
                  const mx_uint *input_shape_indptr,
                  const mx_uint *input_shape_data, PredictorHandle *out) {
  Gil gil;
  auto rec = static_cast<PredRecord *>(handle);
  PyObject *shapes = BuildShapesDict(num_input_nodes, input_keys,
                                     input_shape_indptr,
                                     input_shape_data);
  if (!shapes) { SetPyError(); return -1; }
  // reference semantics: the caller owns a NEW handle backed by a new
  // executor; the old handle stays usable at its original shapes (the
  // weights are shared).  Predictor.reshaped returns that new object.
  PyObject *fresh_pred = PyObject_CallMethod(rec->predictor, "reshaped",
                                             "O", shapes);
  Py_DECREF(shapes);
  if (!fresh_pred) { SetPyError(); return -1; }
  auto fresh = new PredRecord();
  fresh->predictor = fresh_pred;
  fresh->input_keys = rec->input_keys;
  *out = fresh;
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  Gil gil;
  auto rec = static_cast<PredRecord *>(handle);
  Py_XDECREF(rec->predictor);
  delete rec;
  return 0;
}

}  // extern "C"
