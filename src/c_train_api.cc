// C TRAINING ABI slice (include/mxnet_tpu/c_train_api.h) —
// embedded-Python implementation.
//
// Role parity: the MXSymbol*/MXExecutor* training subset of the
// reference's src/c_api/c_api_executor.cc, as consumed by its
// cpp-package (cpp-package/include/mxnet-cpp/executor.h Forward/
// Backward + optimizer Update).  Architecture matches
// src/c_predict_api.cc: one embedded CPython per process drives
// mxnet_tpu.c_train.TrainSession; error convention: catch everything,
// stash for MXTrainGetLastError, return -1.
//
// Build: `make libmxtpu_train.so` (src/Makefile); run with PYTHONPATH
// reaching the mxnet_tpu package (tests/test_c_train.py shows the
// exact flow from C).

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "../include/mxnet_tpu/c_train_api.h"
#include "py_embed_common.h"

namespace {

using mxtpu_embed::DevName;
using mxtpu_embed::EnsurePython;
using mxtpu_embed::Gil;
using mxtpu_embed::Ref;
using mxtpu_embed::SetPyError;
using mxtpu_embed::g_last_error;

struct TrainRecord {
  PyObject *session = nullptr;       // mxnet_tpu.c_train.TrainSession
  std::vector<mx_uint> out_shape;    // scratch for GetOutputShape
};

// numpy float32 view of caller floats (copies via frombuffer)
PyObject *FloatsToNumpy(const mx_float *data, mx_uint size) {
  Ref np(PyImport_ImportModule("numpy"));
  if (!np) return nullptr;
  Ref bytes(PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(size) * sizeof(mx_float)));
  if (!bytes) return nullptr;
  return PyObject_CallMethod(np.p, "frombuffer", "Os", bytes.p,
                             "float32");
}

// copy a float32-contiguous numpy array out to the caller's buffer;
// returns copied element count or -1 with the error message set
long CopyNumpyOut(PyObject *arr, mx_float *data, mx_uint size) {
  Ref bytes(PyObject_CallMethod(arr, "tobytes", nullptr));
  if (!bytes) { SetPyError(); return -1; }
  char *buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(bytes.p, &buf, &n) != 0) {
    SetPyError();
    return -1;
  }
  const size_t elems = static_cast<size_t>(n) / sizeof(mx_float);
  if (elems > size) {
    g_last_error = "destination buffer too small";
    return -1;
  }
  std::memcpy(data, buf, static_cast<size_t>(n));
  return static_cast<long>(elems);
}

}  // namespace

// Every handle-taking entry point honours the 0/-1 error contract on a
// NULL handle and guarantees the interpreter exists before taking the
// GIL (reference c_api API_BEGIN role).
#define MXTPU_GUARD_HANDLE(h)                                          \
  do {                                                                 \
    if ((h) == nullptr) {                                              \
      g_last_error =                                                   \
          "null TrainHandle (MXTrainCreate must succeed first)";       \
      return -1;                                                       \
    }                                                                  \
    EnsurePython();                                                    \
  } while (0)

extern "C" {

const char *MXTrainGetLastError() { return g_last_error.c_str(); }

int MXTrainCreate(const char *symbol_json_str, int dev_type, int dev_id,
                  int seed, mx_uint num_input_nodes,
                  const char **input_keys,
                  const mx_uint *input_shape_indptr,
                  const mx_uint *input_shape_data, TrainHandle *out) {
  EnsurePython();
  Gil gil;
  try {
    Ref mod(PyImport_ImportModule("mxnet_tpu.c_train"));
    if (!mod) { SetPyError(); return -1; }
    Ref cls(PyObject_GetAttrString(mod.p, "TrainSession"));
    if (!cls) { SetPyError(); return -1; }

    Ref shapes(PyDict_New());
    if (!shapes) { SetPyError(); return -1; }
    for (mx_uint i = 0; i < num_input_nodes; ++i) {
      const mx_uint lo = input_shape_indptr[i];
      const mx_uint hi = input_shape_indptr[i + 1];
      Ref shape(PyTuple_New(hi - lo));
      if (!shape) { SetPyError(); return -1; }
      for (mx_uint j = lo; j < hi; ++j) {
        PyTuple_SET_ITEM(shape.p, j - lo,
                         PyLong_FromUnsignedLong(input_shape_data[j]));
      }
      if (PyDict_SetItemString(shapes.p, input_keys[i], shape.p) != 0) {
        SetPyError();
        return -1;
      }
    }

    Ref session(PyObject_CallFunction(cls.p, "sOsii", symbol_json_str,
                                      shapes.p, DevName(dev_type),
                                      dev_id, seed));
    if (!session) { SetPyError(); return -1; }
    auto rec = new TrainRecord();
    rec->session = session.p;
    Py_INCREF(session.p);
    *out = rec;
    return 0;
  } catch (const std::exception &e) {
    g_last_error = e.what();
    return -1;
  }
}

int MXTrainSetInput(TrainHandle handle, const char *key,
                    const mx_float *data, mx_uint size) {
  MXTPU_GUARD_HANDLE(handle);
  Gil gil;
  auto rec = static_cast<TrainRecord *>(handle);
  Ref flat(FloatsToNumpy(data, size));
  if (!flat) { SetPyError(); return -1; }
  Ref r(PyObject_CallMethod(rec->session, "set_input", "sO", key,
                            flat.p));
  if (!r) { SetPyError(); return -1; }
  return 0;
}

int MXTrainForward(TrainHandle handle, int is_train) {
  MXTPU_GUARD_HANDLE(handle);
  Gil gil;
  auto rec = static_cast<TrainRecord *>(handle);
  Ref r(PyObject_CallMethod(rec->session, "forward", "i", is_train));
  if (!r) { SetPyError(); return -1; }
  return 0;
}

int MXTrainBackward(TrainHandle handle) {
  MXTPU_GUARD_HANDLE(handle);
  Gil gil;
  auto rec = static_cast<TrainRecord *>(handle);
  Ref r(PyObject_CallMethod(rec->session, "backward", nullptr));
  if (!r) { SetPyError(); return -1; }
  return 0;
}

int MXTrainSGDUpdate(TrainHandle handle, mx_float lr, mx_float momentum,
                     mx_float wd, mx_float rescale_grad) {
  MXTPU_GUARD_HANDLE(handle);
  Gil gil;
  auto rec = static_cast<TrainRecord *>(handle);
  Ref r(PyObject_CallMethod(rec->session, "sgd_update", "ffff",
                            static_cast<double>(lr),
                            static_cast<double>(momentum),
                            static_cast<double>(wd),
                            static_cast<double>(rescale_grad)));
  if (!r) { SetPyError(); return -1; }
  return 0;
}

int MXTrainGetOutputCount(TrainHandle handle, mx_uint *out) {
  MXTPU_GUARD_HANDLE(handle);
  Gil gil;
  auto rec = static_cast<TrainRecord *>(handle);
  Ref r(PyObject_CallMethod(rec->session, "num_outputs", nullptr));
  if (!r) { SetPyError(); return -1; }
  *out = static_cast<mx_uint>(PyLong_AsUnsignedLong(r.p));
  return 0;
}

int MXTrainGetOutputShape(TrainHandle handle, mx_uint index,
                          mx_uint **shape_data, mx_uint *shape_ndim) {
  MXTPU_GUARD_HANDLE(handle);
  Gil gil;
  auto rec = static_cast<TrainRecord *>(handle);
  Ref shape(PyObject_CallMethod(rec->session, "get_output_shape", "I",
                                index));
  if (!shape) { SetPyError(); return -1; }
  const Py_ssize_t nd = PyTuple_Size(shape.p);
  rec->out_shape.clear();
  for (Py_ssize_t i = 0; i < nd; ++i) {
    rec->out_shape.push_back(static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shape.p, i))));
  }
  *shape_data = rec->out_shape.data();
  *shape_ndim = static_cast<mx_uint>(nd);
  return 0;
}

int MXTrainGetOutput(TrainHandle handle, mx_uint index, mx_float *data,
                     mx_uint size) {
  MXTPU_GUARD_HANDLE(handle);
  Gil gil;
  auto rec = static_cast<TrainRecord *>(handle);
  Ref arr(PyObject_CallMethod(rec->session, "get_output", "I", index));
  if (!arr) { SetPyError(); return -1; }
  return CopyNumpyOut(arr.p, data, size) < 0 ? -1 : 0;
}

int MXTrainGetArray(TrainHandle handle, const char *kind,
                    const char *name, mx_float *data, mx_uint size) {
  MXTPU_GUARD_HANDLE(handle);
  Gil gil;
  auto rec = static_cast<TrainRecord *>(handle);
  Ref arr(PyObject_CallMethod(rec->session, "get_array", "ss", name,
                              kind));
  if (!arr) { SetPyError(); return -1; }
  return CopyNumpyOut(arr.p, data, size) < 0 ? -1 : 0;
}

int MXTrainFree(TrainHandle handle) {
  if (handle == nullptr) return 0;  // free(NULL) semantics
  EnsurePython();
  Gil gil;
  auto rec = static_cast<TrainRecord *>(handle);
  Py_XDECREF(rec->session);
  delete rec;
  return 0;
}

}  // extern "C"
