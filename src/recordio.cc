// Native RecordIO reader with threaded prefetch.
//
// Reference: dmlc-core recordio + src/io/iter_prefetcher.h — the reference's
// data pipeline is a C++ threaded reader feeding a double-buffered queue
// (SURVEY §2.1 Data IO row).  This is the TPU build's native equivalent:
// a mmap-free buffered reader parsing the same on-disk format
// ([uint32 magic][uint32 lrecord][payload][pad4]) plus a background
// prefetch thread with a bounded record queue, exposed over a C ABI
// consumed via ctypes (mxnet_tpu/io_native.py).
//
// Build: make -C src  (produces libmxtpu_io.so)

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "recordio_format.h"

namespace {

struct Record {
  std::vector<uint8_t> data;
};

// Bounded MPSC queue — the role of dmlc::ConcurrentBlockingQueue.
class RecordQueue {
 public:
  explicit RecordQueue(size_t cap) : cap_(cap), done_(false) {}

  void Push(Record&& r) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return q_.size() < cap_ || done_; });
    if (done_) return;
    q_.emplace_back(std::move(r));
    not_empty_.notify_one();
  }

  bool Pop(Record* out) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return !q_.empty() || done_; });
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return true;
  }

  void Finish() {
    std::lock_guard<std::mutex> lk(mu_);
    done_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

 private:
  size_t cap_;
  bool done_;
  std::deque<Record> q_;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
};

class RecordIOReader {
 public:
  RecordIOReader(const char* path, size_t queue_cap)
      : path_(path), queue_(queue_cap ? queue_cap : 64) {
    f_ = std::fopen(path, "rb");
    if (f_ != nullptr) {
      worker_ = std::thread([this] { this->Run(); });
    }
  }

  ~RecordIOReader() {
    queue_.Finish();
    if (worker_.joinable()) worker_.join();
    if (f_) std::fclose(f_);
  }

  bool ok() const { return f_ != nullptr; }

  // Returns record size, 0 at EOF, -1 on error.  Copies up to buf_size
  // bytes into buf when buf != nullptr.
  int64_t Next(uint8_t* buf, int64_t buf_size) {
    Record r;
    if (!queue_.Pop(&r)) return 0;
    int64_t n = static_cast<int64_t>(r.data.size());
    if (buf != nullptr) {
      std::memcpy(buf, r.data.data(), std::min(n, buf_size));
    } else {
      // peek mode: stash so the follow-up call with a buffer gets it
      pending_ = std::move(r);
      has_pending_ = true;
    }
    return n;
  }

  int64_t TakePending(uint8_t* buf, int64_t buf_size) {
    if (!has_pending_) return -1;
    int64_t n = static_cast<int64_t>(pending_.data.size());
    std::memcpy(buf, pending_.data.data(), std::min(n, buf_size));
    has_pending_ = false;
    return n;
  }

  // Size of a record already stashed by a peek, or -1 if none.
  int64_t PendingSize() const {
    return has_pending_ ? static_cast<int64_t>(pending_.data.size()) : -1;
  }

 private:
  void Run() {
    while (true) {
      Record r;
      if (!mxtpu::ReadRecRecord(f_, &r.data)) break;
      queue_.Push(std::move(r));
    }
    queue_.Finish();
  }

  std::string path_;
  std::FILE* f_;
  RecordQueue queue_;
  std::thread worker_;
  Record pending_;
  bool has_pending_ = false;
};

}  // namespace

extern "C" {

void* MXTPURecordIOReaderCreate(const char* path, int64_t queue_cap) {
  auto* r = new RecordIOReader(path, static_cast<size_t>(queue_cap));
  if (!r->ok()) {
    delete r;
    return nullptr;
  }
  return r;
}

void MXTPURecordIOReaderFree(void* handle) {
  delete static_cast<RecordIOReader*>(handle);
}

// Two-phase read: call with buf=nullptr to get the size (record is held),
// then with a buffer to copy it out.  Single-phase works too when the
// caller passes a max-size buffer.
int64_t MXTPURecordIOReaderNext(void* handle, uint8_t* buf,
                                int64_t buf_size) {
  auto* r = static_cast<RecordIOReader*>(handle);
  if (buf == nullptr) return r->Next(nullptr, 0);
  int64_t n = r->TakePending(buf, buf_size);
  if (n >= 0) return n;
  return r->Next(buf, buf_size);
}

// Batch float parse: interpret each record as IRHeader + raw float32
// payload, filling label/data batch arrays host-side in one call
// (the hot path the python loop would otherwise do per record).
int64_t MXTPURecordIOReadFloatBatch(void* handle, float* labels,
                                    float* data, int64_t record_floats,
                                    int64_t batch) {
  auto* r = static_cast<RecordIOReader*>(handle);
  std::vector<uint8_t> buf(24 + record_floats * 4);
  int64_t i = 0;
  while (i < batch) {
    // two-phase: peek the size so flag>0 extra-label records never
    // overflow or truncate regardless of label count; honor a record a
    // caller already stashed via a bare-peek MXTPURecordIOReaderNext
    int64_t n = r->PendingSize();
    if (n < 0) n = r->Next(nullptr, 0);
    if (n <= 0) break;
    if (n > static_cast<int64_t>(buf.size())) buf.resize(n);
    n = r->TakePending(buf.data(), static_cast<int64_t>(buf.size()));
    if (n <= 0) break;
    // IRHeader: uint32 flag, float label, uint64 id, uint64 id2 (24 B).
    // flag > 0 means `flag` label floats follow the header before the
    // data payload (image_recordio.h:68-73 layout).
    if (n < 24) continue;  // truncated / non-IRHeader record: skip
    int64_t avail = std::min<int64_t>(n, static_cast<int64_t>(buf.size()));
    uint32_t flag;
    std::memcpy(&flag, buf.data(), 4);
    int64_t data_off = 24;
    if (flag > 0) {
      data_off = 24 + static_cast<int64_t>(flag) * 4;
      if (data_off > avail) continue;  // header claims more labels than bytes
      std::memcpy(&labels[i], buf.data() + 24, 4);
    } else {
      std::memcpy(&labels[i], buf.data() + 4, 4);
    }
    int64_t nfloats =
        std::min<int64_t>(record_floats,
                          std::max<int64_t>(0, (avail - data_off) / 4));
    if (nfloats > 0) {
      std::memcpy(data + i * record_floats, buf.data() + data_off,
                  nfloats * 4);
    }
    ++i;
  }
  return i;
}

}  // extern "C"
