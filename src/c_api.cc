// Flat C ABI (include/mxnet_tpu/c_api.h): the MXNDArray*/MXSymbol*
// subsets of the reference include/mxnet/c_api.h, implemented over the
// embedded interpreter like the predict/train ABIs (architecture:
// src/c_predict_api.cc).  Handles own references to REAL framework
// objects (mxnet_tpu NDArray / Symbol via mxnet_tpu/c_api.py), so the
// ABI is a boundary onto the framework, not a bespoke session object:
// files written from C load in python and vice versa.
//
// Reference counterparts: src/c_api/c_api.cc:1-847 (ndarray+symbol
// sections); error convention API_BEGIN/API_END -> guard macros here.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "../include/mxnet_tpu/c_api.h"
#include "py_embed_common.h"

namespace {

using mxtpu_embed::EnsurePython;
using mxtpu_embed::Gil;
using mxtpu_embed::Ref;
using mxtpu_embed::SetPyError;
using mxtpu_embed::g_last_error;

// a handle owns one python object plus scratch buffers backing the
// const char*/mx_uint* returns made from it (freed with the handle)
struct Handle {
  PyObject *obj = nullptr;
  std::vector<std::string> str_store;
  std::vector<const char *> str_ptrs;
  std::vector<mx_uint> shape_store;
  std::string text;
  explicit Handle(PyObject *o) : obj(o) {}  // steals the reference
  ~Handle() { Py_XDECREF(obj); }
};

// thread-local scratch for returns not tied to one handle (load lists,
// creator names) — reference keeps these in its per-thread ret store
struct Scratch {
  std::vector<std::string> names;
  std::vector<const char *> name_ptrs;
  std::vector<NDArrayHandle> handles;
  std::vector<AtomicSymbolCreator> creators;
};
inline Scratch &TlsScratch() {
  static thread_local Scratch s;
  return s;
}

// cached op-name list; creator == index+1 (0 stays invalid).  Filled
// exactly once (see FillOpNames): the GIL alone is NOT a critical
// section here, because the CallDriver that produces the list runs
// Python code that can release the GIL mid-call — two threads in
// MXSymbolListAtomicSymbolCreators could interleave and double-append,
// corrupting the creator-index mapping.  Once non-empty the vector is
// immutable.
std::vector<std::string> &OpNames() {
  static std::vector<std::string> names;
  return names;
}

std::mutex &OpNamesMutex() {
  static std::mutex m;
  return m;
}

PyObject *Driver() {  // borrowed module ref (cached by CPython)
  return PyImport_ImportModule("mxnet_tpu.c_api");
}

// call mxnet_tpu.c_api.<fn>(...) -> new reference or nullptr
PyObject *CallDriver(const char *fn, PyObject *args) {
  Ref mod(Driver());
  if (!mod) return nullptr;
  Ref f(PyObject_GetAttrString(mod.p, fn));
  if (!f) return nullptr;
  return PyObject_CallObject(f.p, args);
}

// fill OpNames() from the driver if still empty; returns false with
// g_last_error set on driver failure.  The list is built in a LOCAL
// vector (no lock held across CallDriver — holding a lock while the
// GIL can be released and re-taken by a waiter deadlocks) and swapped
// in under the mutex only if no other thread won the race.
bool FillOpNames() {
  {
    std::lock_guard<std::mutex> lock(OpNamesMutex());
    if (!OpNames().empty()) return true;
  }
  Ref args(PyTuple_New(0));
  Ref lst(CallDriver("op_names", args.p));
  if (!lst) { SetPyError(); return false; }
  std::vector<std::string> local;
  const Py_ssize_t n = PyList_Size(lst.p);
  for (Py_ssize_t i = 0; i < n; ++i) {
    local.emplace_back(PyUnicode_AsUTF8(PyList_GET_ITEM(lst.p, i)));
  }
  std::lock_guard<std::mutex> lock(OpNamesMutex());
  if (OpNames().empty()) OpNames().swap(local);
  return true;
}

PyObject *StrList(const char **strs, mx_uint n) {
  PyObject *lst = PyList_New(n);
  if (!lst) return nullptr;
  for (mx_uint i = 0; i < n; ++i) {
    PyList_SET_ITEM(lst, i, PyUnicode_FromString(strs[i] ? strs[i] : ""));
  }
  return lst;
}

const char *DTypeName(int dtype) {
  switch (dtype) {  // reference type codes + bfloat16 extension
    case 0: return "float32";
    case 1: return "float64";
    case 2: return "float16";
    case 3: return "uint8";
    case 4: return "int32";
    case 5: return "int8";
    case 6: return "int64";
    case 7: return "bfloat16";
    default: return nullptr;
  }
}

int DTypeCode(const std::string &name) {
  const char *names[] = {"float32", "float64", "float16", "uint8",
                         "int32",   "int8",    "int64",   "bfloat16"};
  for (int i = 0; i < 8; ++i) {
    if (name == names[i]) return i;
  }
  return -1;
}

size_t DTypeBytes(int code) {
  switch (code) {
    case 1: case 6: return 8;
    case 0: case 4: return 4;
    case 2: case 7: return 2;
    default: return 1;
  }
}

// copy a python list of str into a handle's string store
bool FillStrs(Handle *h, PyObject *lst) {
  h->str_store.clear();
  h->str_ptrs.clear();
  const Py_ssize_t n = PyList_Size(lst);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *it = PyList_GET_ITEM(lst, i);
    const char *s = PyUnicode_AsUTF8(it);
    if (s == nullptr) return false;
    h->str_store.emplace_back(s);
  }
  for (auto &s : h->str_store) h->str_ptrs.push_back(s.c_str());
  return true;
}

}  // namespace

#define API_GUARD()  EnsurePython()

#define CHECK_HANDLE(h)                                              \
  do {                                                               \
    if ((h) == nullptr) {                                            \
      g_last_error = "null handle";                                  \
      return -1;                                                     \
    }                                                                \
  } while (0)

extern "C" {

const char *MXGetLastError() { return g_last_error.c_str(); }

// ------------------------------------------------------------ ndarray

int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim, int dev_type,
                      int dev_id, int /*delay_alloc*/, int dtype,
                      NDArrayHandle *out) {
  API_GUARD();
  Gil gil;
  const char *dt = DTypeName(dtype);
  if (dt == nullptr) {
    g_last_error = "unknown dtype code " + std::to_string(dtype);
    return -1;
  }
  Ref shp(PyTuple_New(ndim));
  if (!shp) { SetPyError(); return -1; }
  for (mx_uint i = 0; i < ndim; ++i) {
    PyTuple_SET_ITEM(shp.p, i, PyLong_FromUnsignedLong(shape[i]));
  }
  Ref args(Py_BuildValue("(Osii)", shp.p, dt, dev_type, dev_id));
  if (!args) { SetPyError(); return -1; }
  PyObject *arr = CallDriver("nd_create", args.p);
  if (arr == nullptr) { SetPyError(); return -1; }
  *out = new Handle(arr);
  return 0;
}

int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle *out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc,
                           0, out);
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  int code = 0;
  {
    Ref args(Py_BuildValue("(O)", h->obj));
    Ref dt(CallDriver("nd_dtype", args.p));
    if (!dt) { SetPyError(); return -1; }
    code = DTypeCode(PyUnicode_AsUTF8(dt.p));
  }
  Ref bytes(PyBytes_FromStringAndSize(
      static_cast<const char *>(data),
      static_cast<Py_ssize_t>(size * DTypeBytes(code))));
  if (!bytes) { SetPyError(); return -1; }
  Ref args(Py_BuildValue("(OO)", h->obj, bytes.p));
  Ref r(CallDriver("nd_from_bytes", args.p));
  if (!r) { SetPyError(); return -1; }
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t size) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  Ref args(Py_BuildValue("(O)", h->obj));
  Ref bytes(CallDriver("nd_to_bytes", args.p));
  if (!bytes) { SetPyError(); return -1; }
  char *buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(bytes.p, &buf, &n) != 0) {
    SetPyError();
    return -1;
  }
  int code = 0;
  {
    Ref a2(Py_BuildValue("(O)", h->obj));
    Ref dt(CallDriver("nd_dtype", a2.p));
    if (!dt) { SetPyError(); return -1; }
    code = DTypeCode(PyUnicode_AsUTF8(dt.p));
  }
  // reference contract (c_api.cc CHECK_EQ): the caller-declared size
  // must match the array EXACTLY.  Rejecting only the too-small side
  // would silently short-copy when the caller over-declares, leaving
  // the buffer tail untouched and the binding bug unnoticed.
  const size_t want = size * DTypeBytes(code);
  if (static_cast<size_t>(n) != want) {
    g_last_error =
        "MXNDArraySyncCopyToCPU: size mismatch (array is " +
        std::to_string(static_cast<size_t>(n)) + " bytes, caller declared " +
        std::to_string(size) + " elements = " + std::to_string(want) +
        " bytes); size must equal the array's element count";
    return -1;
  }
  std::memcpy(data, buf, static_cast<size_t>(n));
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                      const mx_uint **out_pdata) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  Ref args(Py_BuildValue("(O)", h->obj));
  Ref shp(CallDriver("nd_shape", args.p));
  if (!shp) { SetPyError(); return -1; }
  const Py_ssize_t nd = PyTuple_Size(shp.p);
  h->shape_store.clear();
  for (Py_ssize_t i = 0; i < nd; ++i) {
    h->shape_store.push_back(static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shp.p, i))));
  }
  *out_dim = static_cast<mx_uint>(nd);
  *out_pdata = h->shape_store.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  Ref args(Py_BuildValue("(O)", h->obj));
  Ref dt(CallDriver("nd_dtype", args.p));
  if (!dt) { SetPyError(); return -1; }
  *out_dtype = DTypeCode(PyUnicode_AsUTF8(dt.p));
  return 0;
}

int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                        int *out_dev_id) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  Ref args(Py_BuildValue("(O)", h->obj));
  Ref ctx(CallDriver("nd_context", args.p));
  if (!ctx) { SetPyError(); return -1; }
  *out_dev_type = static_cast<int>(
      PyLong_AsLong(PyTuple_GET_ITEM(ctx.p, 0)));
  *out_dev_id = static_cast<int>(
      PyLong_AsLong(PyTuple_GET_ITEM(ctx.p, 1)));
  return 0;
}

int MXNDArrayReshape(NDArrayHandle handle, int ndim, int *dims,
                     NDArrayHandle *out) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  Ref shp(PyTuple_New(ndim));
  if (!shp) { SetPyError(); return -1; }
  for (int i = 0; i < ndim; ++i) {
    PyTuple_SET_ITEM(shp.p, i, PyLong_FromLong(dims[i]));
  }
  Ref args(Py_BuildValue("(OO)", h->obj, shp.p));
  PyObject *arr = CallDriver("nd_reshape", args.p);
  if (arr == nullptr) { SetPyError(); return -1; }
  *out = new Handle(arr);
  return 0;
}

int MXNDArraySlice(NDArrayHandle handle, mx_uint slice_begin,
                   mx_uint slice_end, NDArrayHandle *out) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  Ref args(Py_BuildValue("(OII)", h->obj, slice_begin, slice_end));
  PyObject *arr = CallDriver("nd_slice", args.p);
  if (arr == nullptr) { SetPyError(); return -1; }
  *out = new Handle(arr);
  return 0;
}

int MXNDArraySave(const char *fname, mx_uint num_args,
                  NDArrayHandle *args_h, const char **keys) {
  API_GUARD();
  Gil gil;
  Ref arrs(PyList_New(num_args));
  if (!arrs) { SetPyError(); return -1; }
  for (mx_uint i = 0; i < num_args; ++i) {
    if (args_h[i] == nullptr) {
      g_last_error = "null NDArrayHandle in save list";
      return -1;
    }
    PyObject *o = static_cast<Handle *>(args_h[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(arrs.p, i, o);
  }
  Ref keylist(keys ? StrList(keys, num_args)
                   : (Py_INCREF(Py_None), Py_None));
  if (!keylist) { SetPyError(); return -1; }
  Ref args(Py_BuildValue("(sOO)", fname, arrs.p, keylist.p));
  Ref r(CallDriver("nd_save", args.p));
  if (!r) { SetPyError(); return -1; }
  return 0;
}

int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names) {
  API_GUARD();
  Gil gil;
  Ref args(Py_BuildValue("(s)", fname));
  Ref res(CallDriver("nd_load", args.p));
  if (!res) { SetPyError(); return -1; }
  PyObject *names = PyTuple_GET_ITEM(res.p, 0);
  PyObject *arrs = PyTuple_GET_ITEM(res.p, 1);
  Scratch &sc = TlsScratch();
  sc.names.clear();
  sc.name_ptrs.clear();
  sc.handles.clear();
  const Py_ssize_t n = PyList_Size(arrs);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GET_ITEM(arrs, i);
    Py_INCREF(o);
    sc.handles.push_back(new Handle(o));
  }
  if (names != Py_None) {
    const Py_ssize_t m = PyList_Size(names);
    for (Py_ssize_t i = 0; i < m; ++i) {
      sc.names.emplace_back(PyUnicode_AsUTF8(PyList_GET_ITEM(names, i)));
    }
  }
  for (auto &s : sc.names) sc.name_ptrs.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(sc.handles.size());
  *out_arr = sc.handles.data();
  *out_name_size = static_cast<mx_uint>(sc.name_ptrs.size());
  *out_names = sc.name_ptrs.data();
  return 0;
}

int MXNDArrayWaitAll() {
  API_GUARD();
  return 0;  // host copies above are synchronous already
}

int MXNDArrayFree(NDArrayHandle handle) {
  if (handle == nullptr) return 0;
  EnsurePython();
  Gil gil;
  delete static_cast<Handle *>(handle);
  return 0;
}

// ------------------------------------------------------------- symbol

int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                     AtomicSymbolCreator **out_array) {
  API_GUARD();
  Gil gil;
  if (!FillOpNames()) return -1;
  Scratch &sc = TlsScratch();
  sc.creators.clear();
  for (size_t i = 0; i < OpNames().size(); ++i) {
    sc.creators.push_back(reinterpret_cast<AtomicSymbolCreator>(i + 1));
  }
  *out_size = static_cast<mx_uint>(sc.creators.size());
  *out_array = sc.creators.data();
  return 0;
}

int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char **name) {
  API_GUARD();
  Gil gil;
  const size_t idx = reinterpret_cast<size_t>(creator);
  if (idx == 0 || idx > OpNames().size()) {
    g_last_error = "invalid AtomicSymbolCreator (call "
                   "MXSymbolListAtomicSymbolCreators first)";
    return -1;
  }
  *name = OpNames()[idx - 1].c_str();
  return 0;
}

int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator,
                               mx_uint num_param, const char **keys,
                               const char **vals, SymbolHandle *out) {
  API_GUARD();
  Gil gil;
  const size_t idx = reinterpret_cast<size_t>(creator);
  if (idx == 0 || idx > OpNames().size()) {
    g_last_error = "invalid AtomicSymbolCreator";
    return -1;
  }
  Ref ks(StrList(keys, num_param));
  Ref vs(StrList(vals, num_param));
  if (!ks || !vs) { SetPyError(); return -1; }
  Ref args(Py_BuildValue("(sOO)", OpNames()[idx - 1].c_str(), ks.p, vs.p));
  PyObject *stub = CallDriver("create_atomic", args.p);
  if (stub == nullptr) { SetPyError(); return -1; }
  *out = new Handle(stub);
  return 0;
}

int MXSymbolCreateVariable(const char *name, SymbolHandle *out) {
  API_GUARD();
  Gil gil;
  Ref args(Py_BuildValue("(s)", name));
  PyObject *v = CallDriver("create_variable", args.p);
  if (v == nullptr) { SetPyError(); return -1; }
  *out = new Handle(v);
  return 0;
}

int MXSymbolCompose(SymbolHandle sym, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args_h) {
  API_GUARD();
  CHECK_HANDLE(sym);
  Gil gil;
  auto h = static_cast<Handle *>(sym);
  Ref arglist(PyList_New(num_args));
  if (!arglist) { SetPyError(); return -1; }
  for (mx_uint i = 0; i < num_args; ++i) {
    if (args_h[i] == nullptr) {
      g_last_error = "null SymbolHandle in compose args";
      return -1;
    }
    PyObject *o = static_cast<Handle *>(args_h[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(arglist.p, i, o);
  }
  Ref ks(keys ? StrList(keys, num_args)
              : (Py_INCREF(Py_None), Py_None));
  Ref cargs(Py_BuildValue("(OsOO)", h->obj, name ? name : "", ks.p,
                          arglist.p));
  PyObject *composed = CallDriver("compose", cargs.p);
  if (composed == nullptr) { SetPyError(); return -1; }
  // reference semantics: compose mutates the symbol in place
  Py_XDECREF(h->obj);
  h->obj = composed;
  return 0;
}

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  API_GUARD();
  Gil gil;
  Ref args(Py_BuildValue("(s)", json));
  PyObject *s = CallDriver("sym_from_json", args.p);
  if (s == nullptr) { SetPyError(); return -1; }
  *out = new Handle(s);
  return 0;
}

int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out) {
  API_GUARD();
  Gil gil;
  Ref args(Py_BuildValue("(s)", fname));
  PyObject *s = CallDriver("sym_from_file", args.p);
  if (s == nullptr) { SetPyError(); return -1; }
  *out = new Handle(s);
  return 0;
}

int MXSymbolSaveToJSON(SymbolHandle symbol, const char **out_json) {
  API_GUARD();
  CHECK_HANDLE(symbol);
  Gil gil;
  auto h = static_cast<Handle *>(symbol);
  Ref args(Py_BuildValue("(O)", h->obj));
  Ref js(CallDriver("sym_to_json", args.p));
  if (!js) { SetPyError(); return -1; }
  h->text = PyUnicode_AsUTF8(js.p);
  *out_json = h->text.c_str();
  return 0;
}

int MXSymbolSaveToFile(SymbolHandle symbol, const char *fname) {
  API_GUARD();
  CHECK_HANDLE(symbol);
  Gil gil;
  auto h = static_cast<Handle *>(symbol);
  Ref args(Py_BuildValue("(Os)", h->obj, fname));
  Ref r(CallDriver("sym_save", args.p));
  if (!r) { SetPyError(); return -1; }
  return 0;
}

static int ListStrings(SymbolHandle symbol, const char *fn,
                       mx_uint *out_size, const char ***out_str_array) {
  API_GUARD();
  CHECK_HANDLE(symbol);
  Gil gil;
  auto h = static_cast<Handle *>(symbol);
  Ref args(Py_BuildValue("(O)", h->obj));
  Ref lst(CallDriver(fn, args.p));
  if (!lst) { SetPyError(); return -1; }
  if (!FillStrs(h, lst.p)) { SetPyError(); return -1; }
  *out_size = static_cast<mx_uint>(h->str_ptrs.size());
  *out_str_array = h->str_ptrs.data();
  return 0;
}

int MXSymbolListArguments(SymbolHandle symbol, mx_uint *out_size,
                          const char ***out_str_array) {
  return ListStrings(symbol, "sym_list_arguments", out_size,
                     out_str_array);
}

int MXSymbolListOutputs(SymbolHandle symbol, mx_uint *out_size,
                        const char ***out_str_array) {
  return ListStrings(symbol, "sym_list_outputs", out_size, out_str_array);
}

int MXSymbolListAuxiliaryStates(SymbolHandle symbol, mx_uint *out_size,
                                const char ***out_str_array) {
  return ListStrings(symbol, "sym_list_aux", out_size, out_str_array);
}

int MXSymbolGetAttr(SymbolHandle symbol, const char *key,
                    const char **out, int *success) {
  API_GUARD();
  CHECK_HANDLE(symbol);
  Gil gil;
  auto h = static_cast<Handle *>(symbol);
  Ref args(Py_BuildValue("(Os)", h->obj, key));
  Ref v(CallDriver("sym_get_attr", args.p));
  if (!v) { SetPyError(); return -1; }
  // (found, value): empty-but-present attrs stay success=1
  *success = PyObject_IsTrue(PyTuple_GET_ITEM(v.p, 0)) ? 1 : 0;
  h->text = PyUnicode_AsUTF8(PyTuple_GET_ITEM(v.p, 1));
  *out = h->text.c_str();
  return 0;
}

int MXSymbolSetAttr(SymbolHandle symbol, const char *key,
                    const char *value) {
  API_GUARD();
  CHECK_HANDLE(symbol);
  Gil gil;
  auto h = static_cast<Handle *>(symbol);
  Ref args(Py_BuildValue("(Oss)", h->obj, key, value));
  Ref r(CallDriver("sym_set_attr", args.p));
  if (!r) { SetPyError(); return -1; }
  return 0;
}

int MXSymbolListAttrShallow(SymbolHandle symbol, mx_uint *out_size,
                            const char ***out_str_array) {
  // flat [k0, v0, k1, v1, ...] like the reference
  return ListStrings(symbol, "sym_list_attr", out_size, out_str_array);
}

int MXSymbolGetName(SymbolHandle symbol, const char **out, int *success) {
  API_GUARD();
  CHECK_HANDLE(symbol);
  Gil gil;
  auto h = static_cast<Handle *>(symbol);
  Ref args(Py_BuildValue("(O)", h->obj));
  Ref nm(CallDriver("sym_name", args.p));
  if (!nm) { SetPyError(); return -1; }
  h->text = PyUnicode_AsUTF8(nm.p);
  *success = h->text.empty() ? 0 : 1;
  *out = h->text.c_str();
  return 0;
}

int MXSymbolFree(SymbolHandle symbol) {
  if (symbol == nullptr) return 0;
  EnsurePython();
  Gil gil;
  delete static_cast<Handle *>(symbol);
  return 0;
}

// ------------------------------------------------------------ kvstore

static PyObject *KvPairs(mx_uint num, const int *keys,
                         NDArrayHandle *vals, PyObject **out_keys) {
  // -> new refs: (key list, value list) or nullptr
  PyObject *ks = PyList_New(num);
  PyObject *vs = PyList_New(num);
  if (!ks || !vs) {
    Py_XDECREF(ks);
    Py_XDECREF(vs);
    return nullptr;
  }
  for (mx_uint i = 0; i < num; ++i) {
    if (vals[i] == nullptr) {
      g_last_error = "null NDArrayHandle in kvstore list";
      Py_DECREF(ks);
      Py_DECREF(vs);
      return nullptr;
    }
    PyList_SET_ITEM(ks, i, PyLong_FromLong(keys[i]));
    PyObject *o = static_cast<Handle *>(vals[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(vs, i, o);
  }
  *out_keys = ks;
  return vs;
}

int MXKVStoreCreate(const char *type, KVStoreHandle *out) {
  API_GUARD();
  Gil gil;
  Ref args(Py_BuildValue("(s)", type));
  PyObject *kv = CallDriver("kv_create", args.p);
  if (kv == nullptr) { SetPyError(); return -1; }
  *out = new Handle(kv);
  return 0;
}

int MXKVStoreGetType(KVStoreHandle handle, const char **type) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  Ref args(Py_BuildValue("(O)", h->obj));
  Ref t(CallDriver("kv_type", args.p));
  if (!t) { SetPyError(); return -1; }
  h->text = PyUnicode_AsUTF8(t.p);
  *type = h->text.c_str();
  return 0;
}

static int KvInt(KVStoreHandle handle, const char *fn, int *out) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  Ref args(Py_BuildValue("(O)", h->obj));
  Ref v(CallDriver(fn, args.p));
  if (!v) { SetPyError(); return -1; }
  *out = static_cast<int>(PyLong_AsLong(v.p));
  return 0;
}

int MXKVStoreGetRank(KVStoreHandle handle, int *rank) {
  return KvInt(handle, "kv_rank", rank);
}

int MXKVStoreGetGroupSize(KVStoreHandle handle, int *size) {
  return KvInt(handle, "kv_num_workers", size);
}

static int KvOp(KVStoreHandle handle, const char *fn, mx_uint num,
                const int *keys, NDArrayHandle *vals, int priority,
                bool with_priority) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  PyObject *ks = nullptr;
  Ref vs(KvPairs(num, keys, vals, &ks));
  if (!vs) { if (PyErr_Occurred()) SetPyError(); return -1; }
  Ref ksr(ks);
  Ref args(with_priority
               ? Py_BuildValue("(OOOi)", h->obj, ksr.p, vs.p, priority)
               : Py_BuildValue("(OOO)", h->obj, ksr.p, vs.p));
  Ref r(CallDriver(fn, args.p));
  if (!r) { SetPyError(); return -1; }
  return 0;
}

int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals) {
  return KvOp(handle, "kv_init", num, keys, vals, 0, false);
}

int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority) {
  return KvOp(handle, "kv_push", num, keys, vals, priority, true);
}

int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority) {
  return KvOp(handle, "kv_pull", num, keys, vals, priority, true);
}

// internal trampoline helper for kv_set_updater (mxnet_tpu/c_api.py):
// wrap a live python NDArray as an owned ABI handle.  The caller MUST
// hold the GIL (ctypes.PyDLL does).
NDArrayHandle MXTPUWrapNDArray(PyObject *obj) {
  Py_INCREF(obj);
  return new Handle(obj);
}

int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle) {
  API_GUARD();
  CHECK_HANDLE(handle);
  if (updater == nullptr) {
    g_last_error = "null updater function";
    return -1;
  }
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  // pass the trampoline addresses (MXTPUWrapNDArray / MXNDArrayFree)
  // explicitly: the python side must not resolve them through the
  // GLOBAL symbol table (ctypes.PyDLL(None)), which is empty for this
  // library when the host application dlopen()ed it with the default
  // RTLD_LOCAL — the plausible way to consume a C ABI (ADVICE).
  Ref args(Py_BuildValue(
      "(OKKKK)", h->obj,
      static_cast<unsigned long long>(
          reinterpret_cast<uintptr_t>(updater)),
      static_cast<unsigned long long>(
          reinterpret_cast<uintptr_t>(updater_handle)),
      static_cast<unsigned long long>(
          reinterpret_cast<uintptr_t>(&MXTPUWrapNDArray)),
      static_cast<unsigned long long>(
          reinterpret_cast<uintptr_t>(&MXNDArrayFree))));
  if (!args) { SetPyError(); return -1; }
  Ref r(CallDriver("kv_set_updater", args.p));
  if (!r) { SetPyError(); return -1; }
  return 0;
}

int MXKVStoreFree(KVStoreHandle handle) {
  if (handle == nullptr) return 0;
  EnsurePython();
  Gil gil;
  delete static_cast<Handle *>(handle);
  return 0;
}

// ----------------------------------------------------------- recordio

static int RecCreate(const char *uri, const char *fn,
                     RecordIOHandle *out) {
  API_GUARD();
  Gil gil;
  Ref args(Py_BuildValue("(s)", uri));
  PyObject *rec = CallDriver(fn, args.p);
  if (rec == nullptr) { SetPyError(); return -1; }
  *out = new Handle(rec);
  return 0;
}

int MXRecordIOWriterCreate(const char *uri, RecordIOHandle *out) {
  return RecCreate(uri, "recordio_writer", out);
}

int MXRecordIOReaderCreate(const char *uri, RecordIOHandle *out) {
  return RecCreate(uri, "recordio_reader", out);
}

int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char *buf,
                                size_t size) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  Ref bytes(PyBytes_FromStringAndSize(buf,
                                      static_cast<Py_ssize_t>(size)));
  if (!bytes) { SetPyError(); return -1; }
  Ref args(Py_BuildValue("(OO)", h->obj, bytes.p));
  Ref r(CallDriver("recordio_write", args.p));
  if (!r) { SetPyError(); return -1; }
  return 0;
}

int MXRecordIOReaderReadRecord(RecordIOHandle handle,
                               char const **out_buf, size_t *size) {
  API_GUARD();
  CHECK_HANDLE(handle);
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  Ref args(Py_BuildValue("(O)", h->obj));
  Ref rec(CallDriver("recordio_read", args.p));
  if (!rec) { SetPyError(); return -1; }
  if (rec.p == Py_None) {
    *out_buf = nullptr;
    *size = 0;
    return 0;
  }
  char *buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(rec.p, &buf, &n) != 0) {
    SetPyError();
    return -1;
  }
  h->text.assign(buf, static_cast<size_t>(n));
  *out_buf = h->text.data();
  *size = static_cast<size_t>(n);
  return 0;
}

static int RecFree(RecordIOHandle handle) {
  if (handle == nullptr) return 0;
  EnsurePython();
  Gil gil;
  auto h = static_cast<Handle *>(handle);
  Ref args(Py_BuildValue("(O)", h->obj));
  Ref r(CallDriver("recordio_close", args.p));
  // close errors are surfaced, but the handle is freed either way
  int rc = r ? 0 : (SetPyError(), -1);
  delete h;
  return rc;
}

int MXRecordIOWriterFree(RecordIOHandle handle) { return RecFree(handle); }

int MXRecordIOReaderFree(RecordIOHandle handle) { return RecFree(handle); }

}  // extern "C"
