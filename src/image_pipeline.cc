// Native high-throughput image record pipeline.
//
// Reference: src/io/iter_image_recordio_2.cc:28-612 (ImageRecordIOParser2)
// — the reference's ImageNet input path: a reader thread walks the .rec
// file while N worker threads JPEG-decode, resize and layout each record,
// feeding batches to the device copy without per-image Python cost.
//
// This is the TPU build's equivalent: one reader thread parses the
// recordio framing ([magic][len][IRHeader][jpeg bytes]) into a bounded
// work queue; N decode threads run libjpeg + a bilinear resize to the
// target (H, W) and emit (label, RGB u8 HWC) results into a bounded
// output queue; MXTPUImagePipelineNextBatch assembles whole batches for
// the Python iterator (mxnet_tpu/io_native.py ImageRecordIter).
// Decode order is not deterministic across threads (the reference's
// parser also re-chunks); training input order is already shuffled at
// .rec creation (tools/im2rec.py).
//
// Build: make -C src   (links -ljpeg; gated by HAVE_JPEG)

#include <atomic>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include "recordio_format.h"

#ifdef HAVE_JPEG
#include <jpeglib.h>
#endif

namespace {

struct RawRecord {
  float label = 0.0f;
  uint64_t index = 0;            // record ordinal (per-record RNG stream)
  std::vector<uint8_t> payload;  // jpeg bytes
};

// Augmentation knobs (reference DefaultImageAugmentParam,
// src/io/image_aug_default.cc): rand_crop resizes the shorter edge
// ~1.15x above target then takes a random window; rand_mirror flips
// horizontally with p=0.5.  Deterministic per (seed, record index).
struct AugConfig {
  bool rand_crop = false;
  bool rand_mirror = false;
  uint64_t seed = 0;
};

struct Decoded {
  float label = 0.0f;
  std::vector<uint8_t> pixels;   // out_h * out_w * 3, RGB, HWC
};

template <typename T>
class BoundedQueue {
 public:
  explicit BoundedQueue(size_t cap) : cap_(cap) {}

  bool Push(T&& v) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return q_.size() < cap_ || done_; });
    if (done_) return false;
    q_.emplace_back(std::move(v));
    not_empty_.notify_one();
    return true;
  }

  bool Pop(T* out) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return !q_.empty() || done_; });
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return true;
  }

  // closes the queue for writers but lets readers drain remaining items
  void FinishWriting() {
    std::lock_guard<std::mutex> lk(mu_);
    done_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

 private:
  size_t cap_;
  bool done_ = false;
  std::deque<T> q_;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
};

#ifdef HAVE_JPEG
struct JpegErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void JpegErrorExit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

// Decode JPEG bytes to RGB u8 HWC; returns false on corrupt input.
bool DecodeJpeg(const uint8_t* data, size_t size, std::vector<uint8_t>* out,
                int* width, int* height) {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = JpegErrorExit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(size));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *width = cinfo.output_width;
  *height = cinfo.output_height;
  out->resize(static_cast<size_t>(*width) * (*height) * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() +
                   static_cast<size_t>(cinfo.output_scanline) * (*width) * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}
#endif  // HAVE_JPEG

// Bilinear resize RGB u8 HWC (the role of the reference's cv::resize in
// DefaultImageAugmenter, src/io/image_aug_default.cc).  Fixed-point with
// a precomputed x-axis LUT: the horizontal pass is the hot loop and the
// source geometry repeats across rows.
void ResizeBilinear(const uint8_t* src, int sw, int sh, uint8_t* dst,
                    int dw, int dh) {
  if (sw == dw && sh == dh) {
    std::memcpy(dst, src, static_cast<size_t>(sw) * sh * 3);
    return;
  }
  constexpr int kBits = 11;           // 2^11 weight scale (fits 8b*11b in 32b)
  constexpr int kOne = 1 << kBits;
  const float sx = dw > 1 ? static_cast<float>(sw - 1) / (dw - 1) : 0.0f;
  const float sy = dh > 1 ? static_cast<float>(sh - 1) / (dh - 1) : 0.0f;
  std::vector<int> x0s(dw), x1s(dw), wxs(dw);
  for (int x = 0; x < dw; ++x) {
    float fx = x * sx;
    int x0 = static_cast<int>(fx);
    x0s[x] = x0 * 3;
    x1s[x] = (x0 + 1 < sw ? x0 + 1 : sw - 1) * 3;
    wxs[x] = static_cast<int>((fx - x0) * kOne + 0.5f);
  }
  for (int y = 0; y < dh; ++y) {
    float fy = y * sy;
    int y0 = static_cast<int>(fy);
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    int wy = static_cast<int>((fy - y0) * kOne + 0.5f);
    const uint8_t* r0 = src + static_cast<size_t>(y0) * sw * 3;
    const uint8_t* r1 = src + static_cast<size_t>(y1) * sw * 3;
    uint8_t* out = dst + static_cast<size_t>(y) * dw * 3;
    for (int x = 0; x < dw; ++x) {
      int wx = wxs[x];
      const uint8_t* a0 = r0 + x0s[x];
      const uint8_t* b0 = r0 + x1s[x];
      const uint8_t* a1 = r1 + x0s[x];
      const uint8_t* b1 = r1 + x1s[x];
      for (int c = 0; c < 3; ++c) {
        int top = a0[c] * (kOne - wx) + b0[c] * wx;        // <= 8b+11b
        int bot = a1[c] * (kOne - wx) + b1[c] * wx;
        int v = ((top >> 2) * (kOne - wy) + (bot >> 2) * wy +
                 (1 << (2 * kBits - 3))) >> (2 * kBits - 2);
        out[x * 3 + c] = static_cast<uint8_t>(v > 255 ? 255 : v);
      }
    }
  }
}

class ImagePipeline {
 public:
  ImagePipeline(const char* path, int out_h, int out_w, int n_threads,
                size_t queue_cap, int num_parts, int part_index,
                const AugConfig& aug, size_t shuffle_buffer)
      : out_h_(out_h), out_w_(out_w), num_parts_(num_parts < 1 ? 1
                                                                : num_parts),
        part_index_(part_index), aug_(aug),
        shuffle_buffer_(shuffle_buffer),
        shuffle_rng_(static_cast<unsigned>(aug.seed ^ 0x5bd1e995)),
        work_(queue_cap ? queue_cap : 256),
        done_(queue_cap ? queue_cap : 256) {
    f_ = std::fopen(path, "rb");
    if (!f_) return;
    if (n_threads < 1) n_threads = 1;
    reader_ = std::thread([this] { this->ReadLoop(); });
    decoders_active_ = n_threads;
    for (int i = 0; i < n_threads; ++i) {
      decoders_.emplace_back([this] { this->DecodeLoop(); });
    }
  }

  ~ImagePipeline() {
    stop_ = true;
    work_.FinishWriting();
    done_.FinishWriting();
    if (reader_.joinable()) reader_.join();
    for (auto& t : decoders_) {
      if (t.joinable()) t.join();
    }
    if (f_) std::fclose(f_);
  }

  bool ok() const { return f_ != nullptr; }

  // Fill up to `batch` images; returns the number filled (0 at EOF).
  // With shuffle_buffer > 0, emits from a reservoir of decoded images in
  // random order (streaming-shuffle; the reference parser's chunk
  // shuffle plays the same role on top of im2rec-time shuffling).
  int64_t NextBatch(float* labels, uint8_t* data, int64_t batch) {
    const size_t img = static_cast<size_t>(out_h_) * out_w_ * 3;
    int64_t i = 0;
    while (i < batch) {
      Decoded d;
      if (shuffle_buffer_ > 0) {
        // top up the reservoir, then emit a random element
        while (reservoir_.size() < shuffle_buffer_) {
          Decoded x;
          if (!done_.Pop(&x)) break;
          reservoir_.emplace_back(std::move(x));
        }
        if (reservoir_.empty()) break;
        size_t j = std::uniform_int_distribution<size_t>(
            0, reservoir_.size() - 1)(shuffle_rng_);
        d = std::move(reservoir_[j]);
        reservoir_[j] = std::move(reservoir_.back());
        reservoir_.pop_back();
      } else {
        if (!done_.Pop(&d)) break;
      }
      labels[i] = d.label;
      std::memcpy(data + i * img, d.pixels.data(), img);
      ++i;
    }
    return i;
  }

 private:
  void ReadLoop() {
    uint64_t ordinal = 0;
    std::vector<uint8_t> rec;
    while (!stop_) {
      if (!mxtpu::ReadRecRecord(f_, &rec)) break;
      uint64_t idx = ordinal++;
      // data-parallel sharding: worker part_index of num_parts
      // (reference ImageRecordIOParser2 kv-sharded read)
      if (static_cast<int>(idx % num_parts_) != part_index_) continue;
      if (rec.size() < 24) continue;  // not an IRHeader record
      // IRHeader: uint32 flag, float label, uint64 id[2]
      // (image_recordio.h:20-35); flag>0 = extra label floats
      uint32_t flag;
      std::memcpy(&flag, rec.data(), 4);
      size_t off = 24 + static_cast<size_t>(flag > 0 ? flag : 0) * 4;
      if (off >= rec.size()) continue;
      RawRecord r;
      r.index = idx;
      if (flag > 0) {
        std::memcpy(&r.label, rec.data() + 24, 4);
      } else {
        std::memcpy(&r.label, rec.data() + 4, 4);
      }
      r.payload.assign(rec.begin() + off, rec.end());
      if (!work_.Push(std::move(r))) break;
    }
    work_.FinishWriting();
  }

  void DecodeLoop() {
    RawRecord r;
    while (work_.Pop(&r)) {
#ifdef HAVE_JPEG
      std::vector<uint8_t> rgb;
      int w = 0, h = 0;
      if (!DecodeJpeg(r.payload.data(), r.payload.size(), &rgb, &w, &h)) {
        continue;  // skip corrupt records like the reference parser
      }
      Decoded d;
      d.label = r.label;
      d.pixels.resize(static_cast<size_t>(out_h_) * out_w_ * 3);
      std::mt19937 rng(static_cast<unsigned>(aug_.seed * 2654435761u +
                                             r.index));
      if (aug_.rand_crop) {
        // resize shorter edge to ~1.15x target, then random window
        // (DefaultImageAugmenter resize+rand_crop recipe)
        int short_t = out_h_ < out_w_ ? out_h_ : out_w_;
        int target = short_t + short_t / 7;
        int rs_w, rs_h;
        if (w < h) {
          rs_w = target;
          rs_h = static_cast<int>(static_cast<int64_t>(h) * target / w);
        } else {
          rs_h = target;
          rs_w = static_cast<int>(static_cast<int64_t>(w) * target / h);
        }
        if (rs_w < out_w_) rs_w = out_w_;
        if (rs_h < out_h_) rs_h = out_h_;
        std::vector<uint8_t> resized(
            static_cast<size_t>(rs_w) * rs_h * 3);
        ResizeBilinear(rgb.data(), w, h, resized.data(), rs_w, rs_h);
        int x0 = std::uniform_int_distribution<int>(0, rs_w - out_w_)(rng);
        int y0 = std::uniform_int_distribution<int>(0, rs_h - out_h_)(rng);
        for (int y = 0; y < out_h_; ++y) {
          std::memcpy(d.pixels.data() + static_cast<size_t>(y) * out_w_ * 3,
                      resized.data() +
                          (static_cast<size_t>(y0 + y) * rs_w + x0) * 3,
                      static_cast<size_t>(out_w_) * 3);
        }
      } else {
        ResizeBilinear(rgb.data(), w, h, d.pixels.data(), out_w_, out_h_);
      }
      if (aug_.rand_mirror &&
          std::uniform_int_distribution<int>(0, 1)(rng)) {
        for (int y = 0; y < out_h_; ++y) {
          uint8_t* row = d.pixels.data() +
                         static_cast<size_t>(y) * out_w_ * 3;
          for (int x = 0; x < out_w_ / 2; ++x) {
            for (int c = 0; c < 3; ++c) {
              std::swap(row[x * 3 + c], row[(out_w_ - 1 - x) * 3 + c]);
            }
          }
        }
      }
      if (!done_.Push(std::move(d))) break;
#else
      (void)r;
      break;
#endif
    }
    if (--decoders_active_ == 0) done_.FinishWriting();
  }

  int out_h_, out_w_;
  int num_parts_ = 1;
  int part_index_ = 0;
  AugConfig aug_;
  size_t shuffle_buffer_ = 0;
  std::vector<Decoded> reservoir_;
  std::mt19937 shuffle_rng_;
  std::FILE* f_ = nullptr;
  std::atomic<bool> stop_{false};
  std::atomic<int> decoders_active_{0};
  BoundedQueue<RawRecord> work_;
  BoundedQueue<Decoded> done_;
  std::thread reader_;
  std::vector<std::thread> decoders_;
};

}  // namespace

extern "C" {

int MXTPUImagePipelineHasJpeg() {
#ifdef HAVE_JPEG
  return 1;
#else
  return 0;
#endif
}

void* MXTPUImagePipelineCreate(const char* path, int64_t out_h, int64_t out_w,
                               int64_t n_threads, int64_t queue_cap,
                               int64_t num_parts, int64_t part_index,
                               int64_t rand_crop, int64_t rand_mirror,
                               int64_t seed, int64_t shuffle_buffer) {
  AugConfig aug;
  aug.rand_crop = rand_crop != 0;
  aug.rand_mirror = rand_mirror != 0;
  aug.seed = static_cast<uint64_t>(seed);
  auto* p = new ImagePipeline(path, static_cast<int>(out_h),
                              static_cast<int>(out_w),
                              static_cast<int>(n_threads),
                              static_cast<size_t>(queue_cap),
                              static_cast<int>(num_parts),
                              static_cast<int>(part_index), aug,
                              static_cast<size_t>(shuffle_buffer));
  if (!p->ok()) {
    delete p;
    return nullptr;
  }
  return p;
}

void MXTPUImagePipelineFree(void* handle) {
  delete static_cast<ImagePipeline*>(handle);
}

// labels: (batch,) f32; data: (batch, out_h, out_w, 3) u8.
int64_t MXTPUImagePipelineNextBatch(void* handle, float* labels,
                                    uint8_t* data, int64_t batch) {
  return static_cast<ImagePipeline*>(handle)->NextBatch(labels, data, batch);
}

}  // extern "C"
