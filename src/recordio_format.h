// Shared .rec framing walker (single source of the on-disk format for
// recordio.cc and image_pipeline.cc).
//
// Reference: dmlc-core recordio — every record is
// [uint32 magic][uint32 lrec][payload][pad to 4B] where lrec's upper 3
// bits are the continuation flag: 0 = whole record, 1 = start, 2 =
// middle, 3 = end.  The writer splits a record at 4-aligned occurrences
// of the magic word inside the payload (the occurrence itself is
// dropped); the reader re-inserts the magic between re-joined parts.
#ifndef MXTPU_RECORDIO_FORMAT_H_
#define MXTPU_RECORDIO_FORMAT_H_

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

namespace mxtpu {

constexpr uint32_t kRecMagic = 0xced7230a;
constexpr uint32_t kRecLengthMask = (1u << 29) - 1;

inline uint32_t RecDecodeFlag(uint32_t lrec) { return lrec >> 29; }

// Reads one framed part; false at EOF or corrupt stream.
inline bool ReadRecPart(std::FILE* f, uint32_t* cflag,
                        std::vector<uint8_t>* part) {
  uint8_t header[8];
  if (std::fread(header, 1, 8, f) != 8) return false;
  uint32_t magic, lrec;
  std::memcpy(&magic, header, 4);
  std::memcpy(&lrec, header + 4, 4);
  if (magic != kRecMagic) return false;
  *cflag = RecDecodeFlag(lrec);
  uint32_t len = lrec & kRecLengthMask;
  part->resize(len);
  if (len && std::fread(part->data(), 1, len, f) != len) return false;
  uint32_t pad = (4 - (len % 4)) % 4;
  if (pad && std::fseek(f, pad, SEEK_CUR) != 0) return false;
  return true;
}

// Reads one LOGICAL record, re-joining continuation parts with the magic
// word re-inserted (dmlc RecordIOReader::NextRecord semantics).
// Returns false at EOF or on a framing error.
inline bool ReadRecRecord(std::FILE* f, std::vector<uint8_t>* out) {
  std::vector<uint8_t> part;
  uint32_t cflag = 0;
  if (!ReadRecPart(f, &cflag, &part)) return false;
  if (cflag == 0) {
    out->swap(part);
    return true;
  }
  if (cflag != 1) return false;  // middle/end with no start: corrupt
  out->swap(part);
  while (true) {
    if (!ReadRecPart(f, &cflag, &part)) return false;
    if (cflag != 2 && cflag != 3) return false;
    const uint8_t* m = reinterpret_cast<const uint8_t*>(&kRecMagic);
    out->insert(out->end(), m, m + 4);
    out->insert(out->end(), part.begin(), part.end());
    if (cflag == 3) return true;
  }
}

}  // namespace mxtpu

#endif  // MXTPU_RECORDIO_FORMAT_H_
