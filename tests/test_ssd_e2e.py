"""SSD end-to-end: detection data path, training convergence, VOC mAP.

Reference: example/ssd/ (train/train_net.py, evaluate/evaluate_net.py,
eval_metric.py; published mAP 77.8/79.9 on VOC07 — README.md:32-36).
Here a mini SSD converges on the synthetic rectangle set and the metric
implementations are checked against hand-computed values.
"""
import os
import sys
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SSD = os.path.join(ROOT, "examples", "ssd")
for p in (SSD, os.path.join(SSD, "symbol"), os.path.join(SSD, "tools")):
    if p not in sys.path:
        sys.path.insert(0, p)


def test_det_record_iter_roundtrip():
    """Detection records round-trip through pack_det_label + DetRecordIter
    with the reference label layout (imdb.py:55-80)."""
    from synth_dataset import make_record_file
    from mxnet_tpu.image_det import DetRecordIter
    with tempfile.TemporaryDirectory() as d:
        rec = make_record_file(os.path.join(d, "t.rec"), num_images=6,
                               image_size=64, seed=3)
        it = DetRecordIter(rec, batch_size=3, data_shape=(3, 64, 64),
                           mean_pixels=(0, 0, 0))
        total = 0
        for b in it:
            lab = b.label[0].asnumpy()
            assert lab.shape[2] == 6
            valid = lab[lab[:, :, 0] >= 0]
            assert valid.size > 0
            assert (valid[:, 1:5] >= 0).all() and (valid[:, 1:5] <= 1).all()
            assert (valid[:, 0] < 3).all()
            total += b.data[0].shape[0] - b.pad
        assert total == 6


def test_det_record_iter_mirror_flips_boxes():
    from synth_dataset import make_record_file
    from mxnet_tpu.image_det import DetRecordIter
    with tempfile.TemporaryDirectory() as d:
        rec = make_record_file(os.path.join(d, "t.rec"), num_images=4,
                               image_size=64, seed=4)
        plain = DetRecordIter(rec, 4, (3, 64, 64), mean_pixels=(0, 0, 0))
        b0 = next(iter(plain))
        # seed chosen so at least one sample mirrors within a batch
        mirrored = DetRecordIter(rec, 4, (3, 64, 64), mean_pixels=(0, 0, 0),
                                 rand_mirror=True, seed=1)
        b1 = next(iter(mirrored))
        d0, d1 = b0.data[0].asnumpy(), b1.data[0].asnumpy()
        flipped = [i for i in range(4)
                   if not np.allclose(d0[i], d1[i])]
        assert flipped, "no sample mirrored"
        i = flipped[0]
        np.testing.assert_allclose(d1[i], d0[i][:, :, ::-1], atol=1e-5)
        l0 = b0.label[0].asnumpy()[i]
        l1 = b1.label[0].asnumpy()[i]
        v0, v1 = l0[l0[:, 0] >= 0], l1[l1[:, 0] >= 0]
        np.testing.assert_allclose(v1[:, 1], 1.0 - v0[:, 3], atol=1e-6)
        np.testing.assert_allclose(v1[:, 3], 1.0 - v0[:, 1], atol=1e-6)


def test_map_metric_hand_computed():
    """MApMetric/VOC07MApMetric against hand-computed AP values
    (eval_metric.py:4-258 semantics)."""
    from metric import MApMetric, VOC07MApMetric
    # one image, 2 gts of class 0; 3 dets: one TP (iou>0.5), one FP,
    # one duplicate on the first gt
    labels = [mx.nd.array(np.array([[[0, 0.1, 0.1, 0.5, 0.5, 0],
                                     [0, 0.6, 0.6, 0.9, 0.9, 0]]], "f"))]
    preds = [mx.nd.array(np.array([[[0, 0.9, 0.1, 0.1, 0.5, 0.5],   # tp
                                    [0, 0.8, 0.0, 0.0, 0.05, 0.05],  # fp
                                    [0, 0.7, 0.12, 0.12, 0.5, 0.5],  # dup
                                    [-1, 0.0, 0, 0, 0, 0]]], "f"))]
    m = MApMetric(ovp_thresh=0.5)
    m.update(labels, preds)
    name, value = m.get()
    # ranked: tp(0.9), fp(0.8), fp-dup(0.7); recalls .5,.5,.5
    # precision ladder: 1, 1/2, 1/3 -> AP = 0.5 * 1.0 = 0.5
    assert abs(value - 0.5) < 1e-6
    v07 = VOC07MApMetric(ovp_thresh=0.5)
    v07.update(labels, preds)
    _, value07 = v07.get()
    # 11-point: p=1.0 for t in {0, .1, ..., .5}, 0 beyond -> 6/11
    assert abs(value07 - 6.0 / 11.0) < 1e-6


def test_map_metric_missed_class_counts():
    """A class present in labels but absent from detections must drag the
    mean down with AP=0, not drop out (reference missing-class sentinel)."""
    from metric import MApMetric
    labels = [mx.nd.array(np.array([[[0, 0.1, 0.1, 0.5, 0.5, 0],
                                     [1, 0.6, 0.6, 0.9, 0.9, 0]]], "f"))]
    preds = [mx.nd.array(np.array([[[0, 0.9, 0.1, 0.1, 0.5, 0.5],
                                    [-1, 0, 0, 0, 0, 0]]], "f"))]
    m = MApMetric(ovp_thresh=0.5)
    m.update(labels, preds)
    _, value = m.get()
    # class 0: AP 1.0; class 1: wholly missed, AP 0 -> mean 0.5
    assert abs(value - 0.5) < 1e-6


def test_multibox_target_negative_mining():
    """negative_mining_ratio keeps ratio*npos hard negatives and ignores
    the rest (multibox_target.cc hard-negative mining)."""
    feat = mx.nd.zeros((1, 8, 8, 8))
    anc = mx.contrib.nd.MultiBoxPrior(feat, sizes=(0.3,), ratios=(1.0,))
    gt = mx.nd.array(np.array([[[0, 0.3, 0.3, 0.62, 0.62, 0]]], "f"))
    rng = np.random.RandomState(0)
    pred = mx.nd.array(rng.randn(1, 2, 64).astype("f"))
    _, _, ct = mx.contrib.nd.MultiBoxTarget(
        anc, gt, pred, overlap_threshold=0.5, ignore_label=-1,
        negative_mining_ratio=2)
    ct = ct.asnumpy()[0]
    npos = int((ct > 0).sum())
    nneg = int((ct == 0).sum())
    nign = int((ct < 0).sum())
    assert npos >= 1
    assert nneg == min(2 * npos, 64 - npos)
    assert npos + nneg + nign == 64


@pytest.mark.slow
def test_ssd_toy_convergence_map():
    """Mini SSD converges on the synthetic rectangle set: train then
    VOC07-mAP well above chance (reference converges to 0.778 on VOC)."""
    import logging
    from synth_dataset import make_record_file, CLASS_NAMES
    from train import train_net
    from evaluate import evaluate_net
    logging.disable(logging.INFO)
    try:
        with tempfile.TemporaryDirectory() as d:
            rec = make_record_file(os.path.join(d, "toy.rec"),
                                   num_images=24, image_size=96, seed=0)
            mod = train_net(rec, network="mini", num_classes=3, batch_size=8,
                            data_shape=(3, 96, 96), num_epochs=40, lr=0.05,
                            rand_mirror=False, mean_pixels=(128, 128, 128),
                            frequent=10000)
            res = dict(evaluate_net(
                mod, rec, 3, network="mini", batch_size=8,
                data_shape=(3, 96, 96), class_names=list(CLASS_NAMES),
                mean_pixels=(128, 128, 128)))
            assert res["mAP"] > 0.35, res
    finally:
        logging.disable(logging.NOTSET)
