"""Static memory-liveness analyzer (analysis.memlive, MXG017-021).

Interval oracles are hand-computed on a tiny fc->relu chain: the topo
is [data, fc_weight, fc_bias, fc, act] (N=5), so the train timeline is
forward t=0..4, backward t=5..9 (node i's backward at 2N-1-i), update
t=10.  Seeded-defect tests then assert each rule names the offending
node, and the drift regression pins the static prediction to the XLA
memory_analysis total on a real zoo model (satellite: the telemetry
budget check and the analyzer must agree within MXNET_TPU_MEMLIVE_TOL).
"""
import json
import os
import subprocess
import sys

import pytest

import mxnet_tpu.symbol as sym
from mxnet_tpu.analysis import memlive
from mxnet_tpu.analysis.verifier import Report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny():
    d = sym.var("data")
    fc = sym.FullyConnected(d, num_hidden=4, name="fc")
    return sym.Activation(fc, act_type="relu", name="act")


def _buf(analysis, name):
    hits = [b for b in analysis.buffers if b.name == name]
    assert hits, "no buffer %r in %s" % (name, analysis.buffers)
    return hits[0]


def _rules(report):
    return [d.rule for d in report]


def _find(report, rule):
    return [d for d in report if d.rule == rule]


# ------------------------------------------------- interval oracles

def test_eval_intervals_tiny_chain():
    a = memlive.analyze(_tiny(), shapes={"data": (2, 8)},
                        is_train=False, fuse=False)
    assert a.n_nodes == 5
    # data (2,8)f32=64B is read once, by fc at t=3, and dies there
    d = _buf(a, "data")
    assert (d.start, d.end, d.first_use) == (0, 3, 3) and d.is_input
    # params live to the end of the forward (t = N-1 = 4)
    assert (_buf(a, "fc_weight").start, _buf(a, "fc_weight").end) == (0, 4)
    # fc's output (2,4)f32=32B is born at its position, read by act
    assert (_buf(a, "fc").start, _buf(a, "fc").end) == (3, 4)
    assert (_buf(a, "act").start, _buf(a, "act").end) == (4, 4)
    # peak is at fc's position: data64 + weight128 + bias16 + fc32
    assert a.peak_bytes == 240 and a.peak_pos == 3
    assert a.peak_node == "fc"
    # eval mode has no residuals and no optimizer state
    assert not any(b.category in ("residuals", "optimizer")
                   for b in a.buffers)


def test_train_intervals_tiny_chain():
    a = memlive.analyze(_tiny(), shapes={"data": (2, 8)},
                        is_train=True, n_slots=2, fuse=False)
    # input is a residual of fc's backward: last = 2N-1-3 = 6
    assert (_buf(a, "data").start, _buf(a, "data").end) == (0, 6)
    # fc's output is act's residual: saved at t=3 until act's backward
    # at 2N-1-4 = 5
    fc = _buf(a, "fc")
    assert (fc.category, fc.start, fc.end) == ("residuals", 3, 5)
    # cotangent of fc is born at act's backward, consumed at fc's own
    # backward (t=6)
    assert (_buf(a, "d(fc)").start, _buf(a, "d(fc)").end) == (5, 6)
    # weight gradient lives from fc's backward to the update (t=2N=10)
    assert (_buf(a, "d(fc_weight)").start,
            _buf(a, "d(fc_weight)").end) == (6, 10)
    # Adam: 2 f32 slots per param, alive the whole step
    w_opt = _buf(a, "fc_weight.opt")
    assert (w_opt.category, w_opt.nbytes) == ("optimizer", 2 * 128)
    assert (w_opt.start, w_opt.end) == (0, 10)
    # the un-donated update writes double-buffer at the update slot
    assert (_buf(a, "fc_weight'").start,
            _buf(a, "fc_weight'").end) == (10, 10)


def test_peak_equals_live_sum():
    a = memlive.analyze(_tiny(), shapes={"data": (2, 8)},
                        is_train=True, n_slots=2, fuse=False)
    assert a.peak_bytes == sum(b.nbytes for b in a.live_at_peak)
    # the sweep found the true maximum over every timeline slot
    assert a.peak_bytes == max(
        sum(b.nbytes for b in a.live_at(t))
        for t in range(2 * a.n_nodes + 1))


def test_donation_arms_update_in_place():
    # trainer convention (donate=True): params/opt updated in place,
    # no "name'" double-buffers, so the update-slot peak drops
    plain = memlive.analyze(_tiny(), shapes={"data": (2, 8)},
                            is_train=True, n_slots=2, fuse=False)
    donated = memlive.analyze(_tiny(), shapes={"data": (2, 8)},
                              is_train=True, n_slots=2, fuse=False,
                              donate=True)
    assert donated.peak_bytes < plain.peak_bytes
    assert not any(b.name.endswith("'") for b in donated.buffers)


def test_sharding_divides_bytes():
    full = memlive.analyze(_tiny(), shapes={"data": (4, 8)},
                           is_train=False, fuse=False)
    shard = memlive.analyze(_tiny(), shapes={"data": (4, 8)},
                            is_train=False, fuse=False,
                            mesh={"data": 4})
    # batch-dim buffers (input + op outputs) shrink 4x; params don't
    assert _buf(shard, "data").nbytes == _buf(full, "data").nbytes // 4
    assert _buf(shard, "fc_weight").nbytes == _buf(full,
                                                   "fc_weight").nbytes


# --------------------------------------------------- seeded defects

def test_mxg017_over_budget_names_peak_node():
    report = Report()
    memlive.check_memory(_tiny(), shapes={"data": (2, 8)},
                         report=report, budget_bytes=100,
                         is_train=False, advice=False, fuse=False)
    bad = _find(report, "MXG017")
    assert bad and bad[0].severity == "error", str(report)
    assert bad[0].node == "fc"                 # the peak position
    assert bad[0].advice["peak_bytes"] == 240
    assert bad[0].advice["budget_bytes"] == 100
    assert "fc" in bad[0].message and "breakdown" in bad[0].message
    with pytest.raises(Exception):
        report.raise_if_errors("test")


def test_mxg017_within_budget_is_silent():
    report = Report()
    memlive.check_memory(_tiny(), shapes={"data": (2, 8)},
                         report=report, budget_bytes=10**9,
                         is_train=False, advice=False, fuse=False)
    assert not _rules(report), str(report)


def test_mxg018_drift_fires_and_respects_tol():
    report = Report()
    memlive.check_memory(_tiny(), shapes={"data": (2, 8)},
                         report=report, is_train=False, advice=False,
                         fuse=False, plan_total=240 * 100, tol=0.5)
    bad = _find(report, "MXG018")
    assert bad and bad[0].advice["static_peak_bytes"] == 240
    assert bad[0].advice["plan_total_bytes"] == 24000
    # same drift inside a huge tolerance: silent
    report2 = Report()
    memlive.check_memory(_tiny(), shapes={"data": (2, 8)},
                         report=report2, is_train=False, advice=False,
                         fuse=False, plan_total=240 * 100, tol=1e6)
    assert not _find(report2, "MXG018")


def test_mxg019_remat_ranked_by_score():
    # fc's 32B residual costs 2*2*8*4 = 128 recompute FLOPs
    a = memlive.analyze(_tiny(), shapes={"data": (2, 8)},
                        is_train=True, n_slots=0, fuse=False)
    cands = a.remat_candidates()
    assert cands and cands[0]["node"] == "fc"
    assert cands[0]["bytes_freed"] == 32
    assert cands[0]["recompute_flops"] == 128
    scores = [c["score"] for c in cands]
    assert scores == sorted(scores, reverse=True)
    report = Report()
    memlive.check_memory(_tiny(), shapes={"data": (2, 8)},
                         report=report, is_train=True, n_slots=0,
                         fuse=False)
    hits = _find(report, "MXG019")
    assert hits and hits[0].node == "fc"
    assert hits[0].advice["kind"] == "remat"


def test_mxg020_zero_audit_replicated_slots():
    report = Report()
    memlive.check_memory(_tiny(), shapes={"data": (4, 8)},
                         report=report, is_train=True, n_slots=2,
                         mesh={"data": 4}, fuse=False)
    bad = _find(report, "MXG020")
    assert bad, str(report)
    adv = bad[0].advice
    assert adv["kind"] == "zero"
    # 2 slots x (128+16)B params = 288B replicated; 3/4 saved per rank
    assert adv["total_slot_bytes"] == 288
    assert adv["total_saving_per_rank"] == 216
    assert bad[0].node == "fc_weight"          # largest slot named
    # no data axis -> nothing to shard -> silent
    report2 = Report()
    memlive.check_memory(_tiny(), shapes={"data": (4, 8)},
                         report=report2, is_train=True, n_slots=2,
                         fuse=False)
    assert not _find(report2, "MXG020")


def test_mxg021_undonated_dead_input():
    report = Report()
    memlive.check_memory(_tiny(), shapes={"data": (2, 8)},
                         report=report, is_train=False, fuse=False)
    bad = _find(report, "MXG021")
    assert bad, str(report)
    assert bad[0].advice["input"] == "data"
    assert bad[0].advice["bytes"] == 64
    # donating it silences the finding
    report2 = Report()
    memlive.check_memory(_tiny(), shapes={"data": (2, 8)},
                         report=report2, is_train=False, fuse=False,
                         donate=("data",))
    assert not _find(report2, "MXG021")


def test_fusion_plan_removes_interior_edges():
    # with fusion on, interior edges of fused blocks never materialize:
    # the fused analysis can only be <= the unfused one, and whatever
    # it dropped is accounted in skipped_bytes
    from mxnet_tpu import models
    net = models.get_model("lenet", num_classes=10)
    shapes = {"data": (2, 1, 28, 28), "softmax_label": (2,)}
    unfused = memlive.analyze(net, shapes, is_train=True, fuse=False)
    fused = memlive.analyze(net, shapes, is_train=True, fuse=True)
    assert fused.peak_bytes <= unfused.peak_bytes
    assert fused.skipped_bytes > 0


# ------------------------------------------ verify() / bind wiring

def test_symbol_verify_memory_opt_in():
    net = _tiny()
    # plain verify: no memory rules at all
    report = net.verify(data=(2, 8))
    assert not any(r.startswith("MXG02") or r == "MXG017"
                   for r in _rules(report))
    # opt-in via the memory dict
    report = net.verify(data=(2, 8),
                        memory={"is_train": False, "advice": False,
                                "budget_bytes": 100, "fuse": False})
    assert _find(report, "MXG017"), str(report)


def test_bind_strict_memory_budget(monkeypatch):
    import mxnet_tpu as mx
    from mxnet_tpu.base import MXNetError
    # a 100-byte HBM "device": the strict bind must reject the graph
    # at bind time, before any compile, naming the peak
    monkeypatch.setenv("MXNET_TPU_HBM_LIMIT_BYTES", "100")
    net = _tiny()
    with pytest.raises(MXNetError, match="MXG017"):
        net.simple_bind(mx.cpu(), grad_req="null", strict=True,
                        data=(2, 8))
    # without the budget signal the same strict bind stays green
    monkeypatch.delenv("MXNET_TPU_HBM_LIMIT_BYTES")
    net.simple_bind(mx.cpu(), grad_req="null", strict=True,
                    data=(2, 8))


# ------------------------------------------- drift regression (MXG018)

@pytest.mark.slow
def test_static_matches_xla_plan_mlp():
    """Satellite 3: the static predictor and the XLA memory_analysis
    agree within MXNET_TPU_MEMLIVE_TOL on a real zoo model, and the
    telemetry drift gauge carries the residual."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import models
    from mxnet_tpu.symbol import eval_graph, _classify_vars
    from mxnet_tpu.analysis.verifier import (_topo_from_entries,
                                             _shape_pass)
    from mxnet_tpu.telemetry import memory as tmem

    net = models.get_model("mlp", num_classes=10)
    shapes = {"data": (2, 784), "softmax_label": (2,)}
    topo = _topo_from_entries(net._entries)
    arg_shapes, structs = _shape_pass(net, topo, shapes, {}, Report())
    args_v, aux_v = _classify_vars(topo)
    avals = {id(n): jax.ShapeDtypeStruct(tuple(arg_shapes[n.name]),
                                         jnp.float32)
             for n in args_v + aux_v}

    def fwd(vals):
        outs, _ = eval_graph(topo, net._entries, vals, is_train=False)
        return outs

    compiled = jax.jit(fwd).lower(avals).compile()
    plan = tmem.plan_of(compiled, "test_memlive.mlp")
    assert plan.total_bytes > 0

    report = Report()
    analysis = memlive.check_memory(
        net, shapes, report=report, is_train=False, advice=False,
        plan_total=plan, topo=topo, structs=structs,
        record=True, program="test_memlive.mlp")
    assert not _find(report, "MXG018"), str(report)
    drift = abs(analysis.peak_bytes - plan.total_bytes) \
        / float(plan.total_bytes)
    assert drift <= memlive.memlive_tolerance()
    # the static-prediction slot and gauge side of the dedup
    rec = tmem.static_prediction("test_memlive.mlp")
    assert rec and rec["peak_bytes"] == analysis.peak_bytes


# --------------------------------------------------- CLI + mem_top

@pytest.mark.slow
def test_mem_top_json_advice_records():
    """Acceptance: an over-budget model's mem_top --json carries at
    least one ranked remat candidate and one ZeRO advice record."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mem_top.py"),
         "--model", "mlp", "--mesh", "data=8", "--opt-slots", "2",
         "--budget", "1000000", "--json"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 1, out.stderr      # over budget
    doc = json.loads(out.stdout)
    assert doc["schema"] == "mxtpu-memtop/1"
    assert doc["over_budget"] is True
    kinds = {r["kind"] for r in doc["advice"]}
    assert "remat" in kinds and "zero" in kinds
    assert doc["buffers"] and doc["live_at_peak"]
    # worst-liveness-first: byte-steps non-increasing
    steps = [b["byte_steps"] for b in doc["buffers"]]
    assert steps == sorted(steps, reverse=True)


def test_mem_top_usage_error():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mem_top.py")],
        capture_output=True, text=True)
    assert out.returncode == 2
    assert "exactly one of" in out.stderr
