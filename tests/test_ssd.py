"""SSD symbol + contrib MultiBox ops end-to-end — reference example/ssd +
tests for src/operator/contrib/multibox_*.cc."""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "examples", "ssd", "symbol"))

import mxnet_tpu as mx


def test_multibox_prior_shapes():
    x = mx.nd.zeros((1, 8, 4, 4))
    anchors = mx.contrib.nd.MultiBoxPrior(x, sizes=(0.5, 0.25),
                                          ratios=(1.0, 2.0))
    # (1, num_anchors, 4); 4x4 grid x (2 sizes + 2 ratios - 1)
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    a = anchors.asnumpy()
    assert (a >= -0.5).all() and (a <= 1.5).all()


def test_multibox_target_and_detection():
    rng = np.random.RandomState(0)
    num_anchors, num_classes = 20, 3
    anchor = mx.nd.array(
        np.clip(np.sort(rng.rand(1, num_anchors, 4), axis=-1), 0, 1))
    # one gt box: class 1
    label = mx.nd.array(np.array(
        [[[1, 0.1, 0.1, 0.5, 0.5], [-1, 0, 0, 0, 0]]], np.float32))
    cls_pred = mx.nd.array(rng.rand(1, num_classes + 1, num_anchors))
    out = mx.contrib.nd.MultiBoxTarget(anchor, label, cls_pred)
    loc_target, loc_mask, cls_target = out
    assert loc_target.shape == (1, num_anchors * 4)
    assert cls_target.shape == (1, num_anchors)

    cls_prob = mx.nd.array(rng.rand(1, num_classes + 1, num_anchors))
    loc_pred = mx.nd.array(rng.rand(1, num_anchors * 4) * 0.1)
    det = mx.contrib.nd.MultiBoxDetection(cls_prob, loc_pred, anchor)
    assert det.shape[0] == 1 and det.shape[2] == 6


@pytest.mark.slow
def test_ssd_train_forward_backward():
    import ssd_vgg16
    net = ssd_vgg16.get_symbol_train(num_classes=4)
    ex = net.simple_bind(mx.cpu(), grad_req="write",
                         data=(1, 3, 128, 128), label=(1, 3, 5))
    init = mx.initializer.Xavier()
    for k, v in ex.arg_dict.items():
        if k not in ("data", "label"):
            init(k, v)
    rng = np.random.RandomState(0)
    x = rng.rand(1, 3, 128, 128).astype(np.float32)
    lab = np.array([[[1, 0.2, 0.2, 0.6, 0.6],
                     [2, 0.5, 0.5, 0.9, 0.9],
                     [-1, 0, 0, 0, 0]]], np.float32)
    ex.forward(is_train=True, data=x, label=lab)
    outs = [o.asnumpy() for o in ex.outputs]
    assert all(np.isfinite(o).all() for o in outs)
    ex.backward()
    g = ex.grad_dict["conv1_1_weight"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
