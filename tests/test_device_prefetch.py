"""DevicePrefetchIter semantics (reference iter_prefetcher.h role).

Perf on the bench host is documented in docs/perf.md (the tunnel is
the cap there); these tests pin the CONTRACT: staged batches match the
wrapped iterator's batches in order, epochs end with StopIteration,
reset restarts cleanly even when the sentinel was already consumed,
and worker-thread errors surface on the consumer."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _iter(n=24, batch=4):
    x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    y = np.arange(n, dtype=np.float32)
    return mx.io.NDArrayIter(x, y, batch_size=batch,
                             label_name="softmax_label")


def _stage(host_dict):
    # stand-in for ShardedTrainer.put_batch: device arrays per input
    import jax.numpy as jnp
    return {k: jnp.asarray(v) for k, v in host_dict.items()}


def test_prefetch_order_and_epochs():
    it = _iter()
    pre = mx.io.DevicePrefetchIter(it, _stage, depth=2)
    for epoch in range(3):
        got = [np.asarray(b["data"])[0, 0] for b in pre]
        assert got == [0.0, 12.0, 24.0, 36.0, 48.0, 60.0], (epoch, got)
        pre.reset()


def test_prefetch_reset_mid_epoch():
    pre = mx.io.DevicePrefetchIter(_iter(), _stage, depth=2)
    next(pre)
    next(pre)
    pre.reset()          # worker may be blocked on a full queue here
    got = [np.asarray(b["data"])[0, 0] for b in pre]
    assert got[0] == 0.0 and len(got) == 6, got


def test_prefetch_propagates_worker_errors():
    def bad_stage(host_dict):
        raise RuntimeError("staging exploded")
    pre = mx.io.DevicePrefetchIter(_iter(), bad_stage, depth=2)
    with pytest.raises(RuntimeError, match="staging exploded"):
        next(pre)
    # exhausted after the error: iterator protocol, no hang
    with pytest.raises(StopIteration):
        next(pre)


def test_prefetch_exhaustion_is_sticky():
    pre = mx.io.DevicePrefetchIter(_iter(), _stage, depth=2)
    list(pre)
    with pytest.raises(StopIteration):
        next(pre)          # probing again must not hang


def test_prefetch_none_and_tuple_payloads():
    """stage_fn return values are opaque: None and tuples pass through."""
    pre = mx.io.DevicePrefetchIter(_iter(), lambda d: None, depth=2)
    assert [b for b in pre] == [None] * 6
    pre2 = mx.io.DevicePrefetchIter(
        _iter(), lambda d: ("x", d["data"]), depth=2)
    got = list(pre2)
    assert len(got) == 6 and all(g[0] == "x" for g in got)


def test_prefetch_reset_reraises_unseen_worker_error():
    hits = []

    def flaky(d):
        hits.append(1)
        if len(hits) == 2:
            raise RuntimeError("corrupt record")
        return d
    pre = mx.io.DevicePrefetchIter(_iter(), flaky, depth=1)
    next(pre)
    # reset() cancels pending work by design, so a not-yet-raised error
    # may legitimately vanish — wait until the worker has actually hit
    # the failure (thread exit) before asserting reset re-raises it
    pre._thread.join(timeout=5)
    assert not pre._thread.is_alive(), "worker never hit the failure"
    with pytest.raises(RuntimeError, match="corrupt record"):
        pre.reset()


def test_tunnel_warning_emitted(monkeypatch, caplog):
    """VERDICT r4 weak #8: enabling the device queue on a tunnel-
    limited host must warn (measured 0.63x there, docs/perf.md)."""
    import logging
    monkeypatch.setattr(mx.io, "tunnel_limited_backend", lambda: True)
    with caplog.at_level(logging.WARNING):
        pre = mx.io.DevicePrefetchIter(_iter(), _stage, depth=2)
        list(pre)
    assert any("tunnel-limited" in r.message for r in caplog.records)


def test_fused_fit_device_queue_parity(tmp_path):
    """VERDICT r4 #4: the fused fit loop trains identically with the
    double-buffered device queue on and off (real-data path)."""
    import argparse
    import os
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(__file__), "..", "examples",
        "image_classification"))
    from common import fit as fit_mod

    protos = np.random.RandomState(42).rand(10, 16).astype("f")

    def loader(args, kv):
        r = np.random.RandomState(0)
        y = r.randint(0, 10, 320)
        x = (protos[y] + r.randn(320, 16).astype("f") * 0.2).astype("f")
        train = mx.io.NDArrayIter(x, y.astype("f"), args.batch_size,
                                  label_name="softmax_label")
        return train, None

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    weights = {}
    for queue in (0, 1):
        mx.random.seed(5)
        np.random.seed(5)
        args = argparse.Namespace(
            network="mlp", num_layers=None, gpus=None, tpus=None,
            kv_store="local", num_epochs=2, lr=0.3, lr_factor=0.1,
            lr_step_epochs="", optimizer="sgd", mom=0.9, wd=1e-4,
            batch_size=32, disp_batches=0, model_prefix=None,
            load_epoch=None, top_k=0, data_nthreads=1, test_io=0,
            monitor=0, fused=1, dtype="float32", num_examples=320,
            device_queue=queue)
        trainer = fit_mod.fit(args, net, loader)
        weights[queue] = np.asarray(trainer.params["fc1_weight"])
    np.testing.assert_allclose(weights[0], weights[1], rtol=1e-6)
