"""DevicePrefetchIter semantics (reference iter_prefetcher.h role).

Perf on the bench host is documented in docs/perf.md (the tunnel is
the cap there); these tests pin the CONTRACT: staged batches match the
wrapped iterator's batches in order, epochs end with StopIteration,
reset restarts cleanly even when the sentinel was already consumed,
and worker-thread errors surface on the consumer."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _iter(n=24, batch=4):
    x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    y = np.arange(n, dtype=np.float32)
    return mx.io.NDArrayIter(x, y, batch_size=batch,
                             label_name="softmax_label")


def _stage(host_dict):
    # stand-in for ShardedTrainer.put_batch: device arrays per input
    import jax.numpy as jnp
    return {k: jnp.asarray(v) for k, v in host_dict.items()}


def test_prefetch_order_and_epochs():
    it = _iter()
    pre = mx.io.DevicePrefetchIter(it, _stage, depth=2)
    for epoch in range(3):
        got = [np.asarray(b["data"])[0, 0] for b in pre]
        assert got == [0.0, 12.0, 24.0, 36.0, 48.0, 60.0], (epoch, got)
        pre.reset()


def test_prefetch_reset_mid_epoch():
    pre = mx.io.DevicePrefetchIter(_iter(), _stage, depth=2)
    next(pre)
    next(pre)
    pre.reset()          # worker may be blocked on a full queue here
    got = [np.asarray(b["data"])[0, 0] for b in pre]
    assert got[0] == 0.0 and len(got) == 6, got


def test_prefetch_propagates_worker_errors():
    def bad_stage(host_dict):
        raise RuntimeError("staging exploded")
    pre = mx.io.DevicePrefetchIter(_iter(), bad_stage, depth=2)
    with pytest.raises(RuntimeError, match="staging exploded"):
        next(pre)
    # exhausted after the error: iterator protocol, no hang
    with pytest.raises(StopIteration):
        next(pre)


def test_prefetch_exhaustion_is_sticky():
    pre = mx.io.DevicePrefetchIter(_iter(), _stage, depth=2)
    list(pre)
    with pytest.raises(StopIteration):
        next(pre)          # probing again must not hang


def test_prefetch_none_and_tuple_payloads():
    """stage_fn return values are opaque: None and tuples pass through."""
    pre = mx.io.DevicePrefetchIter(_iter(), lambda d: None, depth=2)
    assert [b for b in pre] == [None] * 6
    pre2 = mx.io.DevicePrefetchIter(
        _iter(), lambda d: ("x", d["data"]), depth=2)
    got = list(pre2)
    assert len(got) == 6 and all(g[0] == "x" for g in got)


def test_prefetch_reset_reraises_unseen_worker_error():
    hits = []

    def flaky(d):
        hits.append(1)
        if len(hits) == 2:
            raise RuntimeError("corrupt record")
        return d
    pre = mx.io.DevicePrefetchIter(_iter(), flaky, depth=1)
    next(pre)
    # reset() cancels pending work by design, so a not-yet-raised error
    # may legitimately vanish — wait until the worker has actually hit
    # the failure (thread exit) before asserting reset re-raises it
    pre._thread.join(timeout=5)
    assert not pre._thread.is_alive(), "worker never hit the failure"
    with pytest.raises(RuntimeError, match="corrupt record"):
        pre.reset()
