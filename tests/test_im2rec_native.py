"""Native C++ im2rec packer (tools/im2rec.cc) round-trip.

Reference role: tools/im2rec.cc (the C++ packer next to the python
twin).  The test writes JPEGs + a reference-format .lst, packs with the
native tool, and proves the output is byte-compatible with this
framework's readers: python MXRecordIO/unpack sees identical headers
and payloads as a python-packed file, and the native ImageRecordIter
trains-reads the file end to end."""
import os
import shutil
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


def _build(tmp_path):
    exe = str(tmp_path / "im2rec")
    subprocess.run(["g++", "-O2", "-std=c++17",
                    os.path.join(ROOT, "tools", "im2rec.cc"), "-o", exe],
                   check=True, capture_output=True)
    return exe


def _make_images(tmp_path, n=12, size=64):
    from PIL import Image
    rng = np.random.RandomState(0)
    lst_lines = []
    for i in range(n):
        arr = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
        name = "img_%d.jpg" % i
        Image.fromarray(arr).save(str(tmp_path / name), quality=90)
        lst_lines.append("%d\t%d\t%s" % (i, i % 4, name))
    (tmp_path / "list.lst").write_text("\n".join(lst_lines) + "\n")
    return n


def test_native_packer_matches_python_packer(tmp_path):
    exe = _build(tmp_path)
    n = _make_images(tmp_path)
    rec_native = str(tmp_path / "native.rec")
    res = subprocess.run(
        [exe, str(tmp_path / "list.lst"), str(tmp_path), rec_native,
         "--index"], capture_output=True, text=True)
    assert res.returncode == 0, res.stderr

    # python twin over the same list
    rec_py = str(tmp_path / "python.rec")
    w = mx.recordio.MXRecordIO(rec_py, "w")
    for line in (tmp_path / "list.lst").read_text().splitlines():
        idx, label, name = line.split("\t")
        jpg = (tmp_path / name).read_bytes()
        w.write(mx.recordio.pack(
            mx.recordio.IRHeader(0, float(label), int(idx), 0), jpg))
    w.close()

    ra = mx.recordio.MXRecordIO(rec_native, "r")
    rb = mx.recordio.MXRecordIO(rec_py, "r")
    for _ in range(n):
        a, b = ra.read(), rb.read()
        assert a == b          # byte-identical record payloads
    assert ra.read() is None and rb.read() is None

    # the .idx positions drive MXIndexedRecordIO
    ir = mx.recordio.MXIndexedRecordIO(
        rec_native.replace(".rec", ".idx"), rec_native, "r")
    hdr, payload = mx.recordio.unpack(ir.read_idx(7))
    assert hdr.id == 7 and hdr.label == 3.0
    assert payload[:2] == b"\xff\xd8"      # JPEG SOI


def test_native_packer_magic_split_and_multilabel(tmp_path):
    """The continuation-record framing (payload containing the aligned
    magic word) and the multi-label flag=N path, checked byte-for-byte
    against the python packer."""
    import struct
    exe = _build(tmp_path)
    magic = struct.pack("<I", 0xced7230a)
    # 24-byte IRHeader precedes the file bytes, so a 4-aligned offset
    # in the file is 4-aligned in the record payload too
    tricky = b"A" * 8 + magic + b"B" * 5 + magic + b"C" * 7
    (tmp_path / "t0.bin").write_bytes(tricky)
    (tmp_path / "t1.bin").write_bytes(b"plain payload!")
    (tmp_path / "list.lst").write_text(
        "0\t1.0\t2.5\t3.0\tt0.bin\n"      # 3 labels -> flag=3 array
        "1\t7.0\tt1.bin\n")
    rec_native = str(tmp_path / "native.rec")
    res = subprocess.run([exe, str(tmp_path / "list.lst"),
                          str(tmp_path), rec_native],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr

    rec_py = str(tmp_path / "python.rec")
    w = mx.recordio.MXRecordIO(rec_py, "w")
    w.write(mx.recordio.pack(
        mx.recordio.IRHeader(0, [1.0, 2.5, 3.0], 0, 0), tricky))
    w.write(mx.recordio.pack(
        mx.recordio.IRHeader(0, 7.0, 1, 0), b"plain payload!"))
    w.close()
    assert (tmp_path / "native.rec").read_bytes() == \
        (tmp_path / "python.rec").read_bytes()

    r = mx.recordio.MXRecordIO(rec_native, "r")
    hdr, payload = mx.recordio.unpack(r.read())
    assert payload == tricky                      # magic round-trips
    np.testing.assert_allclose(hdr.label, [1.0, 2.5, 3.0])
    hdr2, payload2 = mx.recordio.unpack(r.read())
    assert hdr2.label == 7.0 and payload2 == b"plain payload!"


def test_native_packer_feeds_image_record_iter(tmp_path):
    exe = _build(tmp_path)
    n = _make_images(tmp_path)
    rec = str(tmp_path / "native.rec")
    subprocess.run([exe, str(tmp_path / "list.lst"), str(tmp_path), rec],
                   check=True, capture_output=True)
    it = mx.io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                               batch_size=4, preprocess_threads=1)
    seen = 0
    labels = []
    for batch in it:
        seen += batch.data[0].shape[0]
        labels.extend(batch.label[0].asnumpy().tolist())
    assert seen == n
    assert sorted(set(labels)) == [0.0, 1.0, 2.0, 3.0]
