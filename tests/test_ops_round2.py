"""Ops closing the remaining reference registration sites: pick,
softmax_cross_entropy, IdentityAttachKLSparseReg, LSoftmax.

Reference: tensor/broadcast_reduce_op_index.cc (pick),
loss_binary_op.cc (softmax_cross_entropy),
identity_attach_KL_sparse_reg-inl.h, lsoftmax.cc/.cu.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import check_numeric_gradient

RNG = np.random.RandomState(5)


def test_pick_forward_and_clip():
    x = RNG.randn(4, 5).astype("f")
    i = np.array([0, 4, 2, 9], "f")  # 9 clips to 4
    out = mx.nd.pick(mx.nd.array(x), mx.nd.array(i)).asnumpy()
    want = x[np.arange(4), np.clip(i.astype(int), 0, 4)]
    np.testing.assert_allclose(out, want, rtol=1e-6)
    out2 = mx.nd.pick(mx.nd.array(x), mx.nd.array(i), keepdims=True)
    assert out2.shape == (4, 1)
    # 3-d with axis 1
    x3 = RNG.randn(2, 3, 4).astype("f")
    i3 = RNG.randint(0, 3, (2, 4)).astype("f")
    out3 = mx.nd.pick(mx.nd.array(x3), mx.nd.array(i3), axis=1).asnumpy()
    want3 = np.take_along_axis(x3, i3.astype(int)[:, None, :], axis=1)[:, 0]
    np.testing.assert_allclose(out3, want3, rtol=1e-6)


def test_pick_grad():
    """Gradient scatters into the picked slots (data input only — the
    integer index input is non-differentiable)."""
    x = RNG.randn(3, 4).astype("f")
    i = np.array([1, 3, 0], "f")
    data = nd.array(x)
    idx = nd.array(i)
    g = nd.zeros_like(data)
    autograd.mark_variables([data], [g])
    with autograd.record():
        loss = nd.pick(data, idx).sum()
    autograd.backward([loss])
    want = np.zeros_like(x)
    want[np.arange(3), i.astype(int)] = 1.0
    np.testing.assert_allclose(g.asnumpy(), want, rtol=1e-6)


def test_softmax_cross_entropy():
    x = RNG.randn(4, 6).astype("f")
    y = np.array([1, 5, 0, 3], "f")
    out = float(mx.nd.softmax_cross_entropy(
        mx.nd.array(x), mx.nd.array(y)).asnumpy()[0])
    e = np.exp(x - x.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    want = -np.sum(np.log(p[np.arange(4), y.astype(int)]))
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_kl_sparse_reg_identity_and_penalty():
    x = RNG.uniform(0.05, 0.95, (8, 6)).astype("f")
    data = nd.array(x)
    g = nd.zeros_like(data)
    autograd.mark_variables([data], [g])
    with autograd.record():
        out = nd.IdentityAttachKLSparseReg(
            data, nd.zeros((6,)), sparseness_target=0.2, penalty=0.01,
            momentum=0.0)
        loss = out.sum()
    np.testing.assert_allclose(out.asnumpy(), x, rtol=1e-6)  # identity fwd
    autograd.backward([loss])
    # momentum 0 -> moving avg = batch mean; grad = 1 + penalty*KL'
    ma = x.mean(axis=0)
    reg = 0.01 * (-0.2 / ma + 0.8 / (1 - ma))
    np.testing.assert_allclose(
        g.asnumpy(), np.broadcast_to(1.0 + reg[None, :], x.shape),
        rtol=1e-4, atol=1e-5)


def test_lsoftmax_margin1_is_linear_and_margin_penalizes():
    x = RNG.randn(5, 8).astype("f")
    w = RNG.randn(4, 8).astype("f")
    y = np.array([0, 1, 2, 3, 1], "f")
    plain = mx.nd.LSoftmax(mx.nd.array(x), mx.nd.array(w), mx.nd.array(y),
                           num_hidden=4, margin=1).asnumpy()
    np.testing.assert_allclose(plain, x @ w.T, rtol=1e-5)
    # training-mode margin=2/beta=0: label-class logit is psi(theta) scaled,
    # always <= the plain inner product
    from mxnet_tpu.ops.registry import OpContext, get_op
    import jax.numpy as jnp
    op = get_op("LSoftmax")
    attrs = op.parse_attrs({"num_hidden": 4, "margin": 2, "beta": 0.0})
    out = np.asarray(op.fcompute(attrs, OpContext(is_train=True),
                                 jnp.asarray(x), jnp.asarray(w),
                                 jnp.asarray(y)))
    yi = y.astype(int)
    assert (out[np.arange(5), yi] <= plain[np.arange(5), yi] + 1e-5).all()
    others = np.ones_like(plain, bool)
    others[np.arange(5), yi] = False
    np.testing.assert_allclose(out[others], plain[others], rtol=1e-5)
    # psi identity check: psi = (-1)^k cos(2t) - 2k reproduces the output
    xn = np.linalg.norm(x, axis=1)
    wn = np.linalg.norm(w[yi], axis=1)
    cos = (x @ w.T)[np.arange(5), yi] / (xn * wn)
    t = np.arccos(np.clip(cos, -1, 1))
    k = (t > np.pi / 2).astype(int)
    psi = ((-1.0) ** k) * np.cos(2 * t) - 2 * k
    np.testing.assert_allclose(out[np.arange(5), yi], xn * wn * psi,
                               rtol=1e-4)
