"""_contrib_RingAttention as a framework operator: single-device
fallback parity, sequence-parallel trainer parity over the virtual
mesh, and the sequence-parallel transformer example.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel import ShardedTrainer, build_mesh


def test_ring_op_single_device_matches_flash():
    """Without an active sequence_parallel context the op IS plain
    attention — identical to _contrib_FlashAttention."""
    rng = np.random.RandomState(0)
    q = mx.nd.array(rng.randn(2, 16, 2, 8).astype("f"))
    k = mx.nd.array(rng.randn(2, 16, 2, 8).astype("f"))
    v = mx.nd.array(rng.randn(2, 16, 2, 8).astype("f"))
    for causal in (False, True):
        a = mx.nd._contrib_RingAttention(q, k, v, causal=causal)
        b = mx.nd._contrib_FlashAttention(q, k, v, causal=causal)
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                   rtol=1e-5, atol=1e-5)


def _ring_lm(seq, vocab, d=16, heads=2):
    """Tiny causal LM around _contrib_RingAttention."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    x = mx.sym.Embedding(data, input_dim=vocab, output_dim=d,
                         name="embed")
    h = mx.sym.LayerNorm(x, name="ln1")
    qkv = mx.sym.FullyConnected(h, num_hidden=3 * d, flatten=False,
                                name="qkv")
    qkv = mx.sym.Reshape(qkv, shape=(0, 0, 3, heads, -1))
    q = mx.sym.Reshape(mx.sym.slice_axis(qkv, axis=2, begin=0, end=1),
                       shape=(0, 0, -3, -2))
    k = mx.sym.Reshape(mx.sym.slice_axis(qkv, axis=2, begin=1, end=2),
                       shape=(0, 0, -3, -2))
    v = mx.sym.Reshape(mx.sym.slice_axis(qkv, axis=2, begin=2, end=3),
                       shape=(0, 0, -3, -2))
    att = mx.sym._contrib_RingAttention(q, k, v, causal=True,
                                        name="attn")
    att = mx.sym.Reshape(att, shape=(0, 0, -3))
    x = x + mx.sym.FullyConnected(att, num_hidden=d, flatten=False,
                                  name="proj")
    x = mx.sym.LayerNorm(x, name="ln_f")
    x = mx.sym.Reshape(x, shape=(-1, d))
    logits = mx.sym.FullyConnected(x, num_hidden=vocab, name="head")
    label = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(logits, label=label, name="softmax")


def _batch(bsz, seq, vocab, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(0, vocab, (bsz, seq)).astype("f")
    return {"data": x, "softmax_label": x.copy()}


def test_sequence_parallel_trainer_matches_single_device():
    """Training with the sequence sharded 4 ways == single-device,
    step for step (the ring schedule is numerically the same attention)."""
    bsz, seq, vocab = 4, 16, 16

    def make(sp):
        np.random.seed(41)
        return ShardedTrainer(
            _ring_lm(seq, vocab), build_mesh(n_devices=sp, tp=sp),
            data_shapes={"data": (bsz, seq)},
            label_shapes={"softmax_label": (bsz, seq)},
            learning_rate=0.05, momentum=0.9, seed=13,
            sequence_parallel=sp > 1)

    a, b = make(1), make(4)
    for i in range(2):
        batch = _batch(bsz, seq, vocab, seed=i)
        la, lb = float(a.step(batch)), float(b.step(batch))
        assert np.isclose(la, lb, rtol=2e-4), (la, lb)
    for name in a.params:
        np.testing.assert_allclose(
            np.asarray(a.params[name]), np.asarray(b.params[name]),
            rtol=5e-4, atol=5e-5, err_msg=name)


def test_sequence_parallel_requires_model_axis():
    with pytest.raises(mx.base.MXNetError, match="model"):
        ShardedTrainer(
            _ring_lm(16, 16), build_mesh(n_devices=2, tp=1),
            data_shapes={"data": (4, 16)},
            label_shapes={"softmax_label": (4, 16)},
            sequence_parallel=True)


def test_sequence_parallel_example_converges():
    """The dp x sp transformer example (examples/transformer) descends
    on the Markov corpus with the sequence sharded over the mesh."""
    import os
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(__file__), "..", "examples", "transformer"))
    import train_lm

    first, last = train_lm.train_sequence_parallel(
        sp=4, steps=40, batch_size=8, seq_len=32, vocab_size=16,
        d_model=32, n_heads=2, n_layers=1)
    assert np.isfinite(last)
    assert last < first * 0.8, (first, last)
