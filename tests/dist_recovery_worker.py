"""Worker for the kill-a-worker recovery test (VERDICT r3 #5).

Reference behavior being matched: ps-lite heartbeats detect a dead
node and the job surfaces a failure (src/kvstore/kvstore_dist.h:39-80);
recovery is restart-from-checkpoint.  Here the fused multi-host path
trains with periodic rank-0 checkpoints; in ``crash`` mode one rank
SIGKILLs itself mid-run — the launcher (tools/launch.py supervision)
must tear the job down with a clear error — and in ``resume`` mode a
fresh job loads the last complete checkpoint and trains on to a loss
threshold, proving the checkpoint/resume recovery story end to end.
"""
import glob
import json
import os
import re
import signal
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.parallel import ShardedTrainer, build_mesh, multihost  # noqa: E402

GBATCH = 64
STEPS = 14
CKPT_EVERY = 3
_PROTOS = np.random.RandomState(42).rand(10, 64).astype("f")


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=10)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _batch(step):
    rng = np.random.RandomState(500 + step)
    y = rng.randint(0, 10, GBATCH)
    x = (_PROTOS[y] + rng.randn(GBATCH, 64) * 0.2).astype("f")
    return x, y.astype("f")


def _build(mesh):
    np.random.seed(11)
    return ShardedTrainer(
        _mlp(), mesh,
        data_shapes={"data": (GBATCH, 64)},
        label_shapes={"softmax_label": (GBATCH,)},
        learning_rate=0.15, momentum=0.9, seed=5)


def _latest_epoch(prefix):
    eps = []
    for f in glob.glob(prefix + "-*.params"):
        m = re.search(r"-(\d{4})\.params$", f)
        # only checkpoints whose .states also landed are complete
        if m and os.path.exists("%s-%s.states" % (prefix, m.group(1))):
            eps.append(int(m.group(1)))
    return max(eps) if eps else None


def main():
    # crash: SIGKILL one rank mid-run (launcher must tear the job down)
    # resume: load the last complete checkpoint, finish training
    # auto: the watchdog-restart path — resume from the latest verified
    #       checkpoint if one exists, and crash only on the FIRST
    #       launch attempt (MXNET_TPU_RESTART_COUNT=0); the restarted
    #       job trains to completion
    mode = os.environ["RECOVERY_MODE"]          # crash | resume | auto
    prefix = os.environ["RECOVERY_CKPT"]
    kill_rank = int(os.environ.get("KILL_RANK", "1"))
    kill_step = int(os.environ.get("KILL_STEP", "7"))
    restart_count = int(os.environ.get("MXNET_TPU_RESTART_COUNT", "0"))

    multihost.ensure_initialized()
    import jax

    rank, nproc = jax.process_index(), jax.process_count()
    mesh = build_mesh(devices=jax.devices(),
                      axis_names=("data", "model"), tp=1)
    trainer = _build(mesh)

    start = 0
    if mode == "resume":
        ep = _latest_epoch(prefix)
        assert ep is not None, "no complete checkpoint to resume from"
        trainer.load_checkpoint(prefix, ep, load_optimizer_states=True)
        start = ep
    elif mode == "auto":
        ep = trainer.load_latest_checkpoint(prefix,
                                            load_optimizer_states=True)
        if ep is not None:
            start = ep

    may_kill = mode == "crash" or (mode == "auto" and restart_count == 0)

    def shard(a):
        per = GBATCH // nproc
        return a[rank * per:(rank + 1) * per]

    losses = []
    for step in range(start, STEPS):
        x, y = _batch(step)
        loss = float(trainer.step({"data": shard(x),
                                   "softmax_label": shard(y)}))
        losses.append(loss)
        done = step + 1
        if done % CKPT_EVERY == 0 and done < STEPS:
            trainer.save_checkpoint(prefix, done,
                                    save_optimizer_states=True)
        if may_kill and rank == kill_rank and done == kill_step:
            sys.stderr.write("worker %d: simulating node failure "
                             "(SIGKILL self) at step %d\n" % (rank, done))
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGKILL)

    assert losses[-1] < 0.35, losses
    multihost.process_barrier("recovery_done")
    print("recovery worker %d/%d OK mode=%s start=%d losses=%s"
          % (rank, nproc, mode, start, json.dumps(losses)))


if __name__ == "__main__":
    main()
