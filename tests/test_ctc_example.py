"""LSTM+CTC toy OCR converges (reference example/warpctc/lstm_ocr.py
role: CTC-aligned sequence recognition through the Module API)."""
import logging
import os
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "examples", "ctc"))


@pytest.mark.slow
def test_lstm_ctc_learns():
    import lstm_ocr
    logging.disable(logging.INFO)
    try:
        mod, acc = lstm_ocr.train(epochs=10, batch_size=32, n_train=384,
                                  lr=0.015)
    finally:
        logging.disable(logging.NOTSET)
    # an untrained decoder scores ~1e-4 exact-match on 4-digit
    # sequences; 0.3 is far outside chance while robust to
    # run-to-run optimization variance
    assert acc > 0.3, acc
