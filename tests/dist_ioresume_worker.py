"""Worker for the exactly-once data-plane CI gate (ISSUE 16).

Each rank consumes its :class:`~mxnet_tpu.io_resume.ShardedLedgerIter`
shard of ONE epoch through a tiny local trainer (no collectives — the
exactly-once property under test is a data-plane property, and this
repo's CPU jax cannot run cross-process collectives), logging every
consumed sample id per step to ``IORESUME_IDLOG.rank<r>``.  Rank 0
checkpoints every ``IORESUME_CKPT_EVERY`` steps; the manifest carries
the ledger's durable ``data_state``.

Phases (``IORESUME_PHASE``):

* ``train``  — EVERY rank SIGKILLs itself at ``IORESUME_KILL_STEP``
  (a fleet death mid-epoch, after at least one checkpoint landed).
* ``resume`` — runs at world size 1: ``load_latest_checkpoint``
  stashes the manifest ``data_state``, ``restore_data_iter`` remaps
  the rank-0-of-W cursor to rank-0-of-1 (the ``io.remap`` path), and
  the survivor consumes the REST of the epoch, logging ids the same
  way.  The CI stage (``tools/ci_check.py io_resume_check``) feeds
  both legs' logs to :class:`~mxnet_tpu.io_resume.SampleAccountant`:
  the union must be exactly one epoch, no drop, no double.
"""
import json
import os
import signal
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import io_resume as ior  # noqa: E402
from mxnet_tpu.parallel import ShardedTrainer, build_mesh  # noqa: E402
from mxnet_tpu.telemetry import ioview  # noqa: E402

N_SAMPLES = 96
BATCH = 8
SEED = 5
_PROTOS = np.random.RandomState(42).rand(10, 16).astype("f")


def _dataset():
    """Deterministic per-sample data: sample id i belongs to cluster
    i % 10 — every process derives the identical arrays."""
    labels = (np.arange(N_SAMPLES) % 10).astype("f")
    noise = np.random.RandomState(7).randn(N_SAMPLES, 16) * 0.2
    data = (_PROTOS[labels.astype(int)] + noise).astype("f")
    return data, labels


def _mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=10)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    phase = os.environ.get("IORESUME_PHASE", "train")
    prefix = os.environ["IORESUME_CKPT"]
    idlog = os.environ["IORESUME_IDLOG"]
    kill_step = int(os.environ.get("IORESUME_KILL_STEP", "5"))
    ckpt_every = int(os.environ.get("IORESUME_CKPT_EVERY", "2"))
    rank = int(os.environ.get("MXNET_TPU_PROCESS_ID", "0"))
    world = int(os.environ.get("MXNET_TPU_NUM_PROCESSES", "1"))

    data, labels = _dataset()
    it = ior.ShardedLedgerIter(data, labels, batch_size=BATCH,
                               seed=SEED, rank=rank, world=world)
    # the tracked iterator's state() rides every checkpoint manifest
    ioview.track(it)

    np.random.seed(11)
    trainer = ShardedTrainer(
        _mlp(), build_mesh(n_devices=1),
        data_shapes={"data": (BATCH, 16)},
        label_shapes={"softmax_label": (BATCH,)},
        learning_rate=0.1, momentum=0.9, seed=3)

    start = 0
    if phase == "resume":
        resumed = trainer.load_latest_checkpoint(
            prefix, load_optimizer_states=True)
        assert resumed is not None, "no checkpoint to resume from"
        entry = trainer.restore_data_iter(it)
        assert entry is not None, \
            "checkpoint manifest carried no data_state entry"
        start = int(resumed)
        sys.stderr.write("worker %d/%d resumed epoch %d at cursor %d\n"
                         % (rank, world, resumed, it.state()["cursor"]))

    log = open("%s.rank%d" % (idlog, rank), "a")
    step = start
    while True:
        try:
            batch = next(it)
        except StopIteration:
            break
        # log BEFORE the train step: a kill between consume and train
        # must count the batch as consumed (the checkpoint cursor the
        # accounting trusts was captured before these samples)
        log.write(json.dumps({"step": step, "phase": phase,
                              "ids": batch.index.tolist()}) + "\n")
        log.flush()
        trainer.step({"data": batch.data[0].asnumpy(),
                      "softmax_label": batch.label[0].asnumpy()})
        step += 1
        if phase == "train" and rank == 0 and step % ckpt_every == 0:
            trainer.save_checkpoint(prefix, step,
                                    save_optimizer_states=True)
        if phase == "train" and step == kill_step:
            sys.stderr.write("worker %d/%d: simulating fleet death "
                             "(SIGKILL self) at step %d\n"
                             % (rank, world, step))
            sys.stderr.flush()
            log.close()
            os.kill(os.getpid(), signal.SIGKILL)
    log.close()
    print("ioresume worker %d/%d OK phase=%s start=%d end=%d"
          % (rank, world, phase, start, step))


if __name__ == "__main__":
    main()
