"""The native C TRAINING ABI slice (src/c_train_api.cc): build the
library, export a toy MLP's symbol JSON and data from python, then run
the complete train loop — bind, set inputs, forward, backward, SGD
update, read outputs — from a C program, asserting that it LEARNS.

Reference roles: the MXExecutor* training subset of
include/mxnet/c_api.h and cpp-package/include/mxnet-cpp/executor.h
(the reference cpp-package trains; VERDICT r3 missing #1)."""
import os
import re
import shutil
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(ROOT, "src")

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [ROOT] + env.get("PYTHONPATH", "").split(os.pathsep))
    # the embedded interpreter must not grab the TPU tunnel in CI
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=5, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _digits(batch=40, dim=16, nclass=5):
    rng = np.random.RandomState(3)
    protos = rng.rand(nclass, dim).astype("f")
    y = rng.randint(0, nclass, batch)
    x = (protos[y] + rng.randn(batch, dim).astype("f") * 0.15).astype("f")
    return x, y.astype("f")


def _build(name, src_c, lib, outdir):
    subprocess.run(["make", lib + ".so"], cwd=SRC, check=True,
                   capture_output=True)
    exe = os.path.join(str(outdir), name)
    cc = ["gcc", "-O1", src_c, "-o", exe, "-L" + SRC,
          "-l" + lib.replace("lib", "", 1), "-Wl,-rpath," + SRC, "-lm"]
    subprocess.run(cc, check=True, capture_output=True)
    return exe


def test_c_train_loop_learns(tmp_path):
    exe = _build("c_train_test",
                 os.path.join(ROOT, "tests", "c_train_test.c"),
                 "libmxtpu_train", tmp_path)
    x, y = _digits()
    net = _mlp()
    sym_path = tmp_path / "net-symbol.json"
    net.save(str(sym_path))
    (tmp_path / "x.f32").write_bytes(x.tobytes())
    (tmp_path / "y.f32").write_bytes(y.tobytes())

    res = subprocess.run(
        [exe, str(sym_path), str(tmp_path / "x.f32"),
         str(tmp_path / "y.f32"), "40", "16", "5", "30"],
        capture_output=True, text=True, timeout=300, env=_env())
    assert res.returncode == 0, res.stdout + res.stderr
    m = re.search(r"first_loss=([\d.]+) last_loss=([\d.]+) "
                  r"acc=([\d.]+)", res.stdout)
    assert m, res.stdout
    first, last, acc = map(float, m.groups())
    assert last < 0.5 * first, res.stdout
    assert acc >= 0.95, res.stdout


def test_cpp_trainer_wrapper_learns(tmp_path):
    """The header-only C++ binding (cpp-package trainer.hpp) over the
    same ABI — the reference cpp-package's training role."""
    subprocess.run(["make", "libmxtpu_train.so"], cwd=SRC, check=True,
                   capture_output=True)
    exe = os.path.join(str(tmp_path), "train_cpp_test")
    subprocess.run(
        ["g++", "-O1", "-std=c++17",
         os.path.join(ROOT, "cpp-package", "example", "train_cpp.cc"),
         "-o", exe, "-I" + os.path.join(ROOT, "cpp-package", "include"),
         "-L" + SRC, "-lmxtpu_train", "-Wl,-rpath," + SRC],
        check=True, capture_output=True)
    x, y = _digits()
    net = _mlp()
    sym_path = tmp_path / "net-symbol.json"
    net.save(str(sym_path))
    (tmp_path / "x.f32").write_bytes(x.tobytes())
    (tmp_path / "y.f32").write_bytes(y.tobytes())
    res = subprocess.run(
        [exe, str(sym_path), str(tmp_path / "x.f32"),
         str(tmp_path / "y.f32"), "40", "16", "5"],
        capture_output=True, text=True, timeout=300, env=_env())
    assert res.returncode == 0, res.stdout + res.stderr
    assert "cpp-train OK" in res.stdout, res.stdout
