"""The python-howto walkthrough scripts run end to end.

Reference: example/python-howto/ (monitor_weights, multiple_outputs,
debug_conv, data_iter) — API walkthroughs, the one example-tail family
that is not dataset/Kaldi-bound (VERDICT r4 missing #5).
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples", "python_howto"))


def test_monitor_weights_runs_and_learns():
    import mxnet_tpu as mx
    import monitor_weights
    model = monitor_weights.main(num_epoch=10)
    x, y = monitor_weights.synthetic_digits(200, seed=2)
    it = mx.io.NDArrayIter(x, y, batch_size=100,
                           label_name="softmax_label")
    prob = model.predict(it)
    assert (np.asarray(prob).argmax(1) == y).mean() > 0.9


def test_multiple_outputs_group():
    import multiple_outputs
    group, executor = multiple_outputs.main()
    assert group.list_outputs() == ["fc1_output", "softmax_output"]
    fc1, sm = executor.outputs
    assert fc1.shape == (4, 128) and sm.shape == (4, 64)
    np.testing.assert_allclose(np.asarray(sm.asnumpy()).sum(1),
                               np.ones(4), rtol=1e-5)  # 64-way softmax


def test_debug_conv_monitor():
    import debug_conv
    res = debug_conv.main()
    assert res.shape == (1, 1, 5, 5)
    assert np.isfinite(res).all()


def test_data_iter_walkthrough():
    pytest.importorskip("PIL")
    import data_iter
    assert data_iter.main() >= 2
