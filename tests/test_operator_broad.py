"""Broad operator sweep: forward vs numpy + sampled numeric gradients.

Reference: tests/python/unittest/test_operator.py (3119 L) checks every op;
this file covers the families programmatically against numpy oracles.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient)

RNG = np.random.RandomState(7)

# ---- unary elementwise: (op, numpy fn, domain)
UNARY = [
    ("abs", np.abs, (-2, 2)),
    ("ceil", np.ceil, (-2, 2)),
    ("floor", np.floor, (-2, 2)),
    ("rint", np.rint, (-2, 2)),
    ("trunc", np.trunc, (-2, 2)),
    ("sign", np.sign, (-2, 2)),
    ("square", np.square, (-2, 2)),
    ("sqrt", np.sqrt, (0.1, 4)),
    ("rsqrt", lambda x: 1 / np.sqrt(x), (0.1, 4)),
    ("cbrt", np.cbrt, (0.1, 4)),
    ("rcbrt", lambda x: 1 / np.cbrt(x), (0.1, 4)),
    ("exp", np.exp, (-2, 2)),
    ("expm1", np.expm1, (-2, 2)),
    ("log", np.log, (0.1, 4)),
    ("log10", np.log10, (0.1, 4)),
    ("log2", np.log2, (0.1, 4)),
    ("log1p", np.log1p, (-0.5, 4)),
    ("sin", np.sin, (-3, 3)),
    ("cos", np.cos, (-3, 3)),
    ("tan", np.tan, (-1, 1)),
    ("arcsin", np.arcsin, (-0.9, 0.9)),
    ("arccos", np.arccos, (-0.9, 0.9)),
    ("arctan", np.arctan, (-3, 3)),
    ("sinh", np.sinh, (-2, 2)),
    ("cosh", np.cosh, (-2, 2)),
    ("tanh", np.tanh, (-2, 2)),
    ("arcsinh", np.arcsinh, (-2, 2)),
    ("arccosh", np.arccosh, (1.1, 4)),
    ("arctanh", np.arctanh, (-0.9, 0.9)),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)), (-3, 3)),
    ("relu", lambda x: np.maximum(x, 0), (-2, 2)),
    ("softsign", lambda x: x / (1 + np.abs(x)), (-2, 2)),
    ("reciprocal", lambda x: 1 / x, (0.5, 3)),
    ("negative", lambda x: -x, (-2, 2)),
    ("degrees", np.degrees, (-3, 3)),
    ("radians", np.radians, (-180, 180)),
    ("gamma", lambda x: np.vectorize(__import__("math").gamma)(x), (0.5, 4)),
    ("gammaln", lambda x: np.vectorize(__import__("math").lgamma)(x),
     (0.5, 4)),
    ("erf", lambda x: np.vectorize(__import__("math").erf)(x), (-2, 2)),
]


@pytest.mark.parametrize("name,fn,dom", UNARY, ids=[u[0] for u in UNARY])
def test_unary_forward(name, fn, dom):
    x = RNG.uniform(dom[0], dom[1], (3, 4)).astype(np.float32)
    out = getattr(mx.nd, name)(mx.nd.array(x))
    assert_almost_equal(out, fn(x), rtol=1e-4, atol=1e-5)


# ---- binary broadcast: (op, numpy fn)
BINARY = [
    ("broadcast_add", np.add),
    ("broadcast_sub", np.subtract),
    ("broadcast_mul", np.multiply),
    ("broadcast_div", np.divide),
    ("broadcast_power", np.power),
    ("broadcast_maximum", np.maximum),
    ("broadcast_minimum", np.minimum),
    ("broadcast_mod", np.mod),
    ("broadcast_hypot", np.hypot),
    ("broadcast_equal", lambda a, b: (a == b).astype(np.float32)),
    ("broadcast_not_equal", lambda a, b: (a != b).astype(np.float32)),
    ("broadcast_greater", lambda a, b: (a > b).astype(np.float32)),
    ("broadcast_greater_equal", lambda a, b: (a >= b).astype(np.float32)),
    ("broadcast_lesser", lambda a, b: (a < b).astype(np.float32)),
    ("broadcast_lesser_equal", lambda a, b: (a <= b).astype(np.float32)),
]


@pytest.mark.parametrize("name,fn", BINARY, ids=[b[0] for b in BINARY])
def test_binary_broadcast_forward(name, fn):
    a = RNG.uniform(0.5, 2.0, (2, 3, 4)).astype(np.float32)
    b = RNG.uniform(0.5, 2.0, (2, 1, 4)).astype(np.float32)
    out = getattr(mx.nd, name)(mx.nd.array(a), mx.nd.array(b))
    assert_almost_equal(out, fn(a, b), rtol=1e-4, atol=1e-5)


# ---- scalar ops
SCALAR = [
    ("_plus_scalar", lambda x, s: x + s),
    ("_minus_scalar", lambda x, s: x - s),
    ("_rminus_scalar", lambda x, s: s - x),
    ("_mul_scalar", lambda x, s: x * s),
    ("_div_scalar", lambda x, s: x / s),
    ("_rdiv_scalar", lambda x, s: s / x),
    ("_power_scalar", lambda x, s: x ** s),
    ("_rpower_scalar", lambda x, s: s ** x),
    ("_maximum_scalar", lambda x, s: np.maximum(x, s)),
    ("_minimum_scalar", lambda x, s: np.minimum(x, s)),
    ("_mod_scalar", lambda x, s: np.mod(x, s)),
]


@pytest.mark.parametrize("name,fn", SCALAR, ids=[s[0] for s in SCALAR])
def test_scalar_forward(name, fn):
    x = RNG.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
    out = getattr(mx.nd, name)(mx.nd.array(x), scalar=1.5)
    assert_almost_equal(out, fn(x, 1.5), rtol=1e-4, atol=1e-5)


# ---- reductions
REDUCE = [
    ("sum", np.sum), ("mean", np.mean), ("max", np.max), ("min", np.min),
    ("prod", np.prod), ("nansum", np.nansum), ("nanprod", np.nanprod),
]


@pytest.mark.parametrize("name,fn", REDUCE, ids=[r[0] for r in REDUCE])
def test_reduce_forward(name, fn):
    x = RNG.uniform(0.5, 1.5, (2, 3, 4)).astype(np.float32)
    out = getattr(mx.nd, name)(mx.nd.array(x), axis=1)
    assert_almost_equal(out, fn(x, axis=1), rtol=1e-4, atol=1e-5)
    out_all = getattr(mx.nd, name)(mx.nd.array(x))
    assert_almost_equal(out_all, np.array(fn(x)), rtol=1e-4, atol=1e-4)


def test_reduce_keepdims():
    x = RNG.uniform(0, 1, (2, 3, 4)).astype(np.float32)
    out = mx.nd.sum(mx.nd.array(x), axis=(0, 2), keepdims=True)
    assert_almost_equal(out, x.sum(axis=(0, 2), keepdims=True), rtol=1e-5)


# ---- shape manipulation
def test_shape_ops():
    x = RNG.uniform(0, 1, (2, 3, 4)).astype(np.float32)
    a = mx.nd.array(x)
    assert_almost_equal(mx.nd.transpose(a), x.T)
    assert_almost_equal(mx.nd.transpose(a, axes=(0, 2, 1)),
                        x.transpose(0, 2, 1))
    assert_almost_equal(mx.nd.expand_dims(a, axis=1),
                        np.expand_dims(x, 1))
    assert_almost_equal(mx.nd.flip(a, axis=2), x[:, :, ::-1])
    assert_almost_equal(mx.nd.tile(a, reps=(2, 1, 1)), np.tile(x, (2, 1, 1)))
    assert_almost_equal(mx.nd.repeat(a, repeats=2, axis=1),
                        np.repeat(x, 2, axis=1))
    assert_almost_equal(mx.nd.Reshape(a, shape=(6, 4)), x.reshape(6, 4))
    assert_almost_equal(mx.nd.Flatten(a), x.reshape(2, 12))
    assert_almost_equal(mx.nd.SwapAxis(a, dim1=0, dim2=2),
                        np.swapaxes(x, 0, 2))
    assert_almost_equal(mx.nd.slice_axis(a, axis=1, begin=1, end=3),
                        x[:, 1:3])


def test_reshape_special_codes():
    """Reference Reshape 0/-1/-2/-3/-4 semantics (matrix_op.cc)."""
    x = RNG.uniform(0, 1, (2, 3, 4)).astype(np.float32)
    a = mx.nd.array(x)
    assert mx.nd.Reshape(a, shape=(0, -1)).shape == (2, 12)
    assert mx.nd.Reshape(a, shape=(-1,)).shape == (24,)
    assert mx.nd.Reshape(a, shape=(0, 0, -1)).shape == (2, 3, 4)


def test_concat_stack_slice():
    x = RNG.uniform(0, 1, (2, 3)).astype(np.float32)
    y = RNG.uniform(0, 1, (2, 3)).astype(np.float32)
    assert_almost_equal(mx.nd.Concat(mx.nd.array(x), mx.nd.array(y), dim=1),
                        np.concatenate([x, y], axis=1))
    assert_almost_equal(mx.nd.stack(mx.nd.array(x), mx.nd.array(y), axis=0),
                        np.stack([x, y]))
    outs = mx.nd.SliceChannel(mx.nd.array(x), num_outputs=3, axis=1)
    assert len(outs) == 3
    assert_almost_equal(outs[1], x[:, 1:2])
    sq = mx.nd.SliceChannel(mx.nd.array(x), num_outputs=3, axis=1,
                            squeeze_axis=True)
    assert sq[0].shape == (2,)


def test_indexing_ops():
    w = RNG.uniform(0, 1, (10, 4)).astype(np.float32)
    idx = np.array([1, 3, 5], np.float32)
    assert_almost_equal(mx.nd.take(mx.nd.array(w), mx.nd.array(idx)),
                        w[[1, 3, 5]])
    assert_almost_equal(
        mx.nd.Embedding(mx.nd.array(idx), mx.nd.array(w), input_dim=10,
                        output_dim=4), w[[1, 3, 5]])
    oh = mx.nd.one_hot(mx.nd.array(np.array([0, 2], np.float32)), depth=3)
    assert_almost_equal(oh, np.eye(3, dtype=np.float32)[[0, 2]])
    x = RNG.uniform(0, 1, (3, 4)).astype(np.float32)
    bt = mx.nd.batch_take(mx.nd.array(x),
                          mx.nd.array(np.array([0, 2, 1], np.float32)))
    assert_almost_equal(bt, x[np.arange(3), [0, 2, 1]])


def test_ordering_ops():
    x = RNG.uniform(0, 1, (3, 5)).astype(np.float32)
    a = mx.nd.array(x)
    assert_almost_equal(mx.nd.argmax(a, axis=1),
                        np.argmax(x, axis=1).astype(np.float32))
    assert_almost_equal(mx.nd.argmin(a, axis=1),
                        np.argmin(x, axis=1).astype(np.float32))
    assert_almost_equal(mx.nd.sort(a, axis=1), np.sort(x, axis=1))
    assert_almost_equal(mx.nd.argsort(a, axis=1),
                        np.argsort(x, axis=1).astype(np.float32))
    topk = mx.nd.topk(a, axis=1, k=2)
    expect = np.argsort(-x, axis=1)[:, :2].astype(np.float32)
    assert_almost_equal(topk, expect)


def test_where_clip():
    cond = np.array([[1, 0], [0, 1]], np.float32)
    x = np.full((2, 2), 2.0, np.float32)
    y = np.full((2, 2), 3.0, np.float32)
    assert_almost_equal(
        mx.nd.where(mx.nd.array(cond), mx.nd.array(x), mx.nd.array(y)),
        np.where(cond > 0, x, y))
    z = np.array([-2.0, 0.5, 2.0], np.float32)
    assert_almost_equal(mx.nd.clip(mx.nd.array(z), a_min=-1, a_max=1),
                        np.clip(z, -1, 1))


def test_dot_ops():
    a = RNG.uniform(-1, 1, (3, 4)).astype(np.float32)
    b = RNG.uniform(-1, 1, (4, 5)).astype(np.float32)
    assert_almost_equal(mx.nd.dot(mx.nd.array(a), mx.nd.array(b)), a @ b,
                        rtol=1e-4, atol=1e-5)
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a.T), mx.nd.array(b), transpose_a=True),
        a @ b, rtol=1e-4, atol=1e-5)
    ba = RNG.uniform(-1, 1, (2, 3, 4)).astype(np.float32)
    bb = RNG.uniform(-1, 1, (2, 4, 5)).astype(np.float32)
    assert_almost_equal(mx.nd.batch_dot(mx.nd.array(ba), mx.nd.array(bb)),
                        ba @ bb, rtol=1e-4, atol=1e-5)


# ---- sampled numeric gradients across families
GRAD_CASES = [
    ("sigmoid", [(3, 4)], {}),
    ("tanh", [(3, 4)], {}),
    ("exp", [(3, 4)], {}),
    ("square", [(3, 4)], {}),
    ("broadcast_mul", [(2, 3), (2, 1)], {}),
    ("broadcast_div", [(2, 3), (2, 1)], {}),
    ("sum", [(3, 4)], {"axis": 1}),
    ("mean", [(3, 4)], {}),
    ("dot", [(3, 4), (4, 2)], {}),
    ("transpose", [(3, 4)], {}),
    ("BatchNorm", None, None),  # covered in test_executor
    ("SoftmaxActivation", [(3, 4)], {}),
    ("L2Normalization", [(3, 4)], {}),
    ("smooth_l1", [(3, 4)], {"scalar": 1.0}),
]


@pytest.mark.parametrize(
    "name,shapes,attrs",
    [c for c in GRAD_CASES if c[1] is not None],
    ids=[c[0] for c in GRAD_CASES if c[1] is not None])
def test_numeric_gradient(name, shapes, attrs):
    arrays = [RNG.uniform(0.5, 1.5, s) for s in shapes]
    check_numeric_gradient(name, arrays, attrs)


def test_random_ops_moments():
    """Reference test_random.py pattern: sample moments."""
    mx.random.seed(42)
    u = mx.nd._random_uniform(low=-1, high=1, shape=(50000,))
    nu = u.asnumpy()
    assert abs(nu.mean()) < 0.02
    assert abs(nu.std() - np.sqrt(4 / 12)) < 0.02
    n = mx.nd._random_normal(loc=2.0, scale=3.0, shape=(50000,))
    nn = n.asnumpy()
    assert abs(nn.mean() - 2.0) < 0.1
    assert abs(nn.std() - 3.0) < 0.1
    p = mx.nd._random_poisson(lam=4.0, shape=(50000,))
    assert abs(p.asnumpy().mean() - 4.0) < 0.15


def test_sequence_ops():
    x = RNG.uniform(0, 1, (4, 2, 3)).astype(np.float32)  # (T, B, C)
    length = np.array([2, 4], np.float32)
    masked = mx.nd.SequenceMask(mx.nd.array(x), mx.nd.array(length),
                                use_sequence_length=True)
    m = masked.asnumpy()
    assert (m[2:, 0] == 0).all() and (m[:, 1] == x[:, 1]).all()
    last = mx.nd.SequenceLast(mx.nd.array(x), mx.nd.array(length),
                              use_sequence_length=True)
    assert_almost_equal(last, np.stack([x[1, 0], x[3, 1]]))
    rev = mx.nd.SequenceReverse(mx.nd.array(x), mx.nd.array(length),
                                use_sequence_length=True)
    r = rev.asnumpy()
    assert_almost_equal(r[0, 0], x[1, 0])
    assert_almost_equal(r[0, 1], x[3, 1])


def test_upsampling_pad():
    x = np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2)
    up = mx.nd.UpSampling(mx.nd.array(x), scale=2, sample_type="nearest")
    assert up.shape == (1, 1, 4, 4)
    assert_almost_equal(up.asnumpy()[0, 0, :2, :2],
                        np.array([[0, 0], [0, 1]], np.float32) * 0 +
                        x[0, 0, 0, 0])
    padded = mx.nd.Pad(mx.nd.array(x), mode="constant",
                       pad_width=(0, 0, 0, 0, 1, 1, 1, 1),
                       constant_value=5.0)
    assert padded.shape == (1, 1, 4, 4)
    assert padded.asnumpy()[0, 0, 0, 0] == 5.0
