"""Block-granularity fusion + layout planning (analysis.fusion +
ops/fused.py ``fused_block_*``): plan correctness over the model zoo,
fused-vs-unfused numerical parity (forward, gradients, aux updates;
train AND eval BN semantics) on the Executor and the ShardedTrainer,
and graceful fallback when a pattern is ineligible.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models, telemetry
from mxnet_tpu.analysis import fusion
from mxnet_tpu.ops.fused import block_fusion
from mxnet_tpu.parallel import ShardedTrainer, build_mesh


def _plan(sym, layout="NHWC", is_train=True):
    return fusion.plan_block_fusion(sym._topo(), sym._entries,
                                    layout=layout, is_train=is_train,
                                    record=False)


def _resnet_style_net(num_classes=10, act="relu", bn_kwargs=None):
    """conv3x3+BN+act trunk -> pallas-eligible conv1x1+BN+act ->
    residual add (the trunk terminal has two consumers) -> FC+relu head."""
    bn_kwargs = bn_kwargs or {}
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                             num_filter=8, no_bias=True, name="conv0")
    net = mx.sym.BatchNorm(net, name="bn0", fix_gamma=False, **bn_kwargs)
    net = mx.sym.Activation(net, act_type=act, name="act0")
    trunk = net
    net = mx.sym.Convolution(net, kernel=(1, 1), num_filter=8,
                             no_bias=True, name="conv1")
    net = mx.sym.BatchNorm(net, name="bn1", fix_gamma=False, **bn_kwargs)
    net = mx.sym.Activation(net, act_type=act, name="act1")
    net = net + trunk
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc0")
    net = mx.sym.Activation(net, act_type="relu", name="fcact")
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(net, name="softmax")


# ------------------------------------------------------------- the plan
def test_plan_resnet_style_blocks():
    plan = _plan(_resnet_style_net())
    s = plan.summary()
    assert s["kinds"] == {"conv_bn_act": 2, "fc_act": 1}
    # conv1 is 1x1/s1/p0/no-bias under NHWC train stats -> Pallas
    assert s["pallas_blocks"] == 1
    by_kind = {b.kind: b for b in plan.blocks.values()}
    assert by_kind["conv_bn_act"].terminal.name in ("act0", "act1")
    # interior edges: 2 per conv_bn_act, 1 per fc_act = 5; plus the
    # act0 -> conv1 block adjacency pinned to one layout = 6
    assert plan.interior_edges == 5
    assert plan.adjacent_edges == 1
    assert s["relayouts_eliminated"] == 6
    assert s["fallbacks"] == {}


def test_plan_longest_chain_wins():
    """conv->BN->relu must match as ONE conv_bn_act, not bn_act."""
    plan = _plan(_resnet_style_net())
    kinds = {b.kind for b in plan.blocks.values()}
    assert "bn_act" not in kinds


def test_plan_eval_mode_disables_pallas():
    plan = _plan(_resnet_style_net(), is_train=False)
    s = plan.summary()
    # same blocks, but the Pallas train-stats kernel is ineligible
    assert s["kinds"] == {"conv_bn_act": 2, "fc_act": 1}
    assert s["pallas_blocks"] == 0
    assert not s["is_train"]


def test_plan_conv_multi_consumer_falls_back_to_bn_act():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=4,
                           no_bias=True, name="c")
    bn = mx.sym.BatchNorm(c, name="bn", fix_gamma=False)
    r = mx.sym.Activation(bn, act_type="relu", name="r")
    out = r + c                     # conv consumed by bn AND the add
    plan = _plan(out)
    s = plan.summary()
    assert s["kinds"] == {"bn_act": 1}
    assert s["fallbacks"] == {"conv_multi_consumer": 1}


def test_plan_ineligible_bn_attrs_fall_back():
    # output_mean_var: the region exposes only output + aux updates
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn", output_mean_var=True)
    out = mx.sym.Activation(bn[0], act_type="relu")
    s = _plan(out).summary()
    assert s["blocks"] == 0
    assert s["fallbacks"] == {"bn_output_mean_var": 1}

    # non-reference channel axis
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn", axis=3)
    out = mx.sym.Activation(bn, act_type="relu")
    s = _plan(out).summary()
    assert s["blocks"] == 0 and s["fallbacks"] == {"bn_axis": 1}


def test_plan_non_relu_bn_activation_falls_back():
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn")
    out = mx.sym.Activation(bn, act_type="sigmoid")
    s = _plan(out).summary()
    assert s["blocks"] == 0 and s["fallbacks"] == {"act_type": 1}


def test_plan_respects_exclusions():
    """Nodes claimed by another trace-time pass are off limits."""
    sym = _resnet_style_net()
    topo = sym._topo()
    conv1 = next(n for n in topo if n.name == "conv1")
    plan = fusion.plan_block_fusion(topo, sym._entries, layout="NHWC",
                                    exclude={id(conv1)}, record=False)
    s = plan.summary()
    # conv1's chain degrades to bn_act; conv0's chain still fuses
    assert s["kinds"] == {"conv_bn_act": 1, "bn_act": 1, "fc_act": 1}
    assert s["fallbacks"] == {"claimed_by_other_pass": 1}


# ------------------------------------ relayout accounting (the matrix)
# Adjacent-same-layout credit across ALL FOUR chain kinds: a boundary
# only counts as an eliminated relayout when an image activation sits
# on both sides — fc_act blocks neither carry an image layout out nor
# read one in (FullyConnected flattens), so FC boundaries never credit.

def _conv_bn(data, i, act=False):
    n = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=4,
                           no_bias=True, name="c%d" % i)
    n = mx.sym.BatchNorm(n, name="b%d" % i, fix_gamma=False)
    if act:
        n = mx.sym.Activation(n, act_type="relu", name="r%d" % i)
    return n


def _bn_act(data, i):
    n = mx.sym.BatchNorm(data, name="nb%d" % i, fix_gamma=False)
    return mx.sym.Activation(n, act_type="relu", name="nr%d" % i)


def _fc_act(data, i):
    n = mx.sym.FullyConnected(data, num_hidden=8, name="f%d" % i)
    return mx.sym.Activation(n, act_type="relu", name="fa%d" % i)


def _counts(sym):
    p = _plan(sym)
    return (p.summary()["kinds"], p.interior_edges, p.adjacent_edges,
            p.relayouts_eliminated)


def test_relayout_adjacent_conv_bn_act_chain():
    d = mx.sym.Variable("data")
    sym = _conv_bn(_conv_bn(d, 0, act=True), 1, act=True)
    kinds, interior, adjacent, total = _counts(sym)
    assert kinds == {"conv_bn_act": 2}
    assert (interior, adjacent, total) == (4, 1, 5)


def test_relayout_adjacent_conv_bn_chain():
    d = mx.sym.Variable("data")
    sym = _conv_bn(_conv_bn(d, 0), 1)
    kinds, interior, adjacent, total = _counts(sym)
    assert kinds == {"conv_bn": 2}
    assert (interior, adjacent, total) == (2, 1, 3)


def test_relayout_adjacent_bn_act_chain():
    d = mx.sym.Variable("data")
    sym = _bn_act(_bn_act(d, 0), 1)
    kinds, interior, adjacent, total = _counts(sym)
    assert kinds == {"bn_act": 2}
    assert (interior, adjacent, total) == (2, 1, 3)


def test_relayout_adjacent_conv_into_bn_act():
    d = mx.sym.Variable("data")
    sym = _bn_act(_conv_bn(d, 0, act=True), 1)
    kinds, interior, adjacent, total = _counts(sym)
    assert kinds == {"conv_bn_act": 1, "bn_act": 1}
    assert (interior, adjacent, total) == (3, 1, 4)


def test_relayout_fc_chain_never_credits_adjacency():
    """fc_act -> fc_act: both boundary tensors are 2-d — no image
    relayout exists to eliminate (the credit used to overcount)."""
    d = mx.sym.Variable("data")
    sym = _fc_act(_fc_act(d, 0), 1)
    kinds, interior, adjacent, total = _counts(sym)
    assert kinds == {"fc_act": 2}
    assert (interior, adjacent, total) == (2, 0, 2)


def test_relayout_conv_into_fc_never_credits_adjacency():
    """conv_bn_act -> fc_act (direct, FC flatten=True): the FC flattens
    the image activation, paying that materialization regardless of
    any layout pinning — no credit (used to overcount)."""
    d = mx.sym.Variable("data")
    sym = _fc_act(_conv_bn(d, 0, act=True), 1)
    kinds, interior, adjacent, total = _counts(sym)
    assert kinds == {"conv_bn_act": 1, "fc_act": 1}
    assert (interior, adjacent, total) == (3, 0, 3)


def test_relayout_flatten_between_blocks_no_credit():
    d = mx.sym.Variable("data")
    sym = _fc_act(mx.sym.Flatten(_conv_bn(d, 0, act=True)), 1)
    _kinds, _interior, adjacent, _total = _counts(sym)
    assert adjacent == 0


# the zoo: every net with a fusable pattern must plan >= 1 block.
# googlenet is the documented zero: convs without BN and an FC head
# with no trailing activation offer nothing to fuse.
_ZOO_MIN_BLOCKS = {"googlenet": 0}


@pytest.mark.parametrize("name", models._MODELS)
def test_plan_zoo_model(name):
    net = models.get_model(name, num_classes=10)
    plan = _plan(net)
    s = plan.summary()
    assert s["blocks"] >= _ZOO_MIN_BLOCKS.get(name, 1), s
    # plans must be internally consistent: interiors are skipped, every
    # terminal is outside every skip set
    for blk in plan.blocks.values():
        assert id(blk.terminal) not in plan.skip
        for n in blk.interior():
            assert id(n) in plan.skip
    if s["blocks"]:
        assert s["relayouts_eliminated"] >= s["blocks"]


# --------------------------------------------------- executor parity
def _exec_run(sym, fuse, is_train, shapes, seed=0, aux_seed=None,
              backward=True):
    with block_fusion(fuse):
        ex = sym.simple_bind(mx.cpu(), **shapes)
    rng = np.random.RandomState(seed)
    for name, arr in ex.arg_dict.items():
        if name == "softmax_label":
            arr[:] = rng.randint(0, 10, arr.shape).astype(np.float32)
        else:
            arr[:] = rng.uniform(-0.5, 0.5, arr.shape).astype(np.float32)
    if aux_seed is not None:
        arng = np.random.RandomState(aux_seed)
        for name, arr in ex.aux_dict.items():
            base = arng.uniform(0.1, 1.0, arr.shape).astype(np.float32)
            arr[:] = base
    ex.forward(is_train=is_train)
    outs = [np.asarray(o.asnumpy()) for o in ex.outputs]
    grads = {}
    if backward and is_train:
        ex.backward()
        grads = {k: v.asnumpy() for k, v in ex.grad_dict.items()
                 if v is not None}
    aux = {k: v.asnumpy() for k, v in ex.aux_dict.items()}
    return outs, grads, aux


_SHAPES = {"data": (4, 3, 8, 8), "softmax_label": (4,)}


def test_executor_train_parity():
    """Fused forward+backward (one custom-vjp region per block, both
    directions) matches the unfused graph: outputs, every gradient."""
    sym = _resnet_style_net()
    o_ref, g_ref, _ = _exec_run(sym, False, True, _SHAPES)
    o_fused, g_fused, _ = _exec_run(sym, True, True, _SHAPES)
    for a, b in zip(o_ref, o_fused):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    assert set(g_ref) == set(g_fused)
    for k in g_ref:
        np.testing.assert_allclose(g_ref[k], g_fused[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


def test_executor_eval_parity_uses_global_stats():
    """Eval-mode BN (moving stats) lowers through the same fused region;
    outputs must match the unfused eval graph bit-for-bit semantics."""
    sym = _resnet_style_net()
    o_ref, _, _ = _exec_run(sym, False, False, _SHAPES, aux_seed=11,
                            backward=False)
    o_fused, _, _ = _exec_run(sym, True, False, _SHAPES, aux_seed=11,
                              backward=False)
    for a, b in zip(o_ref, o_fused):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("act", ["sigmoid", "tanh"])
def test_executor_fc_act_parity(act):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(mx.sym.Flatten(data), num_hidden=8,
                                name="fc0")
    net = mx.sym.Activation(net, act_type=act, name="a0")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc1")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    assert _plan(sym).summary()["kinds"] == {"fc_act": 1}
    o_ref, g_ref, _ = _exec_run(sym, False, True, _SHAPES)
    o_fused, g_fused, _ = _exec_run(sym, True, True, _SHAPES)
    np.testing.assert_allclose(o_ref[0], o_fused[0], rtol=2e-5,
                               atol=2e-6)
    for k in g_ref:
        np.testing.assert_allclose(g_ref[k], g_fused[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


def test_executor_fc_act_flatten_false_parity():
    """FullyConnected(flatten=False) keeps its leading batch dims; the
    fused region's backward must contract ALL of them (review r6)."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, flatten=False,
                                name="fc0")
    net = mx.sym.Activation(net, act_type="relu", name="a0")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=10,
                                name="fc1")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    shapes = {"data": (4, 5, 6), "softmax_label": (4,)}
    o_ref, g_ref, _ = _exec_run(sym, False, True, shapes)
    o_fused, g_fused, _ = _exec_run(sym, True, True, shapes)
    np.testing.assert_allclose(o_ref[0], o_fused[0], rtol=2e-5,
                               atol=2e-6)
    for k in g_ref:
        np.testing.assert_allclose(g_ref[k], g_fused[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


def test_fused_conv_bn_region_bf16_biased_grads():
    """A biased conv under a bf16 compute view: the region's bias
    cotangent must come back in the bias dtype (review r6 — the f32
    accumulator used to fail custom_vjp's aval check)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import fused as F

    conv_attrs = {"kernel": (3, 3), "stride": (1, 1), "dilate": (1, 1),
                  "pad": (1, 1), "num_group": 1}
    bn_attrs = {"eps": 1e-5, "momentum": 0.9}
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.uniform(-1, 1, (2, 4, 4, 3)), jnp.bfloat16)
    w = jnp.asarray(rng.uniform(-1, 1, (8, 3, 3, 3)), jnp.bfloat16)
    b = jnp.asarray(rng.uniform(-1, 1, (8,)), jnp.bfloat16)
    gamma = jnp.ones((8,), jnp.float32)
    beta = jnp.zeros((8,), jnp.float32)
    mm = jnp.zeros((8,), jnp.float32)
    mv = jnp.ones((8,), jnp.float32)

    def loss(x, w, b):
        out, _mm, _mv = F.fused_block_conv_bn_act(
            conv_attrs, bn_attrs, "NHWC", True, "relu", False,
            x, w, b, gamma, beta, mm, mv)
        return jnp.sum(out.astype(jnp.float32))

    dx, dw, db = jax.grad(loss, argnums=(0, 1, 2))(x, w, b)
    assert db.dtype == jnp.bfloat16 and db.shape == (8,)
    assert dx.dtype == x.dtype and dw.dtype == w.dtype
    assert np.isfinite(np.asarray(db, np.float32)).all()


def test_seeded_partial_graph_never_fuses():
    """Pipeline stages evaluate partial topos with seeded boundary
    values; a chain straddling the boundary reaches nodes outside the
    stage topo, so seeded graphs must not fuse (review r6 — the
    planner used to fuse the out-of-topo conv and die on a KeyError)."""
    import jax.numpy as jnp
    from mxnet_tpu.symbol import eval_graph

    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(1, 1), num_filter=4,
                           no_bias=True, name="c")
    bn = mx.sym.BatchNorm(c, name="bn", fix_gamma=False)
    out = mx.sym.Activation(bn, act_type="relu", name="r")
    topo = out._topo()
    conv_node = next(n for n in topo if n.name == "c")
    data_node = next(n for n in topo if n.name == "data")
    # stage-2 topo: the boundary (conv) and its input stay behind
    stage = [n for n in topo if n is not conv_node and n is not data_node]
    rng = np.random.RandomState(0)
    conv_out = jnp.asarray(rng.uniform(-1, 1, (2, 4, 3, 3)), jnp.float32)
    var_values = {
        id(n): jnp.asarray(
            rng.uniform(0.5, 1.0, (4,)) if "gamma" in n.name
            or "var" in n.name else np.zeros(4), jnp.float32)
        for n in stage if n.is_variable}

    def run(fuse):
        with block_fusion(fuse):
            heads, _aux = eval_graph(
                stage, out._entries, dict(var_values), is_train=True,
                seed_vals={id(conv_node): (conv_out,)})
        return np.asarray(heads[0])

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6)


def test_executor_graceful_fallback_runs_unfused():
    """An ineligible pattern (BN axis) under the fused flag must run —
    and match — the unfused graph, never error."""
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn", axis=3)
    net = mx.sym.Activation(bn, act_type="relu")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=10,
                                name="fc")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    o_ref, g_ref, _ = _exec_run(sym, False, True, _SHAPES)
    o_fused, g_fused, _ = _exec_run(sym, True, True, _SHAPES)
    np.testing.assert_allclose(o_ref[0], o_fused[0], rtol=2e-5,
                               atol=2e-6)
    for k in g_ref:
        np.testing.assert_allclose(g_ref[k], g_fused[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


# ---------------------------------------------------- trainer parity
def _make_trainer(fuse, layout="NHWC", dtype="float32"):
    mesh = build_mesh(tp=1)
    np.random.seed(7)
    kwargs = dict(
        data_shapes={"data": (8, 3, 8, 8)},
        label_shapes={"softmax_label": (8,)},
        dtype=dtype, seed=3, learning_rate=0.1, momentum=0.9,
        fuse_blocks=fuse)
    if layout is not None:
        kwargs["layout"] = layout
    return ShardedTrainer(_resnet_style_net(), mesh, **kwargs)


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "data": (rng.uniform(-1, 1, (8, 3, 8, 8)) * 2.0 + 0.25)
        .astype(np.float32),
        "softmax_label": rng.randint(0, 10, 8).astype(np.float32),
    }


@pytest.mark.parametrize("layout", ["NHWC", None])
def test_trainer_step_parity(layout):
    """Two full fused-step training updates (fwd + custom-vjp bwd +
    optimizer + BN aux) match the unfused trainer in either layout."""
    t_ref = _make_trainer(False, layout=layout)
    t_fused = _make_trainer(True, layout=layout)
    losses = []
    for t in (t_ref, t_fused):
        b = t.put_batch(_batch(0))
        losses.append((float(t.step(b)), float(t.step(b))))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5,
                               atol=1e-7)
    for n in t_ref.params:
        np.testing.assert_allclose(
            np.asarray(t_ref.params[n]), np.asarray(t_fused.params[n]),
            rtol=2e-4, atol=2e-5, err_msg=n)
    for n in t_ref.aux:
        np.testing.assert_allclose(
            np.asarray(t_ref.aux[n]), np.asarray(t_fused.aux[n]),
            rtol=2e-4, atol=2e-5, err_msg="aux:" + n)


def test_trainer_eval_forward_parity():
    """trainer.forward (eval BN semantics inside the fused regions)
    matches the unfused inference forward after a training step."""
    t_ref = _make_trainer(False)
    t_fused = _make_trainer(True)
    for t in (t_ref, t_fused):
        float(t.step(t.put_batch(_batch(0))))
    feed = {"data": _batch(1)["data"]}
    np.testing.assert_allclose(
        np.asarray(t_ref.forward(feed)[0]),
        np.asarray(t_fused.forward(feed)[0]), rtol=2e-4, atol=2e-5)


def test_trainer_fusion_summary_and_metrics():
    """The plan leaves its host-side traces: fusion_summary(), the
    module-level last_plan_summary snapshot, and the mxtpu_fusion_*
    counters (one batch of increments per trace)."""
    plans0 = telemetry.counter("mxtpu_fusion_plans_total").get()
    t = _make_trainer(True)
    float(t.step(t.put_batch(_batch(0))))
    s = t.fusion_summary()
    assert s is not None and s["blocks"] >= 3
    assert s == fusion.last_plan_summary()
    assert telemetry.counter("mxtpu_fusion_plans_total").get() > plans0
    assert telemetry.counter("mxtpu_fusion_blocks_total").labels(
        kind="conv_bn_act").get() >= 2
    # unfused trainers surface no summary
    assert _make_trainer(False).fusion_summary() is None
