"""Elastic training: checkpoint resharding, mesh reshape, rank join/leave.

ROADMAP item 5 / ISSUE 10 acceptance: a checkpoint saved on the
multichip dryrun's ``{data:4, model:2}`` mesh must resume BIT-EXACT
(params + aux + optimizer state) on ``{data:2, model:2}``, ``{data:8}``
and single-device meshes; a reshard failure must degrade to the
old-mesh error path; and the whole reshape must be observable
(``mxtpu_reshard_*`` metrics, ``reshard``/``rank_join``/``rank_leave``
flight + JSONL events).  Runs on the conftest's virtual 8-device CPU
mesh.  See docs/api/reshard.md.
"""
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import telemetry  # noqa: E402
from mxnet_tpu.base import MXNetError  # noqa: E402
from mxnet_tpu.parallel import (ShardedTrainer, build_mesh,  # noqa: E402
                                build_mesh_from_axes, multihost, reshard)

GBATCH = 8


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=10)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _make(mesh):
    np.random.seed(3)
    return ShardedTrainer(
        _mlp(), mesh,
        data_shapes={"data": (GBATCH, 64)},
        label_shapes={"softmax_label": (GBATCH,)},
        learning_rate=0.1, momentum=0.9, seed=1)


def _batch(step=0):
    rng = np.random.RandomState(100 + step)
    return {"data": rng.rand(GBATCH, 64).astype("f"),
            "softmax_label": (rng.randint(0, 10, GBATCH)).astype("f")}


def _gather_all(t):
    out = {k: multihost.gather_to_host(v) for k, v in t.params.items()}
    out.update({"aux:" + k: multihost.gather_to_host(v)
                for k, v in t.aux.items()})
    for k, slots in t.opt_state.items():
        for i, s in enumerate(slots):
            out["slot%d:%s" % (i, k)] = multihost.gather_to_host(s)
    return out


@pytest.fixture(scope="module")
def saved_ckpt(tmp_path_factory):
    """Two steps trained + saved on the {data:4, model:2} mesh, with
    the continued-loss reference for the resume tests."""
    prefix = str(tmp_path_factory.mktemp("reshard") / "job")
    src = _make(build_mesh(tp=2))            # 8 devices: data4 x model2
    assert src.tp_rules, "tp=2 must derive sharded weights"
    for step in range(2):
        src.step(_batch(step))
    src.save_checkpoint(prefix, 2, save_optimizer_states=True)
    ref_state = _gather_all(src)
    cont_losses = [float(src.step(_batch(2 + i))) for i in range(2)]
    return {"prefix": prefix, "state": ref_state,
            "cont_losses": cont_losses}


# ------------------------------------------------------------ rule tables

def test_parse_rules_inline_and_match():
    rules = reshard.parse_rules(
        ".*fc1_weight=model;.*fc2_weight=None,model;.*=")
    assert reshard.first_match(rules, "net_fc1_weight") == ("model",)
    assert reshard.first_match(rules, "fc2_weight") == (None, "model")
    assert reshard.first_match(rules, "anything_else") == ()
    specs = reshard.match_partition_rules(
        rules, {"fc1_weight": (32, 64), "fc2_weight": (10, 32),
                "scalar": (1,)})
    assert specs["fc1_weight"] == ("model",)
    assert specs["scalar"] == ()          # scalars never partition


def test_parse_rules_file_form(tmp_path):
    path = tmp_path / "rules.json"
    path.write_text(json.dumps(
        [[".*_weight", ["model"]], [".*", []]]))
    rules = reshard.parse_rules("@" + str(path))
    assert reshard.first_match(rules, "fc1_weight") == ("model",)
    assert reshard.first_match(rules, "fc1_bias") == ()


def test_rules_errors():
    with pytest.raises(MXNetError, match="not a valid regex"):
        reshard.parse_rules("[=model")
    with pytest.raises(MXNetError, match="no reshard rule matches"):
        reshard.match_partition_rules(
            reshard.parse_rules("fc9=model"), {"fc1_weight": (4, 4)})
    with pytest.raises(MXNetError, match="names 2 dims"):
        reshard.match_partition_rules(
            reshard.parse_rules(".*=model,model"), {"v": (8,)})


def test_trainer_reshard_rules_env_override(monkeypatch):
    # force fc1_weight replicated; leave everything else derived
    monkeypatch.setenv("MXNET_TPU_RESHARD_RULES", "fc1_weight=")
    t = _make(build_mesh(tp=2))
    assert "fc1_weight" not in t.tp_rules
    assert "fc2_weight" in t.tp_rules     # untouched derived rule
    monkeypatch.setenv("MXNET_TPU_RESHARD_RULES", "fc1_weight=data")
    with pytest.raises(MXNetError, match="shard only over 'model'"):
        _make(build_mesh(tp=2))


# ----------------------------------------------------- descriptors / plan

def test_mesh_descriptor_and_same_mesh():
    assert reshard.same_mesh({"axes": {"data": 4, "model": 1}},
                             {"axes": {"data": 4}})
    assert not reshard.same_mesh({"axes": {"data": 4, "model": 2}},
                                 {"axes": {"data": 8}})
    assert reshard.same_mesh({"axes": {"data": 1}}, {"axes": {}})
    assert reshard.describe_axes({"axes": {"data": 1}}) == "{1}"
    t = _make(build_mesh(tp=2))
    desc = t.mesh_descriptor()
    assert desc["format"] == 2
    assert reshard.normalized_axes(desc["axes"]) == \
        {"data": 4, "model": 2}
    assert desc["specs"]["fc2_weight"] == [None, "model"]


def test_plan_reshard_rejects_indivisible():
    src = {"axes": {"data": 2}}
    dst = {"axes": {"data": 2, "model": 4},
           "specs": {"w": ["model"], "v": ["model"]}}
    with pytest.raises(MXNetError) as ei:
        reshard.plan_reshard(src, dst, {"w": (10, 4), "v": (8, 2)})
    # every offender is listed; the feasible param is not
    assert "w" in str(ei.value) and "not divisible" in str(ei.value)
    plan = reshard.plan_reshard(src, dst, {"v": (8, 2)})
    assert plan["n_resharded"] == 1
    assert plan["params"]["v"]["resharded"]
    # a typo'd axis name must fail loudly, not silently replicate
    with pytest.raises(MXNetError, match="does not have"):
        reshard.plan_reshard(src, {"axes": {"data": 2},
                                   "specs": {"v": ["modle"]}},
                             {"v": (8, 2)})
    # ...but an axis the mesh declares at size 1 legitimately shards
    # nothing and stays tolerated
    ok = reshard.plan_reshard(src, {"axes": {"data": 2, "model": 1},
                                    "specs": {"v": ["model"]}},
                              {"v": (7, 2)})
    assert ok["n_params"] == 1


def test_build_mesh_from_axes_errors():
    with pytest.raises(ValueError, match="need 64 devices"):
        build_mesh_from_axes({"data": 8, "model": 8})


# ------------------------------------------------- the acceptance matrix

@pytest.mark.parametrize("axes", [{"data": 2, "model": 2},
                                  {"data": 8}, {"data": 1}],
                         ids=["data2xmodel2", "data8", "single"])
def test_reshard_load_bit_exact(saved_ckpt, axes):
    """{data:4, model:2} -> other shapes: params/aux/optimizer state
    bit-exact, the loss trajectory continues identically, and the
    reshape is observable."""
    before = telemetry.counter("mxtpu_reshard_total").labels(
        kind="load").get()
    t = _make(build_mesh_from_axes(axes))
    t.load_checkpoint(saved_ckpt["prefix"], 2,
                      load_optimizer_states=True)
    got = _gather_all(t)
    for k, v in saved_ckpt["state"].items():
        assert np.array_equal(v, got[k]), "state %r differs" % k
    # the trajectory continues where the source left off (different
    # mesh shapes may reorder float reductions; the STATE is bit-exact,
    # the loss is reduction-order-tolerant)
    losses = [float(t.step(_batch(2 + i))) for i in range(2)]
    np.testing.assert_allclose(losses, saved_ckpt["cont_losses"],
                               rtol=1e-4)
    assert telemetry.counter("mxtpu_reshard_total").labels(
        kind="load").get() == before + 1
    ev = [e for e in telemetry.flight.events()
          if e["kind"] == "reshard"
          and e.get("dst") == reshard.describe_axes({"axes": axes})]
    assert ev, "no reshard flight event for %r" % (axes,)
    assert ev[-1]["src"] == "{data:4, model:2}"
    assert ev[-1]["n_params"] > 0


def test_same_mesh_load_does_not_reshard(saved_ckpt):
    before = telemetry.counter("mxtpu_reshard_total").labels(
        kind="load").get()
    t = _make(build_mesh(tp=2))
    t.load_checkpoint(saved_ckpt["prefix"], 2,
                      load_optimizer_states=True)
    assert telemetry.counter("mxtpu_reshard_total").labels(
        kind="load").get() == before


def test_manifest_v2_and_legacy_v1(saved_ckpt, tmp_path):
    man_path = saved_ckpt["prefix"] + "-0002.manifest.json"
    man = json.load(open(man_path))
    assert man["format"] == 2
    mesh = man["meta"]["mesh"]
    assert mesh["axes"] == {"data": 4, "model": 2}
    assert mesh["world"] == 1
    # strip the descriptor -> a v1 manifest: the load takes the legacy
    # (non-reshaping) path even on a different mesh shape
    import shutil
    prefix2 = str(tmp_path / "legacy")
    for suf in ("-symbol.json", "-0002.params", "-0002.states"):
        shutil.copyfile(saved_ckpt["prefix"] + suf, prefix2 + suf)
    man2 = dict(man, format=1, meta={})
    man2["files"] = {f.replace("job", "legacy"): v
                     for f, v in man["files"].items()}
    with open(prefix2 + "-0002.manifest.json", "w") as f:
        json.dump(man2, f)
    before = telemetry.counter("mxtpu_reshard_total").labels(
        kind="load").get()
    t = _make(build_mesh_from_axes({"data": 8}))
    t.load_checkpoint(prefix2, 2, load_optimizer_states=True)
    assert telemetry.counter("mxtpu_reshard_total").labels(
        kind="load").get() == before
    got = _gather_all(t)
    for k, v in saved_ckpt["state"].items():
        assert np.array_equal(v, got[k]), k


def test_world_change_records_rank_join(saved_ckpt, tmp_path,
                                        monkeypatch):
    """A manifest saved at world=2 loaded in this 1-process run is a
    rank LEAVE; the events + counter land, and the JSONL event record
    reaches the per-rank step-log for the run aggregator."""
    import shutil
    prefix2 = str(tmp_path / "w2")
    for suf in ("-symbol.json", "-0002.params", "-0002.states"):
        shutil.copyfile(saved_ckpt["prefix"] + suf, prefix2 + suf)
    man = json.load(open(saved_ckpt["prefix"] + "-0002.manifest.json"))
    man["files"] = {f.replace("job", "w2"): v
                    for f, v in man["files"].items()}
    man["meta"]["mesh"]["world"] = 2
    with open(prefix2 + "-0002.manifest.json", "w") as f:
        json.dump(man, f)
    jsonl = str(tmp_path / "log.jsonl.rank0")
    monkeypatch.setenv("MXNET_TPU_TELEMETRY_JSONL", jsonl)
    before = telemetry.counter("mxtpu_elastic_resizes_total").labels(
        direction="leave").get()
    t = _make(build_mesh(tp=2))
    t.load_checkpoint(prefix2, 2, load_optimizer_states=True)
    monkeypatch.delenv("MXNET_TPU_TELEMETRY_JSONL")
    telemetry.jsonl_event("noop")   # rotate the handle off the file
    assert telemetry.counter("mxtpu_elastic_resizes_total").labels(
        direction="leave").get() == before + 1
    ev = [e for e in telemetry.flight.events()
          if e["kind"] == "rank_leave"]
    assert ev and ev[-1]["from_world"] == 2 and ev[-1]["to_world"] == 1
    recs = [json.loads(l) for l in open(jsonl)]
    assert any(r.get("event") == "rank_leave" for r in recs), recs


def test_aggregator_passes_worker_events_through(tmp_path):
    from mxnet_tpu.telemetry.distview import (RunAggregator,
                                              read_run_timeline)
    base = str(tmp_path / "run.jsonl")
    agg = RunAggregator(base, 1)
    with open(base + ".rank0", "w") as f:
        f.write(json.dumps({"ts": 1.0, "event": "rank_join",
                            "from_world": 1, "to_world": 2}) + "\n")
        f.write(json.dumps({"ts": 2.0, "step": 1,
                            "step_time_s": 0.1}) + "\n")
    agg.poll()
    agg.close()
    recs = read_run_timeline(base + ".run")
    evs = [r for r in recs if r.get("kind") == "event"]
    assert any(r.get("event") == "rank_join" and r.get("rank") == 0
               for r in evs), recs
    assert any(r.get("kind") == "step" for r in recs)


# ------------------------------------------------------- failure modes

def test_reshard_infeasible_target_raises_cleanly(saved_ckpt,
                                                  monkeypatch):
    """A target layout the shapes cannot satisfy fails BEFORE any state
    moves — the old-mesh error path, trainer state untouched."""
    # fc2_bias has 10 elements: force dim 0 over the 4-way model axis
    monkeypatch.setenv("MXNET_TPU_RESHARD_RULES", "")
    t = _make(build_mesh_from_axes({"data": 2, "model": 4}))
    # hand the trainer an impossible target through its own tp_rules
    t.tp_rules = dict(t.tp_rules, fc2_bias=0)
    snap = _gather_all(t)
    with pytest.raises(MXNetError, match="not divisible"):
        t.load_checkpoint(saved_ckpt["prefix"], 2,
                          load_optimizer_states=True)
    for k, v in _gather_all(t).items():
        assert np.array_equal(v, snap[k]), k


@pytest.mark.chaos
def test_chaos_scatter_fault_degrades_to_old_mesh(saved_ckpt):
    """ISSUE 10 satellite: an injected fault inside reshard.scatter
    must surface as a descriptive MXNetError with the live state
    untouched; the next (clean) load succeeds."""
    from mxnet_tpu import resilience as R
    t = _make(build_mesh_from_axes({"data": 8}))
    snap = _gather_all(t)
    R.configure_faults("reshard.scatter:n=1")
    try:
        with pytest.raises(MXNetError, match="resharding checkpoint"):
            t.load_checkpoint(saved_ckpt["prefix"], 2,
                              load_optimizer_states=True)
        stats = R.fault_stats()
        assert stats["reshard.scatter"]["hits"] == 1
    finally:
        R.clear_faults()
    # old-mesh state untouched by the failed reshape
    for k, v in _gather_all(t).items():
        assert np.array_equal(v, snap[k]), k
    # and the path still works once the fault is gone
    t.load_checkpoint(saved_ckpt["prefix"], 2,
                      load_optimizer_states=True)
    got = _gather_all(t)
    for k, v in saved_ckpt["state"].items():
        assert np.array_equal(v, got[k]), k


# ------------------------------------------- find_latest_checkpoint

def test_find_latest_checkpoint_falls_back_past_crc_failure(tmp_path):
    """Satellite regression: the newest epoch passes the quick size
    screen (same-size bit flip) but fails CRC — find_latest_checkpoint
    must return the newest VERIFIED epoch, not the corrupt one."""
    from mxnet_tpu.model import find_checkpoints, find_latest_checkpoint
    prefix = str(tmp_path / "job")
    t = _make(build_mesh_from_axes({"data": 1}))
    t.step(_batch())
    t.save_checkpoint(prefix, 1, save_optimizer_states=True)
    t.step(_batch(1))
    t.save_checkpoint(prefix, 2, save_optimizer_states=True)
    # same-size corruption of the newest params file
    path = prefix + "-0002.params"
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    # the quick screen still lists it newest...
    assert find_checkpoints(prefix, require_states=True) == [1, 2]
    # ...but full verification falls back to epoch 1
    assert find_latest_checkpoint(prefix, require_states=True) == 1
    # and the trainer-side latest-load lands on the same epoch
    t2 = _make(build_mesh_from_axes({"data": 1}))
    assert t2.load_latest_checkpoint(
        prefix, load_optimizer_states=True) == 1


# ------------------------------------------------------ offline converter

def test_offline_convert_and_verify(saved_ckpt, tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "reshard_tool",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "reshard.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)

    out_prefix = str(tmp_path / "conv" / "job")
    plan = tool.convert(saved_ckpt["prefix"], 2, out_prefix,
                        {"data": 8})
    assert plan["dst"] == "{data:8}"
    assert plan["n_resharded"] > 0        # tp-sharded weights respec'd
    assert tool.verify_roundtrip(saved_ckpt["prefix"], 2,
                                 out_prefix, say=lambda s: None) == []
    # the converted manifest makes a {data:8} load NON-reshaping...
    before = telemetry.counter("mxtpu_reshard_total").labels(
        kind="load").get()
    t = _make(build_mesh_from_axes({"data": 8}))
    t.load_checkpoint(out_prefix, 2, load_optimizer_states=True)
    assert telemetry.counter("mxtpu_reshard_total").labels(
        kind="load").get() == before
    got = _gather_all(t)
    for k, v in saved_ckpt["state"].items():
        assert np.array_equal(v, got[k]), k
    # ...and an infeasible target is refused with nothing written
    with pytest.raises(MXNetError, match="not divisible"):
        tool.convert(saved_ckpt["prefix"], 2,
                     str(tmp_path / "bad" / "job"), {"model": 4},
                     rules=".*_weight=model;.*=")
    assert tool.parse_mesh("data=4,model=2") == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        tool.parse_mesh("data=x")


# ------------------------------------------------- elastic supervision

def test_launch_elastic_resize_events(tmp_path):
    """tools/launch.py --elastic: rank 1 of 2 dies on attempt 0; the
    watchdog relaunches ONE worker (rank_leave + elastic_resize events
    in the supervisor stream; MXNET_TPU_NUM_PROCESSES=1 in the resized
    attempt) and the job recovers.  Framework-free workers — this
    tests the supervisor, not jax."""
    import subprocess
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sup = str(tmp_path / "sup.jsonl")
    log = str(tmp_path / "worlds.txt")
    worker = (
        "import os\n"
        "with open(%r, 'a') as f:\n"
        "    f.write('%%s/%%s/%%s\\n' %% ("
        "os.environ['MXNET_TPU_RESTART_COUNT'],"
        "os.environ['MXNET_TPU_PROCESS_ID'],"
        "os.environ['MXNET_TPU_NUM_PROCESSES']))\n"
        "raise SystemExit(3 if os.environ['MXNET_TPU_PROCESS_ID'] == "
        "'1' and os.environ['MXNET_TPU_RESTART_COUNT'] == '0' else 0)\n"
        % log)
    script = tmp_path / "worker.py"
    script.write_text(worker)
    env = dict(os.environ, MXNET_TPU_TELEMETRY_JSONL=sup)
    res = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "--elastic",
         "--restart-budget", "1", "--heartbeat-interval", "0.05",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=120, env=env)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "elastic resize 2 -> 1 worker(s)" in res.stderr, res.stderr
    lines = open(log).read().splitlines()
    assert "0/0/2" in lines and "0/1/2" in lines and "1/0/1" in lines, \
        lines
    events = [json.loads(l) for l in open(sup)]
    leaves = [e for e in events if e.get("event") == "rank_leave"]
    assert leaves and leaves[0]["rank"] == 1, events
    resizes = [e for e in events if e.get("event") == "elastic_resize"]
    assert resizes and resizes[0]["from_workers"] == 2 \
        and resizes[0]["to_workers"] == 1, events


def test_kvstore_state_roundtrip(tmp_path):
    """DistKVStore.save_state/load_state migrate the key/value store
    through the manifest-verified checkpoint format; a forged saved
    world records the kvstore reshard + rank_join."""
    from mxnet_tpu.parallel.dist_kvstore import DistKVStore
    kv = DistKVStore("dist_sync")
    kv.init([3, "named"], [mx.nd.array(np.arange(4, dtype="f")),
                           mx.nd.array(np.ones((2, 2), "f"))])
    # a numeric-looking STRING key must survive as a string (the typed
    # kv:i:/kv:s: encoding keeps it apart from int keys)
    kv.init("7", mx.nd.array(np.full((3,), 9, "f")))
    prefix = str(tmp_path / "kv")
    kv.save_state(prefix, 5)
    kv2 = DistKVStore("dist_sync")
    assert kv2.load_state(prefix, 5) == 1
    out = mx.nd.zeros((4,))
    kv2.pull(3, out=out)
    np.testing.assert_array_equal(out.asnumpy(),
                                  np.arange(4, dtype="f"))
    out7 = mx.nd.zeros((3,))
    kv2.pull("7", out=out7)
    np.testing.assert_array_equal(out7.asnumpy(), np.full((3,), 9, "f"))
    # forge a bigger saved world -> rank_leave + kvstore reshard event
    man = json.load(open(prefix + "-0005.manifest.json"))
    man["meta"]["mesh"]["world"] = 3
    with open(prefix + "-0005.manifest.json", "w") as f:
        json.dump(man, f)
    before = telemetry.counter("mxtpu_reshard_total").labels(
        kind="kvstore").get()
    kv3 = DistKVStore("dist_sync")
    assert kv3.load_state(prefix, 5) == 3
    assert telemetry.counter("mxtpu_reshard_total").labels(
        kind="kvstore").get() == before + 1
    ev = [e for e in telemetry.flight.events()
          if e["kind"] == "rank_leave" and e.get("from_world") == 3]
    assert ev and ev[-1]["to_world"] == 1, ev
