"""Ring attention over the virtual 8-device mesh vs the single-device
oracle (new TPU-native long-context capability; no reference analogue —
SURVEY §5.7 notes ring attention as beyond-reference scope)."""
import numpy as np
import pytest

import mxnet_tpu  # noqa: F401  (jax config via conftest)


def _setup(B=2, T=32, H=4, D=16, seed=0):
    import jax
    rng = np.random.RandomState(seed)
    q = rng.normal(0, 1, (B, T, H, D)).astype(np.float32)
    k = rng.normal(0, 1, (B, T, H, D)).astype(np.float32)
    v = rng.normal(0, 1, (B, T, H, D)).astype(np.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    import jax
    from mxnet_tpu.parallel import build_mesh
    from mxnet_tpu.parallel.sequence import (ring_attention,
                                             attention_reference)
    q, k, v = _setup()
    mesh = build_mesh(n_devices=8, tp=1, axis_names=("sp",))
    out = ring_attention(q, k, v, mesh, seq_axis="sp", causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grad():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import build_mesh
    from mxnet_tpu.parallel.sequence import (ring_attention,
                                             attention_reference)
    q, k, v = _setup(B=1, T=16, H=2, D=8)
    mesh = build_mesh(n_devices=4, tp=1, axis_names=("sp",))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, seq_axis="sp") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)
