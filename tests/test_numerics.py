"""Training-health numerics (telemetry.numerics, docs/api/telemetry.md).

Covers: in-graph stat oracles vs numpy, sampling cadence (the UNSAMPLED
step program's jaxpr is equation-identical to the numerics-off one),
anomaly rules (nonfinite / grad_spike / dead_grad) incl. the strict-mode
raise + flight dump, NaN/Inf provenance naming a seeded node, the
ledger write/read/schema-reject roundtrip, tools/numdiff.py localizing a
seeded single-tensor divergence to the exact step, a fused-vs-unfused
ledger comparison that passes clean on a zoo model, the jit-safe
Monitor default (eager=True opt-in), the metric-layer non-finite guard,
and the out-of-range-label regression (parallel/trainer.py loss
mode="clip").
"""
import importlib.util
import json
import math
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models, resilience, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.telemetry import numerics
from mxnet_tpu.parallel import ShardedTrainer, build_mesh


def _load_tool(name):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(root, "tools", "%s.py" % name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for k in ("MXNET_TPU_NUMERICS_EVERY", "MXNET_TPU_NUMERICS_STRICT",
              "MXNET_TPU_NUMERICS_LEDGER", "MXNET_TPU_NUMERICS_SPIKE",
              "MXNET_TPU_NUMERICS_DEAD", "MXNET_TPU_FLIGHT_DIR",
              "MXNET_TPU_FAULTS", "MXNET_TPU_TELEMETRY_JSONL"):
        monkeypatch.delenv(k, raising=False)
    resilience.clear_faults()
    telemetry.reset()
    yield
    resilience.clear_faults()
    telemetry.reset()


def _mlp_trainer(**kw):
    np.random.seed(11)   # Xavier init draws from numpy's global RNG
    net = models.get_model("mlp", num_classes=10)
    kw.setdefault("dtype", "float32")
    kw.setdefault("seed", 0)
    return ShardedTrainer(net, build_mesh(tp=1),
                          data_shapes={"data": (8, 64)},
                          label_shapes={"softmax_label": (8,)}, **kw)


def _batch(seed=3, bad=False, labels_hi=10):
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, (8, 64)).astype(np.float32)
    if bad:
        x[0, 0] = np.nan
    return {"data": x,
            "softmax_label": rng.randint(0, labels_hi, 8)
            .astype(np.float32)}


# ------------------------------------------------------- stat oracles

def test_tensor_stats_vs_numpy_oracle():
    import jax
    rng = np.random.RandomState(0)
    x = rng.uniform(-2, 2, (7, 13)).astype(np.float32)
    x[0, 0] = np.nan
    x[1, 2] = np.inf
    x[3, :5] = 0.0
    st = jax.device_get(numerics.tensor_stats(x, digest=True))
    finite = x[np.isfinite(x)]
    assert st["nonfinite"] == 2
    assert abs(st["l2"] - np.sqrt((finite ** 2).sum())) < 1e-3
    assert abs(st["mean_abs"]
               - np.abs(np.where(np.isfinite(x), x, 0)).mean()) < 1e-6
    assert abs(st["max_abs"] - np.abs(finite).max()) < 1e-6
    assert abs(st["zero_frac"] - (x == 0).mean()) < 1e-6
    # digest oracle: wrapping uint32 sum of the float32 bit patterns
    want = int(x.view(np.uint32).astype(np.uint64).sum() % (1 << 32))
    assert int(st["digest"]) == want


def test_tensor_stats_inside_jit_and_digest_sensitivity():
    import jax
    import jax.numpy as jnp
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    st = jax.jit(lambda a: numerics.tensor_stats(a, digest=True))(x)
    st = jax.device_get(st)
    assert abs(st["l2"] - np.sqrt((x ** 2).sum())) < 1e-4
    y = x.copy()
    y[2, 3] = np.float32(11.000002)   # a few-ulp flip
    assert y[2, 3] != x[2, 3]
    d2 = int(jax.device_get(numerics.value_digest(jnp.asarray(y))))
    assert d2 != int(st["digest"])


# -------------------------------------------------- sampling cadence

def test_unsampled_step_program_unchanged(monkeypatch):
    """The tentpole no-overhead guarantee: with numerics ENABLED, the
    program dispatched on unsampled steps has exactly the jaxpr of the
    numerics-off step (the stats variant is a separate compile)."""
    import jax
    import jax.numpy as jnp

    def eqn_count(trainer):
        batch = trainer.put_batch(_batch())
        jaxpr = jax.make_jaxpr(trainer._py_step)(
            trainer.params, trainer.opt_state, trainer.aux, batch,
            jax.random.PRNGKey(0), jnp.float32(0.1), jnp.float32(1.0))
        return len(jaxpr.jaxpr.eqns)

    monkeypatch.delenv("MXNET_TPU_NUMERICS_EVERY", raising=False)
    off = eqn_count(_mlp_trainer())
    monkeypatch.setenv("MXNET_TPU_NUMERICS_EVERY", "2")
    tr = _mlp_trainer()
    on = eqn_count(tr)
    assert on == off
    # and the stats VARIANT is a strictly larger program
    tr._build_step(collect_stats=True)
    batch = tr.put_batch(_batch())
    jaxpr = jax.make_jaxpr(tr._py_step_stats)(
        tr.params, tr.opt_state, tr.aux, batch,
        jax.random.PRNGKey(0), jnp.float32(0.1), jnp.float32(1.0))
    assert len(jaxpr.jaxpr.eqns) > on


def test_sampling_cadence_and_ledger(monkeypatch, tmp_path):
    led = str(tmp_path / "run.ledger")
    monkeypatch.setenv("MXNET_TPU_NUMERICS_EVERY", "2")
    monkeypatch.setenv("MXNET_TPU_NUMERICS_LEDGER", led)
    tr = _mlp_trainer()
    batch = _batch()
    for _ in range(5):
        float(tr.step(batch))
    recs = numerics.read_ledger(led)
    assert [r["step"] for r in recs] == [1, 3, 5]
    s = numerics.summary()
    assert s["sampled_steps"] == 3 and s["every"] == 2
    assert s["last_grad_norm"] > 0
    # gauges published
    g = telemetry.gauge("mxtpu_grad_global_norm")
    assert g.get() == pytest.approx(s["last_grad_norm"], rel=1e-6)
    norm = telemetry.gauge("mxtpu_tensor_norm")
    assert norm.labels(tensor="fc1_weight", kind="grad").get() > 0
    assert norm.labels(tensor="fc1_weight", kind="param").get() > 0
    # every record carries the full stat bundle + digests
    for r in recs:
        st = r["tensors"]["param/fc1_weight"]
        for k in ("l2", "mean_abs", "max_abs", "nonfinite",
                  "zero_frac", "digest"):
            assert k in st
        assert r["grad_norm"] > 0 and isinstance(r["digest"], int)


# ------------------------------------------------------ anomaly rules

def test_nonfinite_anomaly_nonstrict_warns_not_raises(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_NUMERICS_EVERY", "1")
    tr = _mlp_trainer()
    float(tr.step(_batch()))
    tr.step(_batch(bad=True))    # NaN data: detected, not fatal
    c = telemetry.counter("mxtpu_numerics_anomalies_total")
    assert c.labels(rule="nonfinite").get() >= 1
    bad = telemetry.counter("mxtpu_nonfinite_total")
    total = sum(bad.samples().values())
    assert total > 0
    evs = [e for e in telemetry.flight.events()
           if e["kind"] == "numerics_anomaly"]
    assert any(e["rule"] == "nonfinite" for e in evs)


def test_strict_mode_raises_with_flight_dump_and_provenance(
        monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_TPU_NUMERICS_EVERY", "1")
    monkeypatch.setenv("MXNET_TPU_NUMERICS_STRICT", "1")
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path))
    tr = _mlp_trainer()
    float(tr.step(_batch()))
    with pytest.raises(MXNetError) as ei:
        tr.step(_batch(bad=True))
    msg = str(ei.value)
    assert "nonfinite" in msg and "grad/" in msg
    assert "producing node" in msg
    dumps = [f for f in os.listdir(str(tmp_path))
             if f.startswith("flight-") and f.endswith(".json")]
    assert dumps, "strict stop left no flight dump"
    provs = []
    for name in dumps:
        with open(os.path.join(str(tmp_path), name)) as f:
            doc = json.load(f)
        for ev in doc["events"]:
            if ev.get("kind") == "numerics_anomaly" and \
                    ev.get("provenance"):
                provs.append(ev["provenance"]["node"])
    assert provs and all(isinstance(p, str) and p for p in provs)


def test_provenance_names_seeded_nan_node_via_fault_seam(monkeypatch):
    """The numerics.nonfinite resilience seam poisons the data input;
    the eager replay must name the FIRST op node downstream of it."""
    monkeypatch.setenv("MXNET_TPU_NUMERICS_EVERY", "1")
    tr = _mlp_trainer()
    float(tr.step(_batch()))
    monkeypatch.setenv("MXNET_TPU_FAULTS", "numerics.nonfinite:n=1")
    tr.step(_batch())
    evs = [e for e in telemetry.flight.events()
           if e["kind"] == "numerics_anomaly" and e.get("provenance")]
    assert evs, "no anomaly event carries provenance"
    node = evs[0]["provenance"]["node"]
    # the MLP's first op after the poisoned data input is its flatten
    # (auto-named flattenN — the counter is process-global)
    import re
    assert re.fullmatch(r"flatten\d+_output", node), node
    assert evs[0]["provenance"]["nonfinite"] > 0


def test_grad_spike_rule_fires_on_ewma_breakout(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_NUMERICS_SPIKE", "10")

    def payload(gn):
        return {"tensors": {"grad/w": {"l2": gn, "mean_abs": 0.1,
                                       "max_abs": 1.0, "nonfinite": 0,
                                       "zero_frac": 0.0}},
                "grad_norm": np.float32(gn)}

    for i, gn in enumerate((1.0, 1.1, 0.9)):
        out = numerics.process_step(payload(gn), step=i + 1,
                                    program="test.step")
        assert "anomalies" not in out
    out = numerics.process_step(payload(500.0), step=4,
                                program="test.step")
    rules = [a["rule"] for a in out["anomalies"]]
    assert "grad_spike" in rules
    c = telemetry.counter("mxtpu_numerics_anomalies_total")
    assert c.labels(rule="grad_spike").get() == 1
    # the spike did NOT fold into the EWMA: a second spike still fires
    out = numerics.process_step(payload(500.0), step=5,
                                program="test.step")
    assert "grad_spike" in [a["rule"] for a in out["anomalies"]]


def test_dead_grad_rule(monkeypatch):
    p = {"tensors": {"grad/w": {"l2": 0.0, "mean_abs": 0.0,
                                "max_abs": 0.0, "nonfinite": 0,
                                "zero_frac": 1.0},
                     "param/w": {"l2": 1.0, "mean_abs": 0.1,
                                 "max_abs": 1.0, "nonfinite": 0,
                                 "zero_frac": 1.0}},
         "grad_norm": 0.0}
    out = numerics.process_step(p, step=1, program="test.dead")
    anomalies = out["anomalies"]
    assert [a["rule"] for a in anomalies] == ["dead_grad"]
    # only grad/* tensors count as dead; the all-zero PARAM does not
    assert anomalies[0]["tensors"] == ["grad/w"]


# ------------------------------------------------------------- ledger

def test_ledger_read_rejects_wrong_schema(tmp_path):
    bad = tmp_path / "notledger.jsonl"
    bad.write_text(json.dumps({"schema": "mxtpu-flight/1",
                               "events": []}) + "\n")
    with pytest.raises(ValueError):
        numerics.read_ledger(str(bad))
    with pytest.raises(ValueError):
        numerics.read_ledger(str(tmp_path / "missing.jsonl"))
    # malformed record (schema but no tensors) also rejected
    bad2 = tmp_path / "malformed.jsonl"
    bad2.write_text(json.dumps({"schema": numerics.SCHEMA,
                                "step": 1}) + "\n")
    with pytest.raises(ValueError):
        numerics.read_ledger(str(bad2))


def test_ledger_roundtrip_and_inline_form(monkeypatch, tmp_path):
    led = tmp_path / "a.jsonl"
    monkeypatch.setenv("MXNET_TPU_NUMERICS_LEDGER", str(led))
    p = {"tensors": {"grad/w": {"l2": 1.0, "mean_abs": 0.1,
                                "max_abs": 1.0, "nonfinite": 0,
                                "zero_frac": 0.0}},
         "grad_norm": 1.0}
    numerics.process_step(p, step=7, program="test.rt")
    recs = numerics.read_ledger(str(led))
    assert len(recs) == 1 and recs[0]["step"] == 7
    assert recs[0]["schema"] == numerics.SCHEMA
    # the inline (step-JSONL) carrier form parses too
    inline = tmp_path / "steps.jsonl"
    inline.write_text(json.dumps({"step": 7, "step_time_s": 0.1,
                                  "numerics": recs[0]}) + "\n")
    recs2 = numerics.read_ledger(str(inline))
    assert recs2 == recs


def _write_ledger(path, steps, mutate=None):
    """Synthesize a ledger; ``mutate(step, tensors)`` may edit."""
    with open(path, "w") as f:
        for step in steps:
            tensors = {
                "param/w": {"l2": 2.0, "mean_abs": 0.2, "max_abs": 1.0,
                            "nonfinite": 0, "zero_frac": 0.0,
                            "digest": 100 + step},
                "grad/w": {"l2": 1.0, "mean_abs": 0.1, "max_abs": 0.5,
                           "nonfinite": 0, "zero_frac": 0.0,
                           "digest": 200 + step},
            }
            if mutate:
                mutate(step, tensors)
            f.write(json.dumps({"schema": numerics.SCHEMA,
                                "step": step, "rank": 0,
                                "program": "t", "grad_norm": 1.0,
                                "digest": 0, "tensors": tensors})
                    + "\n")


def test_numdiff_localizes_seeded_divergence(tmp_path):
    a = str(tmp_path / "a.ledger")
    b = str(tmp_path / "b.ledger")
    _write_ledger(a, range(1, 9))

    def mutate(step, tensors):
        if step >= 5:
            tensors["grad/w"]["l2"] = 3.0     # 3x off from step 5 on
            tensors["grad/w"]["digest"] += 1
    _write_ledger(b, range(1, 9), mutate=mutate)
    numdiff = _load_tool("numdiff")
    rc = numdiff.main([a, b])
    assert rc == 1
    recs_a = numerics.read_ledger(a)
    recs_b = numerics.read_ledger(b)
    res = numerics.compare_ledgers(recs_a, recs_b)
    assert res["divergence"]["step"] == 5
    assert res["divergence"]["tensor"] == "grad/w"
    assert res["divergence"]["rel"] > 0.1
    # identical ledgers: bit-clean, exit 0
    assert numdiff.main([a, a]) == 0
    res = numerics.compare_ledgers(recs_a, recs_a)
    assert res["bit_clean"] and res["divergence"] is None
    # --strict-bits flips a within-tolerance digest skew to exit 1
    c = str(tmp_path / "c.ledger")

    def bitflip(step, tensors):
        tensors["grad/w"]["digest"] += 1      # stats identical
    _write_ledger(c, range(1, 9), mutate=bitflip)
    assert numdiff.main([a, c]) == 0
    assert numdiff.main([a, c, "--strict-bits"]) == 1
    # disjoint step sets: usage error
    d = str(tmp_path / "d.ledger")
    _write_ledger(d, range(100, 103))
    assert numdiff.main([a, d]) == 2


def test_fused_vs_unfused_ledger_clean_on_zoo_model(monkeypatch,
                                                    tmp_path):
    """Acceptance: the fused path's numerics stay within tolerance of
    the unfused reference on a zoo model — continuously auditable
    lowering (Glow's verification story), not a one-shot unit test."""
    monkeypatch.setenv("MXNET_TPU_NUMERICS_EVERY", "1")

    def run(ledger, fuse):
        os.environ["MXNET_TPU_NUMERICS_LEDGER"] = ledger
        numerics.reset()
        tr = _mlp_trainer(fuse_blocks=fuse)
        batch = _batch()
        for _ in range(3):
            float(tr.step(batch))
        if fuse:
            # the fused leg really fused: block/* entries in its ledger
            recs = numerics.read_ledger(ledger)
            assert any(n.startswith("block/")
                       for n in recs[0]["tensors"])

    a = str(tmp_path / "unfused.ledger")
    b = str(tmp_path / "fused.ledger")
    run(a, fuse=False)
    run(b, fuse=True)
    monkeypatch.delenv("MXNET_TPU_NUMERICS_LEDGER", raising=False)
    res = numerics.compare_ledgers(numerics.read_ledger(a),
                                   numerics.read_ledger(b), rtol=1e-3)
    assert res["steps_compared"] == 3
    assert res["divergence"] is None, res["divergence"]
    assert res["only_b"] > 0        # the uncompared block/* entries
    numdiff = _load_tool("numdiff")
    assert numdiff.main([a, b, "--rtol", "1e-3"]) == 0


# ------------------------------------- run_top / distview integration

def test_run_timeline_carries_grad_norm_and_digest(tmp_path):
    from mxnet_tpu.telemetry import distview
    base = str(tmp_path / "steps.jsonl")
    agg = distview.RunAggregator(base, num_ranks=2)
    for step in (1, 2):
        for rank, gn in ((0, 1.0), (1, 1.0 if step == 1 else 9.0)):
            agg.feed(rank, {"step": step, "step_time_s": 0.1,
                            "ts": step + rank / 10.0,
                            "grad_norm": gn,
                            "digest": 42 if step == 1 else 42 + rank})
    agg.close()
    recs = distview.read_run_timeline(base + ".run")
    steps = [r for r in recs if r.get("kind") == "step"]
    assert steps[0].get("grad_skew") == 0.0
    assert steps[1]["grad_skew"] == pytest.approx(8.0)
    assert "digest_mismatch" not in steps[0]
    assert steps[1]["digest_mismatch"] is True
    summary = distview.summarize_run(recs)
    assert summary["grad_skew_max"] == pytest.approx(8.0)
    assert summary["digest_mismatch_steps"] == 1
    assert summary["per_rank"]["1"]["grad_norm_last"] == 9.0
    assert summary["per_rank"]["1"]["digest_last"] == 43
    # run_top renders the numerics columns
    run_top = _load_tool("run_top")
    dash = run_top.format_dashboard(recs, summary)
    assert "grad norm" in dash and "DIGEST MISMATCH" in dash
    text = run_top.format_summary(summary)
    assert "grad-norm skew" in text and "grad_norm=9" in text


def test_step_jsonl_carries_numerics_pair(monkeypatch, tmp_path):
    path = str(tmp_path / "steps.jsonl")
    monkeypatch.setenv("MXNET_TPU_TELEMETRY_JSONL", path)
    monkeypatch.setenv("MXNET_TPU_NUMERICS_EVERY", "2")
    tr = _mlp_trainer()
    batch = _batch()
    for _ in range(2):
        float(tr.step(batch))
    recs = [json.loads(l) for l in open(path)]
    assert "grad_norm" in recs[0] and "digest" in recs[0]   # sampled
    assert "grad_norm" not in recs[1]                       # unsampled
    # with no dedicated ledger file, the step-log IS the ledger: the
    # full record rides inline and numdiff/read_ledger accept the file
    assert recs[0]["numerics"]["schema"] == numerics.SCHEMA
    led = numerics.read_ledger(path)
    assert len(led) == 1 and led[0]["step"] == 1
    assert "param/fc1_weight" in led[0]["tensors"]
    # a dedicated ledger file suppresses the inline duplicate
    monkeypatch.setenv("MXNET_TPU_NUMERICS_LEDGER",
                       str(tmp_path / "own.ledger"))
    float(tr.step(batch))
    recs = [json.loads(l) for l in open(path)]
    assert "grad_norm" in recs[2] and "numerics" not in recs[2]


def test_compare_ledgers_flags_nonfinite_count_mismatch(tmp_path):
    """NaNs appearing in one run and not the other must DIVERGE even
    when the finite-masked l2/mean stats agree within tolerance."""
    a = str(tmp_path / "a.ledger")
    b = str(tmp_path / "b.ledger")
    _write_ledger(a, range(1, 4))

    def mutate(step, tensors):
        if step == 2:
            tensors["grad/w"]["nonfinite"] = 7   # stats left identical
    _write_ledger(b, range(1, 4), mutate=mutate)
    res = numerics.compare_ledgers(numerics.read_ledger(a),
                                   numerics.read_ledger(b))
    assert res["divergence"] == {"step": 2, "tensor": "grad/w",
                                 "stat": "nonfinite", "a": 0, "b": 7,
                                 "rel": 1.0}
    numdiff = _load_tool("numdiff")
    assert numdiff.main([a, b]) == 1


def test_compare_ledgers_max_abs_and_zero_frac(tmp_path):
    """Single-element corruption (max_abs jumps, l2 barely moves) and
    flush-to-zero drift (zero_frac jumps) must DIVERGE; zero_frac
    compares absolutely so a borderline element flip (0 vs 1e-7)
    stays within tolerance."""
    a = str(tmp_path / "a.ledger")
    _write_ledger(a, range(1, 4))

    b = str(tmp_path / "b.ledger")

    def spike(step, tensors):
        if step == 2:
            tensors["grad/w"]["max_abs"] = 5.0    # l2/mean unchanged
    _write_ledger(b, range(1, 4), mutate=spike)
    res = numerics.compare_ledgers(numerics.read_ledger(a),
                                   numerics.read_ledger(b))
    assert res["divergence"]["stat"] == "max_abs"
    assert res["divergence"]["step"] == 2

    c = str(tmp_path / "c.ledger")

    def flush(step, tensors):
        tensors["grad/w"]["zero_frac"] = 0.5      # flush-to-zero
    _write_ledger(c, range(1, 4), mutate=flush)
    res = numerics.compare_ledgers(numerics.read_ledger(a),
                                   numerics.read_ledger(c))
    assert res["divergence"]["stat"] == "zero_frac"

    d = str(tmp_path / "d.ledger")

    def borderline(step, tensors):
        tensors["grad/w"]["zero_frac"] = 1e-7     # one element of 10M
    _write_ledger(d, range(1, 4), mutate=borderline)
    res = numerics.compare_ledgers(numerics.read_ledger(a),
                                   numerics.read_ledger(d))
    assert res["divergence"] is None


def test_grad_spike_ewma_scoped_per_caller():
    """Two step streams with different typical norms must not share a
    baseline: model B's healthy first step would spike against model
    A's tiny EWMA."""
    def payload(gn):
        return {"tensors": {}, "grad_norm": np.float32(gn)}

    for step in (1, 2):
        out = numerics.process_step(payload(0.01), step=step,
                                    program="trainer.step",
                                    scope=("trainer.step", "A"))
        assert "anomalies" not in out
    out = numerics.process_step(payload(1.0), step=1,
                                program="trainer.step",
                                scope=("trainer.step", "B"))
    assert "anomalies" not in out, "scope B tripped on scope A's EWMA"


def test_run_top_digest_columns_survive_all_nan_run(tmp_path):
    """An all-NaN run omits its grad norms from the step records but
    keeps digests — the dashboard must still show the numerics columns
    and the digest-mismatch flag."""
    from mxnet_tpu.telemetry import distview
    base = str(tmp_path / "steps.jsonl")
    agg = distview.RunAggregator(base, num_ranks=2)
    for rank in (0, 1):
        agg.feed(rank, {"step": 1, "step_time_s": 0.1,
                        "ts": 1.0 + rank, "digest": 7 + rank})
    agg.close()
    recs = distview.read_run_timeline(base + ".run")
    summary = distview.summarize_run(recs)
    assert summary["grad_skew_max"] is None
    assert summary["digest_mismatch_steps"] == 1
    run_top = _load_tool("run_top")
    dash = run_top.format_dashboard(recs, summary)
    assert "digest" in dash and "DIGEST MISMATCH" in dash
    text = run_top.format_summary(summary)
    assert "DIGEST MISMATCH" in text


def test_dead_grad_zero_threshold_disables(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_NUMERICS_DEAD", "0")
    p = {"tensors": {"grad/w": {"l2": 0.0, "mean_abs": 0.0,
                                "max_abs": 0.0, "nonfinite": 0,
                                "zero_frac": 1.0}},
         "grad_norm": 0.0}
    out = numerics.process_step(p, step=1, program="test.deadoff")
    assert "anomalies" not in out


def test_nan_seam_defers_to_a_sampled_step(monkeypatch):
    """An armed numerics.nonfinite fault on an unsampled step must NOT
    fire there (the poison would land where detection never runs): the
    seam is evaluated only on sampled steps, so the injection lands on
    the next sampled one and is detected."""
    monkeypatch.setenv("MXNET_TPU_NUMERICS_EVERY", "2")   # samples 1,3
    tr = _mlp_trainer()
    batch = _batch()
    float(tr.step(batch))                                 # step 1
    monkeypatch.setenv("MXNET_TPU_FAULTS", "numerics.nonfinite:n=1")
    float(tr.step(batch))                                 # step 2: unsampled
    assert telemetry.counter("mxtpu_numerics_anomalies_total")
    c = telemetry.counter("mxtpu_numerics_anomalies_total")
    assert c.labels(rule="nonfinite").get() == 0          # not fired yet
    tr.step(batch)                                        # step 3: sampled
    assert c.labels(rule="nonfinite").get() >= 1
    assert resilience.fault_stats()["numerics.nonfinite"]["hits"] == 1


def test_run_steps_warns_once_and_stays_unsampled(monkeypatch, caplog):
    monkeypatch.setenv("MXNET_TPU_NUMERICS_EVERY", "1")
    tr = _mlp_trainer()
    import logging
    with caplog.at_level(logging.WARNING):
        tr.run_steps(_batch(), 3)
        tr.run_steps(_batch(), 3)
    warns = [r for r in caplog.records
             if "run_steps chains are not sampled" in r.getMessage()]
    assert len(warns) == 1
    assert numerics.summary()["sampled_steps"] == 0


def test_grad_spike_zero_factor_disables(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_NUMERICS_SPIKE", "0")

    def payload(gn):
        return {"tensors": {}, "grad_norm": np.float32(gn)}

    numerics.process_step(payload(1.0), step=1, program="test.spikeoff")
    out = numerics.process_step(payload(1e6), step=2,
                                program="test.spikeoff")
    assert "anomalies" not in out


def test_sampling_phased_on_global_step_across_resume(monkeypatch,
                                                      tmp_path):
    """A resumed run must sample the SAME global step numbers as a
    from-scratch one, or pre- vs post-resume ledgers share no steps
    and the headline numdiff comparison exits 2."""
    monkeypatch.setenv("MXNET_TPU_NUMERICS_EVERY", "5")
    led = str(tmp_path / "resumed.ledger")
    monkeypatch.setenv("MXNET_TPU_NUMERICS_LEDGER", led)
    tr = _mlp_trainer()
    tr._resume_epoch = 7        # as load_checkpoint(epoch=7) leaves it
    batch = _batch()
    for _ in range(6):          # global steps 8..13
        float(tr.step(batch))
    recs = numerics.read_ledger(led)
    # cadence 5 phased globally samples 11 (= 1 + 2*5), not 8
    assert [r["step"] for r in recs] == [11]


def test_stats_monitor_publishes_node_norm_gauge():
    data = mx.sym.Variable("data")
    net = mx.sym.sigmoid(data, name="sg")
    ex = net.simple_bind(mx.cpu(), data=(2, 2))
    mon = mx.Monitor(1, pattern=".*")
    mon.install(ex)
    mon.tic()
    ex.forward(data=np.zeros((2, 2), np.float32))
    mon.toc()
    g = telemetry.gauge("mxtpu_tensor_norm")
    # l2 of four 0.5s = sqrt(4 * 0.25) = 1.0
    assert g.labels(tensor="sg_output", kind="node").get() == \
        pytest.approx(1.0, rel=1e-5)


def test_ledger_lines_stay_strict_json_under_nan(monkeypatch, tmp_path):
    led = tmp_path / "nan.ledger"
    monkeypatch.setenv("MXNET_TPU_NUMERICS_LEDGER", str(led))
    p = {"tensors": {"grad/w": {"l2": 0.0, "mean_abs": 0.0,
                                "max_abs": 0.0, "nonfinite": 4,
                                "zero_frac": 0.0}},
         "grad_norm": float("nan"), "loss": float("inf")}
    numerics.process_step(p, step=1, program="test.nan")
    line = led.read_text().strip()
    assert "NaN" not in line and "Infinity" not in line
    rec = json.loads(line)             # strict-JSON parseable
    assert rec["grad_norm"] is None and rec["loss"] is None
    assert rec["tensors"]["grad/w"]["nonfinite"] == 4


# --------------------------------------------- jit-safe Monitor path

def test_monitor_default_is_jit_safe_stats_path():
    data = mx.sym.Variable("data")
    net = mx.sym.sigmoid(data, name="sig")
    ex = net.simple_bind(mx.cpu(), data=(2, 2))
    mon = mx.Monitor(1, pattern=".*")
    assert mon.eager is False
    mon.install(ex)
    assert ex._stats_cb is not None and ex._monitor_callback is None
    mon.tic()
    ex.forward(data=np.full((2, 2), -0.5, np.float32))
    res = mon.toc()
    assert any(k == "sig_output" for (_n, k, _v) in res)
    g = telemetry.gauge("mxtpu_monitor_stat").labels(
        tensor="sig_output")
    # mean |sigmoid(-0.5)| = sigmoid(-0.5)
    assert g.get() == pytest.approx(1 / (1 + math.exp(0.5)), rel=1e-5)
    # deactivated interval: the PLAIN forward program serves the call
    ex.forward(data=np.zeros((2, 2), np.float32))
    assert True  # no stats queued while inactive
    assert mon.toc() == []


def test_monitor_custom_stat_func_selects_eager():
    data = mx.sym.Variable("data")
    net = mx.sym.sigmoid(data, name="sig")
    ex = net.simple_bind(mx.cpu(), data=(2, 2))
    mon = mx.Monitor(1, stat_func=lambda x: x.asnumpy().max(),
                     pattern=".*")
    assert mon.eager is True
    mon.install(ex)
    assert ex._monitor_callback is not None
    mon.tic()
    ex.forward(data=np.zeros((2, 2), np.float32))
    res = mon.toc()
    assert any(k == "sig_output" for (_n, k, _v) in res)


def test_stats_monitor_counts_nonfinite_with_node_provenance():
    data = mx.sym.Variable("data")
    net = mx.sym.log(data, name="lg")       # log(0) = -inf
    ex = net.simple_bind(mx.cpu(), data=(2, 2))
    mon = mx.Monitor(1, pattern=".*")
    mon.install(ex)
    mon.tic()
    ex.forward(data=np.zeros((2, 2), np.float32))
    mon.toc()
    bad = telemetry.counter("mxtpu_nonfinite_total")
    assert bad.labels(tensor="node/lg_output").get() == 4
    evs = [e for e in telemetry.flight.events()
           if e["kind"] == "numerics_anomaly"]
    assert evs and evs[0]["provenance"]["node"] == "lg_output"


# ------------------------------------------------- metric satellites

def test_metric_nonfinite_update_counted_not_poisoning():
    m = mx.metric.MSE()
    m.update([mx.nd.array([1.0, 2.0])],
             [mx.nd.array([[1.0], [2.0]])])
    m.update([mx.nd.array([1.0, 2.0])],
             [mx.nd.array([[np.nan], [2.0]])])
    name, val = m.get()
    assert math.isfinite(val)       # the NaN batch did not poison it
    assert m.num_nonfinite == 1
    c = telemetry.counter("mxtpu_nonfinite_total")
    assert c.labels(tensor="metric/mse").get() == 1
    m.reset()
    assert m.num_nonfinite == 0


def test_metric_crossentropy_inf_guarded():
    m = mx.metric.CrossEntropy(eps=0.0)
    m.update([mx.nd.array([0.0])], [mx.nd.array([[1.0, 0.0]])])
    m.update([mx.nd.array([1.0])], [mx.nd.array([[1.0, 0.0]])])  # -log 0
    _, val = m.get()
    assert math.isfinite(val)
    assert m.num_nonfinite == 1


def test_out_of_range_label_loss_stays_finite(monkeypatch):
    """Regression for the mode='clip' note at parallel/trainer.py
    (jit's default fill mode would turn an out-of-range label into a
    NaN loss and poison the metric): labels >= num_classes must leave
    the monitoring loss finite AND trip no nonfinite anomaly."""
    monkeypatch.setenv("MXNET_TPU_NUMERICS_EVERY", "1")
    tr = _mlp_trainer()
    batch = _batch()
    batch["softmax_label"] = np.full((8,), 99.0, np.float32)  # >= 10
    loss = float(tr.step(batch))
    assert math.isfinite(loss)
    c = telemetry.counter("mxtpu_numerics_anomalies_total")
    assert c.labels(rule="nonfinite").get() == 0


# ----------------------------------------------------- misc contracts

def test_sampled_cadence_helper():
    os.environ["MXNET_TPU_NUMERICS_EVERY"] = "3"
    try:
        assert [s for s in range(1, 10) if numerics.sampled(s)] == \
            [1, 4, 7]
        os.environ["MXNET_TPU_NUMERICS_EVERY"] = "0"
        assert not any(numerics.sampled(s) for s in range(1, 10))
        os.environ["MXNET_TPU_NUMERICS_EVERY"] = "bogus"
        assert numerics.every() == 0
    finally:
        del os.environ["MXNET_TPU_NUMERICS_EVERY"]


def test_reset_clears_ewma_and_summary(monkeypatch):
    p = {"tensors": {}, "grad_norm": 1.0}
    numerics.process_step(p, step=1, program="test.reset")
    assert numerics.summary()["sampled_steps"] == 1
    telemetry.reset()
    s = numerics.summary()
    assert s["sampled_steps"] == 0 and s["last_grad_norm"] is None
