"""Transformer LM example + LayerNorm op.

The causal-attention stack (FlashAttention op, LayerNorm, positional
embeddings) trained through the Module API on a Markov corpus must
approach the generating chain's entropy floor.
"""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "examples", "transformer"))


def test_layernorm_forward_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6, 8).astype("f") * 3 + 1
    g = rng.rand(8).astype("f") + 0.5
    b = rng.randn(8).astype("f")
    out = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(g),
                          mx.nd.array(b), axis=-1, eps=1e-5).asnumpy()
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    want = (x - mean) / np.sqrt(var + 1e-5) * g + b
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-4)


def test_layernorm_grad_finite_difference():
    data = mx.sym.Variable("data")
    net = mx.sym.MakeLoss(mx.sym.sum(mx.sym.square(
        mx.sym.LayerNorm(data, name="ln"))))
    ex = net.simple_bind(mx.cpu(), data=(3, 5))
    rng = np.random.RandomState(1)
    # simple_bind zero-fills args: gamma/beta must be nonzero or the
    # whole computation (and both gradients) collapses to zero
    ex.arg_dict["ln_gamma"][:] = rng.rand(5).astype("f") + 0.5
    ex.arg_dict["ln_beta"][:] = rng.randn(5).astype("f")
    x = rng.randn(3, 5).astype("f")
    ex.forward(is_train=True, data=x)
    ex.backward()
    g = ex.grad_dict["data"].asnumpy()
    eps = 1e-3
    num = np.zeros_like(x)
    for i in range(3):
        for j in range(5):
            for s, sign in ((eps, 1), (-eps, -1)):
                xp = x.copy()
                xp[i, j] += s
                ex.forward(is_train=False, data=xp)
                num[i, j] += sign * float(ex.outputs[0].asnumpy().sum())
    num /= 2 * eps
    np.testing.assert_allclose(g, num, rtol=2e-2, atol=2e-2)


def test_gpt_mini_approaches_entropy_floor():
    import train_lm
    ppl, floor = train_lm.train(epochs=3, seq_len=32, vocab_size=32,
                                d_model=32)
    assert ppl < 1.5 * floor, (ppl, floor)
    assert ppl < 8, ppl     # uniform would be 32
