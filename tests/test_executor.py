"""Executor bind/forward/backward — reference tests/python/unittest/
test_executor.py."""
import numpy as np

import mxnet_tpu as mx


def test_bind_forward_add():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = a + b
    an = np.random.uniform(-1, 1, (3, 4)).astype(np.float32)
    bn = np.random.uniform(-1, 1, (3, 4)).astype(np.float32)
    ex = c.bind(mx.cpu(), {"a": mx.nd.array(an), "b": mx.nd.array(bn)})
    out = ex.forward()
    np.testing.assert_allclose(out[0].asnumpy(), an + bn, rtol=1e-6)


def test_backward_mul():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = mx.sym.MakeLoss(a * b, name="loss")
    an = np.random.uniform(0.5, 1.5, (2, 3)).astype(np.float32)
    bn = np.random.uniform(0.5, 1.5, (2, 3)).astype(np.float32)
    ex = c.simple_bind(mx.cpu(), a=(2, 3), b=(2, 3))
    ex.arg_dict["a"][:] = an
    ex.arg_dict["b"][:] = bn
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(), bn, rtol=1e-5)
    np.testing.assert_allclose(ex.grad_dict["b"].asnumpy(), an, rtol=1e-5)


def test_grad_req_add():
    a = mx.sym.Variable("a")
    loss = mx.sym.MakeLoss(a * 2.0)
    ex = a_bind = loss.simple_bind(mx.cpu(), grad_req="add", a=(2, 2))
    ex.arg_dict["a"][:] = 1.0
    for _ in range(3):
        ex.forward(is_train=True)
        ex.backward()
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(),
                               np.full((2, 2), 6.0), rtol=1e-6)


def test_softmax_output_grad():
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(data=data, name="softmax")
    x = np.random.uniform(-1, 1, (4, 5)).astype(np.float32)
    label = np.array([0, 1, 2, 3], dtype=np.float32)
    ex = net.simple_bind(mx.cpu(), data=(4, 5), softmax_label=(4,))
    ex.forward(is_train=True, data=x, softmax_label=label)
    probs = ex.outputs[0].asnumpy()
    expect = np.exp(x) / np.exp(x).sum(axis=1, keepdims=True)
    np.testing.assert_allclose(probs, expect, rtol=1e-5)
    ex.backward()
    onehot = np.zeros((4, 5), dtype=np.float32)
    onehot[np.arange(4), label.astype(int)] = 1.0
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(),
                               probs - onehot, rtol=1e-4, atol=1e-5)


def test_batchnorm_aux_update():
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data=data, momentum=0.5, fix_gamma=False,
                          name="bn")
    loss = mx.sym.MakeLoss(bn)
    ex = loss.simple_bind(mx.cpu(), data=(8, 3, 4, 4))
    ex.arg_dict["bn_gamma"][:] = 1.0
    ex.aux_dict["bn_moving_var"][:] = 1.0
    x = np.random.normal(2.0, 3.0, (8, 3, 4, 4)).astype(np.float32)
    ex.forward(is_train=True, data=x)
    mm = ex.aux_dict["bn_moving_mean"].asnumpy()
    batch_mean = x.mean(axis=(0, 2, 3))
    np.testing.assert_allclose(mm, 0.5 * batch_mean, rtol=1e-4, atol=1e-4)
    # inference path must not update aux
    ex.forward(is_train=False, data=x)
    np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(), mm)


def test_dropout_fwd_bwd_consistent():
    data = mx.sym.Variable("data")
    net = mx.sym.MakeLoss(mx.sym.Dropout(data=data, p=0.5, name="drop"))
    ex = net.simple_bind(mx.cpu(), data=(100,))
    x = np.ones(100, dtype=np.float32)
    ex.forward(is_train=True, data=x)
    out = ex.outputs[0].asnumpy()
    ex.backward()
    g = ex.grad_dict["data"].asnumpy()
    # gradient mask must match the forward mask exactly
    np.testing.assert_allclose(g, out, rtol=1e-6)


def test_shared_params_two_executors():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=3, name="fc")
    ex1 = fc.simple_bind(mx.cpu(), data=(2, 4))
    w = ex1.arg_dict["fc_weight"]
    w[:] = 1.0
    ex2 = fc.bind(mx.cpu(), ex1.arg_dict)
    x = np.ones((2, 4), dtype=np.float32)
    out = ex2.forward(data=x)[0].asnumpy()
    np.testing.assert_allclose(out, np.full((2, 3), 4.0), rtol=1e-6)


def test_head_gradient():
    a = mx.sym.Variable("a")
    out = a * 3.0
    ex = out.simple_bind(mx.cpu(), a=(2, 2))
    ex.arg_dict["a"][:] = 1.0
    ex.forward(is_train=True)
    og = mx.nd.array(np.full((2, 2), 2.0, dtype=np.float32))
    ex.backward([og])
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(),
                               np.full((2, 2), 6.0), rtol=1e-6)


def test_reshape():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    ex = fc.simple_bind(mx.cpu(), data=(8, 6))
    ex.arg_dict["fc_weight"][:] = 0.5
    ex2 = ex.reshape(data=(2, 6))
    assert ex2.arg_dict["data"].shape == (2, 6)
    # weight shared
    assert ex2.arg_dict["fc_weight"] is ex.arg_dict["fc_weight"]
    out = ex2.forward(data=np.ones((2, 6), dtype=np.float32))
    np.testing.assert_allclose(out[0].asnumpy(), np.full((2, 4), 3.0),
                               rtol=1e-6)


def test_monitor_callback():
    seen = []
    data = mx.sym.Variable("data")
    net = mx.sym.sigmoid(data, name="sig")
    ex = net.simple_bind(mx.cpu(), data=(2, 2))
    ex.set_monitor_callback(lambda name, arr: seen.append(name))
    ex.forward(data=np.zeros((2, 2), dtype=np.float32))
    assert "sig_output" in seen
