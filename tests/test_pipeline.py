"""Pipeline parallelism: pipelined stage stack == sequential stack,
gradients match, schedule really spreads stages across devices.

Reference role: example/model-parallel-lstm (layers on separate devices);
here the compiled GPipe successor (mxnet_tpu.parallel.pipeline).
"""
import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (jax platform setup via conftest)


def _setup(n_stages=4, width=16, batch=8):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import make_pipeline_mesh

    if len(jax.devices()) < n_stages:
        pytest.skip("needs %d devices" % n_stages)
    mesh = make_pipeline_mesh(n_stages)
    rng = np.random.RandomState(0)
    params = {
        "w": jnp.asarray(rng.randn(n_stages, width, width) * 0.3,
                         jnp.float32),
        "b": jnp.asarray(rng.randn(n_stages, width) * 0.1, jnp.float32),
    }
    x = jnp.asarray(rng.randn(batch, width), jnp.float32)

    def stage(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    def sequential(p, h):
        for s in range(n_stages):
            h = stage(jax.tree.map(lambda v: v[s], p), h)
        return h

    return mesh, params, x, stage, sequential


def test_pipeline_forward_matches_sequential():
    import jax
    from mxnet_tpu.parallel import pipeline_apply
    mesh, params, x, stage, sequential = _setup()
    want = sequential(params, x)
    for m in (1, 2, 4, 8):
        got = pipeline_apply(stage, params, x, mesh, microbatches=m)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6, err_msg="m=%d" % m)


def test_pipeline_grad_matches_sequential():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import pipeline_grad
    mesh, params, x, stage, sequential = _setup()
    y = jnp.ones_like(x)

    def loss(out, lab):
        return jnp.mean((out - lab) ** 2)

    l_seq, g_seq = jax.value_and_grad(
        lambda p: loss(sequential(p, x), y))(params)
    l_pipe, g_pipe = pipeline_grad(loss, stage, params, x, y, mesh,
                                   microbatches=4)
    np.testing.assert_allclose(float(l_pipe), float(l_seq), rtol=1e-5)
    for k in g_seq:
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_pipeline_params_stay_sharded():
    """Stage parameters live one-stage-per-device on the pipe axis (no
    replication of the full stack)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel import pipeline_apply
    mesh, params, x, stage, _ = _setup()
    sharded = jax.tree.map(
        lambda v: jax.device_put(
            v, NamedSharding(mesh, P("pipe"))), params)
    shard_rows = sharded["w"].addressable_shards[0].data.shape[0]
    assert shard_rows == 1  # one stage per device
    out = pipeline_apply(stage, sharded, x, mesh, microbatches=4)
    assert np.isfinite(np.asarray(out)).all()


def test_pipeline_schedule_structure():
    """The compiled program contains the ring collective-permute (the
    stage-to-stage stream), not gathered all-to-all parameter movement."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import pipeline_apply
    mesh, params, x, stage, _ = _setup()
    lowered = jax.jit(lambda p, xx: pipeline_apply(
        stage, p, xx, mesh, microbatches=4)).lower(params, x)
    hlo = lowered.as_text()
    assert "collective_permute" in hlo or "collective-permute" in hlo
