"""Symbol composition, inference, serialization — reference
tests/python/unittest/test_symbol.py + test_infer_shape.py."""
import numpy as np
import pytest

import mxnet_tpu as mx


def make_mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=128, name="fc1")
    act1 = mx.sym.Activation(data=fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(data=act1, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(data=fc2, name="softmax")


def test_symbol_compose_names():
    net = make_mlp()
    assert net.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]
    assert net.name == "softmax"


def test_auto_naming():
    with mx.name.NameManager():
        d = mx.sym.Variable("data")
        c1 = mx.sym.Convolution(data=d, kernel=(3, 3), num_filter=8)
        c2 = mx.sym.Convolution(data=c1, kernel=(3, 3), num_filter=8)
        assert c1.name == "convolution0"
        assert c2.name == "convolution1"


def test_infer_shape_mlp():
    net = make_mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 784))
    args = dict(zip(net.list_arguments(), arg_shapes))
    assert args["fc1_weight"] == (128, 784)
    assert args["fc1_bias"] == (128,)
    assert args["fc2_weight"] == (10, 128)
    assert args["softmax_label"] == (32,) or args["softmax_label"] == (32, 10)
    assert out_shapes == [(32, 10)]
    assert aux_shapes == []


def test_infer_shape_conv_bn():
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=16,
                              pad=(1, 1), name="conv")
    bn = mx.sym.BatchNorm(data=conv, name="bn")
    pool = mx.sym.Pooling(data=bn, kernel=(2, 2), stride=(2, 2),
                          pool_type="max", name="pool")
    arg_shapes, out_shapes, aux_shapes = pool.infer_shape(data=(4, 3, 8, 8))
    args = dict(zip(pool.list_arguments(), arg_shapes))
    assert args["conv_weight"] == (16, 3, 3, 3)
    assert args["bn_gamma"] == (16,)
    assert out_shapes == [(4, 16, 4, 4)]
    auxs = dict(zip(pool.list_auxiliary_states(), aux_shapes))
    assert auxs["bn_moving_mean"] == (16,)
    assert auxs["bn_moving_var"] == (16,)


def test_infer_shape_partial():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    arg_shapes, out_shapes, aux_shapes = fc.infer_shape_partial()
    assert out_shapes is None


def test_symbol_arithmetic_and_internals():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = a + b * 2.0
    assert set(c.list_arguments()) == {"a", "b"}
    internals = c.get_internals()
    assert "a" in internals.list_outputs()


def test_group_and_getitem():
    a = mx.sym.Variable("a")
    s1 = mx.sym.sigmoid(a, name="sig")
    s2 = mx.sym.tanh(a, name="tanh")
    g = mx.sym.Group([s1, s2])
    assert g.list_outputs() == ["sig_output", "tanh_output"]
    assert g[1].name == "tanh"
    assert g["sig_output"].name == "sig"


def test_json_roundtrip():
    net = make_mlp()
    js = net.tojson()
    net2 = mx.sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    a1, o1, x1 = net.infer_shape(data=(8, 100))
    a2, o2, x2 = net2.infer_shape(data=(8, 100))
    assert o1 == o2 and a1 == a2


def test_attr_scope_and_variable_attrs():
    with mx.AttrScope(ctx_group="dev1"):
        v = mx.sym.Variable("w", lr_mult=2.0)
        f = mx.sym.FullyConnected(data=v, num_hidden=3, name="fc")
    assert v.attr("ctx_group") == "dev1"
    assert v.attr("__lr_mult__") == "2.0"
    assert f.attr("ctx_group") == "dev1"


def test_infer_type():
    net = make_mlp()
    arg_types, out_types, aux_types = net.infer_type(data=np.float32)
    assert all(t == np.dtype(np.float32) for t in arg_types)
    assert out_types == [np.dtype(np.float32)]


def test_variable_shape_attr():
    v = mx.sym.Variable("x", shape=(2, 3))
    out = mx.sym.sum(v, name="s")
    arg_shapes, out_shapes, _ = out.infer_shape()
    assert arg_shapes == [(2, 3)]
    assert out_shapes == [()] or out_shapes == [(1,)]
