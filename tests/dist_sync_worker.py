"""Worker script for the multi-process dist_sync test.

Run under the launcher (reference tests/nightly/dist_sync_kvstore.py:1-47
semantics, executed via tools/launch.py --launcher local):

    python tools/launch.py -n 4 python tests/dist_sync_worker.py

Each worker pushes rank-dependent values; the deterministic global sums
must come back identical on every worker.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def check_diff_to_scalar(a, x):
    assert np.sum(np.abs(a.asnumpy() - x)) == 0, (a.asnumpy(), x)


def main():
    keys = [3, 5, 7]
    rate = 2
    shape = (2, 2)
    big_shape = (120, 120)

    kv = mx.kv.create("dist_sync")
    nworker = kv.num_workers
    my_rank = kv.rank
    assert nworker == int(os.environ["MXNET_TPU_NUM_PROCESSES"])

    kv.init(keys, [mx.nd.ones(shape)] * len(keys))
    kv.init(99, mx.nd.ones(big_shape))
    kv.set_optimizer(mx.optimizer.create("test", rescale_grad=rate))

    nrepeat = 3
    for _ in range(nrepeat):
        # one push carrying two keys: must ride a single jitted reduce
        kv.push([3, 99], [mx.nd.ones(shape) * (my_rank + 1),
                          mx.nd.ones(big_shape) * (my_rank + 1)])

    num = (nworker + 1) * nworker * rate / 2 * nrepeat + 1
    val = mx.nd.zeros(shape)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, num)

    val2 = mx.nd.zeros(big_shape)
    kv.pull(99, out=val2)
    check_diff_to_scalar(val2, num)

    kv.barrier()
    print("worker %d/%d OK" % (my_rank, nworker))


if __name__ == "__main__":
    main()
