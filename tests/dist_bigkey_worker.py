"""Big-key sharding acid worker (VERDICT r4 #5).

Reference pattern: tests/nightly/dist_sync_kvstore.py — keys above the
bigarray bound exercised against multiple servers, small keys hashed.
Here 4 workers x 2 servers (MXNET_TPU_NUM_SERVERS=2): a key above
MXNET_KVSTORE_BIGARRAY_BOUND is sliced into per-server flat ranges
(reference kvstore_dist.h:273-314 EncodeKey), so correctness of the
slicing/reassembly AND of server-side sharded updates is what this
proves.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

BIG = (1200, 1100)      # 1.32M elements > the 1e6 bigarray bound
SMALL = (47, 9)


def main():
    kv = mx.kv.create("dist_async")
    assert type(kv).__name__ == "AsyncKVStore", type(kv)
    rank, nworker = kv.rank, kv.num_workers
    assert kv._num_servers == 2, kv._num_servers
    assert (kv._server is not None) == (rank < 2)

    # --- init: big key sliced over both servers, small keys hashed
    ramp = np.arange(np.prod(BIG), dtype=np.float32).reshape(BIG) * 1e-3
    kv.init("big", mx.nd.array(ramp))
    smalls = {}
    for i in range(6):
        smalls[i] = np.full(SMALL, float(i + 1), np.float32)
        kv.init("small%d" % i, mx.nd.array(smalls[i]))
    kv.barrier()

    # --- slicing/reassembly is byte-exact across servers
    out = mx.nd.zeros(BIG)
    kv.pull("big", out=out)
    np.testing.assert_array_equal(out.asnumpy(), ramp)
    for i in range(6):
        o = mx.nd.zeros(SMALL)
        kv.pull("small%d" % i, out=o)
        np.testing.assert_array_equal(o.asnumpy(), smalls[i])

    # the big key's parts really live on BOTH servers (no rank-0 funnel)
    stats = kv.server_stats()
    assert len(stats["per_server"]) == 2, stats
    assert all(p["keys"] > 0 for p in stats["per_server"]), stats
    kv.barrier()

    # --- sharded server-side updates: SGD w -= lr*grad per push, push
    # one grad of ones per worker (updates commute, so the result is
    # deterministic without any sync gate)
    opt = mx.optimizer.create("sgd", learning_rate=0.5, momentum=0.0,
                              wd=0.0, rescale_grad=1.0)
    kv.set_optimizer(opt)
    kv.barrier()
    kv.push("big", mx.nd.ones(BIG))
    kv.push("small0", mx.nd.ones(SMALL))
    kv.barrier()

    kv.pull("big", out=out)
    np.testing.assert_allclose(out.asnumpy(), ramp - 0.5 * nworker,
                               rtol=0, atol=1e-5)
    o = mx.nd.zeros(SMALL)
    kv.pull("small0", out=o)
    np.testing.assert_allclose(o.asnumpy(), smalls[0] - 0.5 * nworker,
                               rtol=0, atol=1e-5)

    # every server applied push updates (the big key pushes hit both)
    stats = kv.server_stats()
    assert all(p["updates"] >= nworker for p in stats["per_server"]), stats
    kv.barrier()
    print("bigkey worker %d/%d OK (servers=%s)"
          % (rank, nworker, [p["keys"] for p in stats["per_server"]]))
    kv.close()


if __name__ == "__main__":
    main()
