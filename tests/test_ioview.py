"""Data-plane observability (telemetry.ioview + tools/io_top.py).

Covers the contracts in docs/api/telemetry.md "Input-pipeline
observability": per-stage accounting oracles, time-weighted queue
occupancy (and the depth-gauge consistency fix), producer-starved vs
consumer-stalled attribution, the bottleneck classifier's edges, the
``position()`` API threaded through the DataIter chain and its
roundtrip through checkpoint-manifest meta, the per-step JSONL ``io``
block, io_top's renderings + ``--json`` schema, the run-timeline
io_bottleneck roll-up, and the 2-process end-to-end test where a
seeded slow decode on one rank is named (stage + rank) by
``run_top --summarize``.
"""
import importlib.util
import io as _pyio
import json
import os
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import distview, flight, ioview

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", "%s.py" % name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_telemetry(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_TELEMETRY_JSONL", raising=False)
    monkeypatch.delenv("MXNET_TPU_IOVIEW_EVERY", raising=False)
    monkeypatch.delenv("MXNET_TPU_IOVIEW_WINDOW", raising=False)
    telemetry.reset()
    yield
    from mxnet_tpu import resilience
    resilience.clear_faults()
    telemetry.reset()


# ------------------------------------------------- stage accounting

def test_account_oracle():
    ioview.account("decode", 0.25, items=3, nbytes=1000)
    ioview.account("decode", 0.75, items=1, nbytes=24)
    ioview.account("read", 0.1, items=2)
    snap = ioview.snapshot()
    assert snap["stages"]["decode"] == {"s": 1.0, "items": 4,
                                        "bytes": 1024}
    assert snap["stages"]["read"]["items"] == 2
    # the same numbers land in the catalog metrics
    h = telemetry.histogram("mxtpu_io_stage_seconds").labels(
        stage="decode").get()
    assert h["count"] == 2 and h["sum"] == pytest.approx(1.0)
    assert telemetry.counter("mxtpu_io_stage_items_total").labels(
        stage="decode").get() == 4
    assert telemetry.counter("mxtpu_io_bytes_total").labels(
        stage="decode").get() == 1024


def test_stall_starved_counters():
    ioview.note_stall("host", 0.2)
    ioview.note_starved("host", 0.3)
    ioview.note_starved("device", -1.0)        # clamped, never negative
    snap = ioview.snapshot()
    assert snap["stall_s"]["host"] == pytest.approx(0.2)
    assert snap["starved_s"]["host"] == pytest.approx(0.3)
    assert snap["starved_s"]["device"] == 0.0
    assert telemetry.counter(
        "mxtpu_io_prefetch_starved_seconds_total").labels(
        iter="host").get() == pytest.approx(0.3)


# ------------------------------------------- time-weighted occupancy

def test_occupancy_weighting(monkeypatch):
    clock = [100.0]
    monkeypatch.setattr(ioview, "_now", lambda: clock[0])
    tr = ioview.OccupancyTracker("host")
    tr.set_depth(0)            # t=100, depth 0
    clock[0] = 101.0
    tr.adjust(+1)              # 1s at depth 0
    clock[0] = 104.0
    tr.adjust(+1)              # 3s at depth 1
    clock[0] = 104.5
    tr.adjust(-1)              # 0.5s at depth 2
    snap = tr.snapshot()
    assert snap["depth"] == 1
    assert snap["levels"] == {"0": 1.0, "1": 3.0, "2": 0.5}
    # time-weighted mean: (0*1 + 1*3 + 2*0.5) / 4.5
    assert snap["mean"] == pytest.approx(4.0 / 4.5, abs=1e-3)
    # the weighted histogram: bucket counts are seconds-at-depth
    h = telemetry.histogram("mxtpu_io_queue_occupancy").labels(
        iter="host").get()
    assert h["count"] == pytest.approx(4.5)
    assert h["sum"] == pytest.approx(4.0)
    # the legacy gauge is the consistent last-observed depth
    assert telemetry.gauge("mxtpu_io_prefetch_depth").labels(
        iter="host").get() == 1.0


def test_device_prefetch_depth_consistent():
    """The satellite fix: the tracker owns the depth counter, so the
    exported depth cannot flap negative or stick above the queue; a
    drained iterator ends at depth 0."""
    x = np.arange(24 * 3, dtype=np.float32).reshape(24, 3)
    it = mx.io.NDArrayIter(x, np.zeros(24, np.float32), batch_size=4)
    pre = mx.io.DevicePrefetchIter(it, lambda d: d, depth=2)
    n = sum(1 for _ in pre)
    assert n == 6
    tr = ioview.queue_tracker("device")
    assert tr.depth() == 0
    assert telemetry.gauge("mxtpu_io_prefetch_depth").labels(
        iter="device").get() == 0.0
    levels = tr.snapshot()["levels"]
    assert all(float(d) >= 0 for d in levels)
    # device_stage accounted one unit per staged batch
    assert ioview.snapshot()["stages"]["device_stage"]["items"] == 6


# --------------------------------------------- bottleneck classifier

def test_classifier_edges():
    # no activity at all: no verdict
    assert ioview.classify(force=True) is None
    # producer-bound: the consumer stalls, decode is the slow stage
    ioview.account("decode", 1.0, items=10)
    ioview.account("read", 0.1, items=10)
    ioview.note_stall("host", 0.5)
    v = ioview.classify(force=True)
    assert v["verdict"] == "producer-bound" and v["stage"] == "decode"
    assert telemetry.counter("mxtpu_io_bottleneck_total").labels(
        stage="decode").get() == 1
    assert any(e.get("kind") == "io_bottleneck"
               for e in flight.events())
    # consumer-bound: producers starve waiting on a slow training loop
    ioview.note_starved("device", 0.8)
    v = ioview.classify(force=True)
    assert v["verdict"] == "consumer-bound" and v["stage"] == "consumer"
    # balanced: both sides comparable
    ioview.note_stall("host", 0.1)
    ioview.note_starved("host", 0.1)
    v = ioview.classify(force=True)
    assert v["verdict"] == "balanced"


def test_classifier_respects_window(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_IOVIEW_WINDOW", "3600")
    ioview.account("read", 0.5, items=1)
    ioview.note_stall("host", 0.5)
    assert ioview.classify() is None       # first call arms the window
    ioview.note_stall("host", 0.5)
    assert ioview.classify() is None       # window not elapsed: no verdict
    v = ioview.classify(force=True)
    assert v["verdict"] == "producer-bound"


def test_seeded_slow_prefetch_stage_named():
    """The ci_check stage-14 shape: a kind=delay io.prefetch fault is a
    seeded slow host_prefetch stage the classifier must name."""
    from mxnet_tpu import resilience
    resilience.configure_faults("io.prefetch:kind=delay,delay=0.02")
    x = np.zeros((16, 3), np.float32)
    it = mx.io.PrefetchingIter(
        mx.io.NDArrayIter(x, np.zeros(16, np.float32), batch_size=4))
    n = sum(1 for _ in it)
    assert n == 4
    v = ioview.classify(force=True)
    assert v["verdict"] == "producer-bound"
    assert v["stage"] == "host_prefetch"


def test_host_prefetch_excludes_inner_stage_time(tmp_path):
    """Review fix: a PrefetchingIter over a decode-bound pipeline must
    let the classifier name DECODE — host_prefetch accounts its wall
    exclusive of the inner stages running on the producer thread."""
    from mxnet_tpu import resilience
    rec = _tiny_rec(tmp_path / "t.rec", n=8)
    resilience.configure_faults("io.decode:kind=delay,delay=0.03")
    it = mx.io.PrefetchingIter(
        mx.image.ImageIter(batch_size=4, data_shape=(3, 8, 8),
                           path_imgrec=rec))
    n = sum(1 for _ in it)
    assert n == 2
    snap = ioview.snapshot()["stages"]
    assert snap["decode"]["s"] > snap["host_prefetch"]["s"]
    v = ioview.classify(force=True)
    assert v["verdict"] == "producer-bound" and v["stage"] == "decode"


def test_starved_ignores_idle_gaps():
    """Review fix: a producer parked across a validation pass (an
    interval far beyond the classifier window) is not backpressure and
    must not flip the verdict to consumer-bound."""
    ioview.note_starved("host", 60.0)       # idle gap: dropped
    assert ioview.snapshot()["starved_s"] == {}
    ioview.note_starved("host", 0.5)        # genuine backpressure
    assert ioview.snapshot()["starved_s"]["host"] == pytest.approx(0.5)


def test_summary_is_read_only():
    """Review fix: summary() must not rotate the live classifier
    window, bump the verdict counter, or touch the flight ring."""
    ioview.account("decode", 1.0, items=4)
    ioview.note_stall("host", 0.5)
    assert ioview.classify() is None        # arms the live window
    t0 = ioview._win_state["t0"]
    before_events = len([e for e in flight.events()
                         if e.get("kind") == "io_bottleneck"])
    for _ in range(3):
        s = ioview.summary()
    assert s["bottleneck"]["verdict"] == "producer-bound"
    assert s["bottleneck"]["stage"] == "decode"
    assert ioview._win_state["t0"] == t0    # window not rotated
    assert telemetry.counter("mxtpu_io_bottleneck_total").labels(
        stage="decode").get() == 0
    assert len([e for e in flight.events()
                if e.get("kind") == "io_bottleneck"]) == before_events


def test_device_prefetch_depth_survives_thread_races():
    """Review fix: +1 before the put, -1 after the take — the tracker
    can transiently over-read but never underflows into the 0-clamp
    (which would leave a permanent phantom batch).  Stressed with an
    aggressive switch interval."""
    import sys as _sys
    old = _sys.getswitchinterval()
    _sys.setswitchinterval(1e-6)
    try:
        for _ in range(20):
            x = np.zeros((12, 3), np.float32)
            it = mx.io.NDArrayIter(x, np.zeros(12, np.float32),
                                   batch_size=4)
            pre = mx.io.DevicePrefetchIter(it, lambda d: d, depth=2)
            assert sum(1 for _ in pre) == 3
            assert ioview.queue_tracker("device").depth() == 0
    finally:
        _sys.setswitchinterval(old)


def test_shard_skew_ignores_unmeasured_ranks():
    """Review fix: a rank whose io blocks carry no window data must
    not be named 'slowest at 0 items/s'."""
    recs = []
    for r, window in ((0, 1.0), (1, 1.0), (2, None)):
        io = {"stages": {"read": {"s": 0.1, "items": 100 if r == 0
                                  else 50, "bytes": 1}}}
        if window:
            io["window_s"] = window
        recs.append({"step": 1, "rank": r, "io": io})
    doc = ioview.summarize_io(recs)
    assert doc["shard_skew"]["slowest_rank"] == 1
    assert doc["ranks"]["2"]["ingest_items_per_s"] is None


def test_prefetch_starved_measures_slow_consumer():
    """Satellite: a slow CONSUMER must show up as producer-starved
    time, not read as a healthy pipeline."""
    x = np.zeros((20, 3), np.float32)
    it = mx.io.PrefetchingIter(
        mx.io.NDArrayIter(x, np.zeros(20, np.float32), batch_size=4))
    for _b in it:
        time.sleep(0.02)               # the training loop is the slow side
    snap = ioview.snapshot()
    assert snap["starved_s"].get("host", 0.0) > 0.05
    v = ioview.classify(force=True)
    assert v["verdict"] == "consumer-bound"


# --------------------------------------------------------- position

def test_position_threading_ndarray_and_wrappers():
    x = np.arange(24 * 3, dtype=np.float32).reshape(24, 3)
    it = mx.io.NDArrayIter(x, np.zeros(24, np.float32), batch_size=4)
    assert it.position() == {"epoch": 0, "offset": 0}
    it.next()
    it.next()
    assert it.position() == {"epoch": 0, "offset": 8}
    it.reset()
    assert it.position() == {"epoch": 1, "offset": 0}
    rs = mx.io.ResizeIter(it, 2)
    assert rs.position()["epoch"] == 1
    pre = mx.io.PrefetchingIter(it)
    assert pre.position()["epoch"] == 1
    dev = mx.io.DevicePrefetchIter(it, lambda d: d, depth=1)
    assert dev.position()["epoch"] == 1
    # base iterators default to None
    assert mx.io.DataIter().position() is None


def _tiny_rec(path, n=6, size=8):
    from PIL import Image
    w = mx.recordio.MXRecordIO(str(path), "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
        buf = _pyio.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG", quality=85)
        w.write(mx.recordio.pack(
            mx.recordio.IRHeader(0, float(i), i, 0), buf.getvalue()))
    w.close()
    return str(path)


def test_image_iter_position_and_stage_accounting(tmp_path):
    rec = _tiny_rec(tmp_path / "t.rec")
    it = mx.image.ImageIter(batch_size=3, data_shape=(3, 8, 8),
                            path_imgrec=rec)
    it.next()
    pos = it.position()
    assert pos["epoch"] == 0 and pos["shard"] == 0
    assert pos["offset"] == 3 and pos["resyncs"] == 0
    it.reset()
    assert it.position()["epoch"] == 1
    assert it.position()["offset"] == 0
    snap = ioview.snapshot()["stages"]
    # the real pipeline accounted every stage it touched
    assert snap["read"]["items"] == 3
    assert snap["decode"]["items"] == 3
    assert snap["augment"]["items"] == 3
    assert snap["batch"]["items"] == 3
    assert snap["decode"]["bytes"] > 0


def test_seeded_slow_decode_io_decode_seam(tmp_path):
    from mxnet_tpu import resilience
    rec = _tiny_rec(tmp_path / "t.rec", n=3)
    it = mx.image.ImageIter(batch_size=3, data_shape=(3, 8, 8),
                            path_imgrec=rec)
    base = ioview.snapshot()["stages"].get(
        "decode", {"s": 0.0})["s"]
    resilience.configure_faults("io.decode:kind=delay,delay=0.05")
    it.next()
    slow = ioview.snapshot()["stages"]["decode"]["s"] - base
    assert slow > 0.12          # 3 images x 50ms seeded delay


def test_position_roundtrip_manifest(tmp_path):
    """Acceptance: the tracked iterator's position lands in the
    checkpoint manifest meta as advisory data_position."""
    from mxnet_tpu import resilience
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    it = mx.io.NDArrayIter(x, np.zeros(16, np.float32), batch_size=4)
    it.next()
    it.next()
    ioview.track(it)
    prefix = str(tmp_path / "ckpt")
    mx.model.save_checkpoint(
        prefix, 3, None, {"w": mx.nd.array(np.ones((2, 2)))}, {})
    doc = resilience.load_manifest(prefix, 3)
    assert doc["meta"]["data_position"] == {"epoch": 0, "offset": 8}
    # the checkpoint still loads (symbol=None -> params only)
    _sym, args, _aux = None, None, None
    _epoch = mx.model.find_checkpoints(prefix)
    assert _epoch == [3]
    # untracked runs write no position key
    telemetry.reset()
    mx.model.save_checkpoint(
        prefix, 4, None, {"w": mx.nd.array(np.ones((2, 2)))}, {})
    doc = resilience.load_manifest(prefix, 4)
    assert "data_position" not in doc["meta"]


def test_trainer_checkpoint_carries_position(tmp_path):
    from mxnet_tpu import models, resilience
    from mxnet_tpu.parallel import ShardedTrainer, build_mesh
    trainer = ShardedTrainer(
        models.get_model("mlp", num_classes=10), build_mesh(tp=1),
        data_shapes={"data": (8, 64)},
        label_shapes={"softmax_label": (8,)}, dtype="float32")
    x = np.arange(32 * 64, dtype=np.float32).reshape(32, 64)
    it = mx.io.NDArrayIter(x, np.zeros(32, np.float32), batch_size=8)
    it.next()
    ioview.track(it)
    prefix = str(tmp_path / "tr")
    trainer.save_checkpoint(prefix, 1)
    doc = resilience.load_manifest(prefix, 1)
    assert doc["meta"]["mesh"]           # schema v2 intact
    assert doc["meta"]["data_position"]["offset"] == 4 + 4


def test_current_position_never_raises():
    class Bad:
        def position(self):
            raise RuntimeError("boom")
    b = Bad()
    ioview.track(b)
    assert ioview.current_position() is None
    del b
    assert ioview.current_position() is None    # weakref died


# --------------------------------------------------- step record / JSONL

def test_step_record_cadence_and_deltas(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_IOVIEW_EVERY", "2")
    ioview.account("read", 0.1, items=2, nbytes=10)
    rec = ioview.step_record()               # call 1: emits
    assert rec["stages"]["read"]["items"] == 2
    ioview.account("read", 0.2, items=3, nbytes=20)
    assert ioview.step_record() is None      # call 2: off-cadence
    ioview.account("read", 0.3, items=5, nbytes=30)
    rec = ioview.step_record()               # call 3: emits the DELTA
    assert rec["stages"]["read"]["items"] == 8
    assert rec["stages"]["read"]["s"] == pytest.approx(0.5)
    assert rec["window_s"] > 0
    monkeypatch.setenv("MXNET_TPU_IOVIEW_EVERY", "0")
    ioview.account("read", 0.1, items=1)
    assert ioview.step_record() is None      # disabled


def test_io_block_rides_jsonl_step_records(tmp_path, monkeypatch):
    path = str(tmp_path / "run.jsonl")
    monkeypatch.setenv("MXNET_TPU_TELEMETRY_JSONL", path)
    x = np.zeros((16, 3), np.float32)
    it = mx.io.PrefetchingIter(
        mx.io.NDArrayIter(x, np.zeros(16, np.float32), batch_size=4))
    ioview.track(it)
    for _b in it:
        telemetry.step_end(samples=4, step_time=0.001)
    recs = [json.loads(line) for line in open(path)]
    with_io = [r for r in recs if "io" in r]
    assert with_io, "no io blocks in the step-log"
    last = with_io[-1]["io"]
    assert "host_prefetch" in last.get("stages", {}) or \
        any("host_prefetch" in r["io"].get("stages", {})
            for r in with_io)
    assert with_io[-1]["io"]["position"] == {"epoch": 0, "offset": 16}
    assert "queues" in last


# ------------------------------------------------------------ io_top

def _synthetic_step_log(path, ranks=(0,), slow_stage="decode",
                        slow_rank=0, steps=3):
    with open(path, "w") as f:
        for step in range(1, steps + 1):
            for r in ranks:
                slow = r == slow_rank
                io = {
                    "stages": {
                        "read": {"s": 0.01, "items": 8, "bytes": 800},
                        slow_stage: {"s": 0.2 if slow else 0.02,
                                     "items": 8, "bytes": 8000},
                        "batch": {"s": 0.005, "items": 8,
                                  "bytes": 6144},
                    },
                    "stall_s": {"host": 0.18 if slow else 0.001},
                    "starved_s": {"host": 0.001},
                    "queues": {"host": {"depth": 0, "mean": 0.2,
                                        "levels": {"0": 0.5,
                                                   "1": 0.1}}},
                    "window_s": 0.25,
                    "position": {"epoch": 0, "shard": r,
                                 "offset": 8 * step, "resyncs": 0},
                }
                f.write(json.dumps({"ts": 1000.0 + step, "step": step,
                                    "rank": r, "step_time_s": 0.25,
                                    "io": io}) + "\n")


def test_io_top_renders_and_names_stage(tmp_path, capsys):
    log = str(tmp_path / "io.jsonl")
    _synthetic_step_log(log)
    io_top = _load_tool("io_top")
    assert io_top.main([log]) == 0
    out = capsys.readouterr().out
    assert "bottleneck: producer-bound — stage 'decode'" in out
    assert "read" in out and "batch" in out
    assert "queue host" in out and "position:" in out


def test_io_top_json_schema(tmp_path, capsys):
    log = str(tmp_path / "io.jsonl")
    _synthetic_step_log(log)
    io_top = _load_tool("io_top")
    assert io_top.main([log, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "mxtpu-iotop/1"
    assert doc["bottleneck"]["verdict"] == "producer-bound"
    assert doc["bottleneck"]["stage"] == "decode"
    assert doc["bottleneck"]["rank"] == 0
    assert doc["ranks"]["0"]["position"]["offset"] == 24
    assert doc["stages"]["decode"]["items"] == 24


def test_io_top_rejects_io_free_log(tmp_path, capsys):
    log = str(tmp_path / "none.jsonl")
    with open(log, "w") as f:
        f.write(json.dumps({"step": 1, "step_time_s": 0.1}) + "\n")
    io_top = _load_tool("io_top")
    assert io_top.main([log, "--json"]) == 1
    assert "no io blocks" in capsys.readouterr().err


def test_io_top_timeline_mode_names_rank(tmp_path, monkeypatch,
                                         capsys):
    """A 2-rank mxtpu-run/1 timeline: io_top aggregates per rank and
    names the slow stage on the slow rank; shard skew is reported."""
    monkeypatch.delenv("MXNET_TPU_FLIGHT_DIR", raising=False)
    base = str(tmp_path / "run.jsonl")
    steplog = str(tmp_path / "steps.jsonl")
    _synthetic_step_log(steplog, ranks=(0, 1), slow_rank=1, steps=4)
    agg = distview.RunAggregator(base, 2)
    for line in open(steplog):
        rec = json.loads(line)
        agg.feed(rec["rank"], rec)
    agg.close()
    io_top = _load_tool("io_top")
    assert io_top.main([base + ".run", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["num_ranks"] == 2
    assert doc["bottleneck"] == {
        "verdict": "producer-bound", "stage": "decode", "rank": 1}
    assert doc["shard_skew"] is None or "slowest_rank" in doc["shard_skew"]
    assert io_top.main([base + ".run"]) == 0
    out = capsys.readouterr().out
    assert "stage 'decode' on rank 1" in out


# ----------------------------------------- cross-rank summarize/run_top

def _timeline_with_io(tmp_path, monkeypatch):
    monkeypatch.delenv("MXNET_TPU_FLIGHT_DIR", raising=False)
    base = str(tmp_path / "run.jsonl")
    agg = distview.RunAggregator(base, 2)
    for step in range(1, 4):
        for r in (0, 1):
            slow = r == 1
            io = {"stages": {
                      "decode": {"s": 0.2 if slow else 0.01,
                                 "items": 8, "bytes": 100},
                      "read": {"s": 0.005, "items": 8, "bytes": 800}},
                  "stall_s": {"host": 0.18 if slow else 0.001},
                  "window_s": 0.25,
                  "position": {"epoch": 0, "shard": r,
                               "offset": 8 * step}}
            seg = {"compute": 0.02,
                   "input_wait": 0.21 if slow else 0.01,
                   "collective_wait": 0.0}
            agg.feed(r, {"step": step, "ts": 1000.0 + step,
                         "step_time_s": 0.23 if slow else 0.03,
                         "segments": seg, "io": io})
    agg.close()
    return base + ".run"


def test_summarize_run_names_io_bottleneck(tmp_path, monkeypatch):
    run_path = _timeline_with_io(tmp_path, monkeypatch)
    summary = distview.summarize_run(
        distview.read_run_timeline(run_path))
    assert summary["straggler"] == 1
    iob = summary["io_bottleneck"]
    assert iob["rank"] == 1 and iob["stage"] == "decode"
    assert iob["stage_s"] == pytest.approx(0.6)
    pr = summary["per_rank"]["1"]
    assert pr["io_stages_s"]["decode"] == pytest.approx(0.6)
    assert pr["data_position"]["offset"] == 24
    # the FAST rank is compute-dominated: no io bottleneck claimed on it
    assert summary["per_rank"]["0"]["io_stages_s"]["decode"] == \
        pytest.approx(0.03)


def test_run_top_prints_io_bottleneck(tmp_path, monkeypatch, capsys):
    run_path = _timeline_with_io(tmp_path, monkeypatch)
    run_top = _load_tool("run_top")
    assert run_top.main([run_path, "--summarize"]) == 0
    out = capsys.readouterr().out
    assert "input bottleneck: stage 'decode' on rank 1" in out
    assert run_top.main([run_path]) == 0
    out = capsys.readouterr().out
    assert "input bottleneck: stage 'decode' on rank 1" in out


def test_summarize_run_no_io_bottleneck_when_compute_bound(tmp_path,
                                                           monkeypatch):
    """A compute-dominated straggler must NOT be blamed on the data
    plane even when io stages were reported."""
    monkeypatch.delenv("MXNET_TPU_FLIGHT_DIR", raising=False)
    base = str(tmp_path / "run.jsonl")
    agg = distview.RunAggregator(base, 1)
    agg.feed(0, {"step": 1, "ts": 1.0, "step_time_s": 0.5,
                 "segments": {"compute": 0.45, "input_wait": 0.05,
                              "collective_wait": 0.0},
                 "io": {"stages": {"decode": {"s": 0.04, "items": 8,
                                              "bytes": 1}},
                        "window_s": 0.5}})
    agg.close()
    summary = distview.summarize_run(
        distview.read_run_timeline(base + ".run"))
    assert summary["io_bottleneck"] is None


# --------------------------------------------------- 2-process end-to-end

def test_dist_seeded_slow_decode_named_stage_and_rank(tmp_path):
    """Acceptance: a REAL 2-process run (tools/launch.py) where rank 1's
    decode is seeded slow through the io.decode delay seam — the merged
    timeline must let run_top name the stage AND the rank."""
    import subprocess

    base = str(tmp_path / "run.jsonl")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_TPU_NUM_PROCESSES", None)
    env.pop("MXNET_TPU_PROCESS_ID", None)
    env.pop("MXNET_TPU_FAULTS", None)
    if "PYTHONPATH" in env:
        parts = [p for p in env["PYTHONPATH"].split(os.pathsep)
                 if "axon" not in p]
        if parts:
            env["PYTHONPATH"] = os.pathsep.join(parts)
        else:
            env.pop("PYTHONPATH")
    env.update({"JAX_PLATFORMS": "cpu",
                "MXNET_TPU_TELEMETRY_JSONL": base,
                "DISTVIEW_IO": "1",
                "DISTVIEW_STEPS": "3",
                "DISTVIEW_SLOW_RANK": "1",
                "DISTVIEW_SLOW_S": "0.05",
                "DISTVIEW_BASE_S": "0.02"})
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "local",
         "--heartbeat-interval", "0.1",
         sys.executable,
         os.path.join(ROOT, "tests", "dist_distview_worker.py")],
        capture_output=True, text=True, timeout=240, cwd=ROOT, env=env)
    assert res.returncode == 0, res.stdout + res.stderr
    run_path = base + ".run"
    assert os.path.exists(run_path)

    # run_top --summarize --json names stage AND rank
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "run_top.py"),
         run_path, "--summarize", "--json"],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert res.returncode == 0, res.stderr
    summary = json.loads(res.stdout)
    assert summary["straggler"] == 1
    iob = summary["io_bottleneck"]
    assert iob and iob["rank"] == 1 and iob["stage"] == "decode", iob
    assert summary["per_rank"]["1"]["data_position"]["shard"] == 1

    # the text rendering says it in one line
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "run_top.py"),
         run_path, "--summarize"],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert "input bottleneck: stage 'decode' on rank 1" in res.stdout

    # io_top over the same timeline agrees on stage + rank
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "io_top.py"),
         run_path, "--json"],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert res.returncode == 0, res.stderr
    doc = json.loads(res.stdout)
    assert doc["schema"] == "mxtpu-iotop/1"
    assert doc["bottleneck"]["stage"] == "decode"
    assert doc["bottleneck"]["rank"] == 1
