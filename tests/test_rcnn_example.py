"""Faster R-CNN example end-to-end: anchor targets, proposal-target
sampling, training convergence, detection + VOC mAP.

Reference: example/rcnn (train_end2end.py, rcnn/io/rpn.py assign_anchor,
rcnn/symbol/proposal_target.py, core/tester.py).
"""
import os
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "examples", "rcnn"))
sys.path.insert(0, os.path.join(ROOT, "examples", "ssd"))


def test_bbox_transform_roundtrip():
    import rcnn_lib
    rng = np.random.RandomState(0)
    ex = rng.uniform(0, 50, (20, 2))
    ex = np.hstack([ex, ex + rng.uniform(5, 40, (20, 2))]).astype("f")
    gt = rng.uniform(0, 50, (20, 2))
    gt = np.hstack([gt, gt + rng.uniform(5, 40, (20, 2))]).astype("f")
    deltas = rcnn_lib.bbox_transform(ex, gt)
    rec = rcnn_lib.bbox_pred(ex, deltas)
    np.testing.assert_allclose(rec, gt, atol=1e-3)


def test_assign_anchor_marks_gt_anchors_fg():
    import rcnn_lib
    gt = np.array([[16, 16, 47, 47, 0]], "f")   # 32x32 box
    label, target, weight = rcnn_lib.assign_anchor(
        (12, 12), gt, (96, 96), 8, (2, 4), (1.0,),
        rng=np.random.RandomState(0))
    assert (label == 1).sum() >= 1
    fg = label == 1
    assert (weight[fg] == 1).all()
    # targets for the best-matching anchor should be small offsets
    assert np.abs(target[fg]).max() < 2.0


def test_nms_suppresses_overlaps():
    import rcnn_lib
    dets = np.array([[0, 0, 10, 10, 0.9],
                     [1, 1, 11, 11, 0.8],       # overlaps first
                     [50, 50, 60, 60, 0.7]], "f")
    keep = rcnn_lib.nms(dets, 0.5)
    assert list(keep) == [0, 2]


def test_faster_rcnn_toy_convergence_and_map():
    import mxnet_tpu as mx
    import train_end2end as t
    # Xavier/shuffle draw from the global RNGs: pin them so the result
    # does not depend on which tests ran before this one
    np.random.seed(5)
    mx.random.seed(5)
    mod = t.train(epochs=10, n_train=150, seed=0)
    mAP = t.evaluate(mod, n_test=25, seed=123)
    assert mAP > 0.6, mAP
