"""The native C prediction ABI (src/c_predict_api.cc): build the shared
library, train+checkpoint a tiny net in python, run inference from a C
program, and compare with the in-process Predictor.

Reference roles: include/mxnet/c_predict_api.h, src/c_api/c_predict_api.cc,
amalgamation/ (single-library predict-only deployment).
"""
import os
import shutil
import subprocess
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(ROOT, "src")

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


def _build():
    subprocess.run(["make", "libmxtpu_predict.so"], cwd=SRC, check=True,
                   capture_output=True)
    lib = os.path.join(SRC, "libmxtpu_predict.so")
    exe = os.path.join(SRC, "c_predict_test")
    subprocess.run(
        ["gcc", "-O1", os.path.join(ROOT, "tests", "c_predict_test.c"),
         "-o", exe, "-L" + SRC, "-lmxtpu_predict",
         "-Wl,-rpath," + SRC], check=True, capture_output=True)
    return exe


def _build_cpp():
    """The header-only C++ binding example (cpp-package role)."""
    subprocess.run(["make", "libmxtpu_predict.so"], cwd=SRC, check=True,
                   capture_output=True)
    exe = os.path.join(SRC, "predict_cpp_test")
    subprocess.run(
        ["g++", "-O1", "-std=c++17",
         os.path.join(ROOT, "cpp-package", "example", "predict_cpp.cc"),
         "-o", exe, "-I" + os.path.join(ROOT, "cpp-package", "include"),
         "-L" + SRC, "-lmxtpu_predict", "-Wl,-rpath," + SRC],
        check=True, capture_output=True)
    return exe


def test_c_predict_matches_python():
    exe = _build()
    rng = np.random.RandomState(0)

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="tanh")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")

    mod = mx.module.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 8))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.init.Xavier())

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "net")
        mod.save_checkpoint(prefix, 0)
        x = rng.randn(2, 8).astype("f")
        xfile = os.path.join(d, "x.f32")
        x.tofile(xfile)

        env = dict(os.environ)
        env["PYTHONPATH"] = ROOT + ":" + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [exe, prefix + "-symbol.json", prefix + "-0000.params",
             xfile, "2", "8"],
            capture_output=True, text=True, env=env, timeout=300)
        assert out.returncode == 0, out.stderr
        lines = out.stdout.strip().split("\n")
        assert lines[0].split() == ["shape", "2", "4"], lines[0]
        c_vals = np.array([float(v) for v in lines[1:]]).reshape(2, 4)

        # in-process reference
        pred = mx.predictor.Predictor(
            open(prefix + "-symbol.json").read(),
            prefix + "-0000.params", {"data": (2, 8)})
        pred.forward(data=x)
        py_vals = pred.get_output(0)
    np.testing.assert_allclose(c_vals, py_vals, rtol=1e-4, atol=1e-5)


def test_cpp_binding_matches_python():
    """The C++ RAII binding (cpp-package/) drives the same ABI: a C++
    program loads a python-trained checkpoint and reproduces the
    in-process predictions."""
    exe = _build_cpp()
    rng = np.random.RandomState(1)

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=12, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")

    mod = mx.module.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "net")
        mod.save_checkpoint(prefix, 0)
        x = rng.randn(4, 6).astype("f")
        xfile = os.path.join(d, "x.f32")
        x.tofile(xfile)

        env = dict(os.environ)
        env["PYTHONPATH"] = ROOT + ":" + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [exe, prefix + "-symbol.json", prefix + "-0000.params",
             xfile, "4", "6"],
            capture_output=True, text=True, env=env, timeout=300)
        assert out.returncode == 0, out.stderr
        lines = out.stdout.strip().split("\n")
        assert lines[0].split() == ["shape", "4", "3"], lines[0]
        cpp_vals = np.array([float(v) for v in lines[1:]]).reshape(4, 3)

        pred = mx.predictor.Predictor(
            open(prefix + "-symbol.json").read(),
            prefix + "-0000.params", {"data": (4, 6)})
        pred.forward(data=x)
        py_vals = pred.get_output(0)
    np.testing.assert_allclose(cpp_vals, py_vals, rtol=1e-4, atol=1e-5)


def test_predictor_reshaped_independent_handles():
    """Regression for the round-2 advisor finding: reshaping must hand
    back a NEW predictor while the original keeps its shapes (one
    handle per batch size is the documented reference pattern)."""
    rng = np.random.RandomState(2)
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=5, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.module.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 7))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.init.Xavier())

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "net")
        mod.save_checkpoint(prefix, 0)
        pred = mx.predictor.Predictor(
            open(prefix + "-symbol.json").read(),
            prefix + "-0000.params", {"data": (2, 7)})

        big = pred.reshaped({"data": (6, 7)})
        x2 = rng.randn(2, 7).astype("f")
        x6 = rng.randn(6, 7).astype("f")
        # the ORIGINAL still works at its original shape
        out2 = pred.forward(data=x2).get_output(0)
        assert out2.shape == (2, 5)
        # the new handle runs the new batch size with shared weights
        out6 = big.forward(data=x6).get_output(0)
        assert out6.shape == (6, 5)
        np.testing.assert_allclose(
            big.forward(data=np.concatenate([x2, x2, x2])).get_output(0)[:2],
            out2, rtol=1e-5, atol=1e-6)
