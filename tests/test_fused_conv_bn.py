"""Conv(1x1)+BatchNorm fusion (ops/fused.py): plan eligibility and
numerical parity (forward, gradients, aux updates) against the unfused
graph on the CPU mesh.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import fused
from mxnet_tpu.parallel import ShardedTrainer, build_mesh


def _bottleneck_net(num_classes=10):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                             num_filter=8, no_bias=True, name="conv0")
    net = mx.sym.BatchNorm(net, name="bn0", fix_gamma=False)
    net = mx.sym.Activation(net, act_type="relu")
    # the fusable pair: pointwise conv feeding its BN and nothing else
    net = mx.sym.Convolution(net, kernel=(1, 1), num_filter=16,
                             no_bias=True, name="conv1x1")
    net = mx.sym.BatchNorm(net, name="bn1", fix_gamma=False)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_fusion_plan_finds_pointwise_pair():
    sym = _bottleneck_net()
    plan, skip = fused.plan_conv_bn_fusion(sym._topo(), sym._entries)
    assert len(plan) == 1 and len(skip) == 1
    conv = next(iter(plan.values()))
    assert conv.name == "conv1x1"


def test_fusion_plan_rejects_multi_consumer():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(1, 1), num_filter=4,
                           no_bias=True, name="c")
    bn = mx.sym.BatchNorm(c, name="bn")
    out = bn + c          # conv consumed twice
    plan, skip = fused.plan_conv_bn_fusion(out._topo(), out._entries)
    assert not plan and not skip


def _make(fuse, dtype="float32"):
    mesh = build_mesh(tp=1)
    np.random.seed(7)
    return ShardedTrainer(
        _bottleneck_net(), mesh,
        data_shapes={"data": (8, 3, 8, 8)},
        label_shapes={"softmax_label": (8,)},
        layout="NHWC", dtype=dtype, seed=3, learning_rate=0.1,
        momentum=0.9, fuse_conv_bn=fuse)


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "data": (rng.uniform(-1, 1, (8, 3, 8, 8)) * 3.0 + 0.5)
        .astype(np.float32),
        "softmax_label": rng.randint(0, 10, 8).astype(np.float32),
    }


def test_fused_step_matches_unfused():
    """Two training steps with and without fusion produce the same
    params, aux stats, and loss (f32, CPU fallback kernel)."""
    t_ref = _make(False)
    t_fused = _make(True)
    b1 = t_ref.put_batch(_batch(0))
    b2 = t_fused.put_batch(_batch(0))
    losses = []
    for t, b in ((t_ref, b1), (t_fused, b2)):
        l1 = float(t.step(b))
        l2 = float(t.step(b))
        losses.append((l1, l2))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5,
                               atol=1e-6)
    for k in t_ref.params:
        np.testing.assert_allclose(
            np.asarray(t_fused.params[k]), np.asarray(t_ref.params[k]),
            rtol=1e-4, atol=1e-5, err_msg=k)
    for k in t_ref.aux:
        np.testing.assert_allclose(
            np.asarray(t_fused.aux[k]), np.asarray(t_ref.aux[k]),
            rtol=1e-4, atol=1e-5, err_msg=k)


def _stem_net(num_classes=10):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(7, 7), stride=(2, 2),
                             pad=(3, 3), num_filter=8, no_bias=True,
                             name="conv0")
    net = mx.sym.BatchNorm(net, name="bn0", fix_gamma=False)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_stem_space_to_depth_matches():
    """The 4x4/s1 space-to-depth rewrite of the 7x7/s2 stem trains
    identically to the direct conv (f32)."""
    def make(stem):
        mesh = build_mesh(tp=1)
        np.random.seed(11)
        return ShardedTrainer(
            _stem_net(), mesh,
            data_shapes={"data": (8, 3, 16, 16)},
            label_shapes={"softmax_label": (8,)},
            layout="NHWC", dtype="float32", seed=5, learning_rate=0.1,
            momentum=0.9, stem_space_to_depth=stem)

    t_ref, t_s2d = make(False), make(True)
    rng = np.random.RandomState(3)
    batch = {"data": rng.randn(8, 3, 16, 16).astype("f"),
             "softmax_label": rng.randint(0, 10, 8).astype("f")}
    for t in (t_ref, t_s2d):
        b = t.put_batch(batch)
        t.step(b)
        t.step(b)
    for k in t_ref.params:
        np.testing.assert_allclose(
            np.asarray(t_s2d.params[k]), np.asarray(t_ref.params[k]),
            rtol=1e-4, atol=1e-5, err_msg=k)
