/* Exercises the widened flat C ABI (include/mxnet_tpu/c_api.h):
 * builds a symbol from atomic creators + compose, round-trips it
 * through JSON, and creates/saves/loads NDArrays in the reference
 * container — cross-checked against python by the pytest wrapper
 * (tests/test_c_api.py).
 *
 * Usage: c_api_test <out_dir> <python_written.params>
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxnet_tpu/c_api.h"

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s (last: %s)\n", __FILE__,        \
              __LINE__, #cond, MXGetLastError());                     \
      return 1;                                                       \
    }                                                                 \
  } while (0)

static AtomicSymbolCreator find_creator(const char *want) {
  mx_uint n = 0;
  AtomicSymbolCreator *cs = NULL;
  if (MXSymbolListAtomicSymbolCreators(&n, &cs) != 0) return NULL;
  for (mx_uint i = 0; i < n; ++i) {
    const char *nm = NULL;
    if (MXSymbolGetAtomicSymbolName(cs[i], &nm) != 0) return NULL;
    if (strcmp(nm, want) == 0) return cs[i];
  }
  return NULL;
}

static int has_arg(const char **args, mx_uint n, const char *want) {
  for (mx_uint i = 0; i < n; ++i) {
    if (strcmp(args[i], want) == 0) return 1;
  }
  return 0;
}

/* SGD-flavored updater for the kvstore callback test: the C side owns
 * the rule, mutating `local` in place through the ABI */
static void sgd_updater(int key, NDArrayHandle recv, NDArrayHandle local,
                        void *user) {
  (void)key;
  float r[4], l[4];
  if (MXNDArraySyncCopyToCPU(recv, r, 4) != 0) return;
  if (MXNDArraySyncCopyToCPU(local, l, 4) != 0) return;
  for (int i = 0; i < 4; ++i) l[i] += 0.5f * r[i];
  MXNDArraySyncCopyFromCPU(local, l, 4);
  (*(int *)user)++;
}

int main(int argc, char **argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <out_dir> <py.params>\n", argv[0]);
    return 2;
  }
  char path[1024];

  /* ---- registry: creator discovery (reference ListAtomicSymbolCreators
   * over the single op registry; >=150 ops expected) */
  mx_uint ncreators = 0;
  AtomicSymbolCreator *creators = NULL;
  CHECK(MXSymbolListAtomicSymbolCreators(&ncreators, &creators) == 0);
  CHECK(ncreators >= 150);
  AtomicSymbolCreator fc = find_creator("FullyConnected");
  AtomicSymbolCreator act = find_creator("Activation");
  AtomicSymbolCreator sm = find_creator("SoftmaxOutput");
  CHECK(fc != NULL && act != NULL && sm != NULL);

  /* ---- build an MLP via atomic+compose (the reference binding flow) */
  SymbolHandle data = NULL, l1 = NULL, a1 = NULL, l2 = NULL, out = NULL;
  CHECK(MXSymbolCreateVariable("data", &data) == 0);

  const char *k1[] = {"num_hidden"};
  const char *v1[] = {"16"};
  CHECK(MXSymbolCreateAtomicSymbol(fc, 1, k1, v1, &l1) == 0);
  SymbolHandle in1[] = {data};
  CHECK(MXSymbolCompose(l1, "fc1", 1, NULL, in1) == 0);

  const char *k2[] = {"act_type"};
  const char *v2[] = {"relu"};
  CHECK(MXSymbolCreateAtomicSymbol(act, 1, k2, v2, &a1) == 0);
  SymbolHandle in2[] = {l1};
  CHECK(MXSymbolCompose(a1, "relu1", 1, NULL, in2) == 0);

  const char *v3[] = {"5"};
  CHECK(MXSymbolCreateAtomicSymbol(fc, 1, k1, v3, &l2) == 0);
  SymbolHandle in3[] = {a1};
  CHECK(MXSymbolCompose(l2, "fc2", 1, NULL, in3) == 0);

  CHECK(MXSymbolCreateAtomicSymbol(sm, 0, NULL, NULL, &out) == 0);
  SymbolHandle in4[] = {l2};
  CHECK(MXSymbolCompose(out, "softmax", 1, NULL, in4) == 0);

  mx_uint nargs = 0;
  const char **args = NULL;
  CHECK(MXSymbolListArguments(out, &nargs, &args) == 0);
  CHECK(nargs == 6);
  CHECK(has_arg(args, nargs, "data"));
  CHECK(has_arg(args, nargs, "fc1_weight"));
  CHECK(has_arg(args, nargs, "fc1_bias"));
  CHECK(has_arg(args, nargs, "fc2_weight"));
  CHECK(has_arg(args, nargs, "softmax_label"));

  mx_uint nouts = 0;
  const char **outs = NULL;
  CHECK(MXSymbolListOutputs(out, &nouts, &outs) == 0);
  CHECK(nouts == 1 && strcmp(outs[0], "softmax_output") == 0);

  const char *name = NULL;
  int success = 0;
  CHECK(MXSymbolGetName(out, &name, &success) == 0);
  CHECK(success == 1 && strcmp(name, "softmax") == 0);

  /* ---- attrs: set/get/list (ctx_group-style metadata ride-along) */
  CHECK(MXSymbolSetAttr(out, "ctx_group", "stage1") == 0);
  const char *aval = NULL;
  CHECK(MXSymbolGetAttr(out, "ctx_group", &aval, &success) == 0);
  CHECK(success == 1 && strcmp(aval, "stage1") == 0);
  mx_uint nattr = 0;
  const char **attrs_flat = NULL;
  CHECK(MXSymbolListAttrShallow(out, &nattr, &attrs_flat) == 0);
  CHECK(nattr >= 2 && nattr % 2 == 0);
  int found_attr = 0;
  for (mx_uint i = 0; i + 1 < nattr; i += 2) {
    if (strcmp(attrs_flat[i], "ctx_group") == 0 &&
        strcmp(attrs_flat[i + 1], "stage1") == 0) {
      found_attr = 1;
    }
  }
  CHECK(found_attr);
  CHECK(MXSymbolGetAttr(out, "no_such_attr", &aval, &success) == 0);
  CHECK(success == 0);

  /* ---- JSON round trip + file save (python cross-loads this) */
  const char *json = NULL;
  CHECK(MXSymbolSaveToJSON(out, &json) == 0);
  CHECK(strstr(json, "FullyConnected") != NULL);
  SymbolHandle again = NULL;
  CHECK(MXSymbolCreateFromJSON(json, &again) == 0);
  mx_uint nargs2 = 0;
  const char **args2 = NULL;
  CHECK(MXSymbolListArguments(again, &nargs2, &args2) == 0);
  CHECK(nargs2 == nargs);
  snprintf(path, sizeof(path), "%s/net-symbol.json", argv[1]);
  CHECK(MXSymbolSaveToFile(out, path) == 0);
  SymbolHandle fromfile = NULL;
  CHECK(MXSymbolCreateFromFile(path, &fromfile) == 0);
  MXSymbolFree(fromfile);
  MXSymbolFree(again);

  /* ---- error contract: bad symbol JSON -> -1 + message, not a crash */
  SymbolHandle bad = NULL;
  CHECK(MXSymbolCreateFromJSON("{not json", &bad) == -1);
  CHECK(strlen(MXGetLastError()) > 0);

  /* ---- ndarray: create/fill/readback/shape/dtype/reshape/slice */
  mx_uint shape[2] = {3, 4};
  NDArrayHandle w = NULL;
  CHECK(MXNDArrayCreate(shape, 2, 1, 0, 0, &w) == 0);
  float host[12];
  for (int i = 0; i < 12; ++i) host[i] = (float)i * 0.5f;
  CHECK(MXNDArraySyncCopyFromCPU(w, host, 12) == 0);
  float back[12];
  CHECK(MXNDArraySyncCopyToCPU(w, back, 12) == 0);
  for (int i = 0; i < 12; ++i) CHECK(back[i] == host[i]);

  mx_uint ndim = 0;
  const mx_uint *dims = NULL;
  CHECK(MXNDArrayGetShape(w, &ndim, &dims) == 0);
  CHECK(ndim == 2 && dims[0] == 3 && dims[1] == 4);
  int dtype = -1;
  CHECK(MXNDArrayGetDType(w, &dtype) == 0);
  CHECK(dtype == 0);
  int dev_type = 0, dev_id = -1;
  CHECK(MXNDArrayGetContext(w, &dev_type, &dev_id) == 0);
  CHECK(dev_type == 1 && dev_id == 0);

  int newdims[2] = {4, 3};
  NDArrayHandle wr = NULL;
  CHECK(MXNDArrayReshape(w, 2, newdims, &wr) == 0);
  CHECK(MXNDArrayGetShape(wr, &ndim, &dims) == 0);
  CHECK(ndim == 2 && dims[0] == 4 && dims[1] == 3);
  NDArrayHandle ws = NULL;
  CHECK(MXNDArraySlice(w, 1, 3, &ws) == 0);
  CHECK(MXNDArrayGetShape(ws, &ndim, &dims) == 0);
  CHECK(ndim == 2 && dims[0] == 2 && dims[1] == 4);
  float srow[8];
  CHECK(MXNDArraySyncCopyToCPU(ws, srow, 8) == 0);
  CHECK(srow[0] == host[4] && srow[7] == host[11]);

  /* int32 array via CreateEx */
  mx_uint bshape[1] = {5};
  NDArrayHandle b = NULL;
  CHECK(MXNDArrayCreateEx(bshape, 1, 1, 0, 0, 4, &b) == 0);
  int bi[5] = {1, 2, 3, 4, 5};
  CHECK(MXNDArraySyncCopyFromCPU(b, bi, 5) == 0);
  CHECK(MXNDArrayGetDType(b, &dtype) == 0 && dtype == 4);

  /* ---- save keyed + load back (reference container) */
  snprintf(path, sizeof(path), "%s/c_written.params", argv[1]);
  NDArrayHandle savelist[2] = {w, b};
  const char *keys[2] = {"arg:w", "arg:b"};
  CHECK(MXNDArraySave(path, 2, savelist, keys) == 0);

  mx_uint nload = 0, nname = 0;
  NDArrayHandle *loaded = NULL;
  const char **names = NULL;
  CHECK(MXNDArrayLoad(path, &nload, &loaded, &nname, &names) == 0);
  CHECK(nload == 2 && nname == 2);
  for (mx_uint i = 0; i < nload; ++i) {
    if (strcmp(names[i], "arg:w") == 0) {
      float got[12];
      CHECK(MXNDArraySyncCopyToCPU(loaded[i], got, 12) == 0);
      for (int j = 0; j < 12; ++j) CHECK(got[j] == host[j]);
    } else {
      CHECK(strcmp(names[i], "arg:b") == 0);
    }
  }

  /* ---- cross-language: load the python-written file */
  mx_uint pload = 0, pname = 0;
  NDArrayHandle *pyarrs = NULL;
  const char **pynames = NULL;
  CHECK(MXNDArrayLoad(argv[2], &pload, &pyarrs, &pname, &pynames) == 0);
  CHECK(pload == 1 && pname == 1);
  CHECK(strcmp(pynames[0], "arg:ramp") == 0);
  float ramp[6];
  CHECK(MXNDArraySyncCopyToCPU(pyarrs[0], ramp, 6) == 0);
  for (int i = 0; i < 6; ++i) CHECK(ramp[i] == (float)i * 2.0f);
  MXNDArrayFree(pyarrs[0]);

  /* ---- kvstore: create/init/push/pull through the local store */
  KVStoreHandle kv = NULL;
  CHECK(MXKVStoreCreate("local", &kv) == 0);
  const char *kvtype = NULL;
  CHECK(MXKVStoreGetType(kv, &kvtype) == 0);
  CHECK(strcmp(kvtype, "local") == 0);
  int rank = -1, gsize = 0;
  CHECK(MXKVStoreGetRank(kv, &rank) == 0 && rank == 0);
  CHECK(MXKVStoreGetGroupSize(kv, &gsize) == 0 && gsize == 1);

  mx_uint kshape[1] = {4};
  NDArrayHandle kinit = NULL, kgrad = NULL, kout = NULL;
  CHECK(MXNDArrayCreate(kshape, 1, 1, 0, 0, &kinit) == 0);
  CHECK(MXNDArrayCreate(kshape, 1, 1, 0, 0, &kgrad) == 0);
  CHECK(MXNDArrayCreate(kshape, 1, 1, 0, 0, &kout) == 0);
  float kv0[4] = {0, 0, 0, 0}, kv1[4] = {2, 4, 6, 8};
  CHECK(MXNDArraySyncCopyFromCPU(kinit, kv0, 4) == 0);
  CHECK(MXNDArraySyncCopyFromCPU(kgrad, kv1, 4) == 0);
  int kkeys[1] = {3};
  NDArrayHandle kvals[1] = {kinit};
  CHECK(MXKVStoreInit(kv, 1, kkeys, kvals) == 0);
  kvals[0] = kgrad;
  CHECK(MXKVStorePush(kv, 1, kkeys, kvals, 0) == 0);
  kvals[0] = kout;
  CHECK(MXKVStorePull(kv, 1, kkeys, kvals, 0) == 0);
  float kread[4];
  CHECK(MXNDArraySyncCopyToCPU(kout, kread, 4) == 0);
  for (int i = 0; i < 4; ++i) CHECK(kread[i] == kv1[i]);
  MXNDArrayFree(kinit);
  MXNDArrayFree(kgrad);
  MXNDArrayFree(kout);
  CHECK(MXKVStoreFree(kv) == 0);

  /* ---- kvstore with a C UPDATER: the push applies sgd_updater to the
   * stored value in place (reference MXKVStoreSetUpdater contract) */
  KVStoreHandle kvu = NULL;
  CHECK(MXKVStoreCreate("local", &kvu) == 0);
  int ucount = 0;
  CHECK(MXKVStoreSetUpdater(kvu, sgd_updater, &ucount) == 0);
  NDArrayHandle uinit = NULL, ugrad = NULL, uout = NULL;
  CHECK(MXNDArrayCreate(kshape, 1, 1, 0, 0, &uinit) == 0);
  CHECK(MXNDArrayCreate(kshape, 1, 1, 0, 0, &ugrad) == 0);
  CHECK(MXNDArrayCreate(kshape, 1, 1, 0, 0, &uout) == 0);
  float ubase[4] = {10, 20, 30, 40}, ug[4] = {2, 2, 2, 2};
  CHECK(MXNDArraySyncCopyFromCPU(uinit, ubase, 4) == 0);
  CHECK(MXNDArraySyncCopyFromCPU(ugrad, ug, 4) == 0);
  int ukeys[1] = {7};
  NDArrayHandle uvals[1] = {uinit};
  CHECK(MXKVStoreInit(kvu, 1, ukeys, uvals) == 0);
  uvals[0] = ugrad;
  CHECK(MXKVStorePush(kvu, 1, ukeys, uvals, 0) == 0);
  CHECK(MXKVStorePush(kvu, 1, ukeys, uvals, 0) == 0);
  uvals[0] = uout;
  CHECK(MXKVStorePull(kvu, 1, ukeys, uvals, 0) == 0);
  float ures[4];
  CHECK(MXNDArraySyncCopyToCPU(uout, ures, 4) == 0);
  for (int i = 0; i < 4; ++i) CHECK(ures[i] == ubase[i] + 2 * 0.5f * 2.0f);
  CHECK(ucount == 2);
  MXNDArrayFree(uinit);
  MXNDArrayFree(ugrad);
  MXNDArrayFree(uout);
  CHECK(MXKVStoreFree(kvu) == 0);

  /* ---- recordio: write records from C, read them back (python
   * cross-reads the same file in the pytest wrapper) */
  snprintf(path, sizeof(path), "%s/c_written.rec", argv[1]);
  RecordIOHandle rw = NULL;
  CHECK(MXRecordIOWriterCreate(path, &rw) == 0);
  CHECK(MXRecordIOWriterWriteRecord(rw, "hello", 5) == 0);
  CHECK(MXRecordIOWriterWriteRecord(rw, "tpu-record!", 11) == 0);
  CHECK(MXRecordIOWriterFree(rw) == 0);
  RecordIOHandle rr = NULL;
  CHECK(MXRecordIOReaderCreate(path, &rr) == 0);
  const char *rbuf = NULL;
  size_t rsize = 0;
  CHECK(MXRecordIOReaderReadRecord(rr, &rbuf, &rsize) == 0);
  CHECK(rsize == 5 && memcmp(rbuf, "hello", 5) == 0);
  CHECK(MXRecordIOReaderReadRecord(rr, &rbuf, &rsize) == 0);
  CHECK(rsize == 11 && memcmp(rbuf, "tpu-record!", 11) == 0);
  CHECK(MXRecordIOReaderReadRecord(rr, &rbuf, &rsize) == 0);
  CHECK(rbuf == NULL && rsize == 0);   /* end of file */
  CHECK(MXRecordIOReaderFree(rr) == 0);

  /* ---- error contract on null handles */
  CHECK(MXNDArrayGetDType(NULL, &dtype) == -1);
  CHECK(strlen(MXGetLastError()) > 0);

  MXNDArrayFree(w);
  MXNDArrayFree(wr);
  MXNDArrayFree(ws);
  MXNDArrayFree(b);
  MXSymbolFree(out);
  MXSymbolFree(l2);
  MXSymbolFree(a1);
  MXSymbolFree(l1);
  MXSymbolFree(data);
  printf("c_api OK ops=%u\n", ncreators);
  return 0;
}
