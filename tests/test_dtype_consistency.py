"""Cross-dtype operator consistency sweep.

Reference: tests/python/gpu/test_operator_gpu.py runs the CPU operator
suite under ``check_consistency`` across devices and dtype combinations
(f32/f16).  Devices are uniform under XLA, so dtype is the surviving
axis: every op here must produce bf16/f16 outputs within reduced-precision
tolerance of its f32 result.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import check_consistency

_RNG = np.random.RandomState(7)


def _x(*shape):
    return _RNG.uniform(-2, 2, shape).astype("float32")


CASES = [
    ("relu", [_x(4, 16)], {}),
    ("sigmoid", [_x(4, 16)], {}),
    ("tanh", [_x(4, 16)], {}),
    ("exp", [_x(4, 16) * 0.5], {}),
    ("sqrt", [np.abs(_x(4, 16)) + 0.1], {}),
    ("broadcast_add", [_x(4, 16), _x(1, 16)], {}),
    ("broadcast_mul", [_x(4, 16), _x(1, 16)], {}),
    ("dot", [_x(8, 16), _x(16, 8)], {}),
    ("sum", [_x(4, 16)], {"axis": 1}),
    ("max", [_x(4, 16)], {"axis": 1}),
    ("softmax", [_x(4, 16)], {}),
    ("log_softmax", [_x(4, 16)], {}),
    ("transpose", [_x(4, 16)], {}),
    ("Flatten", [_x(4, 2, 8)], {}),
    ("SwapAxis", [_x(4, 2, 8)], {"dim1": 1, "dim2": 2}),
    ("clip", [_x(4, 16)], {"a_min": -1.0, "a_max": 1.0}),
]


@pytest.mark.parametrize("op_name,arrays,attrs",
                         CASES, ids=[c[0] for c in CASES])
def test_dtype_consistency_bf16(op_name, arrays, attrs):
    check_consistency(op_name, arrays, attrs=attrs,
                      dtypes=("float32", "bfloat16"),
                      rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("op_name,arrays,attrs",
                         CASES[:8], ids=[c[0] for c in CASES[:8]])
def test_dtype_consistency_f16(op_name, arrays, attrs):
    check_consistency(op_name, arrays, attrs=attrs,
                      dtypes=("float32", "float16"),
                      rtol=5e-3, atol=5e-3)


def test_conv_bn_dtype_consistency():
    """Layer ops keep reduced-precision outputs close to f32 (reference
    test_operator_gpu conv/BN consistency cases)."""
    x = _x(2, 3, 8, 8)
    w = _x(4, 3, 3, 3) * 0.2
    check_consistency("Convolution", [x, w],
                      attrs={"kernel": (3, 3), "num_filter": 4,
                             "no_bias": True},
                      dtypes=("float32", "bfloat16"),
                      rtol=5e-2, atol=5e-2)
    g = np.ones(3, "float32")
    b = np.zeros(3, "float32")
    mm = np.zeros(3, "float32")
    mv = np.ones(3, "float32")
    check_consistency("BatchNorm", [x, g, b, mm, mv],
                      attrs={"fix_gamma": False},
                      dtypes=("float32", "bfloat16"),
                      rtol=5e-2, atol=5e-2)
