"""Pallas flash-attention kernel vs the jnp oracle (interpret mode on the
CPU test mesh; the same kernel compiles for the MXU on TPU)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _qkv(B=2, T=128, H=2, D=64, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.normal(0, 1, (B, T, H, D)).astype(np.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_interpret(causal):
    from mxnet_tpu.ops.pallas_kernels import (flash_attention,
                                              _attention_jnp)
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal, True)  # interpret=True
    ref = _attention_jnp(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_backward_kernel(causal):
    """The Pallas flash backward (Q-block streaming, dK/dV accumulation
    over the grid, P reconstituted from the saved log-sum-exp) must
    match the dense jnp attention vjp."""
    import jax
    from mxnet_tpu.ops.pallas_kernels import (flash_attention,
                                              _attention_jnp)
    q, k, v = _qkv(T=256)
    rng = np.random.RandomState(7)
    g = rng.normal(0, 1, q.shape).astype(np.float32)

    _o, vjp = jax.vjp(lambda q, k, v:
                      flash_attention(q, k, v, causal, True), q, k, v)
    _r, ref_vjp = jax.vjp(lambda q, k, v:
                          _attention_jnp(q, k, v, causal), q, k, v)
    for got, want in zip(vjp(g), ref_vjp(g)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)


def test_flash_attention_op_fallback():
    q, k, v = _qkv(T=32)
    out = mx.nd._contrib_FlashAttention(mx.nd.array(q), mx.nd.array(k),
                                        mx.nd.array(v))
    from mxnet_tpu.ops.pallas_kernels import _attention_jnp
    ref = _attention_jnp(q, k, v, False)
    np.testing.assert_allclose(out.asnumpy(), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_streaming_path(causal):
    """T > _BLOCK_K takes the K/V-streaming kernels (online-softmax
    forward scratch, full-sequence dQ accumulator backward, causal
    tile skip) — the path that lifts the old panel kernels' VMEM wall
    at S>=4096 (VERDICT r4 #2).  Exercised here at a shrunk _BLOCK_K
    so interpret mode stays fast while covering the real code path."""
    import jax
    from mxnet_tpu.ops import pallas_kernels as pk
    old_bk = pk._BLOCK_K
    pk._BLOCK_K = 256          # T=512 -> 2 K blocks: streaming engaged
    try:
        q, k, v = _qkv(B=1, T=512, H=2, D=32)
        rng = np.random.RandomState(7)
        g = rng.normal(0, 1, q.shape).astype(np.float32)
        out, vjp = jax.vjp(lambda q, k, v:
                           pk.flash_attention(q, k, v, causal, True),
                           q, k, v)
        ref, ref_vjp = jax.vjp(lambda q, k, v:
                               pk._attention_jnp(q, k, v, causal),
                               q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)
        for got, want in zip(vjp(g), ref_vjp(g)):
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(want),
                                       rtol=2e-4, atol=3e-5)
    finally:
        pk._BLOCK_K = old_bk


def test_block_choice_cliff_shapes():
    """ADVICE r5 perf cliff: a seq length that is not a _BLOCK_K
    multiple used to collapse straight to 128-wide K blocks (3200 ->
    25 tiny streams).  _blocks must now pick the largest block_q-
    multiple divisor of t that still fits the VMEM budget."""
    from mxnet_tpu.ops.pallas_kernels import _BLOCK_K, _BLOCK_Q, _blocks

    # multiples of _BLOCK_K stream the full panel
    assert _blocks(2048) == (128, 2048)
    assert _blocks(4096) == (128, 2048)
    # short sequences keep the single-panel fast path
    assert _blocks(512) == (128, 512)
    # the cliff shapes: largest 128-multiple divisor <= _BLOCK_K
    assert _blocks(3200) == (128, 640)    # 5 K blocks (was 25)
    assert _blocks(2304) == (128, 1152)   # 2 K blocks (was 18)
    assert _blocks(6144) == (128, 2048)   # 3 K blocks (was 48)
    # 2176 = 128 * 17: no larger divisor exists, 128 is genuinely best
    assert _blocks(2176) == (128, 128)

    # invariants across every Q-tileable length: the K block always
    # divides t (the grid is exact), is a block_q multiple (MXU
    # tileable), and never exceeds the VMEM budget
    for t in range(128, 8193, 128):
        bq, bk = _blocks(t)
        assert bq == min(_BLOCK_Q, t)
        assert t % bk == 0, t
        assert bk % bq == 0, t
        assert bk <= max(_BLOCK_K, bq), t
