"""NDArray unit tests (reference: tests/python/unittest/test_ndarray.py)."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx


def test_creation():
    a = mx.nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert a.asnumpy().sum() == 0
    b = mx.nd.ones((2, 2), dtype="float16")
    assert b.dtype == np.float16
    c = mx.nd.full((2,), 7.0)
    np.testing.assert_allclose(c.asnumpy(), [7, 7])
    d = mx.nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2) and d.dtype == np.float32
    e = mx.nd.arange(0, 10, 2)
    np.testing.assert_allclose(e.asnumpy(), [0, 2, 4, 6, 8])


def test_arith():
    a = mx.nd.array([1.0, 2.0, 3.0])
    b = mx.nd.array([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).asnumpy(), [5, 7, 9])
    np.testing.assert_allclose((b - a).asnumpy(), [3, 3, 3])
    np.testing.assert_allclose((a * 2).asnumpy(), [2, 4, 6])
    np.testing.assert_allclose((2 * a).asnumpy(), [2, 4, 6])
    np.testing.assert_allclose((1 / a).asnumpy(), [1, 0.5, 1 / 3], rtol=1e-6)
    np.testing.assert_allclose((a ** 2).asnumpy(), [1, 4, 9])
    np.testing.assert_allclose((-a).asnumpy(), [-1, -2, -3])
    a += b
    np.testing.assert_allclose(a.asnumpy(), [5, 7, 9])


def test_inplace_and_views():
    v = mx.nd.zeros((4, 4))
    v[1] = 7
    row = v[2]
    row[:] = 3
    out = v.asnumpy()
    assert (out[1] == 7).all() and (out[2] == 3).all() and out[0].sum() == 0
    # writes through slices visible to other views of same parent
    r2 = v[1]
    r2[:] = 1
    assert (v.asnumpy()[1] == 1).all()


def test_reshape_specials():
    a = mx.nd.zeros((2, 3, 4))
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, 0, 4)).shape == (2, 3, 4)
    assert mx.nd.reshape(a, shape=(-3, 4)).shape == (6, 4)


def test_reduce_and_argmax():
    a = mx.nd.array(np.arange(12, dtype="float32").reshape(3, 4))
    assert a.sum().asscalar() == 66
    np.testing.assert_allclose(a.sum(axis=1).asnumpy(), [6, 22, 38])
    np.testing.assert_allclose(a.max(axis=0).asnumpy(), [8, 9, 10, 11])
    assert a.argmax().asscalar() == 11


def test_copyto_astype_context():
    a = mx.nd.ones((2, 2))
    b = mx.nd.zeros((2, 2))
    a.copyto(b)
    assert b.asnumpy().sum() == 4
    c = a.astype("float16")
    assert c.dtype == np.float16
    assert a.context.device_type in ("cpu", "tpu")
    d = a.as_in_context(mx.cpu(0))
    assert d.context == mx.cpu(0)


def test_save_load_roundtrip():
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "x.params")
        w = mx.nd.array(np.random.randn(3, 4).astype("float32"))
        b = mx.nd.array(np.random.randn(4).astype("float16"))
        mx.nd.save(path, {"arg:w": w, "aux:b": b})
        d = mx.nd.load(path)
        assert sorted(d) == ["arg:w", "aux:b"]
        np.testing.assert_array_equal(d["arg:w"].asnumpy(), w.asnumpy())
        np.testing.assert_array_equal(d["aux:b"].asnumpy(), b.asnumpy())
        assert d["aux:b"].dtype == np.float16
        # list form
        mx.nd.save(path, [w, b])
        lst = mx.nd.load(path)
        assert isinstance(lst, list) and len(lst) == 2


def test_dtype_bfloat16():
    a = mx.nd.ones((2, 2), dtype="bfloat16")
    assert a.dtype.name == "bfloat16"
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "bf.params")
        mx.nd.save(path, {"x": a})
        back = mx.nd.load(path)["x"]
        assert back.dtype.name == "bfloat16"
        np.testing.assert_array_equal(back.astype("float32").asnumpy(),
                                      np.ones((2, 2), "float32"))


def test_waitall_and_wait_to_read():
    a = mx.nd.ones((8, 8))
    b = a * 2
    b.wait_to_read()
    mx.nd.waitall()
    assert b.asnumpy()[0, 0] == 2
