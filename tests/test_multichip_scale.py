"""Multi-chip evidence beyond the 8-device mesh (VERDICT r4 #9).

``dryrun_multichip`` jits the FULL fused training step (dp x tp mesh,
plus pipeline/sequence/expert legs) over n virtual CPU devices.  The
driver exercises n=8; these tests push the same path to 16 and 32
devices — different mesh shapes, different collective layouts — in a
subprocess (the forced host-platform device count must be set before
jax initializes, so the live test process cannot re-enter at another
count).  Also CI-exercises tools/bandwidth/measure.py (reference
tools/bandwidth/README.md:33-40) so the measurement tool itself is
tested, not just shipped.
"""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_py(code, n_devices, timeout):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=%d" % n_devices).strip()
    return subprocess.run([sys.executable, "-c", code], cwd=ROOT, env=env,
                          capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow
@pytest.mark.timeout(1800)
@pytest.mark.parametrize("n", [16, 32])
def test_dryrun_multichip_scales(n):
    res = _run_py(
        "from __graft_entry__ import dryrun_multichip; "
        "dryrun_multichip(%d)" % n, n, timeout=1700)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-4000:]
    assert "dryrun_multichip(%d)" % n in res.stdout, res.stdout


@pytest.mark.timeout(600)
def test_bandwidth_tool_on_virtual_mesh():
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import runpy, sys; "
        "sys.argv = ['measure.py', '--size-mb', '2', '--num-arrays', '4', "
        "'--iters', '2']; "
        "runpy.run_path('tools/bandwidth/measure.py', run_name='__main__')")
    res = _run_py(code, 8, timeout=550)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-4000:]
    assert "allreduce bandwidth" in res.stdout, res.stdout
    assert "devices: 8" in res.stdout, res.stdout
