"""BatchNorm hand-written backward vs JAX autodiff of the textbook formula.

Regression guard for the fused BN kernel (ops/nn.py `_bn_core`): the
round-2 code review caught an extra factor of `inv` in dx that standard
unit-variance test data could not expose (inv ~ 1 hides scale errors),
so every check here uses data with std far from 1.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mxnet_tpu.ops.registry import OpContext
from mxnet_tpu.ops.nn import batch_norm


def _ref_bn_train(x, gamma, beta, eps):
    """Plain autodiff-able BN with batch stats (biased var)."""
    red = (0, 2, 3)
    mean = jnp.mean(x, axis=red)
    var = jnp.mean(jnp.square(x), axis=red) - jnp.square(mean)
    inv = jax.lax.rsqrt(var + eps)
    b = (1, -1, 1, 1)
    return ((x - mean.reshape(b)) * inv.reshape(b) * gamma.reshape(b)
            + beta.reshape(b))


ATTRS = {"eps": 1e-3, "momentum": 0.9, "fix_gamma": False,
         "use_global_stats": False, "output_mean_var": False, "axis": 1,
         "cudnn_off": False}


def _fused(x, gamma, beta, mm, mv, attrs=ATTRS, is_train=True):
    ctx = OpContext(is_train=is_train, key=None)
    return batch_norm(dict(attrs), ctx, x, gamma, beta, mm, mv)


@pytest.mark.parametrize("scale,shift", [(3.0, 0.0), (0.25, 5.0)])
def test_bn_dx_dgamma_dbeta_match_autodiff(scale, shift):
    rng = np.random.RandomState(0)
    x = (rng.randn(4, 6, 5, 5) * scale + shift).astype(np.float32)
    gamma = rng.uniform(0.5, 1.5, 6).astype(np.float32)
    beta = rng.uniform(-1, 1, 6).astype(np.float32)
    mm = np.zeros(6, np.float32)
    mv = np.ones(6, np.float32)
    cot = rng.randn(4, 6, 5, 5).astype(np.float32)

    def loss_fused(x, gamma, beta):
        out = _fused(x, gamma, beta, mm, mv)[0]
        return jnp.sum(out * cot)

    def loss_ref(x, gamma, beta):
        return jnp.sum(_ref_bn_train(x, gamma, beta, 1e-3) * cot)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, gamma, beta)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b, name in zip(gf, gr, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4, err_msg=name)


def test_bn_eval_mode_grad():
    rng = np.random.RandomState(1)
    x = (rng.randn(3, 4, 2, 2) * 2.5).astype(np.float32)
    gamma = rng.uniform(0.5, 1.5, 4).astype(np.float32)
    beta = np.zeros(4, np.float32)
    mm = rng.randn(4).astype(np.float32)
    mv = rng.uniform(0.5, 4.0, 4).astype(np.float32)
    eps = 1e-3

    def loss(x):
        out = _fused(x, gamma, beta, mm, mv, is_train=False)[0]
        return jnp.sum(jnp.square(out))

    g = jax.grad(loss)(x)
    # analytic: d/dx sum((x-mm)*inv*gamma)^2 = 2*out*gamma*inv
    inv = 1.0 / np.sqrt(mv + eps)
    out = (x - mm.reshape(1, -1, 1, 1)) * (gamma * inv).reshape(1, -1, 1, 1)
    expect = 2 * out * (gamma * inv).reshape(1, -1, 1, 1)
    np.testing.assert_allclose(np.asarray(g), expect, rtol=2e-3, atol=2e-4)


def test_bn_output_mean_var_cotangents_flow():
    """A loss through the mean/var heads must reach x (review finding #4)."""
    rng = np.random.RandomState(2)
    x = (rng.randn(4, 3, 4, 4) * 2.0 + 1.0).astype(np.float32)
    gamma = np.ones(3, np.float32)
    beta = np.zeros(3, np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    attrs = dict(ATTRS, output_mean_var=True)

    def loss_fused(x):
        out, mean, var, _, _ = _fused(x, gamma, beta, mm, mv, attrs=attrs)
        return jnp.sum(jnp.square(mean)) + jnp.sum(var)

    def loss_ref(x):
        red = (0, 2, 3)
        mean = jnp.mean(x, axis=red)
        var = jnp.mean(jnp.square(x), axis=red) - jnp.square(mean)
        return jnp.sum(jnp.square(mean)) + jnp.sum(var)

    gf = jax.grad(loss_fused)(x)
    gr = jax.grad(loss_ref)(x)
    assert float(jnp.max(jnp.abs(gf))) > 0
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=2e-3, atol=2e-4)


def test_fix_gamma_zero_grad():
    rng = np.random.RandomState(3)
    x = (rng.randn(2, 3, 4, 4) * 4).astype(np.float32)
    gamma = np.ones(3, np.float32)
    attrs = dict(ATTRS, fix_gamma=True)

    def loss(gamma):
        out = _fused(x, gamma, np.zeros(3, np.float32),
                     np.zeros(3, np.float32), np.ones(3, np.float32),
                     attrs=attrs)[0]
        return jnp.sum(jnp.square(out))

    g = jax.grad(loss)(gamma)
    np.testing.assert_allclose(np.asarray(g), np.zeros(3), atol=1e-6)
