"""Physical HWIO master storage (round 5, ShardedTrainer
``native_weight_layout``).

Conv weight masters stored HWIO so the canonical layout IS the conv-
preferred one (jit's Layout.AUTO cannot reach lax.scan loop carries —
docs/perf.md).  The graph and all checkpoints still see reference
OIHW, so the feature must be invisible: bit-identical training, the
same checkpoint bytes, and interop in both directions.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.parallel import ShardedTrainer, build_mesh


def _net():
    d = mx.sym.Variable("data")
    c = mx.sym.Convolution(d, num_filter=8, kernel=(3, 3), pad=(1, 1),
                           no_bias=True, name="c1")
    b = mx.sym.BatchNorm(c, name="bn1")
    a = mx.sym.Activation(b, act_type="relu")
    c2 = mx.sym.Convolution(a, num_filter=16, kernel=(1, 1),
                            no_bias=True, name="c2")
    p = mx.sym.Pooling(c2, global_pool=True, pool_type="avg",
                       kernel=(1, 1))
    fc = mx.sym.FullyConnected(mx.sym.Flatten(p), num_hidden=5,
                               name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def _trainer(native, **kw):
    mx.random.seed(7)
    np.random.seed(7)
    return ShardedTrainer(
        _net(), build_mesh(tp=1),
        data_shapes={"data": (8, 3, 16, 16)},
        label_shapes={"softmax_label": (8,)},
        learning_rate=0.1, momentum=0.9, weight_decay=1e-4,
        dtype="float32", layout="NHWC", seed=0,
        native_weight_layout=native, **kw)


def _batch():
    rng = np.random.RandomState(0)
    return {"data": rng.uniform(-1, 1, (8, 3, 16, 16)).astype("f"),
            "softmax_label": rng.randint(0, 5, 8).astype("f")}


def test_native_layout_trains_identically(tmp_path):
    batch = _batch()
    losses, params = {}, {}
    for native in (False, True):
        tr = _trainer(native)
        if native:
            assert tr._native_w == {"c1_weight", "c2_weight"}, tr._native_w
            assert tr.params["c1_weight"].shape == (3, 3, 3, 8)
        else:
            assert tr._native_w == frozenset()
        ls = [float(tr.step(tr.put_batch(batch))) for _ in range(4)]
        # the run_steps scan path shares the storage layout
        ls += [float(v) for v in
               np.asarray(tr.run_steps(tr.put_batch(batch), 3))]
        losses[native] = ls
        pre = str(tmp_path / ("ck%d" % native))
        tr.save_checkpoint(pre, 0, save_optimizer_states=True)
        params[native] = {k: np.asarray(v.asnumpy()) for k, v in
                          mx.nd.load(pre + "-0000.params").items()}
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)
    # checkpoints are reference OIHW from either storage
    assert params[True]["arg:c1_weight"].shape == (8, 3, 3, 3)
    for k in params[False]:
        np.testing.assert_allclose(params[False][k], params[True][k],
                                   rtol=2e-5, atol=1e-6, err_msg=k)


def test_native_layout_checkpoint_interop(tmp_path):
    """native=True resumes a native=False checkpoint and vice versa."""
    batch = _batch()
    t0 = _trainer(False)
    float(t0.step(t0.put_batch(batch)))
    pre = str(tmp_path / "x")
    t0.save_checkpoint(pre, 0, save_optimizer_states=True)
    ref_loss = float(t0.step(t0.put_batch(batch)))

    t1 = _trainer(True)
    t1.load_checkpoint(pre, 0, load_optimizer_states=True)
    got = float(t1.step(t1.put_batch(batch)))
    np.testing.assert_allclose(got, ref_loss, rtol=1e-5)

    pre2 = str(tmp_path / "y")
    t1.save_checkpoint(pre2, 0)
    t2 = _trainer(False)
    t2.load_checkpoint(pre2, 0)
    for k in t0.params:
        a = np.asarray(t2.params[k])
        b = np.asarray(t1.params[k])
        if k in t1._native_w:
            b = b.transpose(3, 2, 0, 1)
        np.testing.assert_allclose(a, b, rtol=1e-6, err_msg=k)


def test_native_layout_shared_weight_excluded():
    """A weight consumed by anything besides Convolution keeps the
    reference layout (shared/tied weights)."""
    d = mx.sym.Variable("data")
    w = mx.sym.Variable("shared_weight")
    c = mx.sym.Convolution(d, weight=w, num_filter=4, kernel=(3, 3),
                           pad=(1, 1), no_bias=True, name="c1")
    # the same w also feeds an elementwise op -> not conv-only
    reg = mx.sym.sum(w * w)
    out = mx.sym.Pooling(c, global_pool=True, pool_type="avg",
                         kernel=(1, 1))
    out = mx.sym.FullyConnected(mx.sym.Flatten(out), num_hidden=3,
                                name="fc")
    net = mx.sym.SoftmaxOutput(out + 0.0 * mx.sym.reshape(reg, shape=(1,)),
                               name="softmax")
    mx.random.seed(3)
    np.random.seed(3)
    tr = ShardedTrainer(
        net, build_mesh(tp=1),
        data_shapes={"data": (4, 2, 8, 8)},
        label_shapes={"softmax_label": (4,)},
        learning_rate=0.05, momentum=0.9, dtype="float32",
        layout="NHWC", native_weight_layout=True)
    assert "shared_weight" not in tr._native_w
