"""Spatial-warp / detection op family: forward vs numpy oracles mirroring
the reference C++ kernels, backward vs finite differences.

Reference kernels: src/operator/{grid_generator,bilinear_sampler,
spatial_transformer,roi_pooling,correlation}.cc and
src/operator/contrib/proposal.cc.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import check_numeric_gradient

RNG = np.random.RandomState(11)


# ------------------------------------------------------- numpy oracles

def np_bilinear_sample(data, grid, border=False):
    """bilinear_sampler.cc:16-67 — zero padding outside the boundary
    (border=True: clamp sample coords to the image rectangle first,
    the SpatialTransformer convention)."""
    n, c, h, w = data.shape
    _, _, oh, ow = grid.shape
    out = np.zeros((n, c, oh, ow), np.float64)
    for b in range(n):
        for y in range(oh):
            for x in range(ow):
                xr = (grid[b, 0, y, x] + 1) * (w - 1) / 2.0
                yr = (grid[b, 1, y, x] + 1) * (h - 1) / 2.0
                if border:
                    xr = min(max(xr, 0.0), w - 1.0)
                    yr = min(max(yr, 0.0), h - 1.0)
                tx, ty = int(np.floor(xr)), int(np.floor(yr))
                wx, wy = 1.0 - (xr - tx), 1.0 - (yr - ty)
                for dy, dx, wt in ((0, 0, wy * wx), (0, 1, wy * (1 - wx)),
                                   (1, 0, (1 - wy) * wx),
                                   (1, 1, (1 - wy) * (1 - wx))):
                    yy, xx = ty + dy, tx + dx
                    if 0 <= yy <= h - 1 and 0 <= xx <= w - 1:
                        out[b, :, y, x] += data[b, :, yy, xx] * wt
    return out


def np_affine_grid(loc, th, tw):
    """grid_generator-inl.h:73-108 affine branch."""
    n = loc.shape[0]
    theta = loc.reshape(n, 2, 3)
    out = np.zeros((n, 2, th, tw), np.float64)
    for y in range(th):
        for x in range(tw):
            xn = -1.0 + x * 2.0 / (tw - 1)
            yn = -1.0 + y * 2.0 / (th - 1)
            v = np.array([xn, yn, 1.0])
            out[:, :, y, x] = theta @ v
    return out


def np_roi_pool(data, rois, ph, pw, scale):
    """roi_pooling.cc ROIPoolForward:21-100."""
    n, c, h, w = data.shape
    r = rois.shape[0]
    out = np.zeros((r, c, ph, pw), np.float64)
    for i in range(r):
        bi = int(rois[i, 0])
        sw = int(round(rois[i, 1] * scale))
        sh = int(round(rois[i, 2] * scale))
        ew = int(round(rois[i, 3] * scale))
        eh = int(round(rois[i, 4] * scale))
        rh, rw = max(eh - sh + 1, 1), max(ew - sw + 1, 1)
        bh, bw = rh / ph, rw / pw
        for p in range(ph):
            for q in range(pw):
                hs = min(max(int(np.floor(p * bh)) + sh, 0), h)
                he = min(max(int(np.ceil((p + 1) * bh)) + sh, 0), h)
                ws = min(max(int(np.floor(q * bw)) + sw, 0), w)
                we = min(max(int(np.ceil((q + 1) * bw)) + sw, 0), w)
                if he <= hs or we <= ws:
                    out[i, :, p, q] = 0.0
                else:
                    out[i, :, p, q] = data[bi, :, hs:he, ws:we].max((1, 2))
    return out


def np_correlation(d1, d2, k, md, s1, s2, pad, mult):
    """correlation.cc CorrelationForward:22-66."""
    n, c, h, w = d1.shape
    hp, wp = h + 2 * pad, w + 2 * pad
    kr = (k - 1) // 2
    border = md + kr
    th = int(np.ceil((hp - 2 * border) / s1))
    tw = int(np.ceil((wp - 2 * border) / s1))
    ngr = md // s2
    ngw = 2 * ngr + 1
    p1 = np.zeros((n, c, hp, wp)); p1[:, :, pad:pad + h, pad:pad + w] = d1
    p2 = np.zeros((n, c, hp, wp)); p2[:, :, pad:pad + h, pad:pad + w] = d2
    out = np.zeros((n, ngw * ngw, th, tw), np.float64)
    for i in range(th):
        for j in range(tw):
            x1, y1 = j * s1 + md, i * s1 + md
            for tc in range(ngw * ngw):
                s2o = (tc % ngw - ngr) * s2
                s2p = (tc // ngw - ngr) * s2
                acc = 0.0
                for hh in range(k):
                    for ww in range(k):
                        a = p1[:, :, y1 + hh, x1 + ww]
                        b = p2[:, :, y1 + s2p + hh, x1 + s2o + ww]
                        acc = acc + ((a * b) if mult else np.abs(a - b)).sum(1)
                out[:, tc, i, j] = acc / (k * k * c)
    return out


# ------------------------------------------------------------- forward

def test_grid_generator_affine_forward():
    loc = RNG.uniform(-1, 1, (2, 6)).astype("f")
    out = mx.nd.GridGenerator(mx.nd.array(loc), transform_type="affine",
                              target_shape=(4, 5)).asnumpy()
    np.testing.assert_allclose(out, np_affine_grid(loc, 4, 5),
                               rtol=1e-5, atol=1e-5)


def test_grid_generator_warp_forward():
    flow = RNG.uniform(-1, 1, (2, 2, 3, 4)).astype("f")
    out = mx.nd.GridGenerator(mx.nd.array(flow),
                              transform_type="warp").asnumpy()
    h, w = 3, 4
    gx, gy = np.meshgrid(np.arange(w), np.arange(h))
    exp = np.stack([(flow[:, 0] + gx) / ((w - 1) / 2.0) - 1,
                    (flow[:, 1] + gy) / ((h - 1) / 2.0) - 1], 1)
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-5)


def test_bilinear_sampler_forward():
    data = RNG.uniform(-1, 1, (2, 3, 5, 6)).astype("f")
    grid = RNG.uniform(-1.3, 1.3, (2, 2, 4, 4)).astype("f")  # incl. OOB
    out = mx.nd.BilinearSampler(mx.nd.array(data), mx.nd.array(grid))
    np.testing.assert_allclose(out.asnumpy(), np_bilinear_sample(data, grid),
                               rtol=1e-4, atol=1e-5)


def test_spatial_transformer_forward():
    """ST == affine grid + border-clamped bilinear sample
    (spatial_transformer.cc:9-53)."""
    data = RNG.uniform(-1, 1, (2, 3, 6, 6)).astype("f")
    loc = np.tile(np.array([0.9, 0.05, 0.0, -0.05, 0.9, 0.0], "f"), (2, 1))
    loc += RNG.uniform(-0.02, 0.02, loc.shape).astype("f")
    out = mx.nd.SpatialTransformer(mx.nd.array(data), mx.nd.array(loc),
                                   target_shape=(5, 5)).asnumpy()
    grid = np_affine_grid(loc, 5, 5)
    np.testing.assert_allclose(out, np_bilinear_sample(data, grid, border=True),
                               rtol=1e-4, atol=1e-5)


def test_spatial_transformer_out_of_bounds_clamps():
    """A zoomed-out/translated affine that leaves [-1,1] samples border
    values (clamped), not zeros."""
    data = np.ones((1, 1, 4, 4), "f")
    loc = np.array([[2.0, 0.0, 1.5, 0.0, 2.0, 1.5]], "f")  # far out of range
    out = mx.nd.SpatialTransformer(mx.nd.array(data), mx.nd.array(loc),
                                   target_shape=(3, 3)).asnumpy()
    np.testing.assert_allclose(out, np.ones((1, 1, 3, 3)), rtol=1e-6)
    grid = np_affine_grid(loc, 3, 3)
    np.testing.assert_allclose(
        out, np_bilinear_sample(data, grid, border=True), rtol=1e-5)


def test_roi_pooling_forward():
    data = RNG.uniform(-1, 1, (2, 4, 8, 8)).astype("f")
    rois = np.array([[0, 0, 0, 7, 7],
                     [0, 2, 2, 6, 6],
                     [1, 1, 0, 5, 3],
                     [1, 4, 4, 4, 4]], "f")    # last: 1x1 roi
    out = mx.nd.ROIPooling(mx.nd.array(data), mx.nd.array(rois),
                           pooled_size=(2, 2), spatial_scale=1.0).asnumpy()
    np.testing.assert_allclose(out, np_roi_pool(data, rois, 2, 2, 1.0),
                               rtol=1e-5, atol=1e-6)


def test_roi_pooling_spatial_scale():
    data = RNG.uniform(-1, 1, (1, 2, 6, 6)).astype("f")
    rois = np.array([[0, 0, 0, 10, 10]], "f")
    out = mx.nd.ROIPooling(mx.nd.array(data), mx.nd.array(rois),
                           pooled_size=(3, 3), spatial_scale=0.5).asnumpy()
    np.testing.assert_allclose(out, np_roi_pool(data, rois, 3, 3, 0.5),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("mult", [True, False])
def test_correlation_forward(mult):
    d1 = RNG.uniform(-1, 1, (2, 3, 7, 7)).astype("f")
    d2 = RNG.uniform(-1, 1, (2, 3, 7, 7)).astype("f")
    out = mx.nd.Correlation(mx.nd.array(d1), mx.nd.array(d2), kernel_size=3,
                            max_displacement=2, stride1=1, stride2=1,
                            pad_size=2, is_multiply=mult).asnumpy()
    exp = np_correlation(d1, d2, 3, 2, 1, 1, 2, mult)
    assert out.shape == exp.shape
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-5)


def test_correlation_strided_shape():
    d1 = RNG.uniform(-1, 1, (1, 2, 10, 10)).astype("f")
    d2 = RNG.uniform(-1, 1, (1, 2, 10, 10)).astype("f")
    out = mx.nd.Correlation(mx.nd.array(d1), mx.nd.array(d2), kernel_size=1,
                            max_displacement=2, stride1=2, stride2=2,
                            pad_size=0).asnumpy()
    exp = np_correlation(d1, d2, 1, 2, 2, 2, 0, True)
    assert out.shape == exp.shape
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------- backward

def test_bilinear_sampler_grad():
    data = RNG.uniform(-1, 1, (1, 2, 4, 4))
    grid = RNG.uniform(-0.8, 0.8, (1, 2, 3, 3))
    # keep sampling points away from integer grid lines (floor() kinks)
    grid = np.round(grid * 8) / 8 + 0.037
    check_numeric_gradient("BilinearSampler", [data, grid], rtol=2e-2,
                           atol=2e-3)


def test_spatial_transformer_grad():
    data = RNG.uniform(-1, 1, (1, 2, 5, 5))
    loc = np.array([[0.63, 0.041, 0.037, -0.029, 0.57, 0.043]])
    check_numeric_gradient("SpatialTransformer", [data, loc],
                           attrs={"target_shape": (4, 4)}, rtol=2e-2,
                           atol=2e-3)


def test_grid_generator_grad():
    loc = RNG.uniform(-1, 1, (2, 6))
    check_numeric_gradient("GridGenerator", [loc],
                           attrs={"transform_type": "affine",
                                  "target_shape": (3, 4)})
    flow = RNG.uniform(-1, 1, (1, 2, 3, 3))
    check_numeric_gradient("GridGenerator", [flow],
                           attrs={"transform_type": "warp"})


def test_roi_pooling_grad():
    data = RNG.uniform(-1, 1, (1, 2, 6, 6))
    rois = np.array([[0, 0, 0, 5, 5], [0, 1, 1, 4, 4]], "f")

    import mxnet_tpu.autograd as autograd
    from mxnet_tpu import nd
    a = nd.array(data.astype("f"))
    r = nd.array(rois)
    g = nd.zeros_like(a)
    autograd.mark_variables([a], [g])
    with autograd.record():
        out = nd.ROIPooling(a, r, pooled_size=(2, 2), spatial_scale=1.0)
        loss = out.sum()
    autograd.backward([loss])
    got = g.asnumpy()

    # finite differences on data only (rois are index-only, zero grad)
    from mxnet_tpu.test_utils import numeric_grad
    def f(xs):
        o = nd.ROIPooling(nd.array(xs[0].astype("f")), r, pooled_size=(2, 2),
                          spatial_scale=1.0)
        return float(o.asnumpy().sum())
    exp = numeric_grad(f, [data.copy()])[0]
    np.testing.assert_allclose(got, exp, rtol=2e-2, atol=2e-3)


def test_correlation_grad():
    d1 = RNG.uniform(-1, 1, (1, 2, 5, 5))
    d2 = RNG.uniform(-1, 1, (1, 2, 5, 5))
    check_numeric_gradient("Correlation", [d1, d2],
                           attrs={"kernel_size": 1, "max_displacement": 1,
                                  "stride1": 1, "stride2": 1, "pad_size": 1},
                           rtol=2e-2, atol=2e-3)


# ------------------------------------------------------------- proposal

def np_proposal(cls_prob, bbox_pred, im_info, scales, ratios, stride,
                pre_nms, post_nms, thresh, min_size):
    """contrib/proposal.cc:252-420 oracle."""
    A = cls_prob.shape[1] // 2
    H, W = cls_prob.shape[2:]
    base = stride - 1.0
    anchors = []
    w = h = base + 1.0
    xc = yc = 0.5 * base
    size = w * h
    for ratio in ratios:
        sr = np.floor(size / ratio)
        for s in scales:
            nw = np.floor(np.sqrt(sr) + 0.5) * s
            nh = np.floor((nw / s * ratio) + 0.5) * s
            anchors.append([xc - 0.5 * (nw - 1), yc - 0.5 * (nh - 1),
                            xc + 0.5 * (nw - 1), yc + 0.5 * (nh - 1)])
    anchors = np.array(anchors)
    props = np.zeros((A * H * W, 5))
    for i in range(A):
        for j in range(H):
            for k in range(W):
                idx = j * W * A + k * A + i
                props[idx, :4] = anchors[i] + np.array(
                    [k * stride, j * stride, k * stride, j * stride])
                props[idx, 4] = cls_prob[0, A + i, j, k]
    im_h, im_w, im_scale = im_info[0]
    real_h, real_w = int(im_h / stride), int(im_w / stride)
    for i in range(A):
        for j in range(H):
            for k in range(W):
                idx = j * W * A + k * A + i
                x1, y1, x2, y2 = props[idx, :4]
                dx, dy, dw, dh = bbox_pred[0, i * 4:(i + 1) * 4, j, k]
                ww, hh = x2 - x1 + 1, y2 - y1 + 1
                cx, cy = x1 + 0.5 * (ww - 1), y1 + 0.5 * (hh - 1)
                pcx, pcy = dx * ww + cx, dy * hh + cy
                pw, phh = np.exp(dw) * ww, np.exp(dh) * hh
                box = [pcx - 0.5 * (pw - 1), pcy - 0.5 * (phh - 1),
                       pcx + 0.5 * (pw - 1), pcy + 0.5 * (phh - 1)]
                box[0] = min(max(box[0], 0), im_w - 1)
                box[1] = min(max(box[1], 0), im_h - 1)
                box[2] = min(max(box[2], 0), im_w - 1)
                box[3] = min(max(box[3], 0), im_h - 1)
                props[idx, :4] = box
                if j >= real_h or k >= real_w:
                    props[idx, 4] = -1.0
    ms = min_size * im_scale
    for i in range(len(props)):
        iw = props[i, 2] - props[i, 0] + 1
        ih = props[i, 3] - props[i, 1] + 1
        if iw < ms or ih < ms:
            props[i, 0] -= ms / 2; props[i, 1] -= ms / 2
            props[i, 2] += ms / 2; props[i, 3] += ms / 2
            props[i, 4] = -1.0
    order = np.argsort(-props[:, 4], kind="stable")[:pre_nms]
    dets = props[order]
    # greedy nms
    area = (dets[:, 2] - dets[:, 0] + 1) * (dets[:, 3] - dets[:, 1] + 1)
    sup = np.zeros(len(dets), bool)
    keep = []
    for i in range(len(dets)):
        if len(keep) >= post_nms:
            break
        if sup[i]:
            continue
        keep.append(i)
        for j in range(i + 1, len(dets)):
            if sup[j]:
                continue
            xx1 = max(dets[i, 0], dets[j, 0]); yy1 = max(dets[i, 1], dets[j, 1])
            xx2 = min(dets[i, 2], dets[j, 2]); yy2 = min(dets[i, 3], dets[j, 3])
            iw = max(0.0, xx2 - xx1 + 1); ih = max(0.0, yy2 - yy1 + 1)
            inter = iw * ih
            if inter / (area[i] + area[j] - inter) > thresh:
                sup[j] = True
    out = np.zeros((post_nms, 5))
    for i in range(post_nms):
        out[i, 1:] = dets[keep[i % len(keep)], :4]
    return out


def test_proposal_forward():
    H, W = 4, 4
    stride = 8
    im_info = np.array([[H * stride, W * stride, 1.0]], "f")
    kw = dict(scales=(2.0, 4.0), ratios=(0.5, 1.0, 2.0), feature_stride=stride,
              rpn_pre_nms_top_n=30, rpn_post_nms_top_n=8, threshold=0.7,
              rpn_min_size=4)
    # num anchors A = len(scales) * len(ratios) = 6
    cls_prob = RNG.uniform(0, 1, (1, 2 * 6, H, W)).astype("f")
    bbox_pred = RNG.uniform(-0.2, 0.2, (1, 4 * 6, H, W)).astype("f")
    out = mx.nd.Proposal(mx.nd.array(cls_prob), mx.nd.array(bbox_pred),
                         mx.nd.array(im_info), **kw).asnumpy()
    exp = np_proposal(cls_prob, bbox_pred, im_info, (2.0, 4.0),
                      (0.5, 1.0, 2.0), stride, 30, 8, 0.7, 4)
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-3)


def test_proposal_output_score():
    A = 4
    cls_prob = RNG.uniform(0, 1, (1, 2 * A, 3, 3)).astype("f")
    bbox_pred = RNG.uniform(-0.1, 0.1, (1, 4 * A, 3, 3)).astype("f")
    im_info = np.array([[48, 48, 1.0]], "f")
    rois, score = mx.nd.Proposal(
        mx.nd.array(cls_prob), mx.nd.array(bbox_pred), mx.nd.array(im_info),
        scales=(4.0, 8.0), ratios=(0.5, 1.0), feature_stride=16,
        rpn_pre_nms_top_n=20, rpn_post_nms_top_n=6, rpn_min_size=2,
        output_score=True)
    assert rois.shape == (6, 5) and score.shape == (6, 1)
    assert (rois.asnumpy()[:, 0] == 0).all()


def test_spatial_ops_symbolic():
    """The new family also works through the symbolic executor."""
    data = mx.sym.Variable("data")
    loc = mx.sym.Variable("loc")
    st = mx.sym.SpatialTransformer(data, loc, target_shape=(4, 4))
    arg_shapes, out_shapes, _ = st.infer_shape(data=(2, 3, 6, 6), loc=(2, 6))
    assert out_shapes[0] == (2, 3, 4, 4)
    ex = st.bind(mx.cpu(), {"data": mx.nd.ones((2, 3, 6, 6)),
                            "loc": mx.nd.array(
                                np.tile([1, 0, 0, 0, 1, 0], (2, 1)).astype("f"))})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, np.ones((2, 3, 4, 4)), rtol=1e-5)
