"""Torch interop bridge: torch.nn modules/criterions as graph operators,
mx.th.* imperative tensor functions.

Reference: plugin/torch (torch_module.cc / torch_criterion.cc) and
python/mxnet/torch.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402


def test_th_imperative_functions():
    x = mx.nd.array(np.array([[1.0, 4.0], [9.0, 16.0]], np.float32))
    np.testing.assert_allclose(mx.th.sqrt(x).asnumpy(),
                               [[1, 2], [3, 4]])
    y = mx.th.mm(x, x)
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() @ x.asnumpy())


def test_torch_module_forward_matches_torch():
    lin = tnn.Linear(8, 4)
    data = mx.sym.Variable("data")
    net = mx.sym.TorchModule(data, module=lin, name="tmod")
    ex = net.simple_bind(mx.cpu(), data=(2, 8), grad_req="write")
    x = np.random.RandomState(0).randn(2, 8).astype("f")
    # feed the torch params through the graph args
    args = dict(zip(net.list_arguments(), ex.arg_arrays))
    params = list(lin.parameters())
    for i, p in enumerate(params):
        args["tmod_torch_param_%d_weight" % i][:] = p.detach().numpy()
    ex.forward(is_train=True, data=x)
    want = lin(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), want,
                               rtol=1e-5, atol=1e-5)


def test_torch_module_trains_through_framework_optimizer():
    """A torch Linear trained by the framework's Module/SGD learns a
    linear map (weights live as graph args, like torch_module-inl.h)."""
    rng = np.random.RandomState(0)
    w_true = rng.randn(4, 8).astype("f")
    x = rng.randn(512, 8).astype("f")
    y = x @ w_true.T

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("lin_label")
    net = mx.sym.TorchModule(data, module=tnn.Linear(8, 4, bias=False),
                             name="tmod")
    net = mx.sym.LinearRegressionOutput(net, label=label, name="lin")

    mod = mx.module.Module(net, context=mx.cpu(),
                           label_names=("lin_label",))
    it = mx.io.NDArrayIter(x, y, 64, shuffle=True, label_name="lin_label")
    mod.fit(it, num_epoch=10, initializer=mx.init.Xavier(),
            optimizer="sgd", optimizer_params={"learning_rate": 0.1})
    rmse = mod.score(it, mx.metric.RMSE())[0][1]
    assert rmse < 0.1, rmse


def test_torch_criterion_loss_head():
    """CrossEntropyLoss as the loss head drives a small classifier."""
    rng = np.random.RandomState(1)
    protos = np.random.RandomState(42).randn(4, 16).astype("f")
    yy = rng.randint(0, 4, 256)
    xx = (protos[yy] + 0.3 * rng.randn(256, 16)).astype("f")

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("ce_label")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.TorchCriterion(fc, label,
                                criterion=tnn.CrossEntropyLoss(),
                                name="tcrit")

    mod = mx.module.Module(net, context=mx.cpu(),
                           label_names=("ce_label",))
    it = mx.io.NDArrayIter(xx, yy.astype("f"), 64, shuffle=True,
                           label_name="ce_label")
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    # torch's CrossEntropyLoss already averages over the batch; undo the
    # framework's default 1/batch gradient rescale
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "rescale_grad": 1.0})
    first_loss = last_loss = None
    for _ in range(8):
        it.reset()
        tot = n = 0
        for b in it:
            mod.forward(b, is_train=True)
            tot += float(mod.get_outputs()[0].asnumpy()[0])
            n += 1
            mod.backward()
            mod.update()
        if first_loss is None:
            first_loss = tot / n
        last_loss = tot / n
    assert last_loss < 0.5 * first_loss, (first_loss, last_loss)
