"""ShardedTrainer on the virtual 8-device CPU mesh.

Covers the fused pjit path bench.py uses (VERDICT r1 weak #7: a
regression there was invisible to CI): layout modes, pluggable
optimizers, reference wd_mult exemptions, and honest initializer errors.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel import ShardedTrainer, build_mesh


def _small_convnet(num_classes=10):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=8,
                             no_bias=True, name="conv1")
    net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    # global pool before Flatten keeps the FC input layout-invariant, so
    # NHWC/NCHW runs share parameter semantics (ResNet/Inception style)
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _batch(batch=8, image=8, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    # non-unit variance + offset: scale-sensitive gradient bugs (e.g. a
    # stray inv factor in BN backward) are invisible on ~N(0,1) data
    data = (rng.uniform(-1, 1, (batch, 3, image, image)) * 3.0 + 0.5)
    return {
        "data": data.astype(np.float32),
        "softmax_label": rng.randint(0, classes, batch).astype(np.float32),
    }


def _make(layout=None, **kw):
    mesh = build_mesh(tp=1)
    kw.setdefault("learning_rate", 0.1)
    np.random.seed(7)  # initializers draw from the global numpy RNG
    return ShardedTrainer(
        _small_convnet(), mesh,
        data_shapes={"data": (8, 3, 8, 8)},
        label_shapes={"softmax_label": (8,)},
        layout=layout, seed=3, **kw)


def test_nhwc_matches_nchw():
    """NHWC is a pure layout change: identical math, identical losses."""
    b = _batch()
    t_nchw = _make(layout=None)
    t_nhwc = _make(layout="NHWC")
    for step in range(3):
        l0 = float(t_nchw.step(b))
        l1 = float(t_nhwc.step(b))
        assert np.isfinite(l0)
        np.testing.assert_allclose(l0, l1, rtol=2e-4), step
    # params stay f32 masters in both
    assert all(v.dtype == np.float32 for v in t_nchw.params.values())
    w0 = np.asarray(t_nchw.params["conv1_weight"])
    w1 = np.asarray(t_nhwc.params["conv1_weight"])
    np.testing.assert_allclose(w0, w1, rtol=1e-3, atol=1e-5)


def test_loss_decreases_sgd():
    t = _make()
    b = _batch()
    first = float(t.step(b))
    for _ in range(15):
        last = float(t.step(b))
    assert last < first


def test_adam_optimizer():
    t = _make(optimizer="adam", optimizer_params={"learning_rate": 1e-2})
    b = _batch()
    first = float(t.step(b))
    for _ in range(15):
        last = float(t.step(b))
    assert last < first
    # adam carries two state slots per param
    assert all(len(s) == 2 for s in t.opt_state.values())


def test_wd_exempts_bias_and_gamma():
    """Reference wd_mult defaults: no decay for params not ending in
    _weight/_gamma (python/mxnet/optimizer.py set_wd_mult)."""
    t = _make(weight_decay=0.5)
    _, wd_bias = t._per_param_hyper("fc1_bias")
    _, wd_beta = t._per_param_hyper("bn1_beta")
    _, wd_w = t._per_param_hyper("conv1_weight")
    assert wd_bias == 0.0 and wd_beta == 0.0
    assert wd_w == pytest.approx(0.5)


def test_initializer_error_propagates():
    class Bad(mx.init.Initializer):
        def _init_weight(self, name, arr):
            arr[:] = np.zeros((1, 2, 3))  # wrong shape: must raise

    with pytest.raises(Exception):
        _make(initializer=Bad())


def test_bfloat16_compute_f32_masters():
    t = _make(dtype="bfloat16")
    b = _batch()
    for _ in range(3):
        loss = float(t.step(b))
    assert np.isfinite(loss)
    assert all(v.dtype == np.float32 for v in t.params.values())
    assert all(v.dtype == np.float32 for v in t.aux.values())


def test_forward_inference():
    t = _make(layout="NHWC")
    heads = t.forward(_batch())
    probs = np.asarray(heads[0], np.float32)
    assert probs.shape == (8, 10)
    np.testing.assert_allclose(probs.sum(-1), np.ones(8), rtol=1e-3)


def test_lr_scheduler_applies():
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    t = _make(optimizer_params={"lr_scheduler": sched,
                                "learning_rate": 0.2})
    b = _batch()
    t.step(b)
    assert t.optimizer.lr_scheduler(t.optimizer.num_update) == \
        pytest.approx(0.2)
    for _ in range(4):
        t.step(b)
    assert sched(t.optimizer.num_update) < 0.2


def test_forward_accepts_staged_batch():
    """put_batch output must not be re-transposed by forward (NHWC)."""
    t = _make(layout="NHWC")
    staged = t.put_batch(_batch())
    heads = t.forward(staged)
    assert np.asarray(heads[0]).shape == (8, 10)


def test_post_build_lr_mult_honored():
    """Reference workflow: set_lr_mult after construction must apply."""
    b = _batch()
    t = _make()
    t.step(b)
    before = {k: np.asarray(v) for k, v in t.params.items()}
    t.optimizer.set_lr_mult({n: 0.0 for n in t.params})
    t.optimizer.momentum = 0.0  # kill momentum carry-over too
    t.step(b)
    after = t.params
    for k in before:
        # lr_mult 0 (and no wd) => params unchanged up to momentum decay
        np.testing.assert_allclose(before[k], np.asarray(after[k]),
                                   rtol=0, atol=1e-4)


def test_nhwc_guard_rejects_axis_ops():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=4,
                             name="c1")
    net = mx.sym.softmax(net, axis=-3)  # channel softmax in NCHW convention
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mesh = build_mesh(tp=1)
    with pytest.raises(Exception, match="NHWC"):
        ShardedTrainer(net, mesh, data_shapes={"data": (8, 3, 8, 8)},
                       label_shapes={"softmax_label": (8,)}, layout="NHWC")


def test_nhwc_deconv_builds():
    """Deconvolution shape hook must resolve channels under NHWC."""
    np.random.seed(0)
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=4,
                             name="c1")
    net = mx.sym.Deconvolution(net, kernel=(2, 2), stride=(2, 2),
                               num_filter=4, name="d1")
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=10, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mesh = build_mesh(tp=1)
    t = ShardedTrainer(net, mesh, data_shapes={"data": (8, 3, 8, 8)},
                       label_shapes={"softmax_label": (8,)}, layout="NHWC")
    assert t.params["d1_weight"].shape == (4, 4, 2, 2)
    loss = float(t.step(_batch()))
    assert np.isfinite(loss)


def test_out_of_range_label_finite_loss():
    """Monitoring loss stays finite when a label exceeds the class count
    (take_along_axis must clip, not NaN-fill, under jit)."""
    np.random.seed(0)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mesh = build_mesh(tp=1)
    t = ShardedTrainer(net, mesh, data_shapes={"data": (8, 4)},
                       label_shapes={"softmax_label": (8,)})
    labels = np.arange(8, dtype=np.float32) % 5  # values up to 4 >= 2 classes
    loss = float(t.step({"data": np.random.randn(8, 4).astype(np.float32),
                         "softmax_label": labels}))
    assert np.isfinite(loss)


def test_bench_script_cpu_smoke(monkeypatch, capsys):
    """bench.py end-to-end on the CPU mesh (tiny config).

    Dry-run is the smoke contract: without it bench.py runs the full
    ResNet-50 config, which on the 8-device virtual CPU mesh never
    finishes inside the tier-1 window (and starves every test after
    this file of its budget)."""
    import importlib
    import json as _json
    monkeypatch.setenv("BENCH_DRYRUN", "1")
    import bench as bench_mod
    importlib.reload(bench_mod)
    bench_mod.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = _json.loads(line)
    assert rec["unit"] in ("img/s/chip", "samples/s/chip")
    assert rec["value"] > 0


def test_auto_layouts_matches_default():
    """auto_layouts=True (XLA-chosen persistent param layouts) trains
    identically to the default-layout step."""
    np.random.seed(0)

    def build(auto):
        np.random.seed(11)  # identical initializer draws for both builds
        data = mx.sym.Variable("data")
        net = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                                 num_filter=4, name="c1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.Pooling(net, global_pool=True, pool_type="avg")
        net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=3,
                                    name="fc")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mesh = build_mesh(tp=1)
        return ShardedTrainer(net, mesh, data_shapes={"data": (8, 3, 8, 8)},
                              label_shapes={"softmax_label": (8,)},
                              learning_rate=0.1, seed=3,
                              auto_layouts=auto)

    batch = _batch(classes=3)
    t0, t1 = build(False), build(True)
    for _ in range(3):
        l0 = float(t0.step(batch))
        l1 = float(t1.step(batch))
    np.testing.assert_allclose(l1, l0, rtol=1e-5)
    for k in t0.params:
        np.testing.assert_allclose(np.asarray(t1.params[k]),
                                   np.asarray(t0.params[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_trainer_checkpoint_roundtrip_and_module_interop(tmp_path):
    """save_checkpoint/load_checkpoint on the fused path: params,
    optimizer slots, and step counter resume identically; the files are
    Module-format (arg:/aux: prefixes + symbol JSON)."""
    import os
    prefix = os.path.join(str(tmp_path), "ck")

    t1 = _make(optimizer="adam")
    b = t1.put_batch(_batch())
    for _ in range(3):
        loss_before = float(t1.step(b))
    t1.save_checkpoint(prefix, 3, save_optimizer_states=True)

    t2 = _make(optimizer="adam")
    t2.load_checkpoint(prefix, 3, load_optimizer_states=True)
    for k in t1.params:
        np.testing.assert_allclose(np.asarray(t2.params[k]),
                                   np.asarray(t1.params[k]),
                                   rtol=1e-6, err_msg=k)
    b2 = t2.put_batch(_batch())
    l1 = float(t1.step(b))
    l2 = float(t2.step(b2))
    np.testing.assert_allclose(l1, l2, rtol=1e-5)

    # Module can read the same files (reference checkpoint interop)
    sym, args, auxs = mx.model.load_checkpoint(prefix, 3)
    assert set(args) == set(t1.params)


def test_trainer_checkpoint_auto_layouts(tmp_path):
    """load_checkpoint preserves XLA-chosen layouts (auto_layouts): the
    loaded state must still feed the AOT-compiled step."""
    import os
    prefix = os.path.join(str(tmp_path), "al")
    t1 = _make(optimizer="adam", auto_layouts=True)
    b = t1.put_batch(_batch())
    float(t1.step(b))
    t1.save_checkpoint(prefix, 1, save_optimizer_states=True)
    t2 = _make(optimizer="adam", auto_layouts=True)
    b2 = t2.put_batch(_batch())
    float(t2.step(b2))  # compile the AOT step before loading
    t2.load_checkpoint(prefix, 1, load_optimizer_states=True)
    l1 = float(t1.step(b))
    l2 = float(t2.step(b2))
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_trainer_checkpoint_optimizer_mismatch_raises(tmp_path):
    import os
    prefix = os.path.join(str(tmp_path), "mm")
    t1 = _make(optimizer="adam")
    b = t1.put_batch(_batch())
    float(t1.step(b))
    t1.save_checkpoint(prefix, 1, save_optimizer_states=True)
    t2 = _make(optimizer="sgd")
    with pytest.raises(mx.base.MXNetError, match="optimizer state"):
        t2.load_checkpoint(prefix, 1, load_optimizer_states=True)
