"""Worker for the multi-host FUSED-path (ShardedTrainer) parity test.

Reference: multi-machine training composes the training loop with
kvstore dist_sync (src/kvstore/kvstore_dist.h:192-238).  Here the
TPU-native performance path itself — ShardedTrainer's single jitted
step — runs over a PROCESS-SPANNING mesh: every process executes the
same XLA program, the data axis spans the processes, and GSPMD's
gradient psum crosses them.  The launcher (tools/launch.py) may start
this worker with any -n; each process gets FUSED_DEVS_PER_PROC virtual
CPU devices, so the global mesh is n*FUSED_DEVS_PER_PROC devices on a
(data x model) grid with tp=2.

The parent test runs this script at n=1 and n=2 with the SAME global
mesh shape and asserts step-for-step loss parity, plus the in-run
resume leg below: rank 0 saves a mid-run checkpoint (gathering the
process-sharded tensor-parallel weights), every rank reloads it into a
FRESH trainer and replays the remaining steps to identical losses.
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_devs = int(os.environ.get("FUSED_DEVS_PER_PROC", "2"))
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=%d" % _devs
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.parallel import ShardedTrainer, build_mesh, multihost  # noqa: E402

GBATCH = 64
STEPS = 8
CKPT_STEP = 3          # save after the 4th update
_PROTOS = np.random.RandomState(42).rand(10, 64).astype("f")


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=32)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc3", num_hidden=10)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _global_batch(step):
    rng = np.random.RandomState(1000 + step)
    y = rng.randint(0, 10, GBATCH)
    x = (_PROTOS[y] + rng.randn(GBATCH, 64) * 0.25).astype("f")
    return x, y.astype("f")


def _build_trainer(mesh):
    np.random.seed(7)           # identical init on every process
    return ShardedTrainer(
        _mlp(), mesh,
        data_shapes={"data": (GBATCH, 64)},
        label_shapes={"softmax_label": (GBATCH,)},
        learning_rate=0.1, momentum=0.9, weight_decay=1e-4, seed=3)


def main():
    multihost.ensure_initialized()
    import jax

    rank, nproc = jax.process_index(), jax.process_count()
    devices = jax.devices()
    assert len(devices) % 2 == 0, devices
    mesh = build_mesh(tp=2, devices=devices)   # (data x model), tp=2

    ckpt = os.environ["FUSED_CKPT_PREFIX"]
    trainer = _build_trainer(mesh)
    # with tp=2 the classifier FC is model-sharded; on the n=2 launch
    # the checkpoint gather below must cross processes
    assert trainer.tp_rules, trainer.tp_rules

    def shard(a):
        per = GBATCH // nproc
        return a[rank * per:(rank + 1) * per]

    losses = []
    for step in range(STEPS):
        x, y = _global_batch(step)
        loss = trainer.step({"data": shard(x),
                             "softmax_label": shard(y)})
        losses.append(float(loss))
        if step == CKPT_STEP:
            trainer.save_checkpoint(ckpt, 0, save_optimizer_states=True)
    assert losses[-1] < losses[0], losses

    # ---- resume leg: fresh trainer, restore, replay steps 4..7
    resumed = _build_trainer(mesh)
    resumed.load_checkpoint(ckpt, 0, load_optimizer_states=True)
    relosses = []
    for step in range(CKPT_STEP + 1, STEPS):
        x, y = _global_batch(step)
        relosses.append(float(resumed.step({"data": shard(x),
                                            "softmax_label": shard(y)})))
    np.testing.assert_allclose(relosses, losses[CKPT_STEP + 1:],
                               rtol=0, atol=1e-6)

    multihost.process_barrier("fused_worker_done")
    print("fused-dist worker %d/%d OK losses=%s"
          % (rank, nproc, json.dumps(losses)))


if __name__ == "__main__":
    main()
