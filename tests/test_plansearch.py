"""Cost-model-guided whole-graph plan search (analysis.plansearch +
fusion decision hooks): digest/identity stability, decision
application, objective, beam search (greedy-seeded, never regresses),
measurement + cache commit, bind-time pickup by Executor and
ShardedTrainer, searched-vs-greedy numerical parity, the perf_top
plan-suggestion rows, and MXG010's --plan mode.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autotune, models, telemetry
from mxnet_tpu.analysis import fusion, infer_node_shapes, plansearch
from mxnet_tpu.ops.fused import block_fusion

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolated_plan_cache():
    """The process-wide tuning cache keeps merged entries across env
    changes; a committed plan from one test must not be consulted by
    another test's (or suite's) bind."""
    autotune.CACHE.clear()
    plansearch.reset_stats()
    yield
    autotune.CACHE.clear()
    plansearch.reset_stats()


def _conv_net(num_classes=10):
    """conv3x3+BN+relu -> pallas-eligible conv1x1+BN+relu -> FC+relu
    -> FC head: one chain of every matchable kind but bn_act."""
    d = mx.sym.Variable("data")
    n = mx.sym.Convolution(d, kernel=(3, 3), pad=(1, 1), num_filter=8,
                           no_bias=True, name="c0")
    n = mx.sym.BatchNorm(n, name="b0", fix_gamma=False)
    n = mx.sym.Activation(n, act_type="relu", name="r0")
    n = mx.sym.Convolution(n, kernel=(1, 1), num_filter=8,
                           no_bias=True, name="c1")
    n = mx.sym.BatchNorm(n, name="b1", fix_gamma=False)
    n = mx.sym.Activation(n, act_type="relu", name="r1")
    n = mx.sym.FullyConnected(mx.sym.Flatten(n), num_hidden=16,
                              name="fc0")
    n = mx.sym.Activation(n, act_type="relu", name="fa0")
    n = mx.sym.FullyConnected(n, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(n, name="softmax")


def _greedy_plan(sym, layout="NCHW"):
    return fusion.plan_block_fusion(sym._topo(), sym._entries,
                                    layout=layout, record=False,
                                    decisions={})


def _chain_of(plan, kind, terminal=None):
    for b in plan.blocks.values():
        if b.kind == kind and (terminal is None or b.name == terminal):
            return b.chain
    raise AssertionError("no %s block in plan" % kind)


# --------------------------------------------------- digest / identity
def test_graph_digest_stable_across_rebuilds():
    """Two builds of one architecture (different auto-generated node
    names) share a digest; an attr change breaks it."""
    first = _conv_net()
    d1 = fusion.graph_digest(first._topo(), first._entries)
    a, b = _conv_net(), _conv_net()
    assert fusion.graph_digest(a._topo(), a._entries) == \
        fusion.graph_digest(b._topo(), b._entries) == d1
    c = _conv_net(num_classes=11)
    assert fusion.graph_digest(c._topo(), c._entries) != d1


def test_graph_digest_shared_across_batch_sizes():
    """The digest hashes structure, not shapes — one committed plan
    serves every batch size of the graph."""
    net = models.get_model("mlp", num_classes=10)
    assert fusion.graph_digest(net._topo(), net._entries) == \
        fusion.graph_digest(net._topo(), net._entries)


def test_decisions_id():
    assert fusion.decisions_id(None) == "greedy"
    assert fusion.decisions_id({}) == "greedy"
    d = {"chains": {"3": "off"}}
    assert fusion.decisions_id(d).startswith("plan-")
    assert fusion.decisions_id(d) == fusion.decisions_id(dict(d))
    assert fusion.decisions_id(d) != \
        fusion.decisions_id({"chains": {"3": "conv_bn"}})


# ----------------------------------------------- decision application
def test_decision_off_unfuses_chain():
    sym = _conv_net()
    g = _greedy_plan(sym)
    cid = _chain_of(g, "conv_bn_act", "r0")
    p = fusion.plan_block_fusion(sym._topo(), sym._entries,
                                 record=False,
                                 decisions={"chains": {cid: "off"}})
    kinds = sorted(b.kind for b in p.blocks.values())
    assert kinds == ["conv_bn_act", "fc_act"]
    assert p.overrides == 1 and p.plan_id.startswith("plan-")


def test_decision_conv_bn_split():
    """conv_bn_act -> conv_bn: the act leaves the region, the terminal
    moves to the BN, the chain id stays the greedy terminal's."""
    sym = _conv_net()
    g = _greedy_plan(sym)
    cid = _chain_of(g, "conv_bn_act", "r0")
    p = fusion.plan_block_fusion(
        sym._topo(), sym._entries, record=False,
        decisions={"chains": {cid: "conv_bn"}})
    blk = next(b for b in p.blocks.values() if b.kind == "conv_bn")
    assert blk.name == "b0" and blk.chain == cid and blk.act is None
    # a split of the PALLAS-eligible 1x1 chain keeps the Pallas leg a
    # naturally-matched conv_bn chain would get
    g_nhwc = _greedy_plan(sym, layout="NHWC")
    cid1 = _chain_of(g_nhwc, "conv_bn_act", "r1")
    p2 = fusion.plan_block_fusion(
        sym._topo(), sym._entries, layout="NHWC", record=False,
        decisions={"chains": {cid1: "conv_bn"}})
    blk2 = next(b for b in p2.blocks.values() if b.kind == "conv_bn")
    assert blk2.name == "b1" and blk2.pallas


def test_decision_bn_act_split():
    """conv_bn_act -> bn_act: the conv leaves the region (evaluates
    unfused), the bn+act half still fuses."""
    sym = _conv_net()
    g = _greedy_plan(sym)
    cid = _chain_of(g, "conv_bn_act", "r0")
    p = fusion.plan_block_fusion(
        sym._topo(), sym._entries, record=False,
        decisions={"chains": {cid: "bn_act"}})
    blk = next(b for b in p.blocks.values() if b.kind == "bn_act")
    assert blk.name == "r0" and blk.conv is None


def test_decision_layout_override_accounting_and_pallas():
    """A region pinned to a non-ambient layout pays 2 explicit
    relayout edges, loses adjacency credit, and re-derives Pallas
    eligibility from the REGION layout (an NHWC override in an NCHW
    trace opens the 1x1 Pallas leg)."""
    sym = _conv_net()
    g = _greedy_plan(sym, layout="NCHW")
    assert all(not b.pallas for b in g.blocks.values())
    assert g.adjacent_edges == 1 and g.relayout_edges_added == 0
    cid = _chain_of(g, "conv_bn_act", "r1")   # the 1x1 chain
    p = fusion.plan_block_fusion(
        sym._topo(), sym._entries, layout="NCHW", record=False,
        decisions={"layouts": {cid: "NHWC"}})
    blk = next(b for b in p.blocks.values() if b.chain == cid)
    assert blk.layout == "NHWC" and blk.pallas
    assert p.relayout_edges_added == 2
    assert p.adjacent_edges == 0      # boundary layouts now differ
    s = p.summary()
    assert s["relayout_edges_added"] == 2 and s["searched"]


def test_decision_pallas_veto():
    sym = _conv_net()
    g = _greedy_plan(sym, layout="NHWC")
    cid = _chain_of(g, "conv_bn_act", "r1")
    blk = next(b for b in g.blocks.values() if b.chain == cid)
    assert blk.pallas
    p = fusion.plan_block_fusion(
        sym._topo(), sym._entries, layout="NHWC", record=False,
        decisions={"pallas": {cid: 0}})
    blk = next(b for b in p.blocks.values() if b.chain == cid)
    assert not blk.pallas


def test_stale_decisions_degrade_to_fuse():
    """Unknown chain ids and ineligible choices read as greedy — a
    stale committed entry must never break a plan."""
    sym = _conv_net()
    g = _greedy_plan(sym)
    fc_cid = _chain_of(g, "fc_act")
    p = fusion.plan_block_fusion(
        sym._topo(), sym._entries, record=False,
        decisions={"chains": {"9999": "off", fc_cid: "conv_bn"}})
    assert sorted(b.kind for b in p.blocks.values()) == \
        sorted(b.kind for b in g.blocks.values())


def test_adjacent_overridden_regions_claim_no_elimination():
    """Two adjacent regions both overridden to NHWC in an NCHW trace
    still round-trip through the ambient layout at their shared
    boundary (apply_block) — crediting adjacency there would
    contradict the 4 relayout edges they demonstrably pay."""
    sym = _conv_net()
    g = _greedy_plan(sym, layout="NCHW")
    cids = sorted(b.chain for b in g.blocks.values()
                  if b.kind == "conv_bn_act")
    p = fusion.plan_block_fusion(
        sym._topo(), sym._entries, layout="NCHW", record=False,
        decisions={"layouts": {cids[0]: "NHWC", cids[1]: "NHWC"}})
    assert p.relayout_edges_added == 4
    assert p.adjacent_edges == 0


# ---------------------------------------------------------- objective
def test_predict_plan_wall_greedy_covers_blocks_and_heavies():
    sym = _conv_net()
    shapes = {"data": (4, 3, 8, 8), "softmax_label": (4,)}
    topo, node_shapes = infer_node_shapes(sym, shapes)
    plan = fusion.plan_block_fusion(topo, sym._entries, record=False,
                                    decisions={})
    total, units = plansearch.predict_plan_wall(topo, sym._entries,
                                                plan, node_shapes)
    assert total > 0
    kinds = {(u["unit"], u["kind"]) for u in units}
    assert ("block", "conv_bn_act") in kinds
    assert ("block", "fc_act") in kinds
    assert ("node", "FullyConnected") in kinds    # the unfused fc1 head


def test_predict_plan_wall_costs_layout_override_relayouts():
    sym = _conv_net()
    shapes = {"data": (4, 3, 8, 8), "softmax_label": (4,)}
    topo, node_shapes = infer_node_shapes(sym, shapes)
    g = fusion.plan_block_fusion(topo, sym._entries, record=False,
                                 decisions={})
    cid = _chain_of(g, "conv_bn_act", "r0")
    p = fusion.plan_block_fusion(topo, sym._entries, record=False,
                                 decisions={"layouts": {cid: "NHWC"}})
    t_g, _ = plansearch.predict_plan_wall(topo, sym._entries, g,
                                          node_shapes)
    t_o, units = plansearch.predict_plan_wall(topo, sym._entries, p,
                                              node_shapes)
    blk = next(u for u in units if u["chain"] == cid)
    assert blk["relayout_s"] > 0
    assert t_o > t_g


def test_predict_plan_wall_sees_split_off_activation_cost():
    """A split/off decision pushes the act OUT of the fused epilogue:
    the objective must charge that extra elementwise pass, or every
    split scores tied with greedy and the measurement budget fills
    with candidates that are strictly worse in reality."""
    sym = _conv_net()
    shapes = {"data": (4, 3, 8, 8), "softmax_label": (4,)}
    topo, node_shapes = infer_node_shapes(sym, shapes)
    g = fusion.plan_block_fusion(topo, sym._entries, record=False,
                                 decisions={})
    cid = _chain_of(g, "conv_bn_act", "r0")
    t_g, _ = plansearch.predict_plan_wall(topo, sym._entries, g,
                                          node_shapes)
    for choice in ("conv_bn", "off"):
        p = fusion.plan_block_fusion(
            topo, sym._entries, record=False,
            decisions={"chains": {cid: choice}})
        t_s, _ = plansearch.predict_plan_wall(topo, sym._entries, p,
                                              node_shapes)
        assert t_s > t_g, choice


def test_search_plan_greedy_seeded_and_never_regressed():
    sym = _conv_net()
    shapes = {"data": (4, 3, 8, 8), "softmax_label": (4,)}
    topo, node_shapes = infer_node_shapes(sym, shapes)
    ranked = plansearch.search_plan(topo, sym._entries, layout="NHWC",
                                    node_shapes=node_shapes,
                                    budget=12, beam=4)
    assert 1 <= len(ranked) <= 12
    greedy = next(r for r in ranked if not r["decisions"])
    assert greedy["plan_id"] == "greedy"
    assert ranked[0]["predicted_s"] <= greedy["predicted_s"]


@pytest.mark.parametrize("name", ["resnet", "inception_resnet_v2"])
def test_search_plan_zoo_predicted_never_worse(name):
    """The ROADMAP targets: on resnet50 and inception_resnet_v2 the
    searched plan's predicted wall is <= the greedy plan's (greedy is
    seeded, so this holds by construction — the test pins it)."""
    kwargs = {"num_layers": 50} if name == "resnet" else {}
    net = models.get_model(name, num_classes=10, **kwargs)
    shapes = {"data": (2, 3, 224, 224)} if name != "resnet" else \
        {"data": (2, 3, 32, 32)}
    shapes["softmax_label"] = (2,)
    topo, node_shapes = infer_node_shapes(net, shapes)
    ranked = plansearch.search_plan(topo, net._entries, layout="NHWC",
                                    node_shapes=node_shapes,
                                    budget=6, beam=2)
    greedy = next(r for r in ranked if not r["decisions"])
    assert greedy["blocks"] > 0
    assert ranked[0]["predicted_s"] <= greedy["predicted_s"]


# ------------------------------------------- measure / commit / lookup
def test_search_and_commit_contract(tmp_path, monkeypatch):
    """One loop: winner committed; predicted <= greedy predicted AND
    measured <= greedy measured; the second run is a pure cache hit
    with zero search."""
    net = models.get_model("mlp", num_classes=10)
    data_shapes = {"data": (4, 784), "softmax_label": (4,)}
    cache = autotune.TuneCache()
    doc = plansearch.search_and_commit(net, data_shapes, layout="NCHW",
                                       budget=8, beam=4, topk=2,
                                       repeats=1, cache=cache)
    assert doc["predicted_s"] <= doc["greedy_predicted_s"] * (1 + 1e-9)
    assert doc["wall_s"] <= doc["greedy_wall_s"] * (1 + 1e-9)
    assert doc["measured"] >= 1 and len(cache) == 1
    entry = cache.entries()[0]
    assert entry["op"] == "graph_plan"
    assert entry["extra"]["graph"] == doc["graph"]
    doc2 = plansearch.search_and_commit(net, data_shapes,
                                        layout="NCHW", cache=cache)
    assert doc2["cached"] and doc2["searched"] == 0
    assert doc2["plan_id"] == doc["plan_id"]


def test_committed_decisions_roundtrip(tmp_path, monkeypatch):
    """Entry -> persistent cache -> fresh merged view -> bind-time
    lookup returns the decision vector, bumping the hit counter and
    dropping a plan_lookup flight event; mode=off skips everything."""
    monkeypatch.setenv("MXNET_TPU_TUNE_CACHE", str(tmp_path))
    autotune.reload_cache()
    sym = _conv_net()
    topo, entries = sym._topo(), sym._entries
    graph = fusion.graph_digest(topo, entries)
    g = _greedy_plan(sym)
    decisions = {"chains": {_chain_of(g, "fc_act"): "off"}}
    autotune.put(plansearch.OP, [], [],
                 config={"decisions": decisions,
                         "plan_id": fusion.decisions_id(decisions)},
                 wall_s=1e-3, extra={"graph": graph, "layout": "NCHW"},
                 source="plan-search")
    autotune.reload_cache()
    plansearch.reset_stats()
    h0 = telemetry.counter("mxtpu_plan_cache_hit_total").get()
    got = plansearch.committed_decisions(topo, entries, "NCHW")
    assert got == decisions
    assert plansearch.stats() == {"hits": 1, "misses": 0}
    assert telemetry.counter("mxtpu_plan_cache_hit_total").get() == \
        h0 + 1
    # a different layout key misses
    assert plansearch.committed_decisions(topo, entries, "NHWC") is None
    assert plansearch.stats()["misses"] == 1
    # mode off: no lookup, no counters
    monkeypatch.setenv("MXNET_TPU_PLAN_SEARCH", "off")
    plansearch.reset_stats()
    assert plansearch.committed_decisions(topo, entries, "NCHW") is None
    assert plansearch.stats() == {"hits": 0, "misses": 0}


def test_executor_bind_picks_up_committed_plan(tmp_path, monkeypatch):
    """The acceptance loop: commit an entry, reload the cache (a fresh
    process's merged view), bind an Executor on a REBUILT graph
    (different node names) — the searched plan must dispatch, visible
    in last_plan_summary's plan identity."""
    monkeypatch.setenv("MXNET_TPU_TUNE_CACHE", str(tmp_path))
    autotune.reload_cache()
    sym = _conv_net()
    g = _greedy_plan(sym)
    decisions = {"chains": {_chain_of(g, "conv_bn_act", "r0"):
                            "conv_bn"}}
    plan_id = fusion.decisions_id(decisions)
    autotune.put(plansearch.OP, [], [],
                 config={"decisions": decisions, "plan_id": plan_id},
                 wall_s=1e-3,
                 extra={"graph": fusion.graph_digest(sym._topo(),
                                                     sym._entries),
                        "layout": "NCHW"},
                 source="plan-search")
    autotune.reload_cache()
    rebuilt = _conv_net()
    with block_fusion(True):
        ex = rebuilt.simple_bind(mx.cpu(), data=(4, 3, 8, 8),
                                 softmax_label=(4,))
    assert ex._plan_decisions == decisions
    rng = np.random.RandomState(0)
    for name, arr in ex.arg_dict.items():
        arr[:] = (rng.randint(0, 10, arr.shape)
                  if name == "softmax_label"
                  else rng.uniform(-0.5, 0.5, arr.shape)) \
            .astype(np.float32)
    ex.forward(is_train=True)
    s = fusion.last_plan_summary()
    assert s["plan_id"] == plan_id and s["searched"]
    assert "conv_bn" in s["kinds"]


def test_executor_searched_vs_greedy_parity():
    """Forward + backward parity of a decision-transformed plan (chain
    split + per-region layout override + pallas veto) against greedy —
    the plan search may only change WHERE the math runs, never what it
    computes."""
    sym = _conv_net()
    g = _greedy_plan(sym)
    decisions = {
        "chains": {_chain_of(g, "conv_bn_act", "r0"): "bn_act"},
        "layouts": {_chain_of(g, "conv_bn_act", "r1"): "NHWC"},
    }
    shapes = {"data": (4, 3, 8, 8), "softmax_label": (4,)}

    def run(dec):
        with block_fusion(True), fusion.plan_decisions(dec):
            ex = sym.simple_bind(mx.cpu(), **shapes)
        assert ex._plan_decisions == dec     # ambient capture at bind
        rng = np.random.RandomState(0)
        for name, arr in ex.arg_dict.items():
            arr[:] = (rng.randint(0, 10, arr.shape)
                      if name == "softmax_label"
                      else rng.uniform(-0.5, 0.5, arr.shape)) \
                .astype(np.float32)
        ex.forward(is_train=True)
        out = ex.outputs[0].asnumpy()
        ex.backward()
        return out, {k: v.asnumpy() for k, v in ex.grad_dict.items()
                     if v is not None}

    o_ref, g_ref = run(None)
    o_alt, g_alt = run(decisions)
    np.testing.assert_allclose(o_ref, o_alt, rtol=2e-5, atol=2e-6)
    for k in g_ref:
        np.testing.assert_allclose(g_ref[k], g_alt[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


def test_trainer_picks_up_mesh_keyed_plan(tmp_path, monkeypatch):
    """ShardedTrainer consults the entry keyed by ITS mesh axis sizes
    and the step stays finite under the searched plan."""
    from mxnet_tpu.parallel import ShardedTrainer, build_mesh
    monkeypatch.setenv("MXNET_TPU_TUNE_CACHE", str(tmp_path))
    autotune.reload_cache()
    net = models.get_model("mlp", num_classes=10)
    mesh = build_mesh(tp=1)
    mesh_d = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    g = fusion.plan_block_fusion(net._topo(), net._entries,
                                 record=False, decisions={})
    decisions = {"chains": {sorted(b.chain for b in
                                   g.blocks.values())[0]: "off"}}
    autotune.put(plansearch.OP, [], [],
                 config={"decisions": decisions,
                         "plan_id": fusion.decisions_id(decisions)},
                 wall_s=1e-3, mesh=mesh_d,
                 extra={"graph": fusion.graph_digest(net._topo(),
                                                     net._entries),
                        "layout": "NCHW"},
                 source="plan-search")
    autotune.reload_cache()
    t = ShardedTrainer(net, mesh, data_shapes={"data": (8, 784)},
                       label_shapes={"softmax_label": (8,)},
                       fuse_blocks=True, learning_rate=0.1)
    assert t._plan_decisions == decisions
    rng = np.random.RandomState(0)
    b = t.put_batch({
        "data": rng.uniform(-1, 1, (8, 784)).astype(np.float32),
        "softmax_label": rng.randint(0, 10, 8).astype(np.float32)})
    assert np.isfinite(float(t.step(b)))
    assert fusion.last_plan_summary()["plan_id"] == \
        fusion.decisions_id(decisions)


# ----------------------------------------------- perf_top integration
def _perf_top(args, env=None):
    e = dict(os.environ, JAX_PLATFORMS="cpu")
    e.update(env or {})
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "perf_top.py")]
        + args, capture_output=True, text=True, timeout=120, env=e)


def _write_costdb(path, graph="abc123def456", plan="greedy",
                  layout=None):
    from mxnet_tpu.telemetry import costdb
    db = costdb.CostDB()
    db.record("block", "r0", wall_s=1e-3, flops=1e6,
              bytes_accessed=1e6, shapes=[(4, 8, 8, 8)],
              dtypes=["float32"], block_kind="conv_bn_act",
              layout=layout, graph=graph, plan=plan, source="test")
    p = db.flush(str(path))
    assert p
    return p


def test_perf_top_suggest_plan_untuned_row(tmp_path):
    db = tmp_path / "db"
    cache = tmp_path / "cache"
    db.mkdir(), cache.mkdir()
    _write_costdb(db)
    # a cache with SOME entry (not graph_plan) so --cache is readable
    c = autotune.TuneCache()
    c.put("matmul_stats", [(8, 8), (8, 8)], ["float32"] * 2,
          {"bm": 8}, wall_s=1e-4, persist=False)
    with open(cache / "tunecache-1.jsonl", "w") as f:
        f.write(json.dumps(c.entries()[0], default=repr) + "\n")
    res = _perf_top([str(db), "--suggest", "--cache", str(cache),
                     "--json"])
    assert res.returncode == 0, res.stderr
    doc = json.loads(res.stdout)
    rows = [r for r in doc["suggestions"] if r["kind"] == "plan"]
    assert len(rows) == 1
    assert rows[0]["status"] == "plan-untuned"
    assert rows[0]["name"] == "abc123def456"
    assert rows[0]["worst_block"] == "r0"


def test_perf_top_suggest_plan_stale_row(tmp_path):
    db = tmp_path / "db"
    cache = tmp_path / "cache"
    db.mkdir(), cache.mkdir()
    _write_costdb(db, plan="greedy")     # run dispatched greedy...
    c = autotune.TuneCache()
    c.put(plansearch.OP, [], [],
          {"decisions": {"chains": {"3": "off"}},
           "plan_id": "plan-deadbeef00"},
          wall_s=1e-3, extra={"graph": "abc123def456",
                              "layout": "NHWC"}, persist=False)
    with open(cache / "tunecache-1.jsonl", "w") as f:
        f.write(json.dumps(c.entries()[0], default=repr) + "\n")
    res = _perf_top([str(db), "--suggest", "--cache", str(cache),
                     "--json"])
    assert res.returncode == 0, res.stderr
    doc = json.loads(res.stdout)
    rows = [r for r in doc["suggestions"] if r["kind"] == "plan"]
    assert len(rows) == 1 and rows[0]["status"] == "plan-stale"
    assert rows[0]["committed_plan"] == "plan-deadbeef00"
    assert rows[0]["dispatched_plan"] == "greedy"


def test_perf_top_suggest_layout_mismatch_reads_untuned(tmp_path):
    """An entry committed at a DIFFERENT trace layout is not this
    record's plan — the row must read plan-untuned, not plan-stale."""
    db = tmp_path / "db"
    cache = tmp_path / "cache"
    db.mkdir(), cache.mkdir()
    _write_costdb(db, plan="greedy", layout="NCHW")
    c = autotune.TuneCache()
    c.put(plansearch.OP, [], [],
          {"decisions": {"chains": {"3": "off"}},
           "plan_id": "plan-deadbeef00"},
          wall_s=1e-3, extra={"graph": "abc123def456",
                              "layout": "NHWC"}, persist=False)
    with open(cache / "tunecache-1.jsonl", "w") as f:
        f.write(json.dumps(c.entries()[0], default=repr) + "\n")
    res = _perf_top([str(db), "--suggest", "--cache", str(cache),
                     "--json"])
    assert res.returncode == 0, res.stderr
    doc = json.loads(res.stdout)
    rows = [r for r in doc["suggestions"] if r["kind"] == "plan"]
    assert len(rows) == 1 and rows[0]["status"] == "plan-untuned"


def test_perf_top_suggest_bad_cache_is_usage_error(tmp_path):
    """--cache pointing at a nonexistent or corrupt file exits 2 with
    a usage error instead of silently rendering zero suggestions."""
    db = tmp_path / "db"
    db.mkdir()
    _write_costdb(db)
    res = _perf_top([str(db), "--suggest", "--cache",
                     str(tmp_path / "nope")])
    assert res.returncode == 2
    assert "does not exist" in res.stderr
    corrupt = tmp_path / "tunecache-bad.jsonl"
    corrupt.write_text("this is not json\n{\"also\": \"bad\"}\n")
    res = _perf_top([str(db), "--suggest", "--cache", str(corrupt)])
    assert res.returncode == 2
    assert "no readable" in res.stderr
    # the ambient env cache stays LENIENT: the directory is created
    # lazily by the first tune write, so a fresh machine must read as
    # all-untuned (with a stderr note), not as a tool failure
    res = _perf_top([str(db), "--suggest"],
                    env={"MXNET_TPU_TUNE_CACHE":
                         str(tmp_path / "gone")})
    assert res.returncode == 0
    assert "does not exist yet" in res.stderr


# --------------------------------------------------- MXG010 --plan mode
def _tiny_cost_model():
    recs = [{"wall_s": 10.0 ** (-6 + i % 3), "flops": 10.0 ** (6 + i),
             "bytes_accessed": 10.0 ** (5 + i),
             "block_config": {"bm": 2 ** (3 + i % 4)}}
            for i in range(12)]
    return autotune.CostModel().fit(recs)


def test_mxg010_plan_mode_names_plan_identity(tmp_path, monkeypatch):
    from mxnet_tpu.analysis import verify_model
    model = _tiny_cost_model()
    path = str(tmp_path / "cm.json")
    model.save(path)
    monkeypatch.setenv("MXNET_TPU_PLAN_SEARCH", "off")  # greedy plan
    _net, report = verify_model("lenet", cost_model=path, plan=True,
                                plan_layout="NCHW")
    msgs = [d.message for d in report if d.rule == "MXG010"]
    # the tiny synthetic model predicts wildly — what matters is that
    # plan-mode diagnostics run clean through the committed-plan path
    # and name the plan identity that owns each prediction
    for m in msgs:
        assert "committed plan greedy" in m


def test_analysis_cli_plan_flag(tmp_path):
    model = _tiny_cost_model()
    path = str(tmp_path / "cm.json")
    model.save(path)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_PLAN_SEARCH="off")
    res = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--model", "mlp",
         "--cost-model", path, "--plan", "--layout", "NCHW"],
        capture_output=True, text=True, timeout=180, env=env,
        cwd=_ROOT)
    assert res.returncode == 0, res.stdout + res.stderr
    res = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--model", "mlp",
         "--plan"],
        capture_output=True, text=True, timeout=180, env=env,
        cwd=_ROOT)
    assert res.returncode == 2        # --plan needs --cost-model
    assert "cost-model" in res.stderr


# ------------------------------------------------ measured zoo A/B
@pytest.mark.slow
def test_resnet_measured_ab_never_worse(tmp_path):
    """Measured top-k A/B on a (reduced-image) resnet50: the committed
    winner is never worse than greedy on the measured run."""
    net = models.get_model("resnet", num_layers=50, num_classes=10,
                           image_shape="3,32,32")
    data_shapes = {"data": (2, 3, 32, 32), "softmax_label": (2,)}
    cache = autotune.TuneCache()
    doc = plansearch.search_and_commit(net, data_shapes, layout="NHWC",
                                       budget=6, beam=2, topk=1,
                                       repeats=1, cache=cache)
    assert doc["wall_s"] <= doc["greedy_wall_s"] * (1 + 1e-9)
    assert doc["predicted_s"] <= doc["greedy_predicted_s"] * (1 + 1e-9)
