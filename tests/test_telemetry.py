"""Telemetry subsystem: registry semantics, spans, exporters, e2e fit.

Covers the contracts in docs/api/telemetry.md: labeled counter/gauge/
histogram semantics and thread safety, catalog enforcement, span
nesting + Chrome-trace round trip, JSONL/Prometheus golden outputs,
report() percentiles/throughput/compile accounting, the absorbed
IO/kvstore/resilience counters, an end-to-end Module.fit run on a
zoo model with the JSONL step-log enabled, the memory-observability
layer (version-tolerant plan accessors, plan gauges, HBM budget check,
RESOURCE_EXHAUSTED annotation), and the flight recorder (ring
wraparound, thread safety, dump schema + reader, crash-guard dedup).
"""
import importlib.util
import json
import os
import threading
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.telemetry import flight
from mxnet_tpu.telemetry import memory as tmem


def _load_tool(name):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(root, "tools", "%s.py" % name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_telemetry(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_TELEMETRY_JSONL", raising=False)
    monkeypatch.delenv("MXNET_TPU_FLIGHT_DIR", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


# ------------------------------------------------------------- registry

def test_counter_basic():
    c = telemetry.counter("mxtpu_step_total")
    c.inc()
    c.inc(4)
    assert c.get() == 5


def test_counter_rejects_decrease():
    with pytest.raises(MXNetError):
        telemetry.counter("mxtpu_step_total").inc(-1)


def test_labels_separate_series():
    c = telemetry.counter("mxtpu_io_records_total")
    c.labels(source="recordio").inc(2)
    c.labels(source="native").inc(3)
    samples = c.samples()
    assert samples[(("source", "recordio"),)] == 2
    assert samples[(("source", "native"),)] == 3


def test_label_mismatch_raises():
    c = telemetry.counter("mxtpu_io_records_total")
    with pytest.raises(MXNetError):
        c.labels(wrong="x")
    with pytest.raises(MXNetError):
        c.inc()        # labeled metric needs .labels(...)


def test_undeclared_name_raises():
    with pytest.raises(MXNetError, match="not declared"):
        telemetry.counter("mxtpu_not_in_catalog_total")


def test_kind_mismatch_raises():
    with pytest.raises(MXNetError):
        telemetry.gauge("mxtpu_step_total")


def test_gauge_set_inc_dec():
    g = telemetry.gauge("mxtpu_kvstore_pending_async")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.get() == 4


def test_histogram_buckets():
    r = telemetry.Registry(catalog=None)
    h = r.histogram("h", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    s = h.get()
    assert s["buckets"] == [1, 1, 1, 1]   # one per bucket + overflow
    assert s["count"] == 4
    assert abs(s["sum"] - 55.55) < 1e-9


def test_histogram_rejects_unsorted_buckets():
    r = telemetry.Registry(catalog=None)
    with pytest.raises(MXNetError):
        r.histogram("h", buckets=(1.0, 0.5))


def test_thread_safety_writer_pool():
    c = telemetry.counter("mxtpu_samples_total")
    h = telemetry.histogram("mxtpu_step_seconds")
    n_threads, n_iter = 8, 500

    def work():
        for _ in range(n_iter):
            c.inc()
            h.observe(0.001)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.get() == n_threads * n_iter
    assert h.get()["count"] == n_threads * n_iter


def test_reset_keeps_cached_children_valid():
    child = telemetry.counter("mxtpu_io_records_total").labels(
        source="recordio")
    child.inc()
    telemetry.reset()
    child.inc(2)
    assert child.get() == 2


# ---------------------------------------------------------------- spans

def test_span_records_histogram_and_nesting():
    with telemetry.span("outer"):
        with telemetry.span("inner"):
            pass
        with telemetry.span("inner"):
            pass
    samples = telemetry.histogram("mxtpu_span_seconds").samples()
    assert samples[(("span", "outer"),)]["count"] == 1
    assert samples[(("span", "inner"),)]["count"] == 2
    # outer wall time covers both inners
    assert samples[(("span", "outer"),)]["sum"] >= \
        samples[(("span", "inner"),)]["sum"]


def test_span_decorator():
    calls = []

    @telemetry.span("decorated")
    def fn(x):
        calls.append(x)
        return x + 1

    assert fn(1) == 2
    assert calls == [1]
    samples = telemetry.histogram("mxtpu_span_seconds").samples()
    assert samples[(("span", "decorated"),)]["count"] == 1


def test_span_chrome_trace_roundtrip(tmp_path):
    fname = str(tmp_path / "trace.json")
    mx.profiler.profiler_set_config(mode="all", filename=fname)
    mx.profiler.profiler_set_state("run")
    with telemetry.span("telemetry_span", category="unit"):
        pass
    mx.profiler.profiler_set_state("stop")
    mx.profiler.dump_profile()
    with open(fname) as f:
        trace = json.load(f)
    evts = [e for e in trace["traceEvents"]
            if e["name"] == "telemetry_span"]
    assert len(evts) == 1
    assert evts[0]["cat"] == "unit"
    assert evts[0]["ph"] == "X"
    assert evts[0]["dur"] >= 0


def test_profiler_record_event_concurrent(tmp_path):
    """Regression: record_event/dump_profile must hold the lock
    consistently — concurrent span callbacks and dumps lose no events
    and never crash."""
    fname = str(tmp_path / "conc.json")
    mx.profiler.profiler_set_config(mode="all", filename=fname)
    mx.profiler.profiler_set_state("run")
    n_threads, n_events = 8, 200
    errors = []

    def writer():
        try:
            for i in range(n_events):
                mx.profiler.record_event("evt", float(i), 1.0)
        except Exception as e:  # noqa: BLE001 - collected for assert
            errors.append(e)

    collected = []

    def dumper():
        try:
            for _ in range(20):
                mx.profiler.dump_profile()
                with open(fname) as f:
                    collected.append(len(json.load(f)["traceEvents"]))
        except Exception as e:  # noqa: BLE001 - collected for assert
            errors.append(e)

    threads = [threading.Thread(target=writer) for _ in range(n_threads)]
    threads.append(threading.Thread(target=dumper))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    mx.profiler.profiler_set_state("stop")
    total = sum(collected) + len(
        json.load(open(mx.profiler.dump_profile()))["traceEvents"])
    assert not errors, errors
    assert total == n_threads * n_events


# ------------------------------------------------------------ exporters

def test_jsonl_step_log(tmp_path, monkeypatch):
    path = str(tmp_path / "steps.jsonl")
    monkeypatch.setenv("MXNET_TPU_TELEMETRY_JSONL", path)
    with telemetry.span("phase_a"):
        pass
    telemetry.step_end(samples=32, step_time=0.01)
    telemetry.step_end(samples=32, step_time=0.02, extra={"loss": 1.5})
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    assert len(recs) == 2
    assert recs[0]["step"] == 1 and recs[1]["step"] == 2
    assert recs[0]["samples"] == 32
    assert recs[0]["spans"]["phase_a"]["count"] == 1
    assert "phase_a" not in recs[1]["spans"]   # drained per step
    assert recs[1]["loss"] == 1.5
    assert recs[1]["counters"]["mxtpu_samples_total"] == 64
    assert "gauges" in recs[0]


def test_render_prom_golden():
    telemetry.counter("mxtpu_io_records_total").labels(
        source="recordio").inc(7)
    telemetry.gauge("mxtpu_kvstore_pending_async").set(2)
    out = telemetry.render_prom()
    assert "# TYPE mxtpu_io_records_total counter" in out
    assert 'mxtpu_io_records_total{source="recordio"} 7' in out
    assert "# TYPE mxtpu_kvstore_pending_async gauge" in out
    assert "mxtpu_kvstore_pending_async 2" in out


def test_render_prom_histogram_cumulative():
    h = telemetry.histogram("mxtpu_step_seconds")
    h.observe(0.0001)
    h.observe(0.3)
    out = telemetry.render_prom()
    assert 'mxtpu_step_seconds_bucket{le="0.0005"} 1' in out
    assert 'mxtpu_step_seconds_bucket{le="+Inf"} 2' in out
    assert "mxtpu_step_seconds_count 2" in out


def test_report_percentiles_and_throughput():
    for i in range(100):
        telemetry.step_end(samples=10, step_time=0.01 * (i + 1))
    rep = telemetry.report()
    assert rep["steps"] == 100
    st = rep["step_time_s"]
    assert st["min"] <= st["p50"] <= st["p90"] <= st["p99"] <= st["max"]
    assert abs(st["p50"] - 0.505) < 0.02
    assert rep["throughput"]["samples_per_sec"] > 0
    assert rep["compile"]["source"] in ("jax.monitoring", "heuristic")


def test_report_phases_from_spans():
    with telemetry.span("phase_x"):
        pass
    rep = telemetry.report()
    assert rep["phases"]["phase_x"]["count"] == 1
    assert rep["phases"]["phase_x"]["total_s"] >= 0


def test_http_endpoint():
    httpd = telemetry.start_http_server(port=0)
    port = httpd.server_address[1]
    telemetry.counter("mxtpu_step_total").inc()
    body = urllib.request.urlopen(
        "http://127.0.0.1:%d/metrics" % port, timeout=10).read().decode()
    assert "mxtpu_step_total 1" in body


def test_selfcheck_and_docs_drift():
    assert telemetry.selfcheck() == []
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cc = _load_tool("ci_check")
    assert cc.telemetry_drift(root) == []


# ------------------------------------------------------- memory / HBM

class _AttrMA:
    """jax 0.4.x CompiledMemoryStats shape: *_size_in_bytes attributes."""
    argument_size_in_bytes = 1000
    output_size_in_bytes = 200
    temp_size_in_bytes = 300
    alias_size_in_bytes = 150
    generated_code_size_in_bytes = 50


class _FakeCompiled:
    def memory_analysis(self):
        return _AttrMA()

    def cost_analysis(self):
        # jax <= 0.4.x list-of-dict form, space-separated key
        return [{"flops": 1e6, "bytes accessed": 2e6}]


def test_memory_accessors_version_tolerant():
    assert tmem.memory_analysis_of(_FakeCompiled()) == {
        "argument": 1000, "output": 200, "temp": 300,
        "alias": 150, "generated_code": 50}
    assert tmem.cost_analysis_of(_FakeCompiled()) == {
        "flops": 1e6, "bytes_accessed": 2e6}

    class DictForm:          # jax >= 0.5 plain-dict shapes
        def memory_analysis(self):
            return {"argument": 7, "temp": 3}

        def cost_analysis(self):
            return {"flops": 5.0, "bytes_accessed": 9.0}

    assert tmem.memory_analysis_of(DictForm()) == {"argument": 7,
                                                   "temp": 3}
    assert tmem.cost_analysis_of(DictForm())["bytes_accessed"] == 9.0

    class Absent:            # backend without analyses
        def memory_analysis(self):
            return None

        def cost_analysis(self):
            raise RuntimeError("unsupported")

    assert tmem.memory_analysis_of(Absent()) is None
    assert tmem.cost_analysis_of(Absent()) is None
    assert tmem.plan_of(Absent(), "x") is None
    assert tmem.memory_analysis_of(object()) is None   # no method at all


def test_plan_totals_and_register_gauges():
    plan = tmem.plan_of(_FakeCompiled(), "unit.prog")
    # arg + out + temp + code - alias
    assert plan.total_bytes == 1000 + 200 + 300 + 50 - 150
    tmem.register_plan(plan)
    g = telemetry.gauge("mxtpu_memory_plan_bytes")
    assert g.labels(program="unit.prog", category="argument").get() == 1000
    assert g.labels(program="unit.prog", category="total").get() == 1400
    assert telemetry.gauge("mxtpu_program_flops").labels(
        program="unit.prog").get() == 1e6
    assert tmem.get_plan("unit.prog") is plan
    rep = telemetry.report()
    assert rep["memory"]["plans"]["unit.prog"]["total_bytes"] == 1400
    assert any(e["kind"] == "memory_plan" for e in flight.events())
    # the breakdown string names every category
    for cat in ("argument", "output", "temp", "total"):
        assert cat in plan.breakdown()


def test_budget_check_raises_with_breakdown(monkeypatch):
    plan = tmem.plan_of(_FakeCompiled(), "unit.budget")
    # capacity unknown: inert
    monkeypatch.delenv("MXNET_TPU_HBM_LIMIT_BYTES", raising=False)
    tmem.check_budget(plan)
    # explicit capacity below the plan: descriptive raise
    monkeypatch.setenv("MXNET_TPU_HBM_LIMIT_BYTES", "1000")
    with pytest.raises(MXNetError) as ei:
        tmem.check_budget(plan)
    msg = str(ei.value)
    assert "unit.budget" in msg
    assert "argument=" in msg and "temp=" in msg
    assert "MXNET_BACKWARD_DO_MIRROR" in msg     # remat advice
    assert "batch size" in msg
    # disabled check never raises
    monkeypatch.setenv("MXNET_TPU_MEMORY_BUDGET", "0")
    tmem.check_budget(plan)
    monkeypatch.setenv("MXNET_TPU_MEMORY_BUDGET", "2.0")
    tmem.check_budget(plan)                      # 2x1000 covers 1400


def test_planned_executable_real_jit():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(a):
        return (a @ a).sum()

    x = jnp.ones((8, 8))
    exe = tmem.planned_executable("unit.jit", f, (x,))
    assert float(exe(x)) == 512.0
    plan = tmem.get_plan("unit.jit")
    assert plan is not None and (plan.memory or plan.cost)
    # a function with no .lower degrades to itself, no plan
    calls = []

    def plain(a):
        calls.append(1)
        return a

    assert tmem.planned_executable("unit.plain", plain, (x,)) is plain
    assert tmem.get_plan("unit.plain") is None


def test_annotate_oom_message_counter_and_passthrough():
    tmem.register_plan(tmem.plan_of(_FakeCompiled(), "unit.oom"))
    with pytest.raises(tmem.HbmOomError) as ei:
        with tmem.annotate_oom("unit.oom"):
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory while "
                               "trying to allocate 9437184 bytes.")
    msg = str(ei.value)
    assert "RESOURCE_EXHAUSTED" in msg
    assert "static memory plan" in msg and "argument=" in msg
    assert "live device memory" in msg
    assert isinstance(ei.value.__cause__, RuntimeError)
    assert telemetry.counter("mxtpu_oom_total").labels(
        program="unit.oom").get() == 1
    assert any(e["kind"] == "oom" for e in flight.events())
    # non-OOM errors pass through untouched
    with pytest.raises(ValueError, match="plain"):
        with tmem.annotate_oom("unit.oom"):
            raise ValueError("plain failure")
    assert telemetry.counter("mxtpu_oom_total").labels(
        program="unit.oom").get() == 1


# --------------------------------------------------- flight recorder

def test_flight_ring_wraparound():
    r = flight.FlightRecorder(capacity_=16)
    for i in range(50):
        r.record("unit", i=i)
    evs = r.events()
    assert len(evs) == 16
    assert evs[0]["i"] == 34 and evs[-1]["i"] == 49
    assert evs[-1]["seq"] == 50          # seq keeps counting past drops
    r.clear()
    assert len(r.events()) == 0


def test_flight_thread_safety():
    r = flight.FlightRecorder(capacity_=100_000)
    n_threads, n_iter = 8, 500

    def work(tid):
        for i in range(n_iter):
            r.record("unit", tid=tid, i=i)

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = r.events()
    assert len(evs) == n_threads * n_iter
    assert len({e["seq"] for e in evs}) == len(evs)   # no seq collisions


def test_flight_dump_schema_and_reader(tmp_path):
    flight.record("unit", detail="x")
    assert flight.dump("unit") is None        # no dir configured: no-op
    path = flight.dump("unit test!", directory=str(tmp_path))
    assert path and os.path.exists(path)
    assert "unit-test-" in os.path.basename(path)     # slugged reason
    fr = _load_tool("flight_read")
    doc = fr.load(path)
    assert doc["schema"] == "mxtpu-flight/1"
    assert doc["pid"] == os.getpid()
    assert any(e["kind"] == "unit" for e in doc["events"])
    text = fr.format_dump(doc)
    assert "reason=unit test!" in text and "events" in text
    assert telemetry.counter("mxtpu_flight_dumps_total").labels(
        reason="unit-test-").get() == 1
    # a malformed file is rejected with a named error
    bad = tmp_path / "bad.json"
    bad.write_text("{\"schema\": \"nope\"}")
    with pytest.raises(ValueError, match="schema"):
        fr.load(str(bad))


def test_crash_guard_dumps_once_and_only_mxnet_errors(tmp_path,
                                                      monkeypatch):
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path))
    with pytest.raises(MXNetError, match="boom"):
        with flight.crash_guard("outer"):
            with flight.crash_guard("inner"):
                raise MXNetError("boom")
    dumps = [f for f in os.listdir(str(tmp_path))
             if f.startswith("flight-")]
    assert len(dumps) == 1                     # nested guards dedup
    doc = _load_tool("flight_read").load(str(tmp_path / dumps[0]))
    assert doc["reason"] == "error"
    assert doc["error"] == "boom"
    errs = [e for e in doc["events"] if e["kind"] == "error"]
    assert errs and errs[0]["site"] == "inner"
    # non-framework errors are not black-boxed by the guard
    with pytest.raises(ValueError):
        with flight.crash_guard("outer"):
            raise ValueError("not ours")
    assert len([f for f in os.listdir(str(tmp_path))
                if f.startswith("flight-")]) == 1


def test_step_end_records_flight_event_with_deltas():
    telemetry.step_end(samples=8, step_time=0.01)
    telemetry.counter("mxtpu_io_records_total").labels(
        source="native").inc(5)
    telemetry.step_end(samples=8, step_time=0.01)
    ends = [e for e in flight.events() if e["kind"] == "step_end"]
    assert len(ends) == 2
    d = ends[1]["counter_deltas"]
    assert d["mxtpu_step_total"] == 1
    assert d['mxtpu_io_records_total{source="native"}'] == 5
    assert ends[1]["step"] == 2


# ------------------------------------------------- absorbed counters

def test_kvstore_push_pull_bytes():
    kv = mx.kv.create("local")
    a = mx.nd.ones((4, 8))
    kv.init("w", a)
    kv.push("w", mx.nd.ones((4, 8)))
    out = mx.nd.zeros((4, 8))
    kv.pull("w", out=out)
    pushed = telemetry.counter(
        "mxtpu_kvstore_push_bytes_total").labels(store="local").get()
    pulled = telemetry.counter(
        "mxtpu_kvstore_pull_bytes_total").labels(store="local").get()
    assert pushed == 4 * 8 * 4
    assert pulled == 4 * 8 * 4


def test_recordio_read_counter(tmp_path):
    path = str(tmp_path / "t.rec")
    w = mx.recordio.MXRecordIO(path, "w")
    for i in range(5):
        w.write(b"payload-%d" % i)
    w.close()
    r = mx.recordio.MXRecordIO(path, "r")
    n = 0
    while r.read() is not None:
        n += 1
    r.close()
    assert n == 5
    got = telemetry.counter("mxtpu_io_records_total").labels(
        source="recordio").get()
    assert got == 5


def test_fault_and_retry_counters():
    from mxnet_tpu import resilience
    resilience.configure_faults("recordio.read:n=2")
    try:
        for _ in range(2):
            with pytest.raises(resilience.FaultInjected):
                resilience.fault_point("recordio.read")
    finally:
        resilience.clear_faults()
    assert telemetry.counter("mxtpu_fault_injected_total").labels(
        site="recordio.read").get() == 2

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ValueError("transient")
        return "ok"

    assert resilience.retry_call(flaky, retries=3, base_delay=0.001,
                                 jitter=0, name="unit.flaky") == "ok"
    assert telemetry.counter("mxtpu_retry_total").labels(
        site="unit.flaky").get() == 2


def test_prefetch_stall_and_depth():
    x = np.random.RandomState(0).rand(64, 4).astype(np.float32)
    y = np.zeros(64, np.float32)
    it = mx.io.PrefetchingIter(
        mx.io.NDArrayIter(x, y, batch_size=16))
    n = sum(1 for _ in it)
    assert n == 4
    stalls = telemetry.counter(
        "mxtpu_io_prefetch_stall_seconds_total").labels(iter="host")
    assert stalls.get() >= 0.0    # present and non-negative
    # the gauge exists and ends drained
    depth = telemetry.gauge("mxtpu_io_prefetch_depth").labels(iter="host")
    assert depth.get() in (0.0, 1.0)


def test_monitor_stats_become_gauges():
    mon = mx.mon.Monitor(interval=1)
    mon.tic()
    mon.stat_helper("fc1_output", mx.nd.ones((2, 2)))
    res = mon.toc()
    assert res, "monitor recorded nothing"
    g = telemetry.gauge("mxtpu_monitor_stat").labels(tensor="fc1_output")
    assert abs(g.get() - 1.0) < 1e-6


# ------------------------------------------------------------ e2e fit

def test_module_fit_e2e_report_and_jsonl(tmp_path, monkeypatch):
    """Acceptance: Module.fit on a zoo model with the JSONL step-log —
    one parseable record per step carrying span timings and the
    absorbed counters; report() shows the step count, >=1 compile, and
    nonzero throughput."""
    path = str(tmp_path / "fit.jsonl")
    monkeypatch.setenv("MXNET_TPU_TELEMETRY_JSONL", path)

    from mxnet_tpu import models
    net = models.get_model("mlp", num_classes=10)
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (96, 64)).astype(np.float32)
    y = rng.randint(0, 10, 96).astype(np.float32)
    train = mx.io.NDArrayIter(x, y, batch_size=32,
                              last_batch_handle="discard")
    # two impersonated devices so the local kvstore path runs (single
    # device skips the store) and its traffic lands in the step-log
    mod = mx.module.Module(net, context=[mx.cpu(0), mx.cpu(1)])
    mod.fit(train, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05},
            initializer=mx.initializer.Xavier())

    rep = telemetry.report()
    assert rep["steps"] == 6                      # 3 batches x 2 epochs
    assert rep["compile"]["count"] >= 1
    assert rep["throughput"]["samples_per_sec"] > 0
    # the instrumented phases all appear in the breakdown
    for phase in ("module.forward_backward", "module.update",
                  "executor.forward_backward", "data.fetch"):
        assert phase in rep["phases"], rep["phases"]

    with open(path) as f:
        recs = [json.loads(line) for line in f]
    assert len(recs) == 6
    for i, rec in enumerate(recs):
        assert rec["step"] == i + 1
        assert rec["samples"] == 32
        assert rec["step_time_s"] > 0
        assert "module.forward_backward" in rec["spans"]
        assert "mxtpu_kvstore_push_bytes_total{store=\"local\"}" \
            in rec["counters"]
        assert "mxtpu_watchdog_restarts" in rec["gauges"]
    # samples counter is cumulative across the run
    assert recs[-1]["counters"]["mxtpu_samples_total"] == 6 * 32
